"""The driver: synchronous on-policy RL loop re-hosted on a TPU mesh.

TPU-native replacement for the reference Trainer (distributed_trainer.py:13–416
— SURVEY §3.2). The reference's mechanisms map as follows:

* **Rollout fan-out** (Ray actors + chunk dispatch, :178–200) → ONE sharded
  ``engine.generate`` call: the batch is laid out over the rollout mesh's dp
  axis and GSPMD parallelizes it. ``chunk_sizes`` is still computed for its
  validation/warning semantics (and exercised by the multi-process control
  plane), but on a single host no per-worker RPC exists.
* **Weight sync** (adapter file save/load every step, :346 / distributed_
  actor.py:150) → the learner's LoRA pytree is PASSED to the engine each
  round — device arrays, no filesystem. ``weight_version`` counts updates and
  the engine round records which version it sampled with (the race detector
  the reference lacks, SURVEY §5). ``write_adapter_file=True`` still exports
  the per-step artifact for compatibility.
* **Gradient merge** (CPU dicts through Ray, :308–342) → inside the pjit'd
  train step (learner/train_step.py); nothing to orchestrate here.
* **Metrics / timing**: exact reference names (:348–366, :412–415) through a
  pluggable sink (metrics.py).
* **Checkpointing**: Orbax {lora, opt_state, step, episode} with true resume
  (the reference is save-only, SURVEY §5).
"""

from __future__ import annotations

import concurrent.futures
import logging
import math
import os
import time
from functools import partial
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu import obs as obs_mod, telemetry
from distrl_llm_tpu.checkpoint import CheckpointManager, save_adapter_file
from distrl_llm_tpu.config import SamplingConfig, TrainConfig
from distrl_llm_tpu.data import DictDataset
from distrl_llm_tpu.learner.optim import make_optimizer
from distrl_llm_tpu.learner.train_step import make_train_step, prepare_update_batch
from distrl_llm_tpu.metrics import MetricsSink, make_sink
from distrl_llm_tpu.models.lora import init_lora_params, lora_scale
from distrl_llm_tpu.ops.quant import default_group_size, quant_bits_for, quantize_params
from distrl_llm_tpu.parallel.mesh import RoleMeshes, build_role_meshes
from distrl_llm_tpu.rewards import (
    RewardComputer,
    make_reward_function,
    reward_function as parity_reward_function,
)
from distrl_llm_tpu.shaping import flatten_for_update, shape_rewards, topk_filter
from distrl_llm_tpu.tokenizer import decode_batch, encode_fixed
from distrl_llm_tpu.utils.chunking import chunk_sizes

log = logging.getLogger(__name__)

RewardFn = Callable[[Sequence[str], Sequence[str]], np.ndarray]


def engine_kwargs_from_config(config: TrainConfig) -> dict[str, Any]:
    """Engine-constructor kwargs derived from the config (paged-engine knobs:
    KV quant, continuous batching, speculative decoding, row cap). Module
    level so the config→engine wiring is unit-testable without a checkpoint."""
    kwargs: dict[str, Any] = {"kv_quant": config.kv_cache_quant}
    if config.decode_scan_chunk is not None:
        # every engine_impl hosts the chunked step (dense, paged wave +
        # refill, paged_sharded, and the speculative scheduler via
        # _spec_chunk_fn — chunk counts verify rounds there). An explicit
        # value — INCLUDING 0 — must reach the engine as a pin, so a
        # --decode_scan_chunk 0 A/B can never be retuned by a stored plan
        kwargs["scan_chunk"] = config.decode_scan_chunk
    if config.engine_impl == "paged":
        if config.continuous_batching:
            kwargs["scheduler"] = "refill"
            # prefix sharing / continuous admission (ISSUE 12): forwarded
            # only when set, so an unset config stays plan-DB-resolvable
            # at the engine (continuous_admission None = consult cb_mode)
            # and the empty-DB default remains byte-identical fixed batches
            if config.prefix_sharing:
                kwargs["prefix_sharing"] = True
            if config.continuous_admission:
                kwargs["continuous_admission"] = True
            # None = unpinned (engine default / plan-DB-resolvable); any
            # explicit value — INCLUDING spec_draft=0 and the default
            # spellings 'ngram'/'fused' — reaches the engine as a pin, so
            # a --spec_draft 0 A/B can never be retuned by a stored plan
            # (the decode_scan_chunk convention)
            if config.spec_draft is not None:
                kwargs["spec_draft"] = config.spec_draft
            if config.spec_ngram is not None:
                kwargs["spec_ngram"] = config.spec_ngram
            if config.spec_drafter is not None:
                kwargs["spec_drafter"] = config.spec_drafter
            if config.spec_verify is not None:
                kwargs["spec_verify"] = config.spec_verify
            if config.spec_adapt:
                kwargs["spec_adapt"] = True
            # tiered KV cache (ISSUE 18): None stays plan-DB-resolvable at
            # the engine; an explicit bool — INCLUDING False — pins past
            # any stored plan (the spec_draft convention). kv_spill is
            # explicit-only, never plan-resolved.
            if config.prefix_cache is not None:
                kwargs["prefix_cache"] = config.prefix_cache
            if config.kv_spill:
                kwargs["kv_spill"] = True
                if config.kv_spill_host_mb:
                    kwargs["kv_spill_host_mb"] = config.kv_spill_host_mb
    if config.max_concurrent_sequences and config.engine_impl != "paged_sharded":
        # the sharded engine admits whole dp-sharded waves; a row cap is the
        # per-replica engines' admission knob
        kwargs["max_concurrent_rows"] = config.max_concurrent_sequences
    if config.clip_ratio > 0.0:
        # behavior-logprob capture costs a per-step vocab logsumexp plus the
        # [B, n, T] f32 transport — only pay it when the clip objective needs it
        kwargs["capture_logprobs"] = True
    # autotune plan resolution (distrl_llm_tpu/autotune): only non-default
    # settings are forwarded, so the kwargs stay minimal and an engine built
    # from a default config keeps consulting the default plan-DB path
    if not config.autotune:
        kwargs["autotune"] = False
    if config.plan_db:
        kwargs["plan_db"] = config.plan_db
    return kwargs


def _env_turn_counts(candidates: list[dict]) -> list[int]:
    """Per-EPISODE turn counts from the provenance riding consumed batches.

    ``cand["turns"]`` nests group-major: one entry per trajectory (group),
    each a list over the group's candidate rows, each row the list of that
    episode's turn records — the episode count is the innermost length, NOT
    the row count (len(grp) is just ``num_candidates``)."""
    return [
        len(row or ())
        for c in candidates if "turns" in c
        for grp in c["turns"]
        for row in (grp or ())
    ]


class StaleWeightsError(RuntimeError):
    """The rollout mesh holds an adapter older than the learner's — the race
    the reference structurally prevents with its synchronous barrier and we
    detect with asserted weight-version counters (SURVEY §5 race detection)."""


class EngineHangError(RuntimeError):
    """A generation round exceeded ``generation_timeout_s`` — the hang
    detector matching the reference's ``ray.get(timeout=240)``
    (distributed_trainer.py:200). The trainer checkpoints before raising;
    restart with ``resume=True`` to continue from the last completed step."""


class Trainer:
    """Owns the episode/batch loop. Heavy pieces (tokenizer, base params,
    engine, meshes) are injectable so the loop tests with fakes (SURVEY §4
    "FakeEngine") and assembles itself for real runs via ``from_pretrained``.
    """

    def __init__(
        self,
        train_dataset,
        test_dataset,
        reward_function: RewardFn,
        config: TrainConfig,
        *,
        tokenizer,
        engine,
        base_params,
        model_cfg,
        meshes: RoleMeshes | None = None,
        base_params_learner=None,
        sink: MetricsSink | None = None,
        reward_computer: RewardComputer | None = None,
    ):
        self.config = config
        self.train_dataset = DictDataset.wrap(train_dataset)
        self.test_dataset = DictDataset.wrap(test_dataset)
        self.tokenizer = tokenizer
        self.engine = engine
        self.base_params = base_params
        # the learner's copy of the frozen base: resident on the learner
        # submesh so the train step never touches rollout devices (the
        # reference's per-worker model load, distributed_actor.py:58); with
        # timeshared roles both names alias one tree.
        self.base_params_learner = (
            base_params_learner if base_params_learner is not None else base_params
        )
        self.model_cfg = model_cfg
        self.meshes = meshes
        self.sink = sink
        # format-reward gate (ISSUE 17 satellite): "strict" swaps the
        # previously-dead strict newline-delimited scorer into the (N, 2)
        # contract. Only the parity default is substitutable — a custom fn
        # plus a non-default gate is ambiguous (which one wins?), refuse.
        if config.format_reward != "soft":
            if reward_function is not parity_reward_function:
                raise ValueError(
                    "format_reward != 'soft' with a custom reward_function "
                    "is ambiguous — encode the gate inside the custom fn, "
                    "or drop one of the two"
                )
            reward_function = make_reward_function(config.format_reward)
        # the computer evaluates THIS trainer's reward_function (a custom fn
        # passed positionally — the reference contract — must actually run).
        # An explicit reward_computer carries parallelism config; the fn is
        # passed per call so a computer shared across Trainers is never
        # mutated. A computer EXPLICITLY built with a different fn than the
        # trainer's is ambiguous — refuse.
        if reward_computer is None:
            reward_computer = RewardComputer(reward_fn=reward_function)
        elif (
            reward_computer.fn_explicit
            and reward_computer.reward_fn is not reward_function
        ):
            raise ValueError(
                "reward_computer was built with a different reward_fn than "
                "the one passed to Trainer — pass the fn in exactly one place"
            )
        self.rewards = reward_computer
        self._reward_fn = reward_function

        # pluggable environments (ISSUE 17): a multi-turn env arms the
        # engine's turn hook per round — finished turns step the env and
        # continuing conversations resume on their resident KV chains.
        # env="math" routes the exact legacy single-turn path (no driver,
        # byte-identical losses and checksums).
        self._env_driver: Any = None
        if config.env != "math":
            from distrl_llm_tpu.env import EnvRolloutDriver

            if not hasattr(engine, "turn_hook"):
                raise ValueError(
                    f"env={config.env!r} needs an engine with a turn_hook "
                    "(the local paged refill engine); "
                    f"{type(engine).__name__} has none"
                )
            self._env_driver = EnvRolloutDriver(
                config.env, tokenizer,
                max_turns=config.max_turns,
                max_new_tokens=config.max_new_tokens,
                format_scorer=config.format_reward,
            )

        # multi-tenant serving gateway (ISSUE 19): built lazily at the top
        # of train() — it serves WHILE training runs, and its rounds share
        # the engine with rollout generation through _engine_mutex
        self._gateway_service: Any = None
        self._gateway_server: Any = None
        self._engine_mutex: Any = None
        if config.gateway_port is not None and not getattr(
            engine, "continuous_admission", False
        ):
            raise ValueError(
                "gateway_port needs a local continuous-admission paged "
                f"engine; {type(engine).__name__} has no request-queue "
                "admission plane"
            )

        # the silent-no-op fix (ISSUE 9): inflight_weight_updates with an
        # engine that cannot actually swap mid-round used to pretend to
        # work (the push was a getattr that quietly found nothing). Any
        # engine that still lacks a real push_lora is rejected HERE, so
        # the combination can never silently regress again. Local engines
        # inherit push_lora from LoraMailbox; RemoteEngine advertises
        # supports_inflight_push only in broadcast-bus mode.
        if config.inflight_weight_updates:
            push = getattr(engine, "push_lora", None)
            if not callable(push) or not getattr(
                engine, "supports_inflight_push", callable(push)
            ):
                raise ValueError(
                    "inflight_weight_updates requires an engine with a real "
                    f"push_lora (in-flight weight-update mailbox); "
                    f"{type(engine).__name__} cannot swap a round in flight "
                    "— use a local engine or a RemoteEngine with "
                    "weight_bus='broadcast'"
                )

        # chunk-composition validation parity (distributed_trainer.py:34–36)
        assert config.number_of_learners > 0, "Need at least one learner"
        chunk_sizes(
            config.batch_size,
            config.number_of_actors,
            config.number_of_learners,
            config.learner_chunk_size,
        )

        self.scale = lora_scale(config.max_lora_rank, config.lora_alpha)
        import threading as _threading

        self._rng = jax.random.PRNGKey(config.seed)
        self._rng_mu = _threading.Lock()
        self._rng, lora_key = jax.random.split(self._rng)
        if config.full_finetune:
            # BASELINE config 3 (bf16 full-rank, no 4-bit): the WHOLE param
            # tree is the trainable state; there is no adapter. self.lora
            # holds whichever tree trains — the engine call sites and weight
            # push branch on _full below. The trainable copy is kept in f32
            # (master weights): with lr=2e-5 a typical update is below bf16's
            # ~0.4% relative resolution, so bf16 apply_updates would round
            # many steps to no-ops; _push_weights casts back down for rollout.
            from distrl_llm_tpu.ops.quant import is_quantized_tree

            if is_quantized_tree(self.base_params_learner):
                raise ValueError("full_finetune requires an unquantized base")
            self._rollout_dtype = jax.tree_util.tree_leaves(
                self.base_params_learner
            )[0].dtype  # rollout samples at the base's dtype (bf16 on TPU)
            self.lora = jax.tree_util.tree_map(
                lambda x: x.astype(jnp.float32), self.base_params_learner
            )
            # the donating train step deletes self.lora's buffers each step;
            # keeping base_params* pointing anywhere would leave stale
            # references whose reads fail far from the cause — full mode has
            # no frozen base, make that explicit
            self.base_params = None
            self.base_params_learner = None
        else:
            self.lora = init_lora_params(
                lora_key, model_cfg, config.max_lora_rank,
                dtype=jnp.float32,  # adapters train in f32; base stays bf16
            )
        self._full = config.full_finetune
        self.optimizer = make_optimizer(config.lr, use_8bit=config.optimizer_8bit)
        self.opt_state = self.optimizer.init(self.lora)
        if meshes is not None:
            # adapter + optimizer state are LEARNER-mesh residents with
            # explicit shardings (FSDP sharding of learner state, SURVEY §2c)
            from distrl_llm_tpu.parallel.partition import shard_opt_state, shard_tree

            # shard_tree derives the right specs for either tree shape
            # (param_specs handles LoRA and full param trees alike)
            self.lora = shard_tree(self.lora, meshes.learner)
            self.opt_state = shard_opt_state(self.opt_state, meshes.learner)
        self.train_step = make_train_step(
            model_cfg,
            learner_type=config.learner,
            optimizer=self.optimizer,
            lora_scale=self.scale,
            micro_size=config.train_batch_size,
            skip_semantics=(
                "all_zero" if config.skip_all_zero_reward_batches else "any_zero"
            ),
            attn_impl=config.attn_impl,
            attn_mesh=meshes.learner if (
                config.attn_impl in ("ring", "ulysses") and meshes is not None
            ) else None,
            lora_dropout=config.lora_dropout,
            logit_chunk=config.logprob_chunk,
            train_mode="full" if self._full else "lora",
            clip_ratio=config.clip_ratio,
            kl_coeff=config.kl_coeff,
            # async trains on data up to max_staleness steps old — the
            # truncated-IS objective (AIPO) with per-token version-lag
            # masking replaces the near-on-policy 1±ε clip. The mask is
            # DROP-mode semantics (trim the stale tokens of admitted
            # mixed-version groups); under the downweight policy it must
            # stay off (0) — the fade deliberately trains beyond-K tokens
            # at reduced weight, and masking them would silently turn
            # downweight back into drop
            off_policy="aipo" if config.rollout_mode == "async" else "clip",
            is_cap=config.rollout_is_cap,
            max_staleness=(
                config.max_staleness
                if config.staleness_policy == "drop" else 0
            ),
            # training-dynamics bundle (ISSUE 16): computed inside the
            # jitted step and returned through the existing aux pytree —
            # it rides the one host fetch the loss already pays
            emit_dynamics=config.learn_obs,
        )

        self.total_batch_steps = 0
        self.total_samples_processed = 0
        self.episode = 0
        self.batch_in_episode = 0  # mid-episode resume cursor (SURVEY §5)
        self.weight_version = 0  # incremented per optimizer step
        self._rollout_weight_version = -1  # version resident on the rollout mesh
        # (role, bucket, rows, n) executables seen — cold ones are exempt
        # from the generation hang detector (compile is slow, not hung)
        self._warm_engine_keys: set[tuple] = set()

        self._last_hf_export_step = -1
        if config.export_hf_snapshots and not config.run_name:
            log.warning(
                "export_hf_snapshots is set but run_name is not — no "
                "snapshots will be written (run_dir is derived from run_name)"
            )

        self.profiler = None
        if config.profile_dir:
            from distrl_llm_tpu.metrics import TraceProfiler

            self.profiler = TraceProfiler(
                config.profile_dir,
                start_step=config.profile_start_step,
                num_steps=config.profile_num_steps,
            )

        # span tracing (telemetry.py): enabled here so directly-driven
        # rounds (tests, tools) record too, not just train(); the trace is
        # exported when the trace_steps window closes or at shutdown
        self._trace_steps_done = 0
        if config.trace_dir:
            telemetry.configure(enabled=True)
        # MFU denominator: one chip's peak FLOP/s, when the hardware is
        # known (telemetry table / DISTRL_PEAK_FLOPS); None suppresses the
        # engine/mfu series rather than publishing a made-up number.
        # decode_tok_s is WHOLE-ENGINE throughput, so MFU divides it by the
        # rollout chip count first (bench.py:learner does the same) —
        # otherwise an 8-chip mesh reports ~8× the true utilisation
        self._peak_flops = telemetry.device_peak_flops()
        self._rollout_chips = (
            int(meshes.rollout.devices.size) if meshes is not None else 1
        )

        # continuous observability plane (distrl_llm_tpu/obs.py, ISSUE 8):
        # live metrics endpoint + fleet aggregation (remote rollout), HBM
        # sampling at phase boundaries, and the anomaly sentinel / flight
        # recorder. None unless a flag armed it — the step loop then pays
        # exactly one attribute check.
        self.obs: Any = None
        if (
            config.metrics_port is not None
            or config.sentinel
            or config.flight_recorder_dir
        ):
            self.obs = obs_mod.ObsPlane(
                metrics_port=config.metrics_port,
                sentinel=config.sentinel,
                flight_recorder_dir=config.flight_recorder_dir,
                ring_size=config.obs_ring_size,
                # fleet aggregation needs the control plane: local-engine
                # runs expose their own registry, nothing to aggregate
                driver=(
                    getattr(engine, "driver", None)
                    if getattr(engine, "is_remote", False) else None
                ),
                profiler=self.profiler,
                staleness_limit=(
                    config.max_staleness
                    if config.rollout_mode == "async" else None
                ),
                # serving SLO gates (ISSUE 13): arm the ttft_blowup /
                # queue_wait_blowup sentinel triggers
                slo_ttft_ms=config.slo_ttft_ms,
                slo_queue_wait_ms=config.slo_queue_wait_ms,
                # training-dynamics gates (ISSUE 16): arm the
                # entropy_collapse / kl_blowup / ratio_saturation /
                # grad_spike triggers over the learn/* bundle
                learn_entropy_floor=config.learn_entropy_floor,
                learn_kl_limit=config.learn_kl_limit,
                learn_ratio_sat_frac=config.learn_ratio_sat_frac,
                learn_grad_spike=config.learn_grad_spike,
                config_snapshot=config.to_flat_dict(),
                plan_provider=lambda: (
                    self.engine.resolved_plan.plan.to_dict()
                    if getattr(self.engine, "resolved_plan", None) else None
                ),
            )

        # trajectory lineage ledger (distrl_llm_tpu/lineage.py, ISSUE 10):
        # per-group causal records (sampling worker + dispatch_id → buffer
        # → staleness verdict → consuming optimizer step → produced weight
        # version) and the derived policy-lag histograms. None unless
        # --lineage armed it; every hook below is one attribute check.
        self.lineage: Any = None
        if config.lineage:
            from distrl_llm_tpu.lineage import LineageLedger

            self.lineage = LineageLedger(
                ring_size=config.lineage_ring, out_dir=config.lineage_dir
            )
            bus = getattr(engine, "bus", None)
            if bus is not None:
                # the policy-lag loop closes at the LAST WORKER ACK of the
                # produced version (PR 9's broadcast), not the local push
                self.lineage.expect_acks = True
                bus.on_broadcast = self.lineage.on_broadcast_complete

        # training-dynamics ledger (distrl_llm_tpu/learn_obs.py, ISSUE 16):
        # host half of the device-fused bundle the armed train step returns
        # — publishes learn/* registry series, tracks reward drift, streams
        # the per-step JSONL. None unless --learn_obs armed it; the step
        # loop's hook is one attribute check when off.
        self.learn: Any = None
        self._last_dynamics: Any = None
        if config.learn_obs:
            from distrl_llm_tpu.learn_obs import LearnLedger

            self.learn = LearnLedger(
                out_dir=config.learn_dir,
                drift_window=config.learn_drift_window,
            )

        # request-level serving ledger (distrl_llm_tpu/serving_obs.py,
        # ISSUE 13): per-group lifecycle + admission audit recorded by the
        # paged engine's refill/continuous loops. None unless
        # --serving_obs armed it; the engine then pays one attribute
        # check per hook site. Config validation guarantees a local paged
        # continuous-batching engine here (fleet runs arm worker-side).
        self.serving: Any = None
        if config.serving_obs:
            from distrl_llm_tpu.serving_obs import ServingLedger

            self.serving = ServingLedger(
                ring_size=config.serving_ring, out_dir=config.serving_dir
            )
            if hasattr(engine, "serving_ledger"):
                engine.serving_ledger = self.serving
            else:
                log.warning(
                    "serving_obs armed but engine %s has no "
                    "serving_ledger hook — nothing will be recorded "
                    "(remote fleets arm worker_main --serving-obs)",
                    type(engine).__name__,
                )

        # self-healing control runtime (distrl_llm_tpu/control/, ISSUE 14):
        # bounded governors acting on the signals the obs plane measures.
        # None unless a --control flag armed one; a run with controllers
        # off is byte-identical to HEAD (the engine hook is a None check).
        self.control: Any = None
        if config.armed_controllers():
            from distrl_llm_tpu.control import build_runtime, injected_nan_step

            self.control = build_runtime(
                config,
                engine=engine,
                recorder=(
                    self.obs.recorder if self.obs is not None else None
                ),
                driver=(
                    getattr(engine, "driver", None)
                    if getattr(engine, "is_remote", False) else None
                ),
                fleet_provider=(
                    self.obs.fleet.refresh
                    if self.obs is not None and self.obs.fleet is not None
                    else None
                ),
                # elastic fleet (ISSUE 20): the launcher attaches the
                # FleetSupervisor to the remote engine; the autoscaling
                # governor actuates the pool through it
                fleet_supervisor=getattr(engine, "fleet_supervisor", None),
            )
            if (
                self.control is not None and self.obs is not None
                and self.obs.sentinel is not None
            ):
                # trigger → action escalation: a fired sentinel trigger
                # reaches its governor exactly once; triggers without a
                # registered governor stay dump-only (the PR 8 contract)
                self.obs.sentinel.on_trigger = self.control.on_trigger
        # seeded chaos hook for the rollback gate (control_smoke): poison
        # the REALIZED loss at the named step — honored only with the
        # rollback controller armed, so the env can never corrupt a
        # controller-less run
        self._inject_nan_step = (
            injected_nan_step()
            if self.control is not None and self.control.nan is not None
            else None
        )

        self.ckpt: CheckpointManager | None = None
        if config.checkpoint_dir:
            self.ckpt = CheckpointManager(config.checkpoint_dir)
            if config.resume:
                self._try_resume()
        self._push_weights()
        if self.control is not None and self.control.nan is not None:
            # the pre-step state is the first "last good" snapshot: a nan
            # on the very first optimizer step rolls back to initialization
            self.control.nan.note_good(
                self.weight_version, self.lora, self.opt_state
            )

    # ------------------------------------------------------------------ setup

    @classmethod
    def from_pretrained(
        cls,
        train_dataset,
        test_dataset,
        reward_function: RewardFn,
        config: TrainConfig,
        *,
        checkpoint_path: str | None = None,
        tokenizer=None,
        sink: MetricsSink | None = None,
    ) -> "Trainer":
        """Assemble the real thing: tokenizer + HF weights + sharded engine.

        ``checkpoint_path`` is a local HF checkpoint directory; when None the
        model id must resolve to a local path. (The reference's from_pretrained
        pulls from the hub — distributed_actor.py:58; this environment has no
        egress, so weights must be on disk.) Pass ``tokenizer`` if the caller
        already loaded it (the CLI does, for dataset templating).
        """
        from distrl_llm_tpu.engine.engine import GenerationEngine
        from distrl_llm_tpu.engine.paged_engine import PagedGenerationEngine
        from distrl_llm_tpu.models.loading import load_pretrained
        from distrl_llm_tpu.parallel.partition import param_specs, shard_tree
        from distrl_llm_tpu.tokenizer import load_tokenizer

        path = checkpoint_path or config.model
        if tokenizer is None:
            tokenizer = load_tokenizer(path)
        meshes = build_role_meshes(config.mesh)
        params, model_cfg = load_pretrained(path, dtype=np.dtype(config.dtype))
        bits = quant_bits_for(config.base_quant)
        if bits is not None:
            # N4 equivalent of the reference's 4-bit base (LOAD_IN_4BIT,
            # distributed_actor.py:17): quantize the frozen projections before
            # sharding so shards ship at int width
            params = quantize_params(
                params, bits=bits,
                group_size=config.quant_group_size or default_group_size(bits),
            )
        specs = param_specs(params)
        eos = [tokenizer.eos_token_id]
        extra_eos = getattr(tokenizer, "eos_token_ids", None)
        if extra_eos:
            eos = sorted(set(eos) | set(extra_eos))
        if config.rollout_workers:
            # generation runs in worker processes: the local mesh serves the
            # LEARNER only — no rollout-mesh base copy, no per-step adapter
            # push (the adapter ships over the wire instead)
            from distrl_llm_tpu.distributed import connect_remote_engine

            params_learner = shard_tree(params, meshes.learner, specs)
            params_rollout = params_learner
            if config.number_of_actors > 0 and not meshes.timeshared:
                log.warning(
                    "rollout_workers is set but number_of_actors=%d local "
                    "chips are carved for a rollout mesh that never "
                    "generates; consider --number_of_actors 0",
                    config.number_of_actors,
                )
            addresses = []
            for spec in config.rollout_workers:
                host, _, port = spec.rpartition(":")
                addresses.append((host or "127.0.0.1", int(port)))
            from distrl_llm_tpu.distributed.resilience import RetryPolicy

            engine = connect_remote_engine(
                addresses,
                max_prompt_tokens=config.max_prompt_tokens,
                max_new_tokens=config.max_new_tokens,
                # generation_timeout_s <= 0 means "hang detector disabled";
                # the control plane still needs SOME deadline — use a day
                timeout_ms=(
                    int(config.generation_timeout_s * 1000)
                    if config.generation_timeout_s > 0 else 86_400_000
                ),
                lora_scale=lora_scale(config.max_lora_rank, config.lora_alpha),
                eos_token_ids=eos,
                # control-plane resilience (distributed/resilience.py):
                # seeded per-run so retry/reconnect backoff replays
                retry_policy=RetryPolicy(
                    max_call_retries=config.rpc_retries,
                    base_s=config.rpc_backoff_s,
                    seed=config.seed,
                ),
                poison_threshold=config.poison_shard_k,
                rejoin=config.worker_rejoin,
                degrade_on_shard_failure=config.degrade_on_poison,
                # versioned weight bus (ISSUE 9): broadcast = one delta
                # push per optimizer step, dispatches carry only a
                # version reference; dispatch = legacy weights-in-request
                weight_bus=config.weight_bus,
            )
            if "autoscale" in config.armed_controllers():
                # elastic fleet (ISSUE 20): the supervisor adopts the
                # connected workers (it can drain-retire them but not
                # respawn them) and spawns OWNED workers for any scale-up
                # past this set; the autoscaling governor finds it through
                # engine.fleet_supervisor at build_runtime time
                from distrl_llm_tpu.distributed.fleet import (
                    FleetSupervisor, spec_from_config,
                )

                supervisor = FleetSupervisor(
                    spec_from_config(config),
                    min_workers=config.fleet_min,
                    max_workers=config.fleet_max,
                )
                supervisor.adopt(addresses)
                supervisor.attach(engine)
        else:
            if config.full_finetune and not meshes.timeshared:
                # full mode never reads a frozen base on the rollout mesh —
                # _push_weights places the TRAINED tree there each step, so a
                # resident base copy would just double rollout-mesh HBM in
                # exactly the memory-tight config
                params_learner = shard_tree(params, meshes.learner, specs)
                params_rollout = None
            else:
                params_rollout = shard_tree(params, meshes.rollout, specs)
                # non-timeshared roles each hold the frozen base (the
                # reference loads the model once per worker,
                # distributed_actor.py:58); timeshared roles alias one copy
                params_learner = (
                    params_rollout if meshes.timeshared
                    else shard_tree(params, meshes.learner, specs)
                )
            engine_cls = (
                PagedGenerationEngine if config.engine_impl == "paged"
                else GenerationEngine
            )
            engine_kwargs = engine_kwargs_from_config(config)
            if config.engine_impl == "paged_sharded":
                # one paged engine, page pool partitioned over the rollout
                # mesh's dp axis (engine/sharded_paged.py)
                from distrl_llm_tpu.engine.sharded_paged import ShardedPagedEngine

                engine_cls = partial(ShardedPagedEngine, mesh=meshes.rollout)
            if config.engine_impl == "paged":
                # --actor_gpu_usage → KV page budget (the reference's vLLM
                # gpu_memory_utilization contract, train_distributed.py:34-35)
                from distrl_llm_tpu.engine.budget import kv_pool_pages, tree_bytes
                from distrl_llm_tpu.ops.paged import DEFAULT_PAGE_SIZE

                # timeshared roles = the reference's LEARNER GPU (training
                # state shares the chip with the engine → the 0.35 fraction);
                # disjoint rollout meshes = its ACTOR GPUs (0.91)
                usage = (
                    config.learner_gpu_usage if meshes.timeshared
                    else config.actor_gpu_usage
                )
                engine_kwargs["max_kv_pages"] = kv_pool_pages(
                    model_cfg,
                    gpu_usage=usage,
                    param_bytes=tree_bytes(params),
                    batch_prompts=config.batch_size,
                    max_prompt_tokens=config.max_prompt_tokens,
                    max_new_tokens=config.max_new_tokens,
                    page_size=DEFAULT_PAGE_SIZE,
                    # pool sizing sees only the EXPLICIT format (the
                    # spec_draft convention): a plan-DB entry resolving
                    # int8 KV at engine construction leaves the pool sized
                    # for the larger bf16 pages — slack, never an OOM
                    kv_quant=config.kv_cache_quant or "none",
                    # pool sizing sees only the EXPLICIT draft length; a
                    # plan-DB entry that enables speculation (spec_draft
                    # None) isn't resolved until engine construction, so
                    # its ≤d extra resident tokens/row ride the pool's
                    # refill-admission slack instead
                    spec_draft=(
                        (config.spec_draft or 0)
                        if config.continuous_batching else 0
                    ),
                    # continuous admission allocates prompt chains FROM the
                    # pool (no static region to subtract); only the
                    # EXPLICIT config flag is visible here — a plan-DB
                    # entry resolving continuous at engine construction
                    # surfaces as the engine's pool-floor error, naming
                    # the pin to set
                    continuous=config.continuous_admission,
                    # only the EXPLICIT flag bumps the floor (same rule):
                    # a plan-resolved cache rides the refill slack instead
                    prefix_cache=bool(config.prefix_cache),
                )
            engine = engine_cls(
                model_cfg,
                max_prompt_tokens=config.max_prompt_tokens,
                max_new_tokens=config.max_new_tokens,
                eos_token_ids=eos,
                pad_token_id=(
                    tokenizer.pad_token_id
                    if tokenizer.pad_token_id is not None
                    else tokenizer.eos_token_id
                ),
                lora_scale=lora_scale(config.max_lora_rank, config.lora_alpha),
                attn_impl=config.attn_impl,
                prompt_buckets=config.prompt_buckets or None,
                **engine_kwargs,
            )
        return cls(
            train_dataset, test_dataset, reward_function, config,
            tokenizer=tokenizer, engine=engine, base_params=params_rollout,
            base_params_learner=params_learner,
            model_cfg=model_cfg, meshes=meshes, sink=sink,
        )

    # ------------------------------------------------------------- checkpoint

    def _state_tree(self) -> dict:
        return {
            "lora": self.lora,
            "opt_state": self.opt_state,
            "step": jnp.asarray(self.total_batch_steps),
            "episode": jnp.asarray(self.episode),
            "batch_in_episode": jnp.asarray(self.batch_in_episode),
            "samples": jnp.asarray(self.total_samples_processed),
            "rng": self._rng,
        }

    def _try_resume(self) -> None:
        assert self.ckpt is not None
        restored = self.ckpt.restore(self._state_tree())
        if restored is None:
            return
        self.lora = restored["lora"]
        self.opt_state = restored["opt_state"]
        from distrl_llm_tpu.learner.optim import check_state_format

        check_state_format(self.opt_state)
        if self.meshes is not None:
            from distrl_llm_tpu.parallel.partition import shard_opt_state, shard_tree

            self.lora = shard_tree(self.lora, self.meshes.learner)
            self.opt_state = shard_opt_state(self.opt_state, self.meshes.learner)
        self.total_batch_steps = int(restored["step"])
        self.episode = int(restored["episode"])
        self.batch_in_episode = int(restored.get("batch_in_episode", 0))
        self.total_samples_processed = int(restored["samples"])
        self._rng = restored["rng"]
        self.weight_version = self.total_batch_steps
        if self.config.rollout_mode == "async":
            from distrl_llm_tpu.checkpoint import load_rollout_state

            # buffered-but-unconsumed trajectories + producer cursor;
            # absent/corrupt sidecar degrades to a fresh buffer
            self._resume_rollout_state = load_rollout_state(
                self.config.checkpoint_dir, self.total_batch_steps
            )
        log.info(
            "resumed from step %d (episode %d, batch %d)",
            self.total_batch_steps, self.episode, self.batch_in_episode,
        )

    def save_checkpoint(self) -> None:
        if self.ckpt is None:
            return
        self.ckpt.save(self.total_batch_steps, self._state_tree())
        buffer = getattr(self, "_rollout_buffer", None)
        if buffer is not None:
            # async regime: the in-flight state (queued trajectories + the
            # producer's episode/batch cursor) rides as a pickle sidecar
            # keyed by the same step, so resume neither loses nor
            # re-generates buffered data
            from distrl_llm_tpu.checkpoint import save_rollout_state

            service = getattr(self, "_rollout_service", None)
            # cursor BEFORE the buffer snapshot: if the producer lands a
            # round between the two reads, the stale cursor re-produces
            # that batch on resume (benign duplicates); the other order
            # could pair a pre-put snapshot with an advanced cursor and
            # LOSE the round's tail
            cursor = service.cursor if service is not None else None
            policy = getattr(self, "_staleness_policy", None)
            save_rollout_state(
                self.config.checkpoint_dir, self.total_batch_steps, {
                    "buffer": buffer.state_dict(),
                    "cursor": cursor,
                    # admission counters ride along so the cumulative
                    # rollout_dropped_stale series never goes BACKWARDS
                    # across a resume (dashboards join on it)
                    "policy_dropped": policy.dropped if policy else 0,
                    "policy_admitted": policy.admitted if policy else 0,
                },
            )

    def export_hf_snapshot(self) -> None:
        """The reference's ``save_pretrained`` artifact: an HF-format
        checkpoint of the MERGED model at run_dir/model_{step}
        (distributed_trainer.py:372–380). On multi-process runs every process
        joins a ``multihost_utils.process_allgather`` pass (each host's
        shards may be non-addressable elsewhere, so the gather is a
        collective all processes MUST enter), then process 0 alone writes —
        write-race-free and byte-identical to the single-host artifact."""
        if self.total_batch_steps == self._last_hf_export_step:
            return  # episode end landing on a save_every step: already written
        from distrl_llm_tpu.models.loading import save_hf_checkpoint

        trained, base = self.lora, None if self._full else self.base_params_learner
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            def gather(tree):
                return jax.tree_util.tree_map(
                    lambda x: (
                        multihost_utils.process_allgather(x, tiled=True)
                        if isinstance(x, jax.Array) else np.asarray(x)
                    ),
                    tree,
                )

            trained = gather(trained)
            base = gather(base) if base is not None else None
            if jax.process_index() != 0:
                self._last_hf_export_step = self.total_batch_steps
                return
        path = os.path.join(
            self.config.run_directory, f"model_{self.total_batch_steps}"
        )
        try:
            if self._full:
                save_hf_checkpoint(
                    trained, self.model_cfg, path,
                    model_type=self.model_cfg.model_type,
                )
            else:
                save_hf_checkpoint(
                    base, self.model_cfg, path,
                    lora=trained, lora_alpha=self.config.lora_alpha,
                    model_type=self.model_cfg.model_type,
                )
            self._last_hf_export_step = self.total_batch_steps
        except (NotImplementedError, RuntimeError) as e:  # quantized base /
            # non-addressable shards: skip rather than kill the run
            log.warning("HF snapshot skipped: %s", e)

    def save_adapter(self) -> None:
        """The reference's per-step adapter artifact (distributed_trainer.py:346
        → save_lora). Export-only here — weight sync is in-memory."""
        if self._full:
            raise RuntimeError("full_finetune has no LoRA adapter to export")
        save_adapter_file(
            self.lora, self.config.lora_save_path,
            rank=self.config.max_lora_rank, alpha=self.config.lora_alpha,
            model_name=self.config.model,
        )

    # ------------------------------------------------------------ weight sync

    def _push_weights(self) -> None:
        """Learner→rollout weight sync: a device-to-device transfer of the LoRA
        pytree onto the rollout submesh, replacing the reference's adapter-file
        bus (save_lora distributed_actor.py:85 / load_lora :150). Records the
        version now resident on the rollout mesh; ``_generate_round`` asserts
        it before sampling."""
        pushed = self.lora
        if self._full:
            # master weights train in f32; rollout samples at the base dtype
            pushed = jax.tree_util.tree_map(
                lambda x: x.astype(self._rollout_dtype), pushed
            )
        if self.config.async_rollout:
            # the train step DONATES self.lora's buffers; in the overlap
            # window the next batch's generation still reads the pushed tree,
            # so it must own its buffers (same-device/same-dtype paths would
            # otherwise alias the donated arrays → "buffer deleted" crashes)
            pushed = jax.tree_util.tree_map(jnp.copy, pushed)
        if getattr(self.engine, "is_remote", False):
            # remote rollout: the adapter ships over the wire — either once
            # per version on the broadcast bus (below) or inside each
            # round's dispatch payloads — no local rollout-mesh copy
            self._lora_rollout = pushed
            if getattr(self.engine, "bus", None) is not None:
                # versioned weight bus (ISSUE 9): ONE asynchronous push per
                # optimizer step; subsequent dispatches reference it as
                # {weight_version} and mid-round swaps ride the same push
                # when inflight_weight_updates is on
                self.engine.push_lora(pushed, version=self.weight_version)
        elif self.meshes is not None and not self.meshes.timeshared:
            from distrl_llm_tpu.parallel.partition import shard_tree

            self._lora_rollout = shard_tree(pushed, self.meshes.rollout)
        else:
            self._lora_rollout = pushed
        self._rollout_weight_version = self.weight_version
        if self._gateway_service is not None:
            # the gateway serves the freshest pushed policy: attribute
            # swap only — a round already being formed finishes on the
            # previous tree (one-round staleness, same as rollout)
            gw_params, gw_lora = self._engine_params("rollout")
            self._gateway_service.params = gw_params
            self._gateway_service.lora = gw_lora
        if self.lineage is not None:
            # weight-version lineage: push time opens the learn-to-act
            # window; with a broadcast bus the policy-lag loop stays open
            # until on_broadcast_complete (the bus hook), locally it closes
            # here — the pushed tree IS resident when this returns
            self.lineage.on_push(self.weight_version)

    # ---------------------------------------------------------------- gateway

    def _start_gateway(self) -> None:
        """Serve the rollout engine over HTTP while training runs
        (ISSUE 19). The service forms class-ordered rounds between the
        trainer's own generation rounds — _engine_mutex serializes the
        two owners — and records into the already-attached serving
        ledger/control limits (it only overrides what it was given)."""
        cfg = self.config
        if cfg.gateway_port is None or self._gateway_service is not None:
            return
        import threading as _threading

        from distrl_llm_tpu.gateway.scheduler import (
            parse_gateway_classes,
            parse_tenant_quota,
        )
        from distrl_llm_tpu.gateway.server import GatewayServer
        from distrl_llm_tpu.gateway.service import GatewayService

        self._engine_mutex = _threading.Lock()
        params, lora = self._engine_params("rollout")
        self._gateway_service = GatewayService(
            self.engine, params, self.tokenizer, lora=lora,
            classes=parse_gateway_classes(cfg.gateway_classes),
            quota=parse_tenant_quota(cfg.tenant_quota),
            max_groups_per_round=max(1, cfg.max_concurrent_sequences or 8),
            seed=cfg.seed,
            engine_lock=self._engine_mutex,
        ).start()
        self._gateway_server = GatewayServer(
            self._gateway_service, port=cfg.gateway_port
        )
        log.info(
            "serving gateway listening on 127.0.0.1:%d (classes %s)",
            self._gateway_server.port, self._gateway_service.classes,
        )

    def _close_gateway(self) -> None:
        if self._gateway_server is not None:
            self._gateway_server.close()
            self._gateway_server = None
        if self._gateway_service is not None:
            self._gateway_service.close()
            self._gateway_service = None
        self._engine_mutex = None

    # ---------------------------------------------------------------- rollout

    def _next_rng(self) -> jax.Array:
        # async_rollout draws keys from the generation thread while the main
        # thread draws dropout keys — serialize the split
        with self._rng_mu:
            self._rng, key = jax.random.split(self._rng)
            return key

    def _dispatch_rollout(
        self, prompt_ids, prompt_mask, sampling: SamplingConfig, n_real: int
    ):
        """Run one generation round over every role's chips.

        Hybrid learner-generation (README.md:19; dispatch at
        distributed_trainer.py:194–197): with disjoint role submeshes, the
        batch splits by ``chunk_sizes`` — the actors' share decodes on the
        rollout mesh while the learners' ``learner_chunk_size`` share decodes
        CONCURRENTLY on the otherwise-idle learner mesh (two threads; JAX
        dispatches to disjoint devices in parallel). Timeshared roles, and
        partial batches whose real rows all fit the actor share (the padding
        rows at the tail would be the learners' only work), take the
        single-call path."""
        cfg = self.config
        hybrid = (
            not self.config.async_rollout  # learner mesh is busy updating
            and self.meshes is not None
            and not self.meshes.timeshared
            and cfg.number_of_actors > 0
            and cfg.learner_chunk_size > 0
            # a remote engine already fans out over worker processes; a
            # second local dispatch would double-generate the batch
            and not getattr(self.engine, "is_remote", False)
            # a mesh-bound engine (paged_sharded) compiles against the
            # rollout mesh; the learner share's params live on a different
            # device set — the whole batch decodes on the sharded engine
            and getattr(self.engine, "mesh", None) is None
            # a multi-turn env round must be ONE engine call: the turn
            # hook's candidate ids index the whole round's rows
            and self._env_driver is None
        )
        if hybrid:
            sizes = chunk_sizes(
                prompt_ids.shape[0], cfg.number_of_actors,
                cfg.number_of_learners, cfg.learner_chunk_size,
            )
            actor_rows = sum(sizes[: cfg.number_of_actors])
            if actor_rows >= n_real:
                hybrid = False  # learner share would be padding-only
        if not hybrid:
            return self._call_engine(
                *self._engine_params("rollout"),
                prompt_ids, prompt_mask, sampling, self._next_rng(),
                role="rollout",
            )

        from concurrent.futures import ThreadPoolExecutor

        key_a, key_l = self._next_rng(), self._next_rng()
        pool = ThreadPoolExecutor(max_workers=2)
        try:
            fut_a = pool.submit(
                self._call_engine, *self._engine_params("rollout"),
                prompt_ids[:actor_rows], prompt_mask[:actor_rows], sampling, key_a,
                role="rollout",
            )
            # the learner share samples with the learner-resident adapter —
            # definitionally the current version
            fut_l = pool.submit(
                self._call_engine, *self._engine_params("learner"),
                prompt_ids[actor_rows:], prompt_mask[actor_rows:], sampling, key_l,
                role="learner",
            )
            res_a, res_l = fut_a.result(), fut_l.result()
        finally:
            # never join a possibly-hung sibling here: a raised
            # EngineHangError must reach train()'s checkpoint handler
            pool.shutdown(wait=False)
        from distrl_llm_tpu.engine.engine import GenerationResult

        both_logps = res_a.logprobs is not None and res_l.logprobs is not None
        both_steps = (
            res_a.steps_dispatched is not None
            and res_l.steps_dispatched is not None
        )
        return GenerationResult(
            tokens=np.concatenate([res_a.tokens, res_l.tokens], axis=0),
            lengths=np.concatenate([res_a.lengths, res_l.lengths], axis=0),
            steps_dispatched=(
                res_a.steps_dispatched + res_l.steps_dispatched
                if both_steps else None
            ),
            alive_slot_steps=(
                res_a.alive_slot_steps + res_l.alive_slot_steps
                if res_a.alive_slot_steps is not None
                and res_l.alive_slot_steps is not None
                else None
            ),
            logprobs=(
                np.concatenate([res_a.logprobs, res_l.logprobs], axis=0)
                if both_logps else None
            ),
        )

    def _engine_params(self, role: str) -> tuple:
        """(params, lora) for an engine call. LoRA mode: frozen base + the
        role's adapter copy. Full-finetune mode: the trained tree IS the
        model — rollout uses the pushed copy, the learner its resident one."""
        if self.config.async_rollout:
            # during the pipeline overlap the trainable tree's buffers are
            # being donated by the concurrent train step — every role must
            # sample the pushed copy (one step stale by design)
            role = "rollout"
        if self._full:
            return (
                (self._lora_rollout, None) if role == "rollout"
                else (self.lora, None)
            )
        return (
            (self.base_params, self._lora_rollout) if role == "rollout"
            else (self.base_params_learner, self.lora)
        )

    def _call_engine(self, *args, role: str = "rollout"):
        """Engine call with the configured hang detector: the generation runs
        in a watchdog thread and exceeding ``generation_timeout_s`` raises
        ``EngineHangError`` (the reference's ray.get(timeout=240) equivalent,
        distributed_trainer.py:200). The hung device computation itself cannot
        be interrupted — like the reference, the recovery unit is the process
        (checkpoint + restart with resume=True).

        Cold executables are exempt: XLA specializes per (bucket, batch
        shape, placement), so warmness is tracked per (role, bucket, rows) —
        a first compile minutes long is slow, not hung."""
        timeout = self.config.generation_timeout_s
        warm_key = None
        if timeout > 0:
            ids, mask, sampling = args[2], args[3], args[4]
            bucket = (
                self.engine.bucket_for(mask)
                if hasattr(self.engine, "bucket_for") else 0
            )
            warm_key = (role, bucket, ids.shape[0], sampling.n)
            if warm_key not in self._warm_engine_keys:
                timeout = 0.0
        # an armed serving gateway shares this engine — the mutex
        # serializes trainer rounds against gateway rounds (absent a
        # gateway there is no mutex and nothing changes)
        from contextlib import nullcontext

        mutex = self._engine_mutex or nullcontext()
        if timeout <= 0:
            with mutex:
                result = self.engine.generate(*args)
            if warm_key is not None:
                self._warm_engine_keys.add(warm_key)
            return result

        import threading

        result: dict[str, Any] = {}

        def run() -> None:
            try:
                with mutex:
                    result["value"] = self.engine.generate(*args)
            except BaseException as e:  # noqa: BLE001 — re-raised on the caller
                result["error"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout)
        if t.is_alive():
            raise EngineHangError(
                f"generation round exceeded {timeout:.0f}s "
                f"(step {self.total_batch_steps}, weights v{self.weight_version})"
            )
        if "error" in result:
            raise result["error"]
        return result["value"]

    def _generate_round(
        self, batch: Mapping[str, Sequence[str]], sampling: SamplingConfig
    ) -> list[dict[str, Any]]:
        """One rollout round → candidate dicts shaped like the reference's
        ``vllm_generate`` output (distributed_actor.py:147–172): per task group,
        n candidate strings, the prompt/solution tiled ×n, token lengths.

        The whole round is one fixed-shape engine call: prompts padded to
        ``batch_size`` rows (masked rows discarded after) so jit compiles once;
        the batch shards over the rollout mesh's dp axis.
        """
        problems = list(batch["problem"])
        solutions = list(batch["solution"])
        b_real = len(problems)
        b_pad = self.config.batch_size
        prompt_ids, prompt_mask = encode_fixed(
            self.tokenizer, problems + [""] * (b_pad - b_real),
            self.config.max_prompt_tokens, side="left",
        )
        # race detector (SURVEY §5): the engine must only ever sample with the
        # adapter version the learner last published — the check the
        # reference's filesystem bus never had. The allowed lag derives from
        # the rollout regime (config.allowed_weight_lag): sync serializes
        # (0), pipelined deliberately samples one step stale (1), async is
        # bounded by the staleness policy (max_staleness); anything beyond
        # the mode's bound is still a bug.
        allowed_lag = self.config.allowed_weight_lag
        # read order matters on the overlapped modes' rollout thread: the
        # ROLLOUT-resident version is read FIRST, so a learner step landing
        # between the two reads surfaces as a benign positive lag — the
        # other order could read pre-step weight_version with post-push
        # rollout version and compute lag -1, crashing a healthy run
        rollout_version = self._rollout_weight_version
        lag = self.weight_version - rollout_version
        if not 0 <= lag <= allowed_lag:
            # lag < 0 (rollout AHEAD of the learner) is version-bookkeeping
            # corruption — e.g. a resume that restored an older learner state
            raise StaleWeightsError(
                f"rollout mesh holds adapter v{rollout_version} "
                f"but learner is at v{self.weight_version} — rollout_mode="
                f"{self.config.rollout_mode!r} allows lag <= {allowed_lag}; "
                "_push_weights() was not called after the last optimizer "
                "step, or the staleness bound is misconfigured"
            )
        # snapshot the mailbox BEFORE dispatch so this round's in-flight
        # swaps (and the versions pushed with them) can be sliced out after
        swaps_before = len(getattr(self.engine, "last_swap_steps", ()))
        base_version = self._rollout_weight_version
        env_round = None
        if self._env_driver is not None:
            # one env per candidate row (group-major, padding rows get
            # synthetic done episodes); the driver IS the engine turn hook
            # for the duration of this round
            self._env_driver.begin_round(
                problems + [""] * (b_pad - b_real),
                solutions + [""] * (b_pad - b_real),
                sampling.n,
            )
            self.engine.turn_hook = self._env_driver
        try:
            result = self._dispatch_rollout(
                prompt_ids, prompt_mask, sampling, b_real
            )
        finally:
            if self._env_driver is not None:
                self.engine.turn_hook = None
        if self._env_driver is not None:
            # score stragglers the engine finished without consulting the
            # hook (final blocking sweep) and assemble masks/rewards/turns
            width = result.tokens.shape[-1]
            env_round = self._env_driver.finish_round(
                np.asarray(result.tokens).reshape(-1, width),
                np.asarray(result.lengths).reshape(-1),
            )

        # degraded remote rounds (poison-shard quarantine with
        # degrade_on_poison): the engine zero-filled the quarantined
        # shards' rows and recorded them — DROP those prompts from the
        # round instead of training on fabricated zeros, with exact
        # conservation accounting (kept + lost == the real batch)
        lost = {
            int(r) for r in getattr(self.engine, "last_lost_rows", ()) or ()
        }
        kept_idx = [i for i in range(b_real) if i not in lost]
        lost_real = b_real - len(kept_idx)
        if lost_real:
            if not kept_idx:
                raise RuntimeError(
                    "every group in the round was lost to quarantined "
                    "shards — nothing survives to train on"
                )
            assert len(kept_idx) + lost_real == b_real  # conservation
            log.warning(
                "dropping %d/%d group(s) lost to quarantined shards",
                lost_real, b_real,
            )
        n = sampling.n
        answers, token_lengths = [], []
        for i in kept_idx:
            answers.append(decode_batch(self.tokenizer, result.tokens[i], result.lengths[i]))
            token_lengths.append([int(x) for x in result.lengths[i]])
        cand: dict[str, Any] = {
            "answers": answers,
            "problem": [[problems[i]] * n for i in kept_idx],
            "solution": [[solutions[i]] * n for i in kept_idx],
            "token_lengths": token_lengths,
        }
        # raw engine tokens + behavior logprobs (when the engine captures
        # them): the PPO-clip objective trains on THESE ids — retokenizing
        # decoded text (the reference's path) can shift token boundaries and
        # corrupt per-token importance ratios
        if result.logprobs is not None:
            cand["answer_tokens"] = [result.tokens[i] for i in kept_idx]
            cand["behavior_logps"] = [result.logprobs[i] for i in kept_idx]
            cand["gen_lengths"] = [result.lengths[i] for i in kept_idx]
            # per-token policy-version tags (rollout/trajectory.py): which
            # learner weight_version sampled each position. The round opens
            # at the rollout-resident version; every consumed in-flight swap
            # (push_lora) advances the tag from its recorded step on. A
            # swap pushed without a version (legacy callers) is inferred as
            # one optimizer step past its predecessor.
            from distrl_llm_tpu.rollout.trajectory import version_tags_for_round

            steps = list(getattr(self.engine, "last_swap_steps", ()))
            versions = list(getattr(self.engine, "last_swap_versions", ()))
            events: list[tuple[int, int]] = []
            inferred = base_version
            for k, step in enumerate(steps[swaps_before:]):
                v = (
                    versions[swaps_before + k]
                    if swaps_before + k < len(versions) else None
                )
                inferred = int(v) if v is not None else inferred + 1
                events.append((int(step), inferred))
            tags = version_tags_for_round(
                n, result.tokens.shape[2], base_version, events
            )
            cand["version_tags"] = [tags for _ in kept_idx]
            cand["base_version"] = base_version
            cand["swap_events"] = events
            if self.lineage is not None:
                # learn-to-act: this round sampled under its entry version
                # and every in-flight swap it consumed — the first round to
                # do so closes each version's push→act window (measured at
                # round completion: an upper bound, the engines log swap
                # steps, not wall times)
                now = time.time()
                self.lineage.note_first_sample(base_version, now)
                for _step, v in events:
                    self.lineage.note_first_sample(v, now)
        if env_round is not None:
            # env-routed rounds: per-group loss masks (1 on policy spans, 0
            # on injected observations), the env's own (n, 2) rewards (the
            # reward pass must NOT re-score — each turn was consumed live),
            # and per-turn provenance for lineage
            n_ = sampling.n
            cand["loss_mask"] = [
                env_round.loss_mask[i * n_:(i + 1) * n_] for i in kept_idx
            ]
            cand["rewards"] = [env_round.group_rewards[i] for i in kept_idx]
            cand["turns"] = [
                env_round.turn_provenance[i * n_:(i + 1) * n_]
                for i in kept_idx
            ]
            cand["env_name"] = self._env_driver.env_name
            cand["env_stats"] = env_round.stats
        # snapshot pool + round telemetry HERE, on the thread that ran the
        # round: with async_rollout the next round (or an eval) may
        # overwrite the engine's shared attributes before _train_batch
        # logs metrics
        pool = getattr(self.engine, "last_pool_stats", None)
        if pool:
            cand["pool_stats"] = dict(pool)
        rstats = getattr(self.engine, "last_round_stats", None)
        if rstats:
            cand["round_stats"] = dict(rstats)
        if self.lineage is not None:
            # sampling provenance per KEPT group: which worker + causal
            # dispatch_id sampled each prompt row (RemoteEngine records the
            # shard→row map; local engines have no dispatch, meta is None)
            cand["sampled_ts"] = time.time()
            shard_meta = getattr(self.engine, "last_shard_meta", None)
            row_meta: list[dict | None] = []
            for i in kept_idx:
                m = None
                for sm in shard_meta or ():
                    lo, hi = sm["rows"]
                    if lo <= i < hi:
                        m = {"worker": sm["worker"],
                             "dispatch_id": sm["dispatch_id"]}
                        break
                row_meta.append(m)
            cand["row_meta"] = row_meta
        return [cand]

    def _compute_round_rewards(self, candidates: list[dict[str, Any]]) -> None:
        """Per-task-group (n, 2) rewards (distributed_trainer.py:205–219),
        host-parallel via RewardComputer."""
        for cand in candidates:
            if "rewards" in cand:
                # env-scored round (ISSUE 17): each turn was rewarded as it
                # happened — re-scoring the decoded text would double-count
                # and lose the per-turn shaping
                continue
            groups = [
                (cand["answers"][j], cand["solution"][j])
                for j in range(len(cand["answers"]))
            ]
            cand["rewards"] = self.rewards(groups, reward_fn=self._reward_fn)

    def _generate_all_candidates(
        self, batch: Mapping[str, Sequence[str]], sampling: SamplingConfig | None = None
    ) -> list[dict[str, Any]]:
        sampling = sampling or self.config.train_sampling()
        candidates = self._generate_round(batch, sampling)
        self._compute_round_rewards(candidates)
        return candidates

    # ------------------------------------------------------------------ train

    def train(self) -> None:
        cfg = self.config
        if self.sink is None:
            self.sink = make_sink(
                cfg.metrics_backend,
                run_name=cfg.run_name,
                project=cfg.project_name,
                config=cfg.to_flat_dict(),
                run_dir=cfg.run_directory if cfg.run_name else ".",
            )
        if cfg.run_name:
            os.makedirs(cfg.run_directory, exist_ok=True)

        try:
            # serving gateway up BEFORE the first eval: "serve while
            # training" covers the whole loop, evals included
            self._start_gateway()
            # initial eval (distributed_trainer.py:241–242)
            self.evaluate()

            if cfg.rollout_mode == "async":
                # fully decoupled regime: RolloutService + trajectory
                # buffer + bounded-staleness learner loop
                self._train_async()
                return

            # self.episode is the next episode to START (end-of-episode saves
            # store episode+1, so a finished run resumes as a no-op).
            # ``batch_in_episode`` is the mid-episode cursor: the episode
            # shuffle is seeded by (config.seed, episode), so a resumed run
            # re-derives the same batch order and skips the batches already
            # trained instead of re-sampling them (SURVEY §5 checkpoint).
            start_episode = self.episode
            gen_pool = None
            if cfg.rollout_mode == "pipelined":
                from concurrent.futures import ThreadPoolExecutor

                gen_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="rollout"
                )
                self._gen_pool = gen_pool
            for episode in range(start_episode, cfg.episodes):
                self.episode = episode
                skip = self.batch_in_episode if episode == start_episode else 0

                # ONE-batch lookahead iterator, streamed — the sync path must
                # not materialize the episode (reference parity: it iterates),
                # and the async pipeline only ever needs the next batch.
                # Pipelined mode: batch t+1's generation is submitted BEFORE
                # batch t's update (LlamaRL/PipelineRL-style overlap), so it
                # samples with weights one step stale while the learner mesh
                # works; the pipeline stays within the episode (batch order
                # and the resume cursor are unchanged).
                stream = self._episode_batch_stream(episode, skip)
                pending = next(stream, None)
                gen_future = None
                if gen_pool is not None and pending is not None:
                    gen_future = gen_pool.submit(
                        self._generate_round, pending[1], cfg.train_sampling()
                    )
                while pending is not None:
                    bi, batch = pending
                    pending = next(stream, None)
                    if self.profiler is not None:
                        self.profiler.step_begin(self.total_batch_steps + 1)
                    next_future = None
                    if gen_pool is not None and pending is not None:
                        next_future = gen_pool.submit(
                            self._generate_round, pending[1], cfg.train_sampling()
                        )
                    self._train_batch(batch, episode, gen_future=gen_future)
                    gen_future = next_future
                    self.batch_in_episode = bi + 1
                    if cfg.eval_every and self.total_batch_steps % cfg.eval_every == 0:
                        if gen_future is not None:
                            # drain the in-flight next-batch generation first:
                            # running eval concurrently would hold two decode
                            # states/KV caches at once (HBM pressure on tight
                            # configs) and skew the eval timing numbers
                            concurrent.futures.wait([gen_future])
                        self.evaluate()
                    if cfg.save_every and self.total_batch_steps % cfg.save_every == 0:
                        self.save_checkpoint()
                        if cfg.export_hf_snapshots and cfg.run_name:
                            self.export_hf_snapshot()
                self.episode = episode + 1
                self.batch_in_episode = 0
                self.save_checkpoint()
                if cfg.export_hf_snapshots and cfg.run_name:
                    self.export_hf_snapshot()
        except EngineHangError:
            # last-gasp state capture so the documented restart path
            # (resume=True) continues from the final completed step
            log.exception("generation round hung; checkpointing before exit")
            self.save_checkpoint()
            raise
        finally:
            # gateway down first: its rounds must not race the teardown
            # of the ledger/lineage streams below
            self._close_gateway()
            service = getattr(self, "_rollout_service", None)
            if service is not None:
                # closes the buffer and stops after the round in flight;
                # never joins a possibly-hung generation (EngineHangError's
                # documented recovery is process restart)
                service.stop()
                self._rollout_service = None
            pool = getattr(self, "_gen_pool", None)
            if pool is not None:
                # never join a possibly-hung generation thread (a raised
                # EngineHangError's documented recovery is process restart),
                # and cancel any queued next-batch generation — letting it
                # start against a hung engine would wedge interpreter exit
                # (ThreadPoolExecutor threads are joined at atexit)
                pool.shutdown(wait=False, cancel_futures=True)
                self._gen_pool = None
            if self.profiler is not None:
                self.profiler.finish()
            # whole-run tracing (trace_steps=0) exports here; a closed
            # trace_steps window already wrote and disabled — no-op then
            self._export_trace()
            if self.lineage is not None:
                # flush unwritten weight-version lines and close the JSONL
                # stream; the ring (open records) stays queryable
                self.lineage.close()
            if self.serving is not None:
                # stream any open serving records plus the stall/occupancy
                # summary line, so serving.jsonl is report-complete
                self.serving.close()
            if self.learn is not None:
                # append the run-summary line so learn.jsonl is
                # report-complete for tools/learn_report.py
                self.learn.close()
            # the obs plane deliberately OUTLIVES train(): a fleet
            # operator scrapes the endpoint while rejoins/drains settle
            # after the loop ends — close_obs() (or process exit; the
            # server thread is a daemon) tears it down
            self.sink.finish()
            self.rewards.close()

    def close_obs(self) -> None:
        """Tear down the observability plane (endpoint + phase hook).
        Separate from train()'s cleanup: the endpoint stays scrapeable
        after the loop ends so post-run fleet state (late rejoins, drains)
        is observable; callers that own the trainer call this last."""
        if self.obs is not None:
            self.obs.close()
            self.obs = None

    def _episode_batch_stream(self, episode: int, skip: int):
        """One episode's (batch_index, batch) stream — the SINGLE owner of
        the per-episode shuffle seed and resume-skip semantics. Both the
        sync/pipelined loop and the async producer iterate this, so the
        regimes can never disagree on which batches exist or their order."""
        cfg = self.config
        dataset = self.train_dataset.shuffle(seed=cfg.seed + 1000 * episode)
        for bi, b in enumerate(dataset.iter(cfg.batch_size)):
            if bi >= skip:
                yield bi, b

    # ------------------------------------------------------------- async RL

    def _episode_batches(self, start_episode: int, start_batch: int):
        """(episode, batch_index, batch) stream in EXACTLY the sync loop's
        order (shared _episode_batch_stream) — the async regime changes
        when batches train, never which ones."""
        for episode in range(start_episode, self.config.episodes):
            skip = start_batch if episode == start_episode else 0
            for bi, b in self._episode_batch_stream(episode, skip):
                yield episode, bi, b

    def _train_async(self) -> None:
        """The fully decoupled regime (``--rollout_mode async``): a
        RolloutService thread generates continuously into a bounded
        TrajectoryBuffer while this loop pulls ``batch_size`` task groups
        per update on its own cadence (LlamaRL/Laminar decoupling;
        PipelineRL-style ``push_lora`` keeps the stream near-on-policy when
        ``inflight_weight_updates`` is on).

        Staleness control is layered: the buffer evicts queued groups
        already beyond ``max_staleness`` (cheap, before reward/update work),
        the StalenessPolicy drops or down-weights at admission, and the
        AIPO objective masks per-token by version lag. Every drop is
        counted, never silent."""
        cfg = self.config
        from distrl_llm_tpu.rollout import (
            RolloutService, StalenessPolicy, TrajectoryBuffer,
            round_to_trajectories, trajectories_to_candidates,
        )

        # capacity floor 2× the per-update pull: a get_batch(batch_size)
        # must always be satisfiable below the backpressure gate, or the
        # learner and a gated producer would deadlock against each other
        capacity = max(
            cfg.rollout_buffer_groups or 4 * cfg.batch_size,
            2 * cfg.batch_size,
        )
        buffer = TrajectoryBuffer(capacity, ledger=self.lineage)
        policy = StalenessPolicy(
            cfg.max_staleness, mode=cfg.staleness_policy,
            downweight=cfg.staleness_downweight, ledger=self.lineage,
        )
        self._rollout_buffer = buffer
        self._staleness_policy = policy
        self._rollout_dropped_stale = 0
        if self.control is not None:
            # staleness governor (ISSUE 14): its plant — the admission
            # policy and the buffer watermarks — exists only now; no-op
            # unless the controller is armed
            from distrl_llm_tpu.control import attach_staleness

            attach_staleness(self.control, cfg, policy, buffer)

        start_episode, start_batch = self.episode, self.batch_in_episode
        restored = getattr(self, "_resume_rollout_state", None)
        if restored:
            # unconsumed trajectories + the producer cursor from the
            # checkpoint sidecar: the run resumes without losing or
            # re-generating in-flight data
            buffer.load_state(restored.get("buffer", {}))
            cursor = restored.get("cursor")
            if cursor is not None:
                start_episode, start_batch = int(cursor[0]), int(cursor[1])
            policy.dropped = int(restored.get("policy_dropped", 0))
            policy.admitted = int(restored.get("policy_admitted", 0))
            self._rollout_dropped_stale = (
                buffer.dropped_stale + policy.dropped
            )

        def produce(episode: int, bi: int, batch) -> list:
            [cand] = self._generate_round(batch, cfg.train_sampling())
            trajs = round_to_trajectories(
                cand,
                base_version=cand.get(
                    "base_version", self._rollout_weight_version
                ),
                swap_events=cand.get("swap_events", ()),
                episode=episode, batch_index=bi,
            )
            if self.lineage is not None:
                # open one LineageRecord per group: sampling worker +
                # causal dispatch_id (remote rounds), weight-version
                # bounds, and the round-completion timestamp
                row_meta = cand.get("row_meta") or []
                ts = cand.get("sampled_ts")
                for j, traj in enumerate(trajs):
                    m = row_meta[j] if j < len(row_meta) else None
                    self.lineage.on_group_sampled(
                        traj,
                        worker=m.get("worker") if m else None,
                        dispatch_id=m.get("dispatch_id") if m else None,
                        ts=ts,
                    )
                    events = cand.get("swap_events")
                    if events:
                        self.lineage.note_swap_events(traj, events)
            return trajs

        from distrl_llm_tpu.distributed.resilience import RetryPolicy

        service = RolloutService(
            produce, buffer, self._episode_batches(start_episode, start_batch),
            # supervised restart budget (seeded backoff): transient produce
            # failures — a worker pool mid-rejoin, an RPC hiccup — retry in
            # place instead of closing the buffer and killing the regime
            max_restarts=cfg.producer_restarts,
            retry_policy=RetryPolicy(
                base_s=cfg.rpc_backoff_s, seed=cfg.seed
            ),
        )
        self._rollout_service = service
        service.start()
        while True:
            if self.profiler is not None:
                # the async loop gets the same step-window (and sentinel-
                # requested) capture hooks as the sync/pipelined loop
                self.profiler.step_begin(self.total_batch_steps + 1)
            timer = telemetry.PhaseSpans()
            if cfg.staleness_policy == "drop":
                # queued groups already beyond the bound will be rejected
                # at admission anyway — evict them first so the buffer
                # refills with usable data while this update runs. NOT in
                # downweight mode: there admission trains beyond-K groups
                # at reduced weight, so evicting them here would silently
                # turn downweight into drop. The EFFECTIVE bound is the
                # policy's (the staleness governor may have shrunk it —
                # identical to cfg.max_staleness with controllers off)
                buffer.evict_stale(
                    self.weight_version, policy.max_staleness
                )
            with timer("generation"):
                # honest accounting: the learner's BLOCKED time waiting on
                # the buffer (decoupling hides the rest of generation)
                groups = buffer.get_batch(cfg.batch_size)
            service.raise_if_failed()
            if not groups:
                break  # producer done and buffer drained
            kept, weights = policy.admit(groups, self.weight_version)
            self._rollout_dropped_stale = (
                buffer.dropped_stale + policy.dropped
            )
            if not kept:
                continue
            # (occupancy gauge: the buffer maintains rollout/buffer_occupancy
            # itself on every mutation — no second writer here)
            cand = trajectories_to_candidates(kept, weights)
            episode = kept[0].episode
            self.episode = episode
            # conservative resume cursor: re-derived from the producer at
            # save time (save_checkpoint stores the service cursor + buffer
            # snapshot; these counters only feed metrics/logs here)
            self.batch_in_episode = kept[-1].batch_index + 1
            self._update_on_candidates(
                [cand], episode, timer, n_samples=len(kept)
            )
            if self.lineage is not None:
                # the optimizer step that consumed these groups and the
                # weight version it produced (both just advanced inside
                # _update_on_candidates) — closes each record and opens
                # the produced version's policy-lag window
                from distrl_llm_tpu.learn_obs import lineage_dynamics

                self.lineage.on_consumed(
                    kept, step=self.total_batch_steps,
                    produced_version=self.weight_version,
                    # the consuming step's dynamics subset (ISSUE 16) —
                    # None unless learn_obs armed the device bundle
                    dynamics=lineage_dynamics(self._last_dynamics),
                )
            if cfg.eval_every and self.total_batch_steps % cfg.eval_every == 0:
                # evals need exclusive engine access (engines are not
                # re-entrant): pause at the next round boundary, resume after
                service.pause()
                try:
                    self.evaluate()
                finally:
                    service.resume()
            if cfg.save_every and self.total_batch_steps % cfg.save_every == 0:
                self.save_checkpoint()
                if cfg.export_hf_snapshots and cfg.run_name:
                    self.export_hf_snapshot()
        service.raise_if_failed()
        self.episode = cfg.episodes
        self.batch_in_episode = 0
        self.save_checkpoint()
        if cfg.export_hf_snapshots and cfg.run_name:
            self.export_hf_snapshot()

    def _train_batch(self, batch: Mapping[str, Sequence[str]], episode: int,
                     gen_future=None) -> None:
        cfg = self.config
        # spans + the reference's exact timing/*_duration metric names
        # (the PhaseTimer contract, now recorded on the driver trace track)
        timer = telemetry.PhaseSpans()

        with timer("generation"):
            # pipelined rollout hands in a future: timing/generation_duration
            # then honestly records the BLOCKED time (overlap hides the rest)
            if gen_future is not None:
                candidates = gen_future.result()
            else:
                candidates = self._generate_round(batch, cfg.train_sampling())
        self._update_on_candidates(
            candidates, episode, timer, n_samples=len(batch["problem"])
        )

    def _update_on_candidates(
        self, candidates: list[dict[str, Any]], episode: int,
        timer: "telemetry.PhaseSpans", n_samples: int,
    ) -> None:
        """Everything after generation: rewards, shaping, the optimizer
        step, weight push, and the metrics record. Shared verbatim by the
        sync/pipelined batch loop (candidates fresh from the round) and the
        async learner loop (candidates reassembled from buffered
        trajectories — rollout/trajectory.py)."""
        cfg = self.config
        with timer("reward"):
            self._compute_round_rewards(candidates)

        if cfg.print_samples and candidates and candidates[0]["answers"]:
            # sample dump parity (distributed_trainer.py:297–299)
            c = candidates[0]
            log.info("sample problem: %.200s", c["problem"][0][0])
            log.info("sample completion: %.400s", c["answers"][0][0])
            log.info("sample reward: %s", np.asarray(c["rewards"][0])[0])

        # policy-sharpening observability: mean rollout-time logprob of the
        # sampled tokens (only when the engine captures them — clip_ratio
        # runs); a steadily rising value = the policy concentrating
        extra_metrics: dict[str, float] = {}
        if candidates and "behavior_logps" in candidates[0]:
            tot, cnt = 0.0, 0
            for cand in candidates:
                for lp_g, len_g in zip(cand["behavior_logps"], cand["gen_lengths"]):
                    lp = np.asarray(lp_g)
                    ln = np.asarray(len_g)
                    m = np.arange(lp.shape[1])[None, :] < ln[:, None]
                    tot += float(lp[m].sum())
                    cnt += int(m.sum())
            if cnt:
                extra_metrics["mean_behavior_logprob"] = tot / cnt

        # shaping: baselines / GRPO group-norm advantages + metric collection
        # (distributed_trainer.py:262–279), then top-k (:281–294)
        stats = shape_rewards(candidates, cfg.learner)
        if cfg.topk < cfg.num_candidates:
            topk_filter(candidates, cfg.topk)

        with timer("update"):
            problems, answers, coeffs, raw = flatten_for_update(
                candidates, cfg.learner
            )
            if cfg.clip_ratio > 0.0 and raw is None:
                raise RuntimeError(
                    "clip_ratio requires engine-captured behavior logprobs; "
                    "this engine returned none (GenerationResult.logprobs)"
                )
            update = prepare_update_batch(
                self.tokenizer, problems, answers, coeffs,
                max_prompt_tokens=cfg.max_prompt_tokens,
                max_new_tokens=cfg.max_new_tokens,
                micro_size=cfg.train_batch_size,
                mesh=self.meshes.learner if self.meshes is not None else None,
                raw_rollout=raw if cfg.clip_ratio > 0.0 else None,
                answer_buckets=cfg.learner_len_buckets or None,
                prompt_buckets=cfg.learner_prompt_buckets or None,
                # async: per-token version lag (learner version − sampling
                # version tag) feeds the AIPO staleness mask; None keeps
                # the sync/pipelined batch pytree unchanged
                current_version=(
                    self.weight_version
                    if cfg.rollout_mode == "async" else None
                ),
            )
            # visibility: which widths this update compiled/ran at (equal
            # the max_* caps unless the learner buckets cut them)
            answer_width = int(update.answer_ids.shape[1])
            prompt_width = int(update.prompt_ids.shape[1])
            step_args = (
                self.lora, self.opt_state,
                None if self._full else self.base_params_learner, update,
                # adapter-input dropout (helper.py:40) needs a fresh key per
                # update; disabled (None) when the rate is 0
                self._next_rng() if cfg.lora_dropout > 0.0 else None,
            )
            if self.learn is not None:
                # training-dynamics bundle (ISSUE 16): the armed step
                # returns it through the aux pytree, and the loss fetch the
                # off path already pays is widened to carry it — still
                # exactly ONE host transfer per optimizer step
                self.lora, self.opt_state, loss_dev, dyn_dev = (
                    self.train_step(*step_args)
                )
                loss_host, self._last_dynamics = jax.device_get(
                    (loss_dev, dyn_dev)
                )
                loss = float(loss_host)
            else:
                self.lora, self.opt_state, loss = self.train_step(*step_args)
                loss = float(loss)
        if (
            self._inject_nan_step is not None
            and self.total_batch_steps + 1 == self._inject_nan_step
        ):
            # seeded chaos injection (ISSUE 14): the sentinel's env hook
            # fakes the METRIC; this one poisons the realized loss so the
            # rollback controller exercises its real path end-to-end
            loss = float("nan")
        # nan-loss rollback (ISSUE 14): a non-finite loss means the update
        # that just donated self.lora is poisoned — restore the last-good
        # (adapter, opt state, version) snapshot and skip the push, so the
        # run trains on from the last finite step instead of spreading
        # NaNs. The metrics record keeps the honest nan loss (the sentinel
        # still dumps its once-per-run incident bundle from it).
        rolled_back_to: int | None = None
        if (
            self.control is not None and self.control.nan is not None
            and not math.isfinite(loss)
        ):
            restored = self.control.nan.rollback(
                self.total_batch_steps + 1, self.control,
                bus=getattr(self.engine, "bus", None),
            )
            if restored is not None:
                self.lora, self.opt_state, rolled_back_to = restored
                if self.lineage is not None:
                    self.lineage.on_rollback(
                        step=self.total_batch_steps + 1,
                        restored_version=rolled_back_to,
                    )
        if rolled_back_to is not None:
            # the poisoned update never becomes a version — no bump — but
            # the restored tree must still be RE-PUSHED under the same
            # version: the previously pushed rollout copy can alias
            # buffers the poisoned train step just donated (sync mode
            # pushes self.lora by reference), and the weight bus's
            # idempotent per-(tree, version) push makes the re-broadcast
            # a no-op for workers that already hold it
            t_sync0 = time.perf_counter()
            self._push_weights()
        else:
            self.weight_version += 1
            t_sync0 = time.perf_counter()
            self._push_weights()
        if rolled_back_to is None and cfg.inflight_weight_updates:
            # PipelineRL-style: hand the fresh adapter to the generation
            # round still in flight on the rollout thread — engines swap at
            # their next decode dispatch (push_lora mailbox, or the remote
            # weight bus's MSG_WEIGHTS broadcast); the captured behavior
            # logprobs keep the clip objective honest about which policy
            # sampled each token. The version rides with the adapter so the
            # round in flight can tag every post-swap position with the
            # policy that sampled it (rollout/trajectory.py version tags).
            push = getattr(self.engine, "push_lora", None)
            if push is None:
                # construction-time validation rejects such engines; a
                # swapped-in engine must fail the same way, never no-op
                raise RuntimeError(
                    "inflight_weight_updates is on but the engine has no "
                    "push_lora — mid-round weight updates would silently "
                    "never happen"
                )
            push(self._lora_rollout, version=self.weight_version)
        if self.obs is not None and getattr(self.engine, "bus", None) is None:
            # weight-sync latency (learner→rollout push; the in-engine
            # push→swap half is the engine/swap_latency_ms histogram).
            # Broadcast-bus engines skip this: the bus sets the gauge from
            # push → LAST WORKER ACK, the honest end-to-end number
            telemetry.gauge_set(
                obs_mod.OBS_WEIGHT_SYNC_MS,
                (time.perf_counter() - t_sync0) * 1e3,
            )
        if (
            self.control is not None and self.control.nan is not None
            and rolled_back_to is None and math.isfinite(loss)
        ):
            # this step's state is the new last-good snapshot (taken after
            # the push, so the snapshot version is one every worker is
            # already being broadcast — a rollback never needs a resync)
            self.control.nan.note_good(
                self.weight_version, self.lora, self.opt_state
            )

        if cfg.write_adapter_file:
            self.save_adapter()

        self.total_batch_steps += 1
        self.total_samples_processed += n_samples
        metrics = {
            "loss": loss,
            "mean_accuracy_reward": float(np.mean(stats.mean_acc)),
            "min_accuracy_reward": float(np.mean(stats.min_acc)),
            "max_accuracy_reward": float(np.mean(stats.max_acc)),
            "mean_format_reward": float(np.mean(stats.mean_format)),
            "mean_token_length": float(np.mean(stats.mean_token_length)),
            "episode": episode,
            "total_batch_steps": self.total_batch_steps,
            "total_samples_processed": self.total_samples_processed,
            # rollout-regime provenance on every train-curve record (the
            # bench rows carry the same three fields — artifacts from
            # different regimes must be distinguishable from the JSONL
            # alone): the mode, the EFFECTIVE staleness bound (0 sync /
            # 1 pipelined / K async), and cumulative stale drops
            "rollout_mode": cfg.rollout_mode,
            "max_staleness": cfg.allowed_weight_lag,
            "rollout_dropped_stale": getattr(
                self, "_rollout_dropped_stale", 0
            ),
        }
        if rolled_back_to is not None:
            # which version the nan-loss rollback restored (the lineage
            # ledger carries the durable record; this is the sink's copy)
            metrics["control/rolled_back_to"] = rolled_back_to
        if cfg.learner_len_buckets:
            metrics["learner/answer_width"] = answer_width
        if cfg.learner_prompt_buckets:
            metrics["learner/prompt_width"] = prompt_width
        # budgeted-pool observability (vLLM's gpu_cache_usage-style
        # telemetry): page pressure + preemption count, snapshotted by
        # _generate_round on the thread that ran THIS round (reading the
        # engine attribute here would race async rollout / eval rounds).
        # A stat the engine didn't produce is SKIPPED, not logged as None
        # (a null metric poisons sink aggregations — ADVICE r5).
        pool = next(
            (c["pool_stats"] for c in candidates if "pool_stats" in c), None
        )
        if pool:
            for name, key in (
                ("pool/pages", "pool_pages"),
                ("pool/peak_pages_used", "peak_pages_used"),
                ("pool/preemptions", "preemptions"),
            ):
                if pool.get(key) is not None:
                    metrics[name] = pool[key]
        # env-routed rounds (ISSUE 17): per-round turn/tool telemetry the
        # driver assembled at finish_round; absent on the legacy path
        env_stats = next(
            (c["env_stats"] for c in candidates if "env_stats" in c), None
        )
        if env_stats is not None:
            metrics["env/turns_mean"] = env_stats.turns_mean
            metrics["env/turns_max"] = env_stats.turns_max
            metrics["env/step_ms_p50"] = env_stats.env_step_ms_p50
            metrics["env/round_tool_calls"] = env_stats.tool_calls
            metrics["env/round_resume_declined"] = env_stats.resume_declined
        elif any("turns" in c for c in candidates):
            # async-consumed env batches: the round-level stats object
            # stayed with the producer, but turn counts are derivable
            # from the provenance that rode the trajectories
            counts = _env_turn_counts(candidates)
            if counts:
                metrics["env/turns_mean"] = float(np.mean(counts))
                metrics["env/turns_max"] = int(np.max(counts))
        metrics.update(self._engine_metrics(candidates))
        metrics.update(extra_metrics)
        metrics.update(timer.metrics())
        if self.obs is not None:
            # learner idle fraction: the share of this step the learner
            # spent BLOCKED on data (generation phase = wait time in the
            # pipelined/async regimes) — the signal RLAX's fleet loop
            # steers on. Published before the snapshot merge below so it
            # rides the same sink record.
            phase_total = sum(
                timer.get(p) for p in ("generation", "reward", "update")
            )
            if phase_total > 0:
                telemetry.gauge_set(
                    obs_mod.OBS_LEARNER_IDLE,
                    timer.get("generation") / phase_total,
                )
        if self.learn is not None and self._last_dynamics is not None:
            # training-dynamics bundle (ISSUE 16): publish this step's
            # device-computed learn/* gauges + IS-ratio histogram BEFORE
            # the snapshot merge below, so the dynamics ride the same sink
            # record (wandb/jsonl curves) and the sentinel's metrics view
            self.learn.on_step(
                self.total_batch_steps, self._last_dynamics,
                reward_mean=metrics.get("mean_accuracy_reward"),
            )
        # registry series (pool/occupancy gauge, cp/rpc_* histograms, …)
        # ride the same sink record
        metrics.update(telemetry.metrics_snapshot())
        self.sink.log(metrics, step=self.total_batch_steps)
        if self.obs is not None:
            # ring record + sentinel pass + fleet refresh — the per-step
            # entry point of the observability plane
            self.obs.on_step(self.total_batch_steps, metrics)
        if self.control is not None:
            # governors read the same metrics record the sentinel just
            # checked (trigger escalations already ran inside on_step
            # above); actions land before the next generation round
            self.control.on_step(self.total_batch_steps, metrics)
        if cfg.trace_dir and telemetry.enabled():
            self._trace_steps_done += 1
            if cfg.trace_steps and self._trace_steps_done >= cfg.trace_steps:
                # window closed: write the trace now (a crashed run past the
                # window still has its file) and stop paying for recording
                self._export_trace()
                telemetry.configure(enabled=False)

    def _engine_metrics(self, candidates) -> dict[str, float]:
        """engine/prefill_tok_s, engine/decode_tok_s, engine/mfu from the
        round stats every engine records (engine.accumulate_round_stats);
        MFU uses the model's FLOPs/token (models/configs.py) at this
        round's realized mean context length."""
        stats = next(
            (c["round_stats"] for c in candidates if "round_stats" in c), None
        )
        if not stats:
            return {}
        out: dict[str, float] = {}
        decode_tok_s = None
        if stats["prefill_s"] > 0 and stats["prefill_tokens"]:
            out["engine/prefill_tok_s"] = (
                stats["prefill_tokens"] / stats["prefill_s"]
            )
        if stats["decode_s"] > 0 and stats["gen_tokens"]:
            decode_tok_s = stats["gen_tokens"] / stats["decode_s"]
            out["engine/decode_tok_s"] = decode_tok_s
        if (
            decode_tok_s is not None and self._peak_flops
            # remote rounds measure N workers' unknown chips against the
            # local peak — no honest per-chip number exists driver-side
            and not getattr(self.engine, "is_remote", False)
            # whole-round stats (sharded engine) fold prefill + compile
            # into decode_s: honest throughput, but not an MFU numerator
            and not stats.get("whole_round")
        ):
            mean_kv = (
                stats["prefill_tokens"] / max(stats["prompt_rows"], 1)
                + stats["gen_tokens"] / max(stats["gen_rows"], 1) / 2
            )
            out["engine/mfu"] = telemetry.mfu(
                decode_tok_s / self._rollout_chips,
                self.model_cfg.decode_flops_per_token(mean_kv),
                self._peak_flops,
            )
        return out

    def _export_trace(self) -> None:
        """Write the Chrome-trace/Perfetto JSON to trace_dir/trace.json with
        the metadata tools/trace_report.py needs for tok/s and MFU."""
        cfg = self.config
        if not cfg.trace_dir or not telemetry.enabled():
            return
        path = telemetry.export_chrome_trace(
            os.path.join(cfg.trace_dir, "trace.json"),
            metadata={
                "model": cfg.model,
                # static context estimate for report-side MFU: full prompt
                # window + half the generation window
                "decode_flops_per_token": self.model_cfg.decode_flops_per_token(
                    cfg.max_prompt_tokens + cfg.max_new_tokens / 2
                ),
                "peak_flops": self._peak_flops,
                # trace_report divides whole-engine tok/s by this before
                # comparing against the single-chip peak
                "chips": self._rollout_chips,
                # measured attribution (ISSUE 8): XLA cost_analysis of the
                # explicitly-compiled step programs + per-phase HBM
                # watermarks — the roofline section's inputs (both empty
                # on runs that recorded neither)
                "costs": obs_mod.costs(),
                "phase_hbm": obs_mod.phase_hbm(),
            },
        )
        log.info("telemetry trace written to %s", path)

    # ------------------------------------------------------------------- eval

    def evaluate(self) -> dict[str, float]:
        """Best-of-n eval (distributed_trainer.py:384–416): pass@1 = mean
        accuracy over candidates, BoN = max; same rollout path with eval
        sampling params."""
        cfg = self.config
        timer = telemetry.PhaseSpans()
        accs, bons, tok_lens = [], [], []
        with timer("eval"):
            for batch in self.test_dataset.iter(cfg.batch_size):
                candidates = self._generate_all_candidates(batch, cfg.eval_sampling())
                for cand in candidates:
                    for rewards, lengths in zip(cand["rewards"], cand["token_lengths"]):
                        acc = np.asarray(rewards)[:, 1]
                        accs.append(float(np.mean(acc)))
                        bons.append(float(np.max(acc)))
                        tok_lens.append(float(np.mean(lengths)))
        n = cfg.eval_n
        metrics = {
            f"eval/pass@1(mean{n})": float(np.mean(accs)),
            f"eval/BoN({n})": float(np.mean(bons)),
            "eval/mean_token_length": float(np.mean(tok_lens)),
            **timer.metrics(),
        }
        if self.sink is not None:
            self.sink.log(metrics, step=self.total_batch_steps)
        return metrics
