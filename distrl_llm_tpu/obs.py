"""Continuous observability plane (ISSUE 8): live metrics endpoint, fleet
aggregation, measured resource attribution, and an anomaly sentinel with a
flight recorder.

PR 1's telemetry is post-hoc — spans, counters, and Perfetto traces you read
after the run. The north star is a production service under heavy traffic,
and its two loudest facts (the ~2% MFU / ~25× roofline headroom, and the
multi-host elastic fleet of ROADMAP item 4) both demand *live*, *attributed*
telemetry. This module adds, on top of ``telemetry.py``'s registry:

* **Live export** — :class:`MetricsServer` serves the process's cumulative
  registry (``telemetry.observe_snapshot``) over HTTP as Prometheus text
  (``/metrics``) and a JSON snapshot (``/metrics.json``), from the driver
  and from every ``worker_main --metrics-port`` process.
* **Fleet aggregation** — workers piggyback their registry snapshot on
  control-plane RESULT frames (the same channel PR 1's span blobs ride);
  :class:`FleetAggregator` folds those per-worker snapshots plus the
  DriverClient's health/rejoin state into ``fleet/*`` series: aggregate
  tok/s, per-worker health, rejoin epoch — the fleet-level rows ROADMAP
  item 4 needs.
* **Measured attribution** — per-phase HBM watermarks sampled from
  ``jax.Device.memory_stats()`` at span boundaries (the PhaseSpans hook), a
  compile/retrace tracker keyed by jitted-fn × shape signature (silent
  retrace storms become a counter), and XLA ``cost_analysis()``-derived
  FLOPs/bytes per explicitly-compiled step program — all surfaced on the
  endpoint, in bench rows, and in ``tools/trace_report.py``'s roofline
  section.
* **Anomaly sentinel + flight recorder** — a bounded in-memory ring of
  recent step records; deterministic triggers (NaN/Inf loss, reward
  collapse, staleness blowup, tok/s regression vs a running EMA, HBM
  watermark breach) dump the ring + span tail + config/plan snapshot into a
  per-incident directory (and request a guarded ``TraceProfiler`` capture
  window), so the first production incident arrives with its own evidence.

Contract: same as PR 1 — near-zero cost when off. Nothing here runs unless
a flag arms it (``--metrics_port`` / ``--sentinel`` / ``--flight_recorder_
dir`` / worker ``--metrics-port`` / ``DISTRL_OBS=1``), and the only
always-on additions are counter bumps at compile sites (inherently slow
paths) and one counter per generation wave.
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import time
from collections import deque
from typing import Any, Callable, Mapping

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.learn_obs import (
    LEARN_CAP_FRAC,
    LEARN_CLIP_FRAC,
    LEARN_ENTROPY,
    LEARN_GRAD_NORM_TOTAL,
    LEARN_KL,
)
from distrl_llm_tpu.serving_obs import (
    FLEET_SERVING_QUEUE_WAIT_MAX_MS,
    FLEET_SERVING_TTFT_MAX_MS,
    SERVING_QUEUE_WAIT_MS,
    SERVING_TTFT_MS,
    fold_fleet_serving,
)

log = logging.getLogger(__name__)

# ------------------------------------------------------------- series names
# (pinned, with their types, in tests/test_telemetry.py)

OBS_GEN_TOKENS = "obs/gen_tokens"            # counter: engine-accounted tokens
OBS_HBM_LIVE = "obs/hbm_live_bytes"          # gauge: bytes_in_use at sample
OBS_HBM_PEAK = "obs/hbm_peak_bytes"          # gauge: device peak watermark
OBS_COMPILES = "obs/compiles"                # counter: tracked compile events
OBS_RETRACES = "obs/retraces"                # counter: compiles BEYOND the
#                                              first per (fn, signature) key
OBS_LEARNER_IDLE = "obs/learner_idle_frac"   # gauge: blocked-on-data share
OBS_WEIGHT_SYNC_MS = "obs/weight_sync_ms"    # gauge: push_weights latency
OBS_INCIDENTS = "obs/incidents"              # counter: flight-recorder dumps

FLEET_TOK_S = "fleet/tok_s"                  # gauge: aggregate worker tok/s
FLEET_GEN_TOKENS = "fleet/gen_tokens_total"  # gauge: cumulative worker tokens
FLEET_WORKERS_HEALTHY = "fleet/workers_healthy"  # gauge
FLEET_WORKERS_TOTAL = "fleet/workers_total"      # gauge
FLEET_REJOIN_EPOCH = "fleet/rejoin_epoch"        # gauge
# elastic fleet (ISSUE 20): the supervisor/autoscaler publish these — the
# constants live here with the rest of the fleet/* series (single-owner
# registry discipline, GC2xx), imported by distributed/fleet.py
FLEET_TARGET_WORKERS = "fleet/target_workers"    # gauge: autoscaler setpoint
FLEET_SCALE_EVENTS = "fleet/scale_events"        # counter: grow/shrink events

# engine-side LoraMailbox push→swap latency (engine/engine.py observes it)
SWAP_LATENCY_MS = "engine/swap_latency_ms"   # histogram


def _jsonable(v: Any) -> Any:
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        try:
            return float(v)
        except (TypeError, ValueError):
            return str(v)


# ------------------------------------------------------------ HBM sampling


def hbm_stats() -> dict[str, float] | None:
    """``memory_stats()`` of the first local device, or None when the
    backend exposes none (CPU hosts). ``DISTRL_OBS_FAKE_HBM`` (a JSON
    object) substitutes deterministic numbers for tests/smokes."""
    fake = os.environ.get("DISTRL_OBS_FAKE_HBM")
    if fake:
        try:
            stats = json.loads(fake)
            return dict(stats) if isinstance(stats, dict) else None
        except ValueError:
            return None
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — no backend at all
        return None
    if not stats:
        return None
    return {k: float(v) for k, v in stats.items()
            if isinstance(v, (int, float))}


_phase_mu = threading.Lock()
_phase_hbm: dict[str, dict[str, float]] = {}


def _on_phase(phase: str) -> None:
    """PhaseSpans-exit hook (installed by ObsPlane): sample device memory at
    the span boundary, publish live/peak gauges, and keep the per-phase
    high-watermark table the flight recorder and trace_report read."""
    stats = hbm_stats()
    if not stats:
        return
    live = float(stats.get("bytes_in_use", 0.0))
    peak = float(stats.get("peak_bytes_in_use", live) or live)
    telemetry.gauge_set(OBS_HBM_LIVE, live)
    telemetry.gauge_set(OBS_HBM_PEAK, peak)
    # per-phase series so trace_report can attribute the HBM budget: the
    # name set is bounded by the driver's phase vocabulary (4–5 names)
    telemetry.gauge_set(f"{OBS_HBM_PEAK}/{phase}", peak)
    with _phase_mu:
        w = _phase_hbm.setdefault(
            phase, {"live_max": 0.0, "peak_max": 0.0, "samples": 0}
        )
        w["live_max"] = max(w["live_max"], live)
        w["peak_max"] = max(w["peak_max"], peak)
        w["samples"] += 1


def phase_hbm() -> dict[str, dict[str, float]]:
    """Per-phase HBM high-watermark table accumulated by the phase hook."""
    with _phase_mu:
        return {k: dict(v) for k, v in _phase_hbm.items()}


# ------------------------------------------- compile / retrace / cost table

_compile_mu = threading.Lock()
_compile_counts: dict[tuple, int] = {}
_costs: dict[str, dict[str, float]] = {}


def note_compile(fn: str, signature: Any = ()) -> None:
    """Record one compile of ``fn`` at ``signature`` (a shape-ish key).
    First compile per key bumps ``obs/compiles``; every later compile of
    the SAME key additionally bumps ``obs/retraces`` — the silent-retrace-
    storm signal. Always on: compiles are inherently seconds-long, so the
    dict write is free by comparison."""
    try:
        key = (fn, signature if isinstance(signature, tuple)
               else tuple(signature) if isinstance(signature, list)
               else (signature,))
        hash(key)
    except TypeError:
        key = (fn, repr(signature))
    with _compile_mu:
        n = _compile_counts.get(key, 0) + 1
        _compile_counts[key] = n
    telemetry.counter_add(OBS_COMPILES)
    if n > 1:
        telemetry.counter_add(OBS_RETRACES)


def compile_counts() -> dict[tuple, int]:
    with _compile_mu:
        return dict(_compile_counts)


def compile_total() -> int:
    with _compile_mu:
        return sum(_compile_counts.values())


def retrace_total() -> int:
    """Compiles beyond the first per (fn, signature) key — 0 in a healthy
    run; anything else is a retrace storm in the making."""
    with _compile_mu:
        return sum(n - 1 for n in _compile_counts.values() if n > 1)


def reset_compile_tracker() -> None:
    """Scope the tracker to a run (bench clears it before warmup, tests
    between cases). Registry counters are NOT rewound — they are monotonic
    by contract."""
    with _compile_mu:
        _compile_counts.clear()
        _costs.clear()
    with _phase_mu:
        _phase_hbm.clear()


def record_cost(what: str, compiled) -> dict[str, float] | None:
    """Extract XLA ``cost_analysis()`` FLOPs/bytes from an explicitly
    compiled program (the AOT paths — ``compile_chunk_guarded`` — already
    hold one) and file it under ``what`` for the endpoint, bench rows, and
    the trace_report roofline section. Returns the entry, or None when the
    backend reports no analysis."""
    try:
        ca = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 — backend without cost analysis
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, Mapping):
        return None
    flops = float(ca.get("flops", 0.0) or 0.0)
    byts = float(ca.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0.0 and byts <= 0.0:
        return None
    entry = {"flops": flops, "bytes_accessed": byts}
    with _compile_mu:
        _costs[what] = entry
    return dict(entry)


def costs() -> dict[str, dict[str, float]]:
    """Measured (cost_analysis) FLOPs/bytes per compiled step program."""
    with _compile_mu:
        return {k: dict(v) for k, v in _costs.items()}


def cost_measurement_enabled() -> bool:
    """DISTRL_MEASURE_COST=1 (bench sets it): engines AOT-lower their
    decode-step programs once and file the XLA cost_analysis — the
    measured-bytes/token source for bench rows and the trace_report
    roofline section (ISSUE 15). Off by default: the AOT compile is
    measurement-only work (deduped by the persistent XLA compile cache,
    but not free)."""
    return os.environ.get("DISTRL_MEASURE_COST") == "1"


def maybe_record_step_cost(what: str, fn_jit, *args, **kwargs) -> None:
    """AOT-lower+compile ``fn_jit`` at these concrete args and record its
    cost_analysis under ``what`` — once per name, only under
    DISTRL_MEASURE_COST=1. Never raises: backends without AOT/cost
    analysis leave the entry absent (bench reports null, not a fabricated
    number). ``lower`` only traces — donated args are not consumed."""
    if not cost_measurement_enabled():
        return
    with _compile_mu:
        if what in _costs:
            return
    try:
        record_cost(what, fn_jit.lower(*args, **kwargs).compile())
    except Exception as e:  # noqa: BLE001 — measurement must not kill a run
        logging.getLogger(__name__).warning(
            "step-cost measurement for %s failed (%s: %s)",
            what, type(e).__name__, e,
        )


# --------------------------------------------------------------- exposition


def _prom_name(name: str) -> str:
    return "distrl_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_num(v: float) -> str:
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def prometheus_text(snapshot: Mapping[str, Any] | None = None,
                    fleet: Mapping[str, Any] | None = None) -> str:
    """Prometheus text exposition of the cumulative registry: counters as
    counters, gauges as gauges, histograms as REAL Prometheus histogram
    types — cumulative ``_bucket{le=...}`` lines over the shared
    ``telemetry.HIST_BUCKET_BOUNDS`` ladder plus ``_sum``/``_count``, so
    ``histogram_quantile()`` computes ``serving/ttft_ms`` percentiles from
    a standard scrape (ISSUE 13 satellite; summary-stat-only exposition
    made latency SLOs unscrapable) — plus the ``_max`` gauge the summary
    always carried. A snapshot without bucket data (older worker blobs,
    synthetic test snapshots) degrades to the ``+Inf`` bucket alone.
    Fleet per-worker detail (when provided) rides as labeled
    ``distrl_fleet_worker_*`` series; the fleet SCALARS are already
    registry gauges (FleetAggregator publishes them), so they are not
    duplicated here."""
    snap = snapshot if snapshot is not None else telemetry.observe_snapshot()
    lines: list[str] = []
    for name, v in sorted(snap.get("counters", {}).items()):
        m = _prom_name(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {_prom_num(v)}")
    for name, v in sorted(snap.get("gauges", {}).items()):
        m = _prom_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {_prom_num(v)}")
    for name, h in sorted(snap.get("hists", {}).items()):
        m = _prom_name(name)
        count = h.get("count", 0.0)
        lines.append(f"# TYPE {m} histogram")
        buckets = h.get("buckets") or ()
        cum = 0.0
        for le, c in zip(telemetry.HIST_BUCKET_BOUNDS, buckets):
            cum += c
            lines.append(
                f'{m}_bucket{{le="{_prom_num(le)}"}} {_prom_num(cum)}'
            )
        lines.append(f'{m}_bucket{{le="+Inf"}} {_prom_num(count)}')
        lines.append(f"{m}_count {_prom_num(count)}")
        lines.append(f"{m}_sum {_prom_num(h.get('sum', 0.0))}")
        lines.append(f"# TYPE {m}_max gauge")
        lines.append(f"{m}_max {_prom_num(h.get('max', 0.0))}")
    if fleet:
        lines.append("# TYPE distrl_fleet_worker_healthy gauge")
        for w in fleet.get("workers", ()):
            addr = str(w.get("address", "?")).replace('"', "'")
            lines.append(
                f'distrl_fleet_worker_healthy{{worker="{addr}"}} '
                f"{1 if w.get('healthy') else 0}"
            )
        wm = fleet.get("worker_metrics", {})
        if wm:
            lines.append("# TYPE distrl_fleet_worker_gen_tokens counter")
            for addr, rec in sorted(wm.items()):
                a = str(addr).replace('"', "'")
                lines.append(
                    f'distrl_fleet_worker_gen_tokens{{worker="{a}"}} '
                    f"{_prom_num(rec.get('gen_tokens', 0.0))}"
                )
    return "\n".join(lines) + "\n"


def json_snapshot(fleet: Mapping[str, Any] | None = None) -> dict[str, Any]:
    """The JSON form of one scrape: cumulative registry + compile/cost/HBM
    tables + (driver-side) the fleet view."""
    snap = telemetry.observe_snapshot()
    return {
        "ts": time.time(),
        "counters": snap["counters"],
        "gauges": snap["gauges"],
        "hists": snap["hists"],
        "compiles": {
            "total": compile_total(),
            "retraces": retrace_total(),
            "keys": len(compile_counts()),
        },
        "costs": costs(),
        "hbm": hbm_stats(),
        "phase_hbm": phase_hbm(),
        "fleet": dict(fleet) if fleet else None,
    }


class MetricsServer:
    """Threaded HTTP exposition endpoint.

    ``GET /metrics`` → Prometheus text format; ``GET /metrics.json`` (alias
    ``/json``) → the JSON snapshot; ``GET /healthz`` → ``ok``. Binds
    127.0.0.1 by default (an operator fronts it; nothing here needs to be
    internet-facing). ``port=0`` auto-assigns — read ``.port``."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 fleet_provider: Callable[[], Mapping[str, Any]] | None = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: ARG002 — quiet
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server contract
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send(200, "text/plain", b"ok\n")
                    elif path == "/metrics":
                        body = prometheus_text(
                            fleet=server._fleet()
                        ).encode()
                        self._send(
                            200, "text/plain; version=0.0.4", body
                        )
                    elif path in ("/metrics.json", "/json"):
                        body = json.dumps(
                            json_snapshot(fleet=server._fleet()),
                            default=_jsonable,
                        ).encode()
                        self._send(200, "application/json", body)
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass  # scraper went away mid-write
                except Exception as e:  # noqa: BLE001 — a scrape must
                    # never kill the serving thread
                    log.warning("metrics scrape failed: %s", e)
                    try:
                        self._send(500, "text/plain", b"scrape failed\n")
                    except OSError:
                        pass

        self._fleet_provider = fleet_provider
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-metrics", daemon=True
        )
        self._thread.start()

    def _fleet(self) -> Mapping[str, Any] | None:
        if self._fleet_provider is None:
            return None
        try:
            return self._fleet_provider()
        except Exception as e:  # noqa: BLE001 — degrade, don't 500
            log.warning("fleet refresh failed during scrape: %s", e)
            return None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 — idempotent teardown
            pass


# ---------------------------------------------------------- fleet aggregator


class FleetAggregator:
    """Driver-side fold of the per-worker registry snapshots (piggybacked on
    control-plane results — ``telemetry.remote_metrics``) plus the
    DriverClient's health/rejoin state into the ``fleet/*`` series.

    Aggregate tok/s is derived from each worker's monotonic
    ``obs/gen_tokens`` counter between refreshes; a worker restart (counter
    reset, raw count goes BACKWARDS) contributes zero to that window
    instead of a negative rate, and the dead incarnation's count is
    retired into the track's base so the published cumulative totals never
    regress. Refreshes are rate-limited (``min_refresh_s``) so a hot
    scrape loop cannot turn into registry churn."""

    def __init__(self, driver, min_refresh_s: float = 0.5):
        self.driver = driver
        self.min_refresh_s = float(min_refresh_s)
        self._mu = threading.Lock()
        self._last: dict[str, Any] | None = None
        self._last_t = 0.0
        # track -> (snapshot ts, cumulative tokens) at the last refresh
        self._marks: dict[str, tuple[float, float]] = {}
        # track -> tokens finalized by PREVIOUS incarnations of the worker
        # (a restart resets its counter; the dead process's count is final
        # and must stay in the published total — totals never regress)
        self._retired: dict[str, float] = {}
        # track -> last seen worker pid (exported in the snapshot): pid
        # change detects a restart EXACTLY, where counter regression alone
        # misses an incarnation that already out-generated its predecessor
        self._pids: dict[str, Any] = {}
        # tokens finalized by workers the fleet SCALED IN (ISSUE 20): a
        # retired worker's whole cumulative count folds here and its track
        # is dropped from the live table — fleet/gen_tokens_total stays
        # monotone and /metrics.json stops carrying the dead track
        self._scaled_in_tokens = 0.0

    @staticmethod
    def _addr(track: str) -> str:
        # ingest_remote tracks are labeled "worker host:port"
        return track[7:] if track.startswith("worker ") else track

    def refresh(self, force: bool = False) -> dict[str, Any]:
        with self._mu:
            now = time.time()
            if (
                not force and self._last is not None
                and now - self._last_t < self.min_refresh_s
            ):
                return self._last
            workers = (
                self.driver.worker_states()
                if hasattr(self.driver, "worker_states") else []
            )
            epoch = int(getattr(self.driver, "rejoin_epoch", 0))
            remote = telemetry.remote_metrics()
            # elastic scale-in (ISSUE 20): a retired worker is TERMINAL
            # membership state — fold its cumulative count into the fleet
            # base (the restart-retirement logic generalized to a whole
            # track) and drop the track so it never leaks into the live
            # table or /metrics.json again
            retired_addrs = {
                (
                    f"{a[0]}:{a[1]}" if isinstance(a, (tuple, list))
                    else str(a)
                )
                for a in (
                    w.get("address") for w in workers if w.get("retired")
                )
            }
            for track in list(remote):
                if self._addr(track) not in retired_addrs:
                    continue
                snap = remote.pop(track)
                tokens = float(
                    snap.get("counters", {}).get(OBS_GEN_TOKENS, 0.0)
                )
                self._scaled_in_tokens += (
                    self._retired.pop(track, 0.0) + tokens
                )
                self._marks.pop(track, None)
                self._pids.pop(track, None)
                telemetry.drop_remote_track(track)
            total_tokens = self._scaled_in_tokens
            rate = 0.0
            per_worker: dict[str, dict[str, float]] = {}
            for track, snap in remote.items():
                tokens = float(
                    snap.get("counters", {}).get(OBS_GEN_TOKENS, 0.0)
                )
                ts = float(snap.get("_ts", now))
                pid = snap.get("pid")
                last_pid = self._pids.get(track)
                self._pids[track] = pid
                mark = self._marks.get(track)
                restarted = mark is not None and (
                    tokens < mark[1]  # counter went backwards
                    # pid change is the EXACT signal: it also catches an
                    # incarnation that regenerated past its predecessor's
                    # count within one refresh gap
                    or (pid is not None and last_pid is not None
                        and pid != last_pid)
                )
                if restarted:
                    # retire the dead incarnation's count into the track's
                    # base so the published cumulative total never
                    # regresses; this window contributes zero rate (no
                    # honest delta exists across the reset)
                    self._retired[track] = (
                        self._retired.get(track, 0.0) + mark[1]
                    )
                elif mark is not None and ts > mark[0]:
                    rate += (tokens - mark[1]) / (ts - mark[0])
                self._marks[track] = (ts, tokens)
                cumulative = self._retired.get(track, 0.0) + tokens
                total_tokens += cumulative
                per_worker[self._addr(track)] = {
                    "gen_tokens": cumulative, "ts": ts,
                    # incarnation id (ISSUE 14): the cumulative total
                    # NEVER regresses across restarts (by design, above),
                    # so a consumer tracking rates — the worker-health
                    # governor — needs the pid to reset its marks at the
                    # exact restart instead of judging the stall window
                    "pid": pid,
                }
            # fleet-wide serving view (ISSUE 13): fold the workers'
            # serving/* histogram summaries and admission-stall counters
            # into fleet gauges + the endpoint's serving section (None —
            # and no gauges — until some worker served a request)
            serving = fold_fleet_serving(remote)
            fleet = {
                "ts": now,
                "rejoin_epoch": epoch,
                "workers": workers,
                "workers_healthy": sum(
                    1 for w in workers if w.get("healthy")
                ),
                # retired workers left the membership — they are reported
                # in "workers" (terminal state, distinctly) but no longer
                # counted in the pool size
                "workers_total": sum(
                    1 for w in workers if not w.get("retired")
                ),
                "tok_s": round(rate, 3),
                "gen_tokens_total": total_tokens,
                "worker_metrics": per_worker,
                "serving": serving,
            }
            telemetry.gauge_set(FLEET_TOK_S, fleet["tok_s"])
            telemetry.gauge_set(FLEET_GEN_TOKENS, total_tokens)
            telemetry.gauge_set(
                FLEET_WORKERS_HEALTHY, fleet["workers_healthy"]
            )
            telemetry.gauge_set(FLEET_WORKERS_TOTAL, fleet["workers_total"])
            telemetry.gauge_set(FLEET_REJOIN_EPOCH, epoch)
            self._last, self._last_t = fleet, now
            return fleet


# ------------------------------------------------- flight recorder + sentinel


class FlightRecorder:
    """Bounded in-memory ring of recent step records; ``dump`` writes one
    incident directory with the ring, the telemetry span tail, and the
    config/plan snapshot — the evidence bundle a production incident should
    arrive with."""

    def __init__(self, out_dir: str, ring_size: int = 256):
        self.out_dir = out_dir
        self._mu = threading.Lock()
        self.ring: deque = deque(maxlen=max(int(ring_size), 1))
        self.incidents: list[str] = []

    def record(self, kind: str, payload: Mapping[str, Any]) -> None:
        rec = {"ts": time.time(), "kind": kind}
        rec.update({k: _jsonable(v) for k, v in payload.items()})
        with self._mu:
            self.ring.append(rec)

    def dump(self, trigger: str, step: int, *,
             config: Mapping[str, Any] | None = None,
             plan: Mapping[str, Any] | None = None,
             extra: Mapping[str, Any] | None = None) -> str:
        """Write ``<out_dir>/incident_step<N>_<trigger>/`` and return its
        path. The directory name is deterministic (step + trigger) so a
        seeded failure produces a stable bundle; a name collision (two
        dumps at one step, e.g. two distinct triggers share a name only if
        equal — they don't) gets a numeric suffix rather than overwrite."""
        base = os.path.join(
            self.out_dir, f"incident_step{step:06d}_{trigger}"
        )
        path = base
        k = 1
        while os.path.exists(path):
            k += 1
            path = f"{base}_{k}"
        os.makedirs(path)
        with self._mu:
            ring = list(self.ring)
        span_tail = telemetry.recent_events()
        with open(os.path.join(path, "metric_ring.jsonl"), "w") as f:
            for rec in ring:
                f.write(json.dumps(rec, default=_jsonable) + "\n")
        with open(os.path.join(path, "span_tail.json"), "w") as f:
            json.dump(span_tail, f, default=_jsonable)
        with open(os.path.join(path, "config.json"), "w") as f:
            json.dump(
                {
                    "config": dict(config) if config else None,
                    "plan": dict(plan) if plan else None,
                },
                f, default=_jsonable, indent=2,
            )
        manifest = {
            "trigger": trigger,
            "step": int(step),
            "time": time.time(),
            "ring_records": len(ring),
            "span_tail_events": len(span_tail),
            "tracing_enabled": telemetry.enabled(),
            "phase_hbm": phase_hbm(),
            "files": ["metric_ring.jsonl", "span_tail.json", "config.json"],
        }
        if extra:
            manifest.update({k: _jsonable(v) for k, v in extra.items()})
        with open(os.path.join(path, "manifest.json"), "w") as f:
            json.dump(manifest, f, default=_jsonable, indent=2)
        with self._mu:
            self.incidents.append(path)
        telemetry.counter_add(OBS_INCIDENTS)
        log.error(
            "sentinel incident %r at step %d — evidence in %s",
            trigger, step, path,
        )
        return path


class Sentinel:
    """Deterministic anomaly triggers over each step's metrics record.

    Each trigger fires AT MOST ONCE per run (the first incident is the
    evidence; repeats would bury it), dumping the flight recorder and
    requesting a guarded ``TraceProfiler`` capture window when a profiler
    is armed. Triggers:

    * ``nan_loss`` — non-finite ``loss`` / ``grad_norm``.
    * ``reward_collapse`` — ``mean_accuracy_reward`` pinned at ≤ 0 for
      ``collapse_steps`` consecutive steps after having been positive.
    * ``staleness_blowup`` — ``rollout/staleness_max`` above the
      configured bound (the admission layers should make this impossible;
      seeing it means a staleness-control bug).
    * ``tok_s_regression`` — ``engine/decode_tok_s`` below ``tok_drop_frac``
      of its running EMA after ``warmup_steps`` observations.
    * ``hbm_breach`` — device peak bytes above ``hbm_frac`` of
      ``bytes_limit`` (when the backend reports one).
    * ``ttft_blowup`` / ``queue_wait_blowup`` — the step's worst observed
      ``serving/ttft_ms`` / ``serving/queue_wait_ms`` (local registry max,
      or the fleet-folded worker max) above the configured SLO
      (``slo_ttft_ms`` / ``slo_queue_wait_ms``; None = trigger unarmed).
    * ``entropy_collapse`` / ``kl_blowup`` / ``ratio_saturation`` /
      ``grad_spike`` — training-dynamics triggers (ISSUE 16) over the
      device-fused ``learn/*`` bundle the trainer merges into the step
      record: masked answer-token entropy below ``learn_entropy_floor``;
      behavior↔policy KL above ``learn_kl_limit``; the AIPO
      cap-saturation (or PPO clip) fraction above
      ``learn_ratio_sat_frac``; the whole-adapter grad norm above
      ``learn_grad_spike`` × its running EMA after ``warmup_steps``
      observations. None = trigger unarmed.

    ``DISTRL_SENTINEL_INJECT="<trigger>:<step>"`` deterministically
    injects any trigger's precondition at the named step — the seeded
    faults the obs/control smokes and chaos gates build on (ISSUE 14
    closed the parse-time asymmetry that rejected ``reward_collapse``,
    ``staleness_blowup`` and ``hbm_breach``): ``nan_loss`` fakes a NaN
    loss, ``tok_s_regression`` a zero-throughput step,
    ``reward_collapse`` a sustained zero-reward run (from the named step
    until the trigger fires, with the had-been-positive precondition
    seeded), ``staleness_blowup`` a staleness reading past the armed
    bound (async mode only), ``hbm_breach`` a one-step synthetic
    watermark breach (the single-step twin of ``DISTRL_OBS_FAKE_HBM``,
    which fakes *sustained* pressure for the HBM governor), and
    ``ttft_blowup`` / ``queue_wait_blowup`` an SLO breach (legal only
    with the matching SLO armed — injecting an unarmable trigger would
    make a CI gate built on it pass vacuously). The training-dynamics
    triggers inject the same way: a reading past their armed threshold
    at the named step (``grad_spike`` additionally seeds the EMA/warmup
    preconditions so the spike is judgeable) — each legal only with its
    ``learn_*`` threshold armed.
    """

    def __init__(self, recorder: FlightRecorder | None, profiler=None, *,
                 warmup_steps: int = 3, tok_drop_frac: float = 0.5,
                 tok_ema_alpha: float = 0.3, hbm_frac: float = 0.95,
                 collapse_steps: int = 3,
                 staleness_limit: float | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_queue_wait_ms: float | None = None,
                 learn_entropy_floor: float | None = None,
                 learn_kl_limit: float | None = None,
                 learn_ratio_sat_frac: float | None = None,
                 learn_grad_spike: float | None = None,
                 capture_steps: int = 2):
        self.recorder = recorder
        self.profiler = profiler
        self.warmup_steps = warmup_steps
        self.tok_drop_frac = tok_drop_frac
        self.tok_ema_alpha = tok_ema_alpha
        self.hbm_frac = hbm_frac
        self.collapse_steps = collapse_steps
        self.staleness_limit = staleness_limit
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_queue_wait_ms = slo_queue_wait_ms
        self.learn_entropy_floor = learn_entropy_floor
        self.learn_kl_limit = learn_kl_limit
        self.learn_ratio_sat_frac = learn_ratio_sat_frac
        self.learn_grad_spike = learn_grad_spike
        self.capture_steps = capture_steps
        self.fired: set[str] = set()
        # trigger escalation hook (ISSUE 14): the trainer points this at
        # ControlRuntime.on_trigger so a fired trigger can ACT (shrink the
        # admission cap, engage shedding, quarantine, …) instead of only
        # dumping. None — or a runtime with no governor registered for the
        # trigger — preserves the PR 8 dump-only contract exactly.
        self.on_trigger: Callable[[str, int, Mapping[str, Any]], Any] | None = None
        self._tok_ema: float | None = None
        self._tok_obs = 0
        self._grad_ema: float | None = None
        self._grad_obs = 0
        self._seen_reward = False
        self._collapse_run = 0
        self._inject: tuple[str, int] | None = None
        spec = os.environ.get("DISTRL_SENTINEL_INJECT")
        if spec:
            try:
                trig, _, at = spec.partition(":")
                trig = trig.strip()
                # every Sentinel trigger is injectable (ISSUE 14 closed the
                # parse-time asymmetry: reward_collapse / staleness_blowup /
                # hbm_breach were valid triggers but rejected here, making
                # chaos gates for them impossible); the guard now only
                # rejects triggers whose ARMING precondition is absent —
                # accepting those and never firing would make a CI gate
                # built on them pass vacuously
                if trig not in ("nan_loss", "tok_s_regression",
                                "reward_collapse", "staleness_blowup",
                                "hbm_breach",
                                "ttft_blowup", "queue_wait_blowup",
                                "entropy_collapse", "kl_blowup",
                                "ratio_saturation", "grad_spike"):
                    raise ValueError(trig)
                # vacuous-gate guards: without the matching limit there is
                # no threshold to breach
                if trig == "ttft_blowup" and slo_ttft_ms is None:
                    raise ValueError("ttft_blowup needs slo_ttft_ms")
                if (trig == "queue_wait_blowup"
                        and slo_queue_wait_ms is None):
                    raise ValueError(
                        "queue_wait_blowup needs slo_queue_wait_ms"
                    )
                if trig == "staleness_blowup" and staleness_limit is None:
                    raise ValueError(
                        "staleness_blowup needs a staleness limit "
                        "(async mode)"
                    )
                if (trig == "entropy_collapse"
                        and learn_entropy_floor is None):
                    raise ValueError(
                        "entropy_collapse needs learn_entropy_floor"
                    )
                if trig == "kl_blowup" and learn_kl_limit is None:
                    raise ValueError("kl_blowup needs learn_kl_limit")
                if (trig == "ratio_saturation"
                        and learn_ratio_sat_frac is None):
                    raise ValueError(
                        "ratio_saturation needs learn_ratio_sat_frac"
                    )
                if trig == "grad_spike" and learn_grad_spike is None:
                    raise ValueError("grad_spike needs learn_grad_spike")
                self._inject = (trig, int(at))
            except ValueError:
                log.warning(
                    "ignoring DISTRL_SENTINEL_INJECT=%r (expected "
                    "'<trigger>:<step>' where <trigger> is one of "
                    "nan_loss, tok_s_regression, reward_collapse, "
                    "staleness_blowup, hbm_breach, ttft_blowup, "
                    "queue_wait_blowup, entropy_collapse, kl_blowup, "
                    "ratio_saturation or grad_spike; staleness_blowup "
                    "only in async mode, the SLO triggers only with their "
                    "slo_* limit armed, the training-dynamics triggers "
                    "only with their learn_* threshold armed)",
                    spec,
                )

    def _fire(self, trigger: str, step: int, *, config, plan,
              extra: Mapping[str, Any] | None = None) -> bool:
        if trigger in self.fired:
            return False
        self.fired.add(trigger)
        if self.recorder is not None:
            self.recorder.dump(
                trigger, step, config=config, plan=plan, extra=extra
            )
        else:
            telemetry.counter_add(OBS_INCIDENTS)
            log.error(
                "sentinel trigger %r at step %d (no flight_recorder_dir "
                "configured — nothing dumped)", trigger, step,
            )
        if self.profiler is not None and hasattr(
            self.profiler, "request_capture"
        ):
            # guarded: a capture already in flight (the configured step
            # window) makes this a counted no-op, never a second
            # start_trace mid-run
            self.profiler.request_capture(self.capture_steps)
        hook = self.on_trigger
        if hook is not None:
            # trigger → action escalation (ISSUE 14): exactly once per
            # trigger per run (this method's own fire-once contract); a
            # runtime with no governor for the trigger returns without
            # acting — the dump above already happened either way, so an
            # un-armed controller leaves the trigger dump-only
            try:
                hook(trigger, step, dict(extra) if extra else {})
            except Exception:  # noqa: BLE001 — an escalation bug must not
                # suppress the incident path that just produced evidence
                log.warning(
                    "control escalation for trigger %r failed", trigger,
                    exc_info=True,
                )
        return True

    def check(self, step: int, metrics: Mapping[str, Any], *,
              config: Mapping[str, Any] | None = None,
              plan: Mapping[str, Any] | None = None) -> list[str]:
        m = dict(metrics)
        forced_hbm: dict[str, float] | None = None
        if self._inject is not None and self._inject[1] == step:
            trig = self._inject[0]
            if trig == "nan_loss":
                m["loss"] = float("nan")
            elif trig == "tok_s_regression":
                m["engine/decode_tok_s"] = 0.0
            elif trig == "staleness_blowup":
                # parse-time guard ensures staleness_limit is armed
                m["rollout/staleness_max"] = float(self.staleness_limit) + 1.0
            elif trig == "hbm_breach":
                # synthesize a one-step breach for the HBM check below —
                # the single-step twin of the DISTRL_OBS_FAKE_HBM hook
                # (which fakes SUSTAINED pressure for the governor gates;
                # this injection proves the trigger itself fires)
                forced_hbm = {
                    "bytes_limit": 1.0,
                    "peak_bytes_in_use": 1.0,
                    "bytes_in_use": 1.0,
                }
            elif trig == "ttft_blowup":
                # parse-time guard ensures slo_ttft_ms is armed
                m[SERVING_TTFT_MS + "_max"] = 1000.0 * self.slo_ttft_ms
            elif trig == "queue_wait_blowup":
                m[SERVING_QUEUE_WAIT_MS + "_max"] = (
                    1000.0 * self.slo_queue_wait_ms
                )
            elif trig == "entropy_collapse":
                # parse-time guards ensure the learn_* thresholds below
                # are armed
                m[LEARN_ENTROPY] = max(
                    self.learn_entropy_floor - 1.0, 0.0
                )
            elif trig == "kl_blowup":
                m[LEARN_KL] = 10.0 * self.learn_kl_limit + 1.0
            elif trig == "ratio_saturation":
                # synthetic reading; may exceed 1.0 when the threshold sits
                # at the ceiling — the check only compares against it
                m[LEARN_CAP_FRAC] = self.learn_ratio_sat_frac + 0.5
            elif trig == "grad_spike":
                # seed the EMA/warmup preconditions so the check below can
                # judge the spike at exactly the named step
                if self._grad_ema is None:
                    self._grad_ema = 1.0
                self._grad_obs = max(self._grad_obs, self.warmup_steps)
                m[LEARN_GRAD_NORM_TOTAL] = (
                    10.0 * self.learn_grad_spike * self._grad_ema
                )
        if (
            self._inject is not None
            and self._inject[0] == "reward_collapse"
            and step >= self._inject[1]
            and "reward_collapse" not in self.fired
        ):
            # reward collapse is a RUN of zero-reward steps after reward
            # had been positive: inject the whole run (zero reward from
            # the named step until the trigger fires), with the
            # had-been-positive precondition seeded too
            self._seen_reward = True
            m["mean_accuracy_reward"] = 0.0
        fired: list[str] = []

        def fire(trigger: str, **extra) -> None:
            if self._fire(trigger, step, config=config, plan=plan,
                          extra=extra or None):
                fired.append(trigger)

        # --- NaN/Inf in loss or grad norm
        for key in ("loss", "grad_norm"):
            v = m.get(key)
            try:
                bad = v is not None and not math.isfinite(float(v))
            except (TypeError, ValueError):
                bad = False
            if bad:
                fire("nan_loss", metric=key, value=str(v))
                break
        # --- reward collapse
        acc = m.get("mean_accuracy_reward")
        if acc is not None:
            if float(acc) > 0.0:
                self._seen_reward = True
                self._collapse_run = 0
            elif self._seen_reward:
                self._collapse_run += 1
                if self._collapse_run >= self.collapse_steps:
                    fire(
                        "reward_collapse",
                        zero_steps=self._collapse_run,
                    )
        # --- staleness histogram blowup
        if self.staleness_limit is not None:
            mx = m.get("rollout/staleness_max")
            if mx is not None and float(mx) > self.staleness_limit:
                fire(
                    "staleness_blowup",
                    staleness_max=float(mx), limit=self.staleness_limit,
                )
        # --- tok/s regression vs running EMA
        tok = m.get("engine/decode_tok_s")
        if tok is not None:
            tok = float(tok)
            self._tok_obs += 1
            if self._tok_ema is None:
                self._tok_ema = tok
            else:
                if (
                    self._tok_obs > self.warmup_steps
                    and tok < self.tok_drop_frac * self._tok_ema
                ):
                    fire(
                        "tok_s_regression",
                        tok_s=tok, ema=round(self._tok_ema, 1),
                    )
                a = self.tok_ema_alpha
                self._tok_ema = a * tok + (1 - a) * self._tok_ema
        # --- serving SLO breaches (ISSUE 13): the step's worst observed
        # latency — the local registry's per-step hist max (the trainer
        # merges metrics_snapshot into the step record) or the fleet-folded
        # worker max gauge, whichever the run produces
        for trigger, slo, keys in (
            ("ttft_blowup", self.slo_ttft_ms,
             (SERVING_TTFT_MS + "_max", FLEET_SERVING_TTFT_MAX_MS)),
            ("queue_wait_blowup", self.slo_queue_wait_ms,
             (SERVING_QUEUE_WAIT_MS + "_max",
              FLEET_SERVING_QUEUE_WAIT_MAX_MS)),
        ):
            if slo is None:
                continue
            observed = [float(m[k]) for k in keys if m.get(k) is not None]
            if observed and max(observed) > slo:
                fire(
                    trigger,
                    observed_ms=round(max(observed), 3), slo_ms=slo,
                )
        # --- training-dynamics triggers (ISSUE 16): the device-fused
        # learn/* bundle the trainer merges into the step record
        if self.learn_entropy_floor is not None:
            ent = m.get(LEARN_ENTROPY)
            if ent is not None and float(ent) < self.learn_entropy_floor:
                fire(
                    "entropy_collapse",
                    entropy=float(ent), floor=self.learn_entropy_floor,
                )
        if self.learn_kl_limit is not None:
            kl = m.get(LEARN_KL)
            if kl is not None and float(kl) > self.learn_kl_limit:
                fire(
                    "kl_blowup",
                    kl=float(kl), limit=self.learn_kl_limit,
                )
        if self.learn_ratio_sat_frac is not None:
            # AIPO runs report the cap-saturation fraction, PPO-clip runs
            # the clip fraction — one trigger covers whichever the loss
            # computes
            sat = m.get(LEARN_CAP_FRAC)
            if sat is None:
                sat = m.get(LEARN_CLIP_FRAC)
            if sat is not None and float(sat) > self.learn_ratio_sat_frac:
                fire(
                    "ratio_saturation",
                    saturated_frac=float(sat),
                    limit=self.learn_ratio_sat_frac,
                )
        if self.learn_grad_spike is not None:
            g = m.get(LEARN_GRAD_NORM_TOTAL)
            if g is not None:
                g = float(g)
                self._grad_obs += 1
                if self._grad_ema is None:
                    self._grad_ema = g
                else:
                    if (
                        self._grad_obs > self.warmup_steps
                        and g > self.learn_grad_spike * self._grad_ema
                    ):
                        fire(
                            "grad_spike",
                            grad_norm=g, ema=round(self._grad_ema, 6),
                            factor=self.learn_grad_spike,
                        )
                    a = self.tok_ema_alpha
                    self._grad_ema = a * g + (1 - a) * self._grad_ema
        # --- HBM watermark breach
        stats = forced_hbm if forced_hbm is not None else hbm_stats()
        if stats and stats.get("bytes_limit"):
            peak = float(
                stats.get("peak_bytes_in_use")
                or stats.get("bytes_in_use", 0.0)
            )
            if peak > self.hbm_frac * float(stats["bytes_limit"]):
                fire(
                    "hbm_breach",
                    peak_bytes=peak, bytes_limit=stats["bytes_limit"],
                )
        return fired


# ------------------------------------------------------------------- plane


class ObsPlane:
    """One handle bundling the pieces a process arms: the HTTP endpoint,
    the fleet aggregator (driver with remote workers only), the flight
    recorder ring, and the sentinel. The trainer owns one when any obs
    flag is set; ``on_step`` is its single per-step entry point."""

    def __init__(self, *, metrics_port: int | None = None,
                 sentinel: bool = False,
                 flight_recorder_dir: str | None = None,
                 ring_size: int = 256,
                 driver=None, profiler=None,
                 staleness_limit: float | None = None,
                 slo_ttft_ms: float | None = None,
                 slo_queue_wait_ms: float | None = None,
                 learn_entropy_floor: float | None = None,
                 learn_kl_limit: float | None = None,
                 learn_ratio_sat_frac: float | None = None,
                 learn_grad_spike: float | None = None,
                 config_snapshot: Mapping[str, Any] | None = None,
                 plan_provider: Callable[[], Mapping[str, Any] | None] | None = None):
        self.fleet = FleetAggregator(driver) if driver is not None else None
        self.server = (
            MetricsServer(
                metrics_port,
                fleet_provider=self.fleet.refresh if self.fleet else None,
            )
            if metrics_port is not None else None
        )
        self.recorder = (
            FlightRecorder(flight_recorder_dir, ring_size)
            if flight_recorder_dir else None
        )
        self.sentinel = (
            Sentinel(
                self.recorder, profiler, staleness_limit=staleness_limit,
                slo_ttft_ms=slo_ttft_ms,
                slo_queue_wait_ms=slo_queue_wait_ms,
                learn_entropy_floor=learn_entropy_floor,
                learn_kl_limit=learn_kl_limit,
                learn_ratio_sat_frac=learn_ratio_sat_frac,
                learn_grad_spike=learn_grad_spike,
            )
            if sentinel else None
        )
        self._config_snapshot = (
            dict(config_snapshot) if config_snapshot else None
        )
        self._plan_provider = plan_provider
        # HBM sampling at every PhaseSpans boundary while this plane lives
        telemetry.set_phase_hook(_on_phase)
        if self.server is not None:
            log.info("obs endpoint serving on %s/metrics", self.server.url)

    def on_step(self, step: int, metrics: Mapping[str, Any]) -> None:
        if self.recorder is not None:
            self.recorder.record("step", {"step": step, "metrics": {
                k: _jsonable(v) for k, v in metrics.items()
            }})
        if self.fleet is not None:
            # keep the fleet gauges flowing into the sink records too, not
            # just scrapes (rate-limited inside refresh)
            self.fleet.refresh()
        if self.sentinel is not None:
            plan = self._plan_provider() if self._plan_provider else None
            self.sentinel.check(
                step, metrics, config=self._config_snapshot, plan=plan
            )

    def close(self) -> None:
        telemetry.set_phase_hook(None)
        if self.server is not None:
            self.server.close()
