"""FakeEngine: a scripted stand-in for the generation engine.

The reference has no way to exercise its trainer loop without GPUs (SURVEY §4)
— this is the fake backend our integration tests use instead. It honors the
engine protocol (``generate(params, lora, prompt_ids, prompt_mask, sampling,
rng) -> GenerationResult``) but produces completions from a host-side script
function, tokenized to the same fixed shapes the real engine emits.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from distrl_llm_tpu.config import SamplingConfig
from distrl_llm_tpu.engine.engine import GenerationResult

# script(prompt, candidate_index) -> completion text
ScriptFn = Callable[[str, int], str]


class FakeEngine:
    """Deterministic engine double. ``calls`` records (batch_size, n,
    weight_version-at-call) so tests can assert rollout/sync behavior."""

    def __init__(self, tokenizer, script: ScriptFn, *, max_new_tokens: int = 64):
        self.tokenizer = tokenizer
        self.script = script
        self.max_new_tokens = max_new_tokens
        self.calls: list[dict] = []

    def generate(
        self,
        params,
        lora,
        prompt_ids: np.ndarray,
        prompt_mask: np.ndarray,
        sampling: SamplingConfig,
        rng,
    ) -> GenerationResult:
        b = prompt_ids.shape[0]
        n = sampling.n
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        self.calls.append({"batch": b, "n": n, "lora": lora})

        pad_id = getattr(self.tokenizer, "pad_token_id", 0) or 0
        tokens = np.full((b, n, max_steps), pad_id, np.int32)
        lengths = np.zeros((b, n), np.int32)
        for i in range(b):
            # recover the prompt text to feed the script
            real = prompt_ids[i][prompt_mask[i].astype(bool)]
            prompt = self.tokenizer.decode(real.tolist())
            for j in range(n):
                toks = self.tokenizer.encode(self.script(prompt, j))[:max_steps]
                tokens[i, j, : len(toks)] = toks
                lengths[i, j] = len(toks)
        return GenerationResult(tokens=tokens, lengths=lengths)
