"""One SHARDED paged engine over a GSPMD dp axis (shard_map edition).

Closes the round-2 "deliberate gap" (PARITY.md): the paged engine targeted
one replica, with data-parallel scale-out running one engine per replica
(vLLM's one-engine-per-GPU model, fanned out via remote workers). On a
single TPU slice the natural idiom is ONE engine whose page pool is
partitioned across the dp axis — this module builds exactly that with
``jax.experimental.shard_map``:

* each dp shard owns a LOCAL page pool and LOCAL page tables (page ids index
  the shard's own pool slice), so the per-step page gather never crosses the
  axis — the pool-partitioned design sketched in paged_engine.py;
* the per-replica jitted pieces (``_paged_prefill``, ``_paged_fanout``,
  ``_paged_decode_step``) are REUSED verbatim as the shard-local program —
  per-shard semantics are identical to a per-replica engine by construction
  (pinned by greedy bit-parity tests, tests/test_sharded_paged.py);
* decode steps dispatch from the host with donated state and async
  early-exit done-snapshots (``run_decode_loop``), exactly like the local
  engines; one dispatch steps every shard;
* sampling folds ``lax.axis_index("dp")`` into the step rng so rows in
  different shards draw independent noise.

Scope: the WAVE scheduler (whole-batch prefill → decode → drain). The
refill/speculative schedulers keep per-candidate host bookkeeping and stay
per-replica (remote-worker fan-out); TP inside a shard is likewise the
per-replica engines' job — this engine requires every non-dp mesh axis to
be size 1. The trainer detects the bound ``mesh`` attribute and routes the
WHOLE batch here (hybrid learner-share generation needs per-role device
placement the bound mesh precludes).

Reference anchor: vLLM data-parallel serving (one engine per GPU,
requirements.txt:6); the sharded pool is the TPU-native alternative the
round-2 verdict asked to build or refute.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from distrl_llm_tpu.config import SamplingConfig
import threading

from distrl_llm_tpu import obs
from distrl_llm_tpu.engine.engine import (
    GenerationResult,
    LoraMailbox,
    accumulate_round_stats,
    cached_chunk_program,
    lora_signature,
    make_swap_aware_chunk_step,
    pool_nbytes,
    pick_chunk,
    run_decode_loop,
    run_nondivisor_tail,
)
from distrl_llm_tpu.engine.paged_engine import (
    _paged_decode_chunk,
    _paged_decode_step,
    _paged_fanout,
    _paged_prefill,
    _PagedDecodeState,
)
from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.ops.paged import pages_per_seq

try:  # jax >= 0.8 promotes shard_map out of experimental
    from jax import shard_map as _raw_shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import (  # type: ignore[no-redef]
        shard_map as _raw_shard_map,
    )


def shard_map(f, *, mesh, in_specs, out_specs):
    """Replication checks off across both shard_map generations (the new API
    renamed check_rep → check_vma)."""
    try:
        return _raw_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return _raw_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

Params = dict[str, Any]


class ShardedPagedEngine(LoraMailbox):
    """Paged wave-mode generation with the page pool partitioned over "dp"."""

    def __init__(
        self,
        cfg: ModelConfig,
        mesh: Mesh,
        *,
        max_prompt_tokens: int,
        max_new_tokens: int,
        eos_token_ids: Sequence[int],
        pad_token_id: int,
        lora_scale: float = 1.0,
        cache_dtype=jnp.bfloat16,
        attn_impl: str = "reference",
        paged_impl: str = "auto",
        page_size: int = 128,
        decode_chunk: int = 128,
        # None = consult the autotune plan DB (ExecutionPlan.kv_format;
        # empty DB = "none"); an explicit value — including "none" — pins
        kv_quant: str | None = None,
        prompt_buckets: Sequence[int] | None = None,  # interface parity
        # None = consult the autotune plan DB (falls back to 0, the
        # historical default); an explicit int — including 0 — always wins
        scan_chunk: int | None = None,
        # blocked-kernel page collapse; None = consult the plan DB (falls
        # back to 0, the kernel default); an explicit int incl. 0 wins
        pages_per_block: int | None = None,
        capture_logprobs: bool = False,
        autotune: bool = True,  # False pins the static defaults (no DB read)
        plan_db: str | None = None,  # plan-DB path; None = env/default path
        plan_rows: int = 0,  # expected rows for plan-KEY selection (0 = any)
        # accepted-and-rejected so misrouted configs fail with a clear
        # error instead of a TypeError deep in trainer wiring
        spec_draft: int | None = None,
    ):
        if spec_draft:
            raise NotImplementedError(
                "speculative decoding is a per-replica refill-scheduler "
                "feature (PagedGenerationEngine with scheduler='refill' — "
                "one engine per rollout replica, distributed/"
                "remote_engine.py); ShardedPagedEngine runs the wave "
                "scheduler over a dp-partitioned pool and does not host it"
            )
        if scan_chunk is not None and scan_chunk < 0:
            raise ValueError(f"scan_chunk must be >= 0, got {scan_chunk}")
        if kv_quant not in (None, "none", "int8"):
            # validated BEFORE plan resolution so a typo'd kwarg fails with
            # the engine's own contract, not a plan-field error
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        if pages_per_block is not None and pages_per_block < 0:
            raise ValueError(
                f"pages_per_block must be >= 0, got {pages_per_block}"
            )
        # execution-plan resolution (distrl_llm_tpu/autotune): explicit
        # kwargs win; no DB entry = the static defaults byte-identically
        from distrl_llm_tpu.autotune import (
            IMPL_TO_PAGED_KERNEL, PAGED_KERNEL_TO_IMPL, resolve_plan,
        )

        requested: dict[str, Any] = {"decode_path": "paged"}
        if scan_chunk is not None:
            requested["scan_chunk"] = scan_chunk
        if pages_per_block is not None:
            requested["pages_per_block"] = pages_per_block
        if kv_quant is not None:
            # explicit "none" is a real pin (the int8-default A/B control)
            requested["kv_format"] = kv_quant
        if paged_impl != "auto":
            # same contract as PagedGenerationEngine: an explicit kwarg —
            # including the plan-unrepresentable "kernel"/"reference" —
            # always wins over a stored paged_kernel
            requested["paged_kernel"] = IMPL_TO_PAGED_KERNEL.get(paged_impl)
        self.resolved_plan = resolve_plan(
            model_cfg=cfg, max_prompt_tokens=max_prompt_tokens,
            max_new_tokens=max_new_tokens, rows=plan_rows,
            requested=requested, db_path=plan_db, enabled=autotune,
        )
        scan_chunk = self.resolved_plan.plan.scan_chunk
        self.plan_top_p_impl = self.resolved_plan.plan.top_p_impl
        if paged_impl == "auto" and self.resolved_plan.plan.paged_kernel:
            paged_impl = PAGED_KERNEL_TO_IMPL[
                self.resolved_plan.plan.paged_kernel
            ]
        self.paged_impl = paged_impl
        self.pages_per_block = self.resolved_plan.plan.pages_per_block
        if "dp" not in mesh.shape:
            raise ValueError(f"mesh needs a 'dp' axis, got {dict(mesh.shape)}")
        other = {k: v for k, v in mesh.shape.items() if k != "dp" and v > 1}
        if other:
            raise ValueError(
                f"ShardedPagedEngine shards over dp only; non-trivial axes "
                f"{other} belong to per-replica engines (TP) — see module doc"
            )
        self.cfg = cfg
        self.mesh = mesh
        self.dp = mesh.shape["dp"]
        self.max_prompt_tokens = max_prompt_tokens
        self.max_new_tokens = max_new_tokens
        cfg.check_within_window(max_prompt_tokens + max_new_tokens)
        self.page_size = page_size
        self.prompt_pages = pages_per_seq(max_prompt_tokens, page_size)
        self.private_pages = 1 + pages_per_seq(max_new_tokens, page_size)
        self.eos_ids = jnp.asarray(list(eos_token_ids), jnp.int32)
        self.pad_id = int(pad_token_id)
        self.lora_scale = lora_scale
        self.decode_chunk = decode_chunk
        self.capture_logprobs = capture_logprobs
        self.prompt_buckets = [max_prompt_tokens]
        # post-resolution KV format (explicit kwarg already won per-field)
        kv_quant = kv_quant if kv_quant is not None else (
            self.resolved_plan.plan.kv_format or "none"
        )
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        self._kv_quant = kv_quant
        self._prefill_kw = dict(
            cfg=cfg, prompt_pages=self.prompt_pages, page_size=page_size,
            lora_scale=lora_scale, cache_dtype=cache_dtype,
            attn_impl=attn_impl, kv_quant=kv_quant,
        )
        self._step_kw = dict(
            cfg=cfg, page_size=page_size, pad_id=self.pad_id,
            lora_scale=lora_scale, paged_impl=paged_impl,
            pages_per_block=self.pages_per_block,
            capture_logprobs=capture_logprobs,
        )
        self.scan_chunk = scan_chunk
        self._built: dict[tuple, tuple] = {}
        self._chunk_compiled: dict = {}
        self._chunk_mu = threading.Lock()
        # in-flight weight-update mailbox (LoraMailbox base)
        self.last_swap_steps: list[int] = []
        self.last_swap_versions: list[int | None] = []

    @property
    def scan_chunk_active(self) -> bool | None:
        """Honesty flag: whether chunked decode actually ran (None before
        the first round / scan_chunk off; False if every attempt fell back
        to per-step dispatch)."""
        if self.scan_chunk <= 1 or not self._chunk_compiled:
            return None
        return any(v is not None for v in self._chunk_compiled.values())

    def bucket_for(self, prompt_mask) -> int:
        return self.max_prompt_tokens

    # ------------------------------------------------------------------ build

    def _state_specs(self) -> _PagedDecodeState:
        page = P(None, "dp", None, None)
        pages = lambda: tuple(  # noqa: E731 — spec tuple per layer
            page for _ in range(self.cfg.num_layers)
        )

        def quant_aware(spec_tuple):
            # quantized pools are QuantizedTensor pytrees (weight + scales):
            # shard_map specs are pytree PREFIXES, so a per-layer P() prefix
            # covers both leaves
            return spec_tuple

        return _PagedDecodeState(
            step=P(),
            out=P("dp", None),
            logps=P("dp", None),
            gen_lengths=P("dp"),
            done=P("dp"),
            logits=P("dp", None),
            seq_lengths=P("dp"),
            k_pages=quant_aware(pages()),
            v_pages=quant_aware(pages()),
        )

    def _build(self, n: int, b_local: int, max_steps: int,
               top_p_impl: str) -> tuple:
        key = (n, b_local, max_steps, top_p_impl)
        if key in self._built:
            return self._built[key]
        obs.note_compile("sharded_paged/build", key)
        mesh = self.mesh
        sspec = self._state_specs()

        def local_setup(params, lora, ids, mask):
            pk, pv, last_logits, real_len = _paged_prefill(
                params, lora, ids, mask, **self._prefill_kw
            )
            row_alive = mask.sum(axis=-1) > 0
            state, table = _paged_fanout(
                pk, pv, last_logits, real_len, row_alive,
                n=n, b=b_local, prompt_pages=self.prompt_pages,
                private_pages=self.private_pages, page_size=self.page_size,
                max_steps=max_steps,
            )
            return state, table

        setup = jax.jit(
            shard_map(
                local_setup, mesh=mesh,
                in_specs=(P(), P(), P("dp", None), P("dp", None)),
                out_specs=(sspec, P("dp", None)),
            )
        )

        def local_step(params, lora, state, rng, table, temperature, top_p):
            # decorrelate shards: every shard holds the same round rng, so
            # without the fold every shard's rows would draw IDENTICAL noise
            rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
            return _paged_decode_step(
                params, lora, state, rng, table,
                eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
                top_p_impl=top_p_impl, **self._step_kw,
            )

        step = jax.jit(
            shard_map(
                local_step, mesh=mesh,
                in_specs=(P(), P(), sspec, P(), P("dp", None), P(), P()),
                out_specs=sspec,
            ),
            donate_argnums=(2,),
        )

        chunk_jit = None
        k = pick_chunk(self.scan_chunk, max_steps)
        if k > 1:
            # K steps per dispatch inside the SAME shard_map program. The
            # scan body is unguarded (a cond's select would double-buffer
            # the carried page pools — scan_steps_guarded); each shard's
            # done rows are per-row no-ops, and the host cadence below
            # keeps every dispatched step under max_steps.
            def local_chunk(params, lora, state, rng, table,
                            temperature, top_p):
                rng = jax.random.fold_in(rng, jax.lax.axis_index("dp"))
                return _paged_decode_chunk(
                    params, lora, state, rng, table, chunk=k,
                    eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p,
                    top_p_impl=top_p_impl, **self._step_kw,
                )

            chunk_jit = jax.jit(
                shard_map(
                    local_chunk, mesh=mesh,
                    in_specs=(P(), P(), sspec, P(), P("dp", None), P(), P()),
                    out_specs=sspec,
                ),
                donate_argnums=(2,),
            )
        self._built[key] = (setup, step, chunk_jit, k)
        return self._built[key]

    # --------------------------------------------------------------- generate

    def generate(
        self,
        params: Params,
        lora: Params | None,
        prompt_ids: np.ndarray,  # [B, P] left-padded (trainer contract)
        prompt_mask: np.ndarray,
        sampling: SamplingConfig,
        rng: jax.Array,
    ) -> GenerationResult:
        b, p = prompt_ids.shape
        if p != self.max_prompt_tokens:
            raise ValueError(
                f"prompts must be padded to {self.max_prompt_tokens}, got {p}"
            )
        t_round = time.perf_counter()
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        n = max(sampling.n, 1)
        # pad the prompt batch to a dp multiple; padding rows have all-zero
        # masks → born done in fanout, pad-token output, zero lengths
        pad_rows = (-b) % self.dp
        if pad_rows:
            prompt_ids = np.concatenate(
                [np.asarray(prompt_ids),
                 np.zeros((pad_rows, p), np.int32)], axis=0
            )
            prompt_mask = np.concatenate(
                [np.asarray(prompt_mask),
                 np.zeros((pad_rows, p), np.int32)], axis=0
            )
        b_pad = b + pad_rows
        top_p_impl = sampling.resolved_top_p_impl(self.plan_top_p_impl)
        setup, step, chunk_jit, k = self._build(
            n, b_pad // self.dp, max_steps, top_p_impl
        )

        state, table = setup(
            params, lora, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask)
        )
        temperature = jnp.asarray(sampling.temperature, jnp.float32)
        top_p = jnp.asarray(sampling.top_p, jnp.float32)
        self._reset_lora_mailbox_round()
        lora_cell = [lora]
        steps_seen = [0]

        chunk_fn = None
        if chunk_jit is not None:
            chunk_fn = cached_chunk_program(
                self._chunk_compiled, self._chunk_mu,
                (n, b_pad, max_steps, top_p_impl, lora_signature(lora)),
                chunk_jit, pool_nbytes(state.k_pages, state.v_pages),
                f"sharded-wave scan_chunk={k}",
                params, lora, state, rng, table, temperature, top_p,
            )

        if chunk_fn is not None:

            def run_step(l, s):
                return step(params, l, s, rng, table, temperature, top_p)

            step_fn = make_swap_aware_chunk_step(
                self, lora_cell, steps_seen, k, max_steps, chunk_fn, lora,
                rebuild=lambda l, s: cached_chunk_program(
                    self._chunk_compiled, self._chunk_mu,
                    (n, b_pad, max_steps, top_p_impl, lora_signature(l)),
                    chunk_jit,
                    pool_nbytes(s.k_pages, s.v_pages),
                    f"sharded-wave scan_chunk={k}",
                    params, l, s, rng, table, temperature, top_p,
                ),
                run_chunk=lambda fn, l, s: fn(
                    params, l, s, rng, table, temperature, top_p
                ),
                run_step=run_step,
            )
            # floor chunks + shared non-divisor tail (run_nondivisor_tail
            # has the cadence invariant)
            full, rem = divmod(max_steps, k)
            state = run_decode_loop(step_fn, state, full, 1)
            state = run_nondivisor_tail(
                self, lora_cell, steps_seen, rem, state, run_step)
        else:

            def step_fn(s):
                self._take_pending_lora(lora_cell, steps_seen[0])
                steps_seen[0] += 1
                return step(
                    params, lora_cell[0], s, rng, table, temperature, top_p
                )

            state = run_decode_loop(step_fn, state, max_steps, self.decode_chunk)
        out = np.asarray(state.out).reshape(b_pad, n, max_steps)[:b]
        lengths = np.asarray(state.gen_lengths).reshape(b_pad, n)[:b]
        logps = (
            np.asarray(state.logps).reshape(b_pad, n, max_steps)[:b]
            if self.capture_logprobs else None
        )
        # round stats (engine.accumulate_round_stats contract, new here):
        # the sharded path previously published no throughput at all —
        # like RemoteEngine, the whole round is accounted as decode time
        # (prefill runs inside the same jitted setup; no honest split).
        # whole_round flags the coarse accounting so the trainer skips
        # engine/mfu on it — a prefill/compile-inclusive "decode" rate
        # against the chip peak would be a misleadingly low MFU (remote
        # rounds are excluded for the same reason via is_remote)
        self.last_round_stats = accumulate_round_stats(
            None, prefill_s=0.0,
            prefill_tokens=int(np.asarray(prompt_mask)[:b].sum()),
            prompt_rows=b,
            decode_s=time.perf_counter() - t_round,
            gen_tokens=int(lengths.sum()), gen_rows=b * n,
        )
        self.last_round_stats["whole_round"] = True
        return GenerationResult(tokens=out, lengths=lengths, logprobs=logps)
