"""HBM budget → KV page-pool sizing (the ``--actor_gpu_usage`` contract).

The reference passes ``--actor_gpu_usage`` straight to vLLM's
``gpu_memory_utilization`` (train_distributed.py:34-35), which sizes the KV
block pool as: usable = usage × device_memory − weights − activation
workspace, pool_blocks = usable / block_bytes. This module is the TPU-native
equivalent: it converts the same fraction into ``max_kv_pages`` for the paged
engine's refill pool (engine/page_pool.py), measured against real HBM when a
TPU is attached and a v5e-sized fallback otherwise.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

log = logging.getLogger(__name__)

# v5e/v5p chips carry 16 GiB; used only when memory_stats() is unavailable
# (CPU test runs, older runtimes)
DEFAULT_HBM_BYTES = 16 * 1024**3

# slice of the budget held back for XLA workspace, decode activations, and
# the donated-state double buffers (vLLM hides the analogous costs inside its
# profiling "dummy run"; a fixed fraction is the static-shape equivalent)
ACTIVATION_RESERVE = 0.08


def device_hbm_bytes(device=None) -> int:
    """Accelerator memory capacity, from the runtime when it reports one."""
    try:
        dev = device or jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # noqa: BLE001 — CPU/interpreted backends
        pass
    return DEFAULT_HBM_BYTES


def tree_bytes(params) -> int:
    """Total bytes of a host/device param tree (quantized containers count
    weight + scales — whatever the leaves actually store)."""
    return sum(
        leaf.nbytes for leaf in jax.tree_util.tree_leaves(params)
        if hasattr(leaf, "nbytes")
    )


def page_bytes(model_cfg, page_size: int, kv_quant: str = "none") -> int:
    """HBM bytes one KV page costs across ALL layers (k + v)."""
    per_layer_one = model_cfg.num_kv_heads * page_size * model_cfg.head_dim
    if kv_quant == "int8":
        # int8 payload + f32 per-token absmax scales [K, P, ps, 1]
        one = per_layer_one * 1 + model_cfg.num_kv_heads * page_size * 4
    else:
        one = per_layer_one * 2  # bf16
    return one * 2 * model_cfg.num_layers


def kv_pool_pages(
    model_cfg,
    *,
    gpu_usage: float,
    param_bytes: int,
    batch_prompts: int,
    max_prompt_tokens: int,
    max_new_tokens: int,
    page_size: int,
    kv_quant: str = "none",
    spec_draft: int = 0,
    hbm_bytes: int | None = None,
    continuous: bool = False,
    prefix_cache: bool = False,
) -> int:
    """Pages available to the refill decode pool under ``gpu_usage``.

    Subtracts, in order: the (1 - usage) exclusion the knob demands, the
    activation reserve, resident weights, and the SHARED prompt page region
    (batch_prompts × prompt_pages — prefill owns those regardless of the
    pool). With ``continuous`` (ISSUE 12 continuous admission) prompt
    chains are allocated FROM the pool, so the static region subtraction
    drops — those bytes become pool capacity — and the single-sequence
    floor carries one prompt chain. Clamped below at that minimum, so a
    too-small budget degrades to serial decoding instead of refusing to
    run (with a warning naming the shortfall)."""
    from distrl_llm_tpu.ops.paged import pages_per_seq

    hbm = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    pb = page_bytes(model_cfg, page_size, kv_quant)
    prompt_pages = pages_per_seq(max_prompt_tokens, page_size)
    shared_bytes = 0 if continuous else batch_prompts * prompt_pages * pb
    budget = int(
        hbm * (gpu_usage - ACTIVATION_RESERVE) - param_bytes - shared_bytes
    )
    pool = budget // pb if budget > 0 else 0
    private_pages = 1 + pages_per_seq(max_new_tokens + max(spec_draft, 0),
                                      page_size)
    floor = 1 + private_pages + (prompt_pages if continuous else 0)
    if prefix_cache:
        # tiered KV cache (ISSUE 18): warm radix-cache pages are resident
        # in the SAME pool, so the floor carries one extra prompt chain —
        # a clamped budget still leaves the cache able to keep at least one
        # cached prefix resident next to the serial-decode minimum
        floor += prompt_pages
    if pool < floor:
        log.warning(
            "actor_gpu_usage=%.2f leaves %d KV pages (< single-sequence "
            "minimum %d) after %.2f GiB weights on %.2f GiB HBM; clamping — "
            "decode will serialize",
            gpu_usage, pool, floor, param_bytes / 1024**3, hbm / 1024**3,
        )
        return floor
    return pool
