"""Host-side KV page allocator for the refill scheduler's page budget.

vLLM sizes its KV block pool from ``gpu_memory_utilization`` and admits /
preempts sequences against that budget (the knob the reference tunes as
``--actor_gpu_usage``, train_distributed.py:34-35). On TPU the page ARRAYS
must be shape-static, but which pages a slot owns is data — so the pool array
is allocated once at the budgeted size and this class tracks ownership and
builds the [R, width] page-table rows on the host. The device only ever sees
the table (a tiny int32 array re-shipped per decode dispatch via
``state._replace``); allocation, admission, and preemption-victim choice are
plain Python against a free list.

Layout contract (shared with paged_engine):
* shared prompt pages occupy ids [0, b·prompt_pages) — written once by
  prefill, never owned by the pool;
* pool pages occupy [first_page, first_page + n_pages); page ``first_page``
  is the SCRATCH page: every dead slot's table row points all columns at it,
  so dead slots' garbage decode writes land somewhere harmless that no live
  row ever reads;
* a slot's table row is: shared full prompt pages below ``full`` columns,
  then its owned pages (partial prompt page first), trailing columns clamped
  to the last owned page (the attention gather reads the whole width; clamped
  columns are beyond every read window).
"""

from __future__ import annotations

import numpy as np

from distrl_llm_tpu import telemetry

# pool-occupancy gauge (one owner; trainer merges it per step, traced runs
# render it as a Perfetto counter track)
POOL_OCCUPANCY = "pool/occupancy"


class PagePool:
    """Free-list page allocator + page-table builder (host-side, numpy)."""

    def __init__(
        self,
        *,
        first_page: int,  # == b·prompt_pages (pool starts after shared region)
        n_pages: int,  # pool size INCLUDING the scratch page
        r_slots: int,
        width: int,  # table columns (prompt_pages + private_pages)
        page_size: int,
        prompt_pages: int,
    ):
        if n_pages < 2:
            raise ValueError(f"pool needs >= 2 pages (scratch + 1), got {n_pages}")
        self.scratch = first_page
        self.page_size = page_size
        self.prompt_pages = prompt_pages
        self.n_pages = n_pages
        # LIFO free list: recently-released pages are re-granted first (their
        # tiles are warm in whatever cache level still holds them)
        self.free: list[int] = list(
            range(first_page + n_pages - 1, first_page, -1)
        )
        self.owned: list[list[int]] = [[] for _ in range(r_slots)]
        self.full = np.zeros(r_slots, np.int32)  # shared full pages per slot
        self.table = np.full((r_slots, width), self.scratch, np.int32)
        self.peak_pages_used = 0
        self.preemptions = 0
        # opt-in per-boundary self-check (tests; DISTRL_POOL_CHECK=1)
        import os

        self.self_check = os.environ.get("DISTRL_POOL_CHECK", "0") == "1"

    # -- accounting --------------------------------------------------------

    @property
    def free_pages(self) -> int:
        return len(self.free)

    @property
    def used_pages(self) -> int:
        return sum(len(o) for o in self.owned)

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable pages (scratch excluded) currently owned."""
        return self.used_pages / max(self.n_pages - 1, 1)

    def _record_occupancy(self) -> None:
        # gauge for the MetricsSink series; while tracing is on this also
        # emits a Chrome counter event, so Perfetto renders pool pressure
        # as a time-series track aligned with the decode spans
        telemetry.gauge_set(POOL_OCCUPANCY, self.occupancy)

    def check_invariants(self) -> None:
        """free + owned must tile the pool exactly, with no page owned twice
        (test hook; O(pool) but pools are small on the host)."""
        all_pages = sorted(self.free + [p for o in self.owned for p in o])
        expected = list(range(self.scratch + 1, self.scratch + self.n_pages))
        assert all_pages == expected, (
            f"pool accounting broken: {len(all_pages)} tracked vs "
            f"{len(expected)} expected"
        )

    # -- sizing helpers ----------------------------------------------------

    def pages_to_cover(self, slot: int, last_position: int) -> int:
        """Owned pages required for the slot's writes through
        ``last_position`` (positions below full·ps live in shared pages)."""
        return max(last_position // self.page_size - int(self.full[slot]) + 1, 1)

    # -- transitions -------------------------------------------------------

    def admit(
        self, slot: int, prompt_idx: int, real_len: int, last_position: int
    ) -> bool:
        """Claim pages for an admission covering writes through
        ``last_position``; build the slot's table row. False (and no state
        change) when the free list can't cover it."""
        assert not self.owned[slot], f"slot {slot} admitted while owning pages"
        full = real_len // self.page_size
        self.full[slot] = full
        need = self.pages_to_cover(slot, last_position)
        if need > len(self.free):
            return False
        grant = [self.free.pop() for _ in range(need)]
        self.owned[slot] = grant
        row = self.table[slot]
        row[:] = self.scratch
        row[:full] = prompt_idx * self.prompt_pages + np.arange(full)
        row[full:full + need] = grant
        row[full + need:] = grant[-1]
        self.peak_pages_used = max(self.peak_pages_used, self.used_pages)
        self._record_occupancy()
        return True

    def ensure(self, slot: int, last_position: int) -> int:
        """Grow the slot's grant to cover writes through ``last_position``.
        Returns the number of pages still MISSING (0 = fully granted)."""
        owned = self.owned[slot]
        assert owned, f"ensure() on unowned slot {slot}"
        need = self.pages_to_cover(slot, last_position)
        missing = need - len(owned)
        take = min(max(missing, 0), len(self.free))
        if take:
            full = int(self.full[slot])
            grant = [self.free.pop() for _ in range(take)]
            row = self.table[slot]
            row[full + len(owned):full + len(owned) + take] = grant
            owned.extend(grant)
            row[full + len(owned):] = owned[-1]
            self.peak_pages_used = max(self.peak_pages_used, self.used_pages)
            self._record_occupancy()
        return max(missing - take, 0)

    def release(self, slot: int) -> None:
        """Return the slot's pages to the free list; redirect its table row
        to scratch (the slot's post-mortem garbage writes must not land in
        pages another slot may be granted)."""
        self.free.extend(reversed(self.owned[slot]))
        self.owned[slot] = []
        self.table[slot, :] = self.scratch
        self._record_occupancy()
