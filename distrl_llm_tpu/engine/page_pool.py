"""Host-side KV page allocator for the refill scheduler's page budget.

vLLM sizes its KV block pool from ``gpu_memory_utilization`` and admits /
preempts sequences against that budget (the knob the reference tunes as
``--actor_gpu_usage``, train_distributed.py:34-35). On TPU the page ARRAYS
must be shape-static, but which pages a slot owns is data — so the pool array
is allocated once at the budgeted size and this class tracks ownership and
builds the [R, width] page-table rows on the host. The device only ever sees
the table (a tiny int32 array re-shipped per decode dispatch via
``state._replace``); allocation, admission, and preemption-victim choice are
plain Python against a free list.

Layout contract (shared with paged_engine):
* shared prompt pages occupy ids [0, b·prompt_pages) — written once by
  prefill. In the legacy layout they are a static region the pool never
  tracks; with ``prefix_sharing`` they are REGISTERED as refcounted prefix
  chains (below) and return to the free list when their group finishes;
* pool pages occupy [first_page, first_page + n_pages); page ``first_page``
  is the SCRATCH page: every dead slot's table row points all columns at it,
  so dead slots' garbage decode writes land somewhere harmless that no live
  row ever reads;
* a slot's table row is: shared full prompt pages below ``full`` columns,
  then its owned pages (partial prompt page first), trailing columns clamped
  to the last owned page (the attention gather reads the whole width; clamped
  columns are beyond every read window).

Prefix sharing (ISSUE 12 — vLLM's copy-on-write prefix caching, host-side):
a prompt's page chain is registered once per GROUP (``register_prefix`` /
``alloc_prefix``) with a refcount HOLD; every admitted candidate ALIASES the
chain's full pages (refcount++ each) instead of pointing at an untracked
static region, and the partial tail page — the one decode extends in place —
is attached copy-on-write: the first write into it (``note_write``, or the
``first_write`` hint ``admit`` receives, since the scheduler knows the first
decode write is imminent) SPLITS it into a freshly granted private page with
the device copy queued for the caller to fuse into its next admit dispatch.
``release`` only decrements; a page returns to the free list when its
refcount hits zero — so a group's prompt KV is resident ~once instead of
once per candidate, and finished groups' prompt pages recycle into decode
capacity.
"""

from __future__ import annotations

import threading
from collections import deque

import numpy as np

from distrl_llm_tpu import telemetry

# pool-occupancy gauge (one owner; trainer merges it per step, traced runs
# render it as a Perfetto counter track). Occupancy counts each PHYSICAL
# page once however many owners reference it (refcount-aware — the per-slot
# sum would over-report under sharing and read > 1.0).
POOL_OCCUPANCY = "pool/occupancy"
# fraction of in-use pages currently referenced by >= 2 owners (prefix
# sharing live); 0.0 on unshared pools
POOL_SHARED_FRAC = "pool/shared_frac"
# copy-on-write tail-page splits (one device page-copy each)
POOL_COW_SPLITS = "pool/cow_splits"
# ---- tiered KV cache (ISSUE 18) — all owned by this module ----
# cumulative radix lookup hit rate in TOKENS (hit/looked-up, full-page
# granular); 0.0 until the first warm lookup
POOL_RADIX_HIT_RATE = "pool/radix_hit_rate"
# prefill tokens the radix cache saved (full cached pages aliased at admit
# instead of re-prefilled)
POOL_PREFILL_TOK_SAVED = "pool/prefill_tok_saved"
# radix nodes evicted off the device (LRU, page pressure)
POOL_EVICTIONS = "pool/evictions"
# KV pages spilled to the host store (tier-1 evictions + tier-2 preempt
# spills; one count per physical page parked)
POOL_SPILLED_PAGES = "pool/spilled_pages"
# host->device restore latency per restore batch (milliseconds)
POOL_RESTORE_MS = "pool/restore_ms"


def _payload_to_host(x):
    """Deep-convert a page payload pytree (nested tuples / namedtuples /
    dicts of device or host arrays) to host numpy, structure-preserving.
    int8 KV payloads carry (weight, scales) namedtuples — the PR 15 quant
    transport idiom — and round-trip bit-exact because the conversion is a
    pure memcpy per leaf."""
    if hasattr(x, "_fields"):  # NamedTuple (quantized page tiles)
        return type(x)(*(_payload_to_host(f) for f in x))
    if isinstance(x, (tuple, list)):
        return type(x)(_payload_to_host(f) for f in x)
    if isinstance(x, dict):
        return {k: _payload_to_host(v) for k, v in x.items()}
    return np.asarray(x)


def _payload_nbytes(x) -> int:
    if hasattr(x, "_fields") or isinstance(x, (tuple, list)):
        return sum(_payload_nbytes(f) for f in x)
    if isinstance(x, dict):
        return sum(_payload_nbytes(v) for v in x.values())
    return int(getattr(x, "nbytes", 0))


class HostPageStore:
    """Host-RAM KV page store (tier 2): parked pages live here between
    eviction/preemption and restore. ``put`` hands the (already device-side
    gathered) payload to a background daemon thread for the device->host
    copy, so the decode loop never blocks on a transfer; ``get`` blocks only
    when the requested key's conversion is still in flight. Payloads are
    opaque pytrees — the pool stores verbatim what the engine gathered
    (int8 weight+scales or raw-dtype tiles), so the round-trip is bit-exact
    by construction. An optional byte cap LRU-evicts the oldest payloads;
    a restore that finds its payload aged out simply re-prefills."""

    _PENDING = object()  # placeholder while the worker converts a payload

    def __init__(self, max_bytes: int = 0):
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        # insertion order doubles as LRU order (refreshed on get)
        self._data: dict = {}
        self._nbytes: dict = {}
        self._queue: deque = deque()
        self._doomed: set = set()  # dropped while still pending
        self.max_bytes = int(max_bytes)
        self.used_bytes = 0
        self.dropped_payloads = 0  # byte-cap LRU evictions
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="kv-spill", daemon=True
        )
        self._worker.start()

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                key, payload = self._queue.popleft()
            host = _payload_to_host(payload)  # device->host copy, no lock
            with self._cv:
                if key in self._doomed:
                    self._doomed.discard(key)
                    self._data.pop(key, None)
                elif self._data.get(key) is self._PENDING:
                    self._data[key] = host
                    n = _payload_nbytes(host)
                    self._nbytes[key] = n
                    self.used_bytes += n
                    self._enforce_cap_locked()
                self._cv.notify_all()

    def _enforce_cap_locked(self) -> None:
        if not self.max_bytes:
            return
        while self.used_bytes > self.max_bytes:
            oldest = next(
                (k for k, v in self._data.items() if v is not self._PENDING),
                None,
            )
            if oldest is None:
                return
            del self._data[oldest]
            # graftcheck: disable=GC103 -- _locked suffix contract: every caller holds self._mu (the _cv lock)
            self.used_bytes -= self._nbytes.pop(oldest)
            self.dropped_payloads += 1

    def put(self, key, payload) -> None:
        """Park ``payload`` under ``key`` (async device->host). Safe to call
        with device arrays as long as they are independent buffers (gathered
        copies) — never views into donated state pools."""
        with self._cv:
            assert not self._closed, "put() on a closed HostPageStore"
            self._doomed.discard(key)
            self._data[key] = self._PENDING
            self._queue.append((key, payload))
            self._cv.notify_all()

    def get(self, key):
        """Fetch a parked payload (blocks while its conversion is in
        flight). None when the key was never stored or aged out."""
        with self._cv:
            while self._data.get(key) is self._PENDING:
                self._cv.wait()
            payload = self._data.get(key)
            if payload is not None:
                self._data[key] = self._data.pop(key)  # LRU refresh
            return payload

    def contains(self, key) -> bool:
        with self._cv:
            return key in self._data

    def drop(self, key) -> None:
        with self._cv:
            if self._data.get(key) is self._PENDING:
                self._doomed.add(key)  # worker discards post-conversion
                return
            if key in self._data:
                del self._data[key]
                self.used_bytes -= self._nbytes.pop(key)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._worker.join(timeout=10)


class _RadixNode:
    """One full KV page of a cached prefix: ``key`` is the page's exact
    ``page_size`` token ids, ``page`` its round-scoped device page id when
    resident (None when spilled), ``store_key`` its host-store payload key
    when one exists. Content is immutable — a full prefix page is never
    written again — so residency and spill state are the only mutables."""

    __slots__ = ("key", "parent", "children", "page", "store_key",
                 "last_use", "nid")

    def __init__(self, key, parent, nid):
        self.key = key
        self.parent = parent
        self.children: dict = {}
        self.page: int | None = None
        self.store_key = None
        self.last_use = 0
        self.nid = nid


class RadixCache:
    """Cross-request radix prefix index (tier 1, SGLang RadixAttention
    style): a tree keyed on exact token ids at full-page granularity. The
    cache object is ENGINE-owned and outlives the per-round ``PagePool`` —
    device page ids on nodes are round-scoped, so the engine flushes
    residency to the host store at round end and the tree persists across
    rounds as a host-resident index. All tree transitions run through the
    pool (it owns the free list and refcounts)."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _RadixNode(None, None, -1)
        self._resident: dict[int, _RadixNode] = {}  # nid -> node
        self._tick = 0
        self._next_nid = 0
        # cumulative counters (engine snapshots per-round deltas for bench)
        self.lookup_tok = 0
        self.hit_tok = 0
        self.prefill_tok_saved = 0
        self.evictions = 0
        self.spilled_pages = 0
        self.restored_pages = 0

    def touch(self, node: _RadixNode) -> None:
        self._tick += 1
        node.last_use = self._tick

    def new_node(self, key, parent) -> _RadixNode:
        node = _RadixNode(key, parent, self._next_nid)
        self._next_nid += 1
        parent.children[key] = node
        return node

    @property
    def resident_pages(self) -> int:
        return len(self._resident)

    def node_count(self) -> int:
        n, stack = 0, list(self.root.children.values())
        while stack:
            cur = stack.pop()
            n += 1
            stack.extend(cur.children.values())
        return n

    def reset_residency(self) -> None:
        """Forget device residency WITHOUT spilling (defensive: a prior
        round aborted before its flush — page ids are stale, and nodes
        with no stored payload will be pruned at their next match)."""
        for node in self._resident.values():
            node.page = None
        self._resident.clear()

    def snapshot(self) -> dict[str, int]:
        """Cumulative counters — callers diff two snapshots for per-round
        figures."""
        return {
            "lookup_tok": self.lookup_tok,
            "hit_tok": self.hit_tok,
            "prefill_tok_saved": self.prefill_tok_saved,
            "evictions": self.evictions,
            "spilled_pages": self.spilled_pages,
            "restored_pages": self.restored_pages,
        }


class PagePool:
    """Free-list page allocator + page-table builder (host-side, numpy)."""

    def __init__(
        self,
        *,
        first_page: int,  # == b·prompt_pages (pool starts after shared region)
        n_pages: int,  # pool size INCLUDING the scratch page
        r_slots: int,
        width: int,  # table columns (prompt_pages + private_pages)
        page_size: int,
        prompt_pages: int,
        prefix_sharing: bool = False,  # refcounted CoW prefix chains
        radix: RadixCache | None = None,  # tier-1 cross-request index
        store: HostPageStore | None = None,  # tier-2 host-RAM spill
    ):
        if n_pages < 2:
            raise ValueError(f"pool needs >= 2 pages (scratch + 1), got {n_pages}")
        if radix is not None and not prefix_sharing:
            raise ValueError("prefix_cache rides the prefix-sharing chain "
                             "machinery; pass prefix_sharing=True")
        self.scratch = first_page
        self.page_size = page_size
        self.prompt_pages = prompt_pages
        self.n_pages = n_pages
        self.prefix_sharing = prefix_sharing
        # LIFO free list: recently-released pages are re-granted first (their
        # tiles are warm in whatever cache level still holds them)
        self.free: list[int] = list(
            range(first_page + n_pages - 1, first_page, -1)
        )
        self.owned: list[list[int]] = [[] for _ in range(r_slots)]
        self.full = np.zeros(r_slots, np.int32)  # shared full pages per slot
        self.table = np.full((r_slots, width), self.scratch, np.int32)
        self.peak_pages_used = 0
        self.preemptions = 0
        # ---- prefix-sharing state (all empty / inert when sharing is off)
        # per-page refcount for SHARED pages only (chain holds + slot
        # aliases); privately owned pages are implicitly refcount 1
        self.ref: dict[int, int] = {}
        # prompt -> (chain page ids, full-page count). len(chain) > full
        # means the last entry is the pristine partial TAIL page.
        self.chains: dict[int, tuple[list[int], int]] = {}
        # per-slot aliased full-prefix pages (leading table columns)
        self.shared: list[list[int]] = [[] for _ in range(r_slots)]
        # per-slot tail page still attached copy-on-write (pre-split)
        self.tail_shared: list[int | None] = [None] * r_slots
        # per-slot queued (src -> owned[slot][0]) CoW copy from the last
        # admit/split; the engine drains it into the admit dispatch
        self.copy_src: list[int | None] = [None] * r_slots
        # pages outside [first_page, first_page + n_pages) the pool has
        # adopted (a static prompt region registered/reclaimed into it)
        self.adopted: set[int] = set()
        # stats the bench/telemetry satellites read
        self.cow_splits = 0
        self.peak_shared_pages = 0
        self.prefix_admissions = 0
        self.total_admissions = 0
        # ---- tiered KV cache (ISSUE 18; both None when the cache is off).
        # The radix tree and host store are ENGINE-owned and outlive this
        # per-round pool; node device-page ids are round-scoped, so a tree
        # arriving with stale residency (a prior round aborted before its
        # flush) is defensively reset.
        self.radix = radix
        self.store = store
        # engine-installed closure: page id -> gathered device payload
        # (independent buffers — never views into donated state pools).
        # MAIN-thread only: it dispatches a device gather.
        self.spill_fn = None
        if radix is not None and radix._resident:
            radix.reset_residency()
        # opt-in per-boundary self-check (tests; DISTRL_POOL_CHECK=1)
        import os

        self.self_check = os.environ.get("DISTRL_POOL_CHECK", "0") == "1"

    # -- accounting --------------------------------------------------------

    @property
    def universe_pages(self) -> int:
        """Allocatable physical pages (scratch excluded, adoptions included)."""
        return self.n_pages - 1 + len(self.adopted)

    @property
    def free_pages(self) -> int:
        return len(self.free)

    @property
    def used_pages(self) -> int:
        """Physical pages in use, each counted ONCE however many owners
        reference it (refcount-aware: equals the per-slot sum on unshared
        pools, where ownership is disjoint by construction)."""
        return self.universe_pages - len(self.free)

    @property
    def shared_pages(self) -> int:
        """Distinct pages currently referenced by >= 2 owners (a chain hold
        counts as an owner — a held chain page aliased by one slot is
        physically one page serving two futures)."""
        return sum(1 for c in self.ref.values() if c >= 2)

    @property
    def occupancy(self) -> float:
        """Fraction of allocatable pages (scratch excluded) currently owned."""
        return self.used_pages / max(self.universe_pages, 1)

    def _record_occupancy(self) -> None:
        # gauge for the MetricsSink series; while tracing is on this also
        # emits a Chrome counter event, so Perfetto renders pool pressure
        # as a time-series track aligned with the decode spans
        telemetry.gauge_set(POOL_OCCUPANCY, self.occupancy)
        if self.prefix_sharing:
            telemetry.gauge_set(
                POOL_SHARED_FRAC, self.shared_pages / max(self.used_pages, 1)
            )

    def _note_peaks(self) -> None:
        self.peak_pages_used = max(self.peak_pages_used, self.used_pages)
        if self.prefix_sharing:
            self.peak_shared_pages = max(
                self.peak_shared_pages, self.shared_pages
            )

    def check_invariants(self) -> None:
        """free + private owned + shared/held must tile the pool exactly —
        each physical page tracked exactly once — and every refcount must
        equal its recomputed owner count (test hook; O(pool) but pools are
        small on the host)."""
        expected = sorted(
            set(range(self.scratch + 1, self.scratch + self.n_pages))
            | self.adopted
        )
        private = [p for o in self.owned for p in o]
        tracked = sorted(self.free + private + list(self.ref))
        assert tracked == expected, (
            f"pool accounting broken: {len(tracked)} tracked vs "
            f"{len(expected)} expected (free={len(self.free)}, "
            f"private={len(private)}, shared={len(self.ref)})"
        )
        # refcount conservation: recompute every shared page's owner count
        # from the chains + per-slot alias lists and compare
        recount: dict[int, int] = {}
        for pages, _full in self.chains.values():
            for p in pages:
                recount[p] = recount.get(p, 0) + 1
        for slot_pages in self.shared:
            for p in slot_pages:
                recount[p] = recount.get(p, 0) + 1
        for p in self.tail_shared:
            if p is not None:
                recount[p] = recount.get(p, 0) + 1
        # tiered cache (ISSUE 18): every RESIDENT radix node holds exactly
        # one cache reference on its page, and the tree's resident page set
        # must be disjoint from the free list (a cached page granted to a
        # slot would serve two owners' writes)
        if self.radix is not None:
            res_pages: list[int] = []
            for node in self.radix._resident.values():
                assert node.page is not None, (
                    f"non-resident node {node.nid} in the resident index"
                )
                recount[node.page] = recount.get(node.page, 0) + 1
                res_pages.append(node.page)
            assert len(res_pages) == len(set(res_pages)), (
                f"radix page double-tracked: {sorted(res_pages)}"
            )
            overlap = set(res_pages) & set(self.free)
            assert not overlap, (
                f"radix-resident pages on the free list: {sorted(overlap)}"
            )
        assert recount == self.ref, (
            f"refcount drift: recomputed {recount} vs tracked {self.ref}"
        )
        assert all(c >= 1 for c in self.ref.values()), (
            f"zero-ref residue in shared table: {self.ref}"
        )

    # -- sizing helpers ----------------------------------------------------

    def pages_to_cover(self, slot: int, last_position: int) -> int:
        """Owned pages required for the slot's writes through
        ``last_position`` (positions below full·ps live in shared pages)."""
        return max(last_position // self.page_size - int(self.full[slot]) + 1, 1)

    # -- prefix chains (prefix_sharing only) -------------------------------

    def register_prefix(self, prompt_idx: int, pages: list[int],
                        full_count: int) -> None:
        """Adopt ``pages`` as prompt ``prompt_idx``'s prefix chain with a
        refcount HOLD: ``full_count`` leading full pages (never written
        again) plus, when ``len(pages) > full_count``, the pristine partial
        tail page. Pages outside the pool range (a static prefill region)
        are adopted into the pool's universe — they return to the free list
        when the chain drops and every alias releases."""
        assert self.prefix_sharing, "register_prefix needs prefix_sharing"
        assert prompt_idx not in self.chains, f"prompt {prompt_idx} re-registered"
        assert len(pages) in (full_count, full_count + 1), (
            f"chain length {len(pages)} vs full_count {full_count}"
        )
        for p in pages:
            if not (self.scratch < p < self.scratch + self.n_pages):
                self.adopted.add(p)
            self.ref[p] = self.ref.get(p, 0) + 1
        self.chains[prompt_idx] = (list(pages), full_count)
        self._note_peaks()

    def alloc_prefix(self, prompt_idx: int, n_chain: int,
                     full_count: int) -> list[int] | None:
        """Allocate a prompt's prefix chain FROM the pool (continuous
        admission: prefill writes into pool pages) and register it. None —
        and no state change — when the free list can't cover it."""
        assert self.prefix_sharing, "alloc_prefix needs prefix_sharing"
        self._reserve(n_chain)
        if n_chain > len(self.free):
            return None
        pages = [self.free.pop() for _ in range(n_chain)]
        self.register_prefix(prompt_idx, pages, full_count)
        self._record_occupancy()
        return pages

    def reclaim(self, pages: list[int]) -> None:
        """Feed unused static-region pages (a dead prompt's region, the
        slack beyond a short prompt's real chain) into the free list as
        decode capacity."""
        assert self.prefix_sharing, "reclaim needs prefix_sharing"
        for p in pages:
            assert p not in self.ref and p not in self.free, f"page {p} live"
            if not (self.scratch < p < self.scratch + self.n_pages):
                self.adopted.add(p)
            self.free.append(p)

    def drop_prefix(self, prompt_idx: int) -> None:
        """Release the group hold: every chain page loses one reference and
        frees when (and only when) no admitted slot still aliases it."""
        pages, _full = self.chains.pop(prompt_idx)
        for p in pages:
            self._deref(p)
        self._record_occupancy()

    def _deref(self, page: int) -> None:
        c = self.ref[page] - 1
        if c:
            self.ref[page] = c
        else:
            del self.ref[page]
            self.free.append(page)

    # -- tiered KV cache (ISSUE 18; radix is None when the cache is off) ---

    def radix_match(self, tokens) -> tuple[list[_RadixNode], int]:
        """Longest cached prefix of ``tokens`` at full-page granularity,
        capped so at least ONE suffix token stays un-cached — its forward
        pass produces the sampling logits the admit needs, and because the
        hit therefore never covers position real_len-1, no suffix prefill
        write ever lands in a cached page. Returns the matched node path
        (contiguous from the root) and the hit length in tokens. Nodes that
        are neither resident nor restorable (payload aged out of the host
        store) are pruned on sight."""
        r = self.radix
        assert r is not None, "radix_match needs a prefix cache"
        ps = self.page_size
        toks = [int(t) for t in tokens]
        max_full = (len(toks) - 1) // ps
        r.lookup_tok += len(toks)
        nodes: list[_RadixNode] = []
        cur = r.root
        for i in range(max_full):
            key = tuple(toks[i * ps:(i + 1) * ps])
            child = cur.children.get(key)
            if child is None:
                break
            if child.page is None and (
                child.store_key is None
                or self.store is None
                or not self.store.contains(child.store_key)
            ):
                self._prune(child)
                break
            nodes.append(child)
            cur = child
        hit = len(nodes) * ps
        r.hit_tok += hit
        for node in nodes:
            r.touch(node)
        telemetry.gauge_set(
            POOL_RADIX_HIT_RATE, r.hit_tok / max(r.lookup_tok, 1)
        )
        return nodes, hit

    def restore_nodes(
        self, nodes: list[_RadixNode],
    ) -> tuple[list[_RadixNode], list[tuple[_RadixNode, int, object]]]:
        """Ensure device residency for a matched node path. Returns
        ``(resident, uploads)``: the contiguous resident prefix of the path
        (truncated at the first node that cannot be restored — payload aged
        out, or the pool cannot free a page for it) and the ``(node, page,
        payload)`` uploads the ENGINE must scatter into the device pools
        before any slot reads through the chain. The whole matched path is
        protected from being evicted to make room for its own restores."""
        protect = {node.nid for node in nodes}
        resident: list[_RadixNode] = []
        uploads: list[tuple[_RadixNode, int, object]] = []
        for node in nodes:
            if node.page is not None:
                resident.append(node)
                continue
            payload = (
                self.store.get(node.store_key)
                if self.store is not None and node.store_key is not None
                else None
            )
            if payload is None:
                break
            self._reserve(1, protect=protect)
            if not self.free:
                break
            page = self.free.pop()
            node.page = page
            self.ref[page] = self.ref.get(page, 0) + 1  # cache hold
            self.radix._resident[node.nid] = node
            self.radix.restored_pages += 1
            uploads.append((node, page, payload))
            resident.append(node)
        if uploads:
            self._note_peaks()
            self._record_occupancy()
        return resident, uploads

    def note_restore_ms(self, ms: float) -> None:
        """Single emission site for the restore-latency histogram (the
        engine owns the timing — the upload dispatch runs there)."""
        telemetry.hist_observe(POOL_RESTORE_MS, float(ms))

    def note_restored(self, n_pages: int) -> None:
        """Counter twin of ``note_spilled`` for pages reloaded from the
        host store OUTSIDE the radix path (tier-2 preempt resumes —
        ``restore_nodes`` counts its own uploads itself)."""
        if n_pages:
            self.radix.restored_pages += n_pages

    def note_spilled(self, n_pages: int) -> None:
        """Single emission site for the spilled-pages counter (tier-2
        preempt spills ride through here; tier-1 evictions call it from
        ``_evict``/``flush_cache``)."""
        if n_pages:
            self.radix.spilled_pages += n_pages
            telemetry.counter_add(POOL_SPILLED_PAGES, float(n_pages))

    def admit_cached(
        self, prompt_idx: int, nodes: list[_RadixNode], n_chain: int,
        full_count: int,
    ) -> list[int] | None:
        """Register prompt ``prompt_idx``'s chain with its leading pages
        ALIASED from resident radix nodes — those pages' prefill is skipped
        entirely — and the un-cached remainder freshly granted. None (and
        no state change) when the free list can't cover the remainder.
        Chain registration adds a chain hold on every page, so cached pages
        are pinned (cache hold + chain hold) for the group's lifetime."""
        assert len(nodes) <= full_count, "cache hit overran the full prefix"
        fresh_need = n_chain - len(nodes)
        self._reserve(fresh_need, protect={node.nid for node in nodes})
        if fresh_need > len(self.free):
            return None
        fresh = [self.free.pop() for _ in range(fresh_need)]
        pages = [node.page for node in nodes] + fresh
        self.register_prefix(prompt_idx, pages, full_count)
        saved = len(nodes) * self.page_size
        if saved:
            self.radix.prefill_tok_saved += saved
            telemetry.counter_add(POOL_PREFILL_TOK_SAVED, float(saved))
        self._record_occupancy()
        return pages

    def cache_chain(self, prompt_idx: int, tokens) -> None:
        """Retire prompt ``prompt_idx``'s finished chain INTO the radix
        tree instead of dropping it: each full page's chain hold transfers
        to a cache hold on its radix node (no refcount churn on fresh
        nodes). A page duplicating an already-resident node derefs — the
        tree keeps one physical copy per distinct prefix — and a spilled
        node re-materialized by a fresh chain adopts the fresh page (page
        content is deterministic in (tokens, adapter), so any stored
        payload stays valid). The mutable partial tail page always derefs:
        only immutable full pages are cacheable."""
        r = self.radix
        assert r is not None, "cache_chain needs a prefix cache"
        ps = self.page_size
        toks = [int(t) for t in tokens]
        pages, full = self.chains.pop(prompt_idx)
        assert full * ps <= len(toks), (
            f"chain covers {full} full pages but only {len(toks)} tokens "
            f"were provided"
        )
        cur = r.root
        for i in range(full):
            page = pages[i]
            key = tuple(toks[i * ps:(i + 1) * ps])
            child = cur.children.get(key)
            if child is None:
                child = r.new_node(key, cur)
                child.page = page  # chain hold becomes the cache hold
                r._resident[child.nid] = child
            elif child.page is None:
                child.page = page
                r._resident[child.nid] = child
            else:
                self._deref(page)  # duplicate of a resident node
            r.touch(child)
            cur = child
        if len(pages) > full:
            self._deref(pages[full])
        self._record_occupancy()

    def _reserve(self, need: int, protect: set | frozenset = frozenset()) -> None:
        """Best-effort pressure valve: evict LRU UNPINNED radix nodes until
        ``need`` pages are free (a node is unpinned when the cache hold is
        its page's only reference). Runs before every allocation path so
        the warm cache can never starve admission; a no-op when the cache
        is off. Eviction spills the page payload to the host store first
        (unless the store already holds it), so evicted prefixes stay
        restorable."""
        r = self.radix
        if r is None:
            return
        # graftcheck: hot-region radix-match-evict
        while len(self.free) < need:
            victim = None
            for node in r._resident.values():
                if node.nid in protect or self.ref.get(node.page, 0) != 1:
                    continue
                if victim is None or node.last_use < victim.last_use:
                    victim = node
            if victim is None:
                break  # nothing evictable: callers decline on capacity
            self._evict(victim)
        # graftcheck: end-hot-region

    def _evict(self, node: _RadixNode) -> None:
        r = self.radix
        if node.store_key is None or self.store is None or (
            not self.store.contains(node.store_key)
        ):
            if self.spill_fn is None or self.store is None:
                # no spill path: forget the subtree rather than leak it
                self._prune(node)
                return
            if node.store_key is None:
                node.store_key = ("radix", node.nid)
            self.store.put(node.store_key, self.spill_fn(node.page))
            self.note_spilled(1)
        page = node.page
        node.page = None
        del r._resident[node.nid]
        self._deref(page)
        r.evictions += 1
        telemetry.counter_add(POOL_EVICTIONS)

    def _prune(self, node: _RadixNode) -> None:
        """Unlink ``node`` (and its whole subtree) from the tree, releasing
        any resident pages and dropping any stored payloads."""
        r = self.radix
        if node.parent is not None:
            node.parent.children.pop(node.key, None)
            node.parent = None
        stack = [node]
        while stack:
            cur = stack.pop()
            if cur.page is not None:
                self._deref(cur.page)
                cur.page = None
                r._resident.pop(cur.nid, None)
                r.evictions += 1
                telemetry.counter_add(POOL_EVICTIONS)
            if cur.store_key is not None and self.store is not None:
                self.store.drop(cur.store_key)
                cur.store_key = None
            stack.extend(cur.children.values())
            cur.children.clear()

    def flush_cache(self) -> None:
        """Round-end flush: every resident node's payload moves to the host
        store and its device page frees — the tree survives the round as a
        host-resident index (device page ids are round-scoped and die with
        this pool). Spills here count as spilled pages, NOT evictions (the
        node wasn't pushed out by pressure). Nodes with no spill path are
        pruned."""
        r = self.radix
        if r is None:
            return
        for node in list(r._resident.values()):
            if node.nid not in r._resident:
                continue  # pruned as part of an earlier node's subtree
            if self.spill_fn is None or self.store is None:
                self._prune(node)
                continue
            if node.store_key is None or not self.store.contains(
                node.store_key
            ):
                if node.store_key is None:
                    node.store_key = ("radix", node.nid)
                self.store.put(node.store_key, self.spill_fn(node.page))
                self.note_spilled(1)
            page = node.page
            node.page = None
            del r._resident[node.nid]
            self._deref(page)
        self._record_occupancy()

    def invalidate_cache(self) -> None:
        """Drop the WHOLE cache — every node, resident or spilled. The
        engine calls this when the adapter identity changes (cached KV is
        only exact under the adapter that wrote it); chains still aliased
        by running groups keep their chain holds and free normally when
        the groups finish."""
        r = self.radix
        if r is None:
            return
        for child in list(r.root.children.values()):
            self._prune(child)
        self._record_occupancy()

    # -- transitions -------------------------------------------------------

    def admit(
        self, slot: int, prompt_idx: int, real_len: int, last_position: int,
        donor: int | None = None, first_write: int | None = None,
    ) -> bool:
        """Claim pages for an admission covering writes through
        ``last_position``; build the slot's table row. False (and no state
        change) when the free list can't cover it.

        With a registered prefix chain (or a ``donor`` slot of the same
        prompt), the chain's full pages are ALIASED (refcount++) instead of
        granted, and the partial tail page is attached copy-on-write: when
        ``first_write`` (the scheduler's imminent first decode write —
        always ``real_len`` in the engine) lands inside it, the split runs
        as part of admission (``copy_src[slot]`` carries the device copy
        source for the caller's admit dispatch); otherwise the tail stays
        shared until ``note_write`` — except a tail sourced from a donor's
        PRIVATE page (its chain already dropped), which always splits
        immediately: that page is mutable and owned-tracked, never
        refcount-attachable. ``donor`` aliases that slot's prefix
        columns — by construction the same physical pages as the chain's —
        and exists so a group sibling can donate even in pools driven
        without a chain ledger (property tests)."""
        assert not self.owned[slot], f"slot {slot} admitted while owning pages"
        assert not self.shared[slot] and self.tail_shared[slot] is None
        full = real_len // self.page_size
        self.full[slot] = full
        need = self.pages_to_cover(slot, last_position)
        self._reserve(need)
        self.copy_src[slot] = None

        prefix: list[int] | None = None
        tail_page: int | None = None
        # a tail sourced from a donor's PRIVATE page is copy-only: it is
        # mutable (the donor's decode extends it) and already tracked as
        # owned, so attaching it refcount-shared would double-track one
        # physical page as both private and shared
        tail_is_private = False
        if self.prefix_sharing:
            chain = self.chains.get(prompt_idx)
            if chain is not None:
                pages, full_count = chain
                assert full_count == full, (
                    f"chain full_count {full_count} vs real_len-derived {full}"
                )
                prefix = pages[:full]
                tail_page = pages[full] if len(pages) > full else None
            elif donor is not None:
                # donor-slot aliasing without a ledger: the donor's prefix
                # columns ARE the prompt's chain; its first private page
                # holds a pristine copy of the prompt tail (the donor only
                # ever wrote positions >= real_len, beyond every read
                # window a fresh candidate can reach before overwriting)
                assert int(self.full[donor]) == full, (
                    f"donor slot {donor} full {int(self.full[donor])} vs {full}"
                )
                prefix = list(self.shared[donor])
                if self.tail_shared[donor] is not None:
                    tail_page = self.tail_shared[donor]
                elif real_len % self.page_size:
                    tail_page = self.owned[donor][0]
                    tail_is_private = True
        if prefix is None:
            # unshared: the historical path, bit-for-bit (the static prompt
            # region holds the prefix; the partial-page copy source is
            # derived device-side by _admit_tables)
            if need > len(self.free):
                return False
            grant = [self.free.pop() for _ in range(need)]
            self.owned[slot] = grant
            row = self.table[slot]
            row[:] = self.scratch
            row[:full] = prompt_idx * self.prompt_pages + np.arange(full)
            row[full:full + need] = grant
            row[full + need:] = grant[-1]
            self.total_admissions += 1
            self._note_peaks()
            self._record_occupancy()
            return True

        split_now = tail_page is not None and (
            # the scheduler's imminent first write lands in the tail block
            (first_write is not None
             and first_write // self.page_size == full)
            # …or the source is donor-private (copy-only — see above)
            or tail_is_private
        )
        # private demand: every covered block, minus the tail block while it
        # stays attached shared (a split consumes the page the tail block
        # would have, so the split case needs exactly the unshared count)
        priv_need = need - (1 if (tail_page is not None and not split_now) else 0)
        if priv_need > len(self.free):
            return False
        grant = [self.free.pop() for _ in range(priv_need)]
        self.owned[slot] = grant
        self.shared[slot] = list(prefix)
        for p in prefix:
            self.ref[p] = self.ref.get(p, 0) + 1
        row = self.table[slot]
        row[:] = self.scratch
        row[:full] = prefix
        if tail_page is not None and not split_now:
            self.ref[tail_page] = self.ref.get(tail_page, 0) + 1
            self.tail_shared[slot] = tail_page
            row[full] = tail_page
            row[full + 1:full + 1 + priv_need] = grant
            row[full + 1 + priv_need:] = grant[-1] if grant else tail_page
        else:
            if split_now:
                # copy-on-write: the first decode write lands in the
                # prompt's partial tail page — split it into the slot's
                # first private page, the device copy riding the caller's
                # admit dispatch (no refcount churn: alias + immediate
                # split nets out to a plain grant + copy)
                self.cow_splits += 1
                telemetry.counter_add(POOL_COW_SPLITS)
                self.copy_src[slot] = tail_page
            row[full:full + priv_need] = grant
            row[full + priv_need:] = grant[-1] if grant else (
                prefix[-1] if prefix else self.scratch
            )
        self.total_admissions += 1
        self.prefix_admissions += 1
        self._note_peaks()
        self._record_occupancy()
        return True

    def note_write(self, slot: int, position: int) -> tuple[int, int] | None:
        """Copy-on-write hook: a write at ``position`` is about to land in
        the slot's pages. Writes into privately owned pages are free; a
        write into the shared tail page SPLITS it — a fresh page is granted,
        the (src, dst) device copy is returned (and queued on
        ``copy_src[slot]``), and the shared page loses this slot's
        reference. Writes below the shared full-prefix region are a
        contract violation (full pages are immutable by construction).
        Returns None when no split was needed; raises when the free list
        cannot back a required split (callers gate admission on capacity)."""
        block = position // self.page_size
        full = int(self.full[slot])
        assert block >= full, (
            f"write at {position} lands in slot {slot}'s immutable shared "
            f"full-prefix region (block {block} < full {full})"
        )
        tail = self.tail_shared[slot]
        if tail is None or block != full:
            return None
        self._reserve(1)
        if not self.free:
            raise RuntimeError(
                f"CoW split for slot {slot} needs a free page and the pool "
                f"is dry — admission must reserve split capacity"
            )
        new = self.free.pop()
        self.cow_splits += 1
        telemetry.counter_add(POOL_COW_SPLITS)
        self.copy_src[slot] = tail
        self.tail_shared[slot] = None
        self._deref(tail)
        self.owned[slot].insert(0, new)
        row = self.table[slot]
        row[full] = new
        # re-clamp trailing columns (they may have clamped onto the tail)
        used = full + len(self.owned[slot])
        row[used:] = self.owned[slot][-1]
        self._note_peaks()
        self._record_occupancy()
        return (tail, new)

    def slot_alias_info(self, slot: int) -> dict[str, int | bool]:
        """Chain-alias facts for one slot's CURRENT admission, as the
        serving ledger records them (ISSUE 13): how many full prefix pages
        the slot aliases, whether its tail page is still attached
        copy-on-write, and whether a CoW copy is queued for the caller's
        admit dispatch. Read-only — a reporting view, not a transition.
        Read it BETWEEN ``admit`` and ``take_copy``: draining the copy
        source resets ``cow_queued``."""
        return {
            "shared_pages": len(self.shared[slot]),
            "tail_shared": self.tail_shared[slot] is not None,
            "cow_queued": self.copy_src[slot] is not None,
        }

    def take_copy(self, slot: int) -> int | None:
        """Drain the slot's queued CoW copy source (the caller fuses the
        src -> owned[slot][0] page copy into its admit dispatch)."""
        src = self.copy_src[slot]
        self.copy_src[slot] = None
        return src

    def ensure(self, slot: int, last_position: int) -> int:
        """Grow the slot's grant to cover writes through ``last_position``.
        Returns the number of pages still MISSING (0 = fully granted)."""
        owned = self.owned[slot]
        assert owned, f"ensure() on unowned slot {slot}"
        assert self.tail_shared[slot] is None, (
            f"ensure() on slot {slot} with an unsplit shared tail"
        )
        need = self.pages_to_cover(slot, last_position)
        missing = need - len(owned)
        self._reserve(max(missing, 0))
        take = min(max(missing, 0), len(self.free))
        if take:
            full = int(self.full[slot])
            grant = [self.free.pop() for _ in range(take)]
            row = self.table[slot]
            row[full + len(owned):full + len(owned) + take] = grant
            owned.extend(grant)
            row[full + len(owned):] = owned[-1]
            self._note_peaks()
            self._record_occupancy()
        return max(missing - take, 0)

    def release(self, slot: int) -> None:
        """Return the slot's private pages to the free list and drop its
        shared references (a shared page frees only when its refcount hits
        zero — the CoW release discipline); redirect its table row to
        scratch (the slot's post-mortem garbage writes must not land in
        pages another slot may be granted)."""
        self.free.extend(reversed(self.owned[slot]))
        self.owned[slot] = []
        for p in self.shared[slot]:
            self._deref(p)
        self.shared[slot] = []
        if self.tail_shared[slot] is not None:
            self._deref(self.tail_shared[slot])
            self.tail_shared[slot] = None
        self.copy_src[slot] = None
        self.table[slot, :] = self.scratch
        self._record_occupancy()
