"""The jit-compiled generation engine: shared-prefill + on-device decode loop.

TPU-native replacement for the reference's in-process vLLM engine
(``policy.fast_generate`` with n-candidate SamplingParams,
distributed_actor.py:147–172 — SURVEY §2b N1/N2). Design:

* **Prefill once per prompt, decode n candidates.** Prompts are left-padded to
  a fixed length and prefilled at batch B; the KV cache is then repeated to
  B·n rows so the n sampled candidates per prompt (``num_candidates``, 16 by
  default) share one prompt forward — a 16× prefill saving the reference
  delegates to vLLM's prefix caching.
* **Host-dispatched donated decode steps.** Each token is one jitted,
  donated step program whose KV cache aliases in place (zero HBM temp bytes —
  an on-device ``lax.while_loop`` carry gets double-buffered by the TPU
  compiler, costing a full cache-sized temp). JAX async dispatch queues steps
  ahead so the device never waits on the host; the host syncs only on the
  done flags every ``decode_chunk`` steps and stops dispatching once every
  row has hit EOS — the fixed-shape equivalent of continuous batching's tail
  behavior. Temperature/top-p are traced scalars, so train and eval sampling
  share the compiled step.
* **LoRA rides the forward** as a pytree argument — "hot-swapping the adapter"
  is passing the latest arrays (SURVEY §2b N2: device-to-device weight sync
  replaces the reference's adapter-file bus, distributed_actor.py:150).

The engine is mesh-agnostic: pass sharded params/batches and GSPMD runs it
TP/DP-sharded; pass host arrays and it runs single-chip.
"""

from __future__ import annotations

import logging
import threading
import time
from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu import obs, telemetry
from distrl_llm_tpu.config import SamplingConfig
from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.models.transformer import (
    forward, init_kv_cache, init_kv_cache_int8,
)
from distrl_llm_tpu.ops.sampling import sample_with_logprob

Params = dict[str, Any]

_logger = logging.getLogger(__name__)

# chunked-dispatch fallback counter (one owner; every engine's chunk cache
# funnels through compile_chunk_guarded here)
ENGINE_CHUNK_FALLBACK = "engine/chunk_fallback"


class GenerationResult(NamedTuple):
    tokens: np.ndarray  # [B, n, T] int32, pad-filled after EOS
    lengths: np.ndarray  # [B, n] generated token counts (incl. EOS)
    # decode step programs dispatched for this round (None where the engine
    # doesn't count them). With speculative decoding, tokens/steps/slots > 1
    # measures the realized draft acceptance — the number to tune spec_draft
    # against on real hardware.
    steps_dispatched: int | None = None
    # sum over dispatched steps of the number of ALIVE slots at that step
    # (refill scheduler only). tokens/alive_slot_steps is the realized
    # per-slot emission rate with the drain-tail idle slots excluded —
    # steps_dispatched*slots systematically understates spec acceptance.
    alive_slot_steps: int | None = None
    # RAW-model log-probabilities of the sampled tokens [B, n, T] f32 (the
    # behavior policy's logprobs — what vLLM returns as `logprobs`); the
    # PPO-clip learner objective ratios the current policy against these.
    logprobs: np.ndarray | None = None


class _DecodeState(NamedTuple):
    step: jax.Array
    out: jax.Array  # [Bn, T]
    logps: jax.Array  # [Bn, T] raw-model logprob of each sampled token
    lengths: jax.Array  # [Bn]
    done: jax.Array  # [Bn] bool
    key_mask: jax.Array  # [Bn, Smax]
    logits: jax.Array  # [Bn, V] logits for the next token
    cache: Params


def _prefill(params, lora, prompt_ids, prompt_mask, *, cfg: ModelConfig,
             max_total: int, lora_scale: float, cache_dtype, attn_impl: str):
    b, p = prompt_ids.shape
    cache = (
        init_kv_cache_int8(cfg, b, max_total)
        if cache_dtype == "int8"
        else init_kv_cache(cfg, b, max_total, dtype=cache_dtype)
    )
    key_mask = jnp.pad(prompt_mask, ((0, 0), (0, max_total - p)))
    last_logits, cache = forward(
        params, cfg, prompt_ids,
        attention_mask=key_mask, lora=lora, lora_scale=lora_scale,
        kv_cache=cache, cache_offset=0, attn_impl=attn_impl,
        logits_slice=(p - 1, 1),
    )
    return cache, key_mask, last_logits[:, 0]


def _decode_init(cache, key_mask, first_logits, row_alive,
                 *, n: int, max_steps: int, pad_id: int):
    """Expand prefill state to candidate rows: row b*n + j is candidate j of
    prompt b."""
    cache = jax.tree_util.tree_map(lambda c: jnp.repeat(c, n, axis=0), cache)
    key_mask = jnp.repeat(key_mask, n, axis=0)
    logits = jnp.repeat(first_logits, n, axis=0)
    bn = logits.shape[0]
    return _DecodeState(
        step=jnp.zeros((), jnp.int32),
        out=jnp.full((bn, max_steps), pad_id, jnp.int32),
        logps=jnp.zeros((bn, max_steps), jnp.float32),
        lengths=jnp.zeros((bn,), jnp.int32),
        # rows with an empty prompt are batch padding — born done, so they
        # never gate the early-exit or sample from their NaN logits
        done=jnp.repeat(~row_alive, n, axis=0),
        key_mask=key_mask,
        logits=logits,
        cache=cache,
    )


def _decode_step(params, lora, state: _DecodeState, rng,
                 *, cfg: ModelConfig, prompt_len: int, eos_ids, pad_id: int,
                 temperature, top_p, lora_scale: float, attn_impl: str,
                 top_p_impl: str = "bisect", capture_logprobs: bool = False,
                 cache_read_formulation: str = "dot"):
    """One decode step: sample from the carried logits, write token + KV,
    forward one position.

    The decode loop lives on the HOST, not in a ``lax.while_loop``: the TPU
    compiler double-buffers a while-loop carry that is updated by
    dynamic_update_slice, costing a full KV-cache-sized HBM temp (~9.4 GB at
    the reference rollout volume — measured via compile memory_analysis; the
    same program as a donated single step has ~0 temp bytes and aliases the
    cache exactly). JAX's async dispatch keeps the device saturated across
    host-dispatched steps, and the host-side gap is where early exit happens —
    once every row has hit EOS the remaining steps are never dispatched (the
    fixed-shape analogue of continuous batching draining its tail)."""
    s = state
    # fused sample+logprob when the kernel is enabled (DISTRL_SAMPLE_KERNEL
    # / probe — ops/sampling.py), multi-pass reference otherwise; greedy
    # outputs bit-identical either way. Done rows' logprobs are zeroed
    # below, so the pre-pad-substitution logprob is observably identical
    # to the old post-substitution token_logprob.
    tok, logp_s = sample_with_logprob(
        jax.random.fold_in(rng, s.step), s.logits, temperature, top_p,
        top_p_impl=top_p_impl, capture_logprob=capture_logprobs,
    )
    tok = jnp.where(s.done, pad_id, tok)
    out = jax.lax.dynamic_update_slice(s.out, tok[:, None], (0, s.step))
    if capture_logprobs:  # per-step vocab logsumexp — only when requested
        logp = jnp.where(s.done, 0.0, logp_s)
        logps = jax.lax.dynamic_update_slice(
            s.logps, logp[:, None], (0, s.step)
        )
    else:
        logps = s.logps
    lengths = s.lengths + (~s.done).astype(jnp.int32)
    hit_eos = jnp.isin(tok, eos_ids)
    # the just-sampled token occupies position prompt_len + step for rows
    # that were still alive; they attend to it on the next forward
    key_mask = jax.lax.dynamic_update_slice(
        s.key_mask, (~s.done).astype(s.key_mask.dtype)[:, None],
        (0, prompt_len + s.step),
    )
    done = s.done | hit_eos
    next_logits, cache = forward(
        params, cfg, tok[:, None],
        attention_mask=key_mask, lora=lora, lora_scale=lora_scale,
        kv_cache=s.cache, cache_offset=prompt_len + s.step,
        attn_impl=attn_impl,
        cache_read_formulation=cache_read_formulation,
    )
    return _DecodeState(
        step=s.step + 1, out=out, logps=logps, lengths=lengths, done=done,
        key_mask=key_mask, logits=next_logits[:, 0], cache=cache,
    )


def _decode_chunk(params, lora, state: _DecodeState, rng,
                  *, chunk: int, cfg: ModelConfig,
                  prompt_len: int, eos_ids, pad_id: int, temperature, top_p,
                  lora_scale: float, attn_impl: str, top_p_impl: str,
                  capture_logprobs: bool,
                  cache_read_formulation: str = "mulred"):
    """``chunk`` decode steps in ONE dispatch via ``lax.scan``.

    Over the axon tunnel each host dispatch can cost a network round trip
    (tools/dispatch_probe.py measures it); at the observed ~44 ms/step against
    a ~5 ms/step chip time, per-dispatch overhead — not the chip — bounds
    decode throughput. Scanning K steps into one program divides that
    overhead by K.

    The body is NOT guarded by ``lax.cond`` — that select double-buffers
    the carried KV cache (see scan_steps_guarded). Steps past all-done are
    per-row no-ops (done rows write pad beyond their recorded length), but
    a step at ``step >= max_steps`` would clamp its dynamic_update_slice
    onto the last valid position and corrupt it, so the HOST must never
    dispatch a chunk crossing ``max_steps``: ``_generate_wave`` runs
    ``max_steps // chunk`` chunks and finishes a non-divisor tail with
    per-step dispatches.

    The engine still compile-checks ``memory_analysis().temp_size_in_bytes``
    before trusting a chunked program and falls back to the host loop if
    the cache got double-buffered anyway (``_chunk_fn_for_bucket``)."""
    def run(s):
        return _decode_step(
            params, lora, s, rng, cfg=cfg, prompt_len=prompt_len,
            eos_ids=eos_ids, pad_id=pad_id, temperature=temperature,
            top_p=top_p, lora_scale=lora_scale, attn_impl=attn_impl,
            top_p_impl=top_p_impl, capture_logprobs=capture_logprobs,
            cache_read_formulation=cache_read_formulation,
        )

    return scan_steps_guarded(run, state, chunk)


def generate_in_waves(
    inner_generate,
    max_rows: int,
    params,
    lora,
    prompt_ids,
    prompt_mask,
    sampling: SamplingConfig,
    rng: jax.Array,
    pad_id: int,
) -> GenerationResult:
    """Cap concurrent candidate rows at ``max_rows`` by running the round in
    sequential WAVES of whole prompt groups — vLLM's ``max_num_seqs``
    admission control, static-shape edition (the reference tunes the same
    knob as engine capacity: 256 concurrent sequences @ actor_gpu_usage,
    train_distributed.py:34). This is what lets a 7B model run the
    reference's 480-row rollout volume on one chip: each wave's KV cache
    fits, waves reuse one compiled program (the tail wave pads with dead
    rows), and early exit drains each wave's stragglers."""
    b = prompt_ids.shape[0]
    n = max(sampling.n, 1)
    if not max_rows or b * n <= max_rows:
        return inner_generate(params, lora, prompt_ids, prompt_mask, sampling, rng)
    per_wave = max(max_rows // n, 1)
    tokens, lengths, logps = [], [], []
    steps = 0
    have_steps = have_logps = True
    for w in range(-(-b // per_wave)):
        lo = w * per_wave
        ids = prompt_ids[lo : lo + per_wave]
        mask = prompt_mask[lo : lo + per_wave]
        pad = per_wave - ids.shape[0]
        if pad:  # tail wave: dead rows keep the compiled shape
            ids = jnp.concatenate(
                [jnp.asarray(ids), jnp.full((pad, ids.shape[1]), pad_id, jnp.int32)]
            )
            mask = jnp.concatenate(
                [jnp.asarray(mask), jnp.zeros((pad, mask.shape[1]), jnp.int32)]
            )
        res = inner_generate(
            params, lora, ids, mask, sampling, jax.random.fold_in(rng, w)
        )
        keep = per_wave - pad
        tokens.append(res.tokens[:keep])
        lengths.append(res.lengths[:keep])
        if res.logprobs is None:
            have_logps = False
        else:
            logps.append(res.logprobs[:keep])
        if res.steps_dispatched is None:
            have_steps = False
        else:
            steps += res.steps_dispatched
    return GenerationResult(
        tokens=np.concatenate(tokens, axis=0),
        lengths=np.concatenate(lengths, axis=0),
        steps_dispatched=steps if have_steps else None,
        logprobs=np.concatenate(logps, axis=0) if have_logps else None,
    )


def scan_steps_guarded(run, state, chunk: int):
    """The one copy of the chunked-dispatch scaffolding every engine's
    chunk body shares: ``chunk`` iterations of ``lax.scan`` running one
    decode step each, UNCONDITIONALLY.

    Earlier rounds wrapped the body in ``lax.cond(halt, skip, run)`` to
    spare flops once every row was done — and that cond was exactly what
    double-buffered the carry: the select between the skipped and stepped
    KV caches keeps both alive, so the TPU compiler materialized a full
    cache-sized temp (r5 silicon finding, tools/scan_alias_probe.py: the
    same body compiles with temp == cache bytes with the cond and ~0
    without, scan and fori_loop alike). Every scan_chunk bench row had
    silently fallen back to host dispatch because of it.

    Running the body unconditionally is semantically safe because the
    step functions are ALREADY per-row no-ops for done rows — partial
    doneness forces that (done rows write pad beyond their length /
    scatter to dropped sentinel rows / park dead slots on the scratch
    page), and the rng step index advances exactly as the host loop
    would. The one case masking does NOT cover is a step whose write
    index would clamp past the output buffer (dense/wave flavors at
    ``step >= max_steps``), so CALLERS must never dispatch a chunk that
    crosses ``max_steps`` — the hosts run ``max_steps // k`` chunks and
    finish a non-divisor tail with per-step dispatches. Refill/spec
    flavors need no cadence guard: their slots self-stop at per-slot
    budgets and their writes drop out-of-range rows."""
    def body(s, _):
        return run(s), None

    return jax.lax.scan(body, state, None, length=chunk)[0]


def compile_chunk_guarded(fn_jit, alias_bytes: int, what: str,
                          *args, fusion_bytes: int = 0, **kwargs):
    """Lower + compile a K-steps-per-dispatch program and inspect its
    ``memory_analysis`` BEFORE it ever runs: if the TPU compiler
    double-buffered the scanned carry (temp bytes on the order of the KV
    buffers it was supposed to alias — ``alias_bytes``), the chunked
    program would OOM the very configs it is meant to speed up, so reject
    it (return None) and let the caller fall back to one dispatch per
    step. Compile failures also return None rather than kill the round.
    Backends without memory analysis (CPU tests) accept the program.

    The rejection needs BOTH a relative and an absolute threshold: at tiny
    test scales, legitimate scratch (attention workspaces, gathers) can
    exceed half of a kilobyte-sized cache without any double-buffering —
    the failure mode this guards against is a CACHE-sized temp, which at
    any scale that matters is hundreds of MBs.

    ``fusion_bytes`` (ADVICE r5) is the second, smaller envelope for
    mulred-formulation programs: the per-layer ``_gqa_mulred``
    broadcast-product temp ([B, KH, G, D, S] f32) that a backend failing
    to fuse reduce-of-product into the cache read would materialize. That
    temp is G× one cache layer but can sit BELOW half the total cache
    (e.g. G=7 over 24 layers ≈ 0.29× cache), sailing under the
    double-buffer check — so it gets its own threshold, with its own
    64 MiB floor against tiny-scale scratch false positives.

    EVERY fallback here is loud: a ``log.warning`` naming the cause plus
    an ``engine/chunk_fallback`` telemetry counter — silently flipping
    ``scan_chunk_active`` is exactly the trap that contaminated the
    round-5 bench rows (VERDICT.md)."""
    try:
        compiled = fn_jit.lower(*args, **kwargs).compile()
        # compile tracker (ISSUE 8): keyed by program name × the arg
        # shape signature, so compiling the SAME shapes twice — the
        # upstream caches are supposed to make that impossible — reads as
        # a retrace, while a genuinely new shape is just a compile
        obs.note_compile(what, arg_shape_signature(args, kwargs))
        temp = None
        try:
            ma = compiled.memory_analysis()
            temp = getattr(ma, "temp_size_in_bytes", None)
        except Exception:  # noqa: BLE001 — backend without memory analysis
            pass
        if temp is not None and temp > 0.5 * alias_bytes and temp > 256 * 2**20:
            _logger.warning(
                "%s: chunked program double-buffers its carry (temp %.2f "
                "GiB vs aliased buffers %.2f GiB) — falling back to "
                "host-dispatched steps",
                what, temp / 2**30, alias_bytes / 2**30,
            )
            telemetry.counter_add(ENGINE_CHUNK_FALLBACK)
            return None
        if (
            temp is not None and fusion_bytes
            and temp > 0.5 * fusion_bytes and temp > 64 * 2**20
        ):
            _logger.warning(
                "%s: chunked program materializes a broadcast-product-sized "
                "temp (%.2f GiB vs _gqa_mulred product %.2f GiB) — the "
                "backend failed to fuse the G-expanded [B,KH,G,D,S] "
                "multiply into the cache read; falling back to "
                "host-dispatched steps",
                what, temp / 2**30, fusion_bytes / 2**30,
            )
            telemetry.counter_add(ENGINE_CHUNK_FALLBACK)
            return None
        # measured roofline input (ISSUE 8): the XLA-reported FLOPs/bytes
        # of the accepted program, surfaced on the obs endpoint and in the
        # trace metadata for trace_report's roofline section
        obs.record_cost(what, compiled)
        return compiled
    except Exception as e:  # pragma: no cover - backend-specific
        _logger.warning(
            "%s: chunked program compile failed (%s: %s) — falling back "
            "to host-dispatched steps",
            what, type(e).__name__, e,
        )
        telemetry.counter_add(ENGINE_CHUNK_FALLBACK)
        return None


def cached_chunk_program(cache: dict, mu, key, fn_jit, alias_bytes: int,
                         what: str, *args, fusion_bytes: int = 0, **kwargs):
    """Mutex-guarded memoization of ``compile_chunk_guarded`` — one shared
    implementation so every engine's chunk-program cache carries the same
    locking (concurrent generate() calls share an engine in the trainer's
    hybrid split) and the same None-means-fell-back convention."""
    with mu:
        if key not in cache:
            cache[key] = compile_chunk_guarded(
                fn_jit, alias_bytes, what, *args,
                fusion_bytes=fusion_bytes, **kwargs
            )
        return cache[key]


def accumulate_round_stats(
    stats: dict | None, *, prefill_s: float, prefill_tokens: int,
    prompt_rows: int, decode_s: float, gen_tokens: int, gen_rows: int,
) -> dict:
    """Fold one wave's timing/token counts into a round's running stats —
    the ``last_round_stats`` contract every engine shares. The trainer
    snapshots this per round (like ``last_pool_stats``) and derives the
    ``engine/prefill_tok_s`` / ``engine/decode_tok_s`` / ``engine/mfu``
    metric series from it."""
    if stats is None:
        stats = {
            "prefill_s": 0.0, "prefill_tokens": 0, "prompt_rows": 0,
            "decode_s": 0.0, "gen_tokens": 0, "gen_rows": 0,
        }
    stats["prefill_s"] += prefill_s
    stats["prefill_tokens"] += prefill_tokens
    stats["prompt_rows"] += prompt_rows
    stats["decode_s"] += decode_s
    stats["gen_tokens"] += gen_tokens
    stats["gen_rows"] += gen_rows
    # monotonic generated-token counter (ISSUE 8): the one series the live
    # endpoint and the driver's fleet aggregator derive tok/s from — one
    # locked dict write per WAVE, not per token
    if gen_tokens:
        telemetry.counter_add(obs.OBS_GEN_TOKENS, gen_tokens)
    return stats


def pool_nbytes(*trees) -> int:
    """Total bytes of the KV buffers a chunked program must alias in place
    (the denominator of compile_chunk_guarded's double-buffer check)."""
    return sum(
        x.nbytes for x in jax.tree_util.tree_leaves(trees)
    )


def arg_shape_signature(args, kwargs=None) -> tuple:
    """Hashable shape/dtype signature of a call's array leaves — the
    "shape signature" half of the obs compile tracker's key (non-array
    leaves are value-like and excluded: their churn is not a retrace)."""
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    return tuple(
        (tuple(x.shape), jnp.dtype(x.dtype).name)
        for x in leaves
        if hasattr(x, "shape") and hasattr(x, "dtype")
    )


def lora_signature(lora):
    """Hashable (structure, leaf shapes/dtypes) key for an adapter pytree.
    Compiled executables (unlike jits) raise on a structurally different
    tree instead of retracing, so chunk-program caches must key on this."""
    return (
        jax.tree_util.tree_structure(lora),
        tuple(
            (tuple(x.shape), jnp.dtype(x.dtype).name)
            for x in jax.tree_util.tree_leaves(lora)
        ),
    )


def make_swap_aware_chunk_step(mailbox, lora_cell: list, steps_seen: list,
                               k: int, max_steps: int, chunk_fn, lora0,
                               rebuild, run_chunk, run_step):
    """Chunk-dispatch step closure shared by the dense, paged-wave, and
    sharded engines: consumes in-flight adapter swaps at chunk boundaries,
    and refetches the chunk program from its signature-keyed cache when a
    swap changes the adapter's STRUCTURE (e.g. a None-adapter round
    receiving its first adapter) — compiled executables raise on a
    structurally different pytree instead of retracing (ADVICE r3).

    When the new signature's program fell back (memory guard / compile
    failure), the round finishes per-step at the same k-step cadence,
    capped at ``max_steps`` total: the per-step functions are UNGUARDED
    (they clamp-write onto the last output column past ``max_steps``),
    and the chunk program's scan body is unguarded too
    (scan_steps_guarded), so the HOST cadence is what keeps every
    dispatched step below ``max_steps``.

    ``rebuild(lora, state) -> program|None``;
    ``run_chunk(program, lora, state) -> state``;
    ``run_step(lora, state) -> state``.
    """
    cell = [chunk_fn, lora_signature(lora0)]

    def step(s):
        # in-flight swaps land at chunk boundaries: the recorded swap step
        # is the first position decoded under the new adapter
        prev = lora_cell[0]
        mailbox._take_pending_lora(lora_cell, steps_seen[0])
        if lora_cell[0] is not prev:
            sig = lora_signature(lora_cell[0])
            if sig != cell[1]:
                cell[0] = rebuild(lora_cell[0], s)
                cell[1] = sig
        start = steps_seen[0]
        steps_seen[0] += k
        if cell[0] is None:
            # min() is defensive: every caller now floor-divides the cadence
            # (run_nondivisor_tail), so start + k <= max_steps always holds
            for _ in range(min(k, max_steps - start)):
                s = run_step(lora_cell[0], s)
            return s
        return run_chunk(cell[0], lora_cell[0], s)

    return step


def pick_chunk(scan_chunk: int, max_steps: int) -> int:
    """Steps-per-dispatch for a wave of ``max_steps``: the largest divisor
    of ``max_steps`` that is ≤ ``scan_chunk``, preferred over a floor
    cadence with a per-step tail — at 1,200 steps and scan_chunk=64 the
    divisor 60 gives 20 full chunks and no tail, vs 18 chunks + 48
    per-step dispatches (each a ~40 ms tunnel round trip). Falls back to
    ``min(scan_chunk, max_steps)`` (run_nondivisor_tail handles the
    remainder) when the best divisor would lose more than half the
    requested amortization (e.g. a prime max_steps)."""
    k = max(1, min(scan_chunk, max_steps))
    best = max((d for d in range(1, k + 1) if max_steps % d == 0), default=1)
    return best if best * 2 > k else k


def run_nondivisor_tail(mailbox, lora_cell: list, steps_seen: list,
                        rem: int, state, run_step):
    """Finish a chunked wave's non-divisor tail with per-step dispatches —
    the one copy of the cadence invariant every wave engine shares:
    unguarded scan bodies (scan_steps_guarded) must never cross
    ``max_steps``, so hosts dispatch ``max_steps // k`` full chunks and
    run the remaining ``rem`` steps here (skipped once every row hit
    EOS). The in-flight-swap recording protocol matches the main loops:
    consume pending adapters before each step, advancing ``steps_seen``.
    ``run_step(lora, state) -> state`` — the same closure shape
    ``make_swap_aware_chunk_step`` takes."""
    # graftcheck: hot-region decode-tail
    # graftcheck: disable=GC301 -- one blocking all-done read per WAVE at tail entry, not per decode step
    if not rem or bool(np.asarray(state.done).all()):
        return state
    for _ in range(rem):
        mailbox._take_pending_lora(lora_cell, steps_seen[0])
        steps_seen[0] += 1
        state = run_step(lora_cell[0], state)
    # graftcheck: end-hot-region
    return state


def run_decode_loop(step_fn, state, max_steps: int, decode_chunk: int):
    """Host-dispatched decode loop shared by the dense and paged engines:
    call ``step_fn(state) -> state`` up to ``max_steps`` times with async
    early exit.

    Every ``check`` steps a COPY of the done flags (the original is donated
    into the next step) starts an async device→host transfer; the oldest
    snapshot is read only once a newer one is in flight, so the read waits on
    a transfer that finished steps ago, never on the device's current step.
    Worst-case overshoot after all rows hit EOS is ~2·check steps — the
    fixed-shape analogue of continuous batching draining its tail."""
    from collections import deque

    check = max(1, min(decode_chunk, 16))
    snapshots: deque = deque()
    steps_done = 0
    # graftcheck: hot-region decode
    while steps_done < max_steps:
        state = step_fn(state)
        steps_done += 1
        if steps_done % check == 0 or steps_done == max_steps:
            snap = jnp.copy(state.done)
            try:
                snap.copy_to_host_async()
            except AttributeError:
                pass
            snapshots.append(snap)
            stop = False
            while len(snapshots) > 1:
                # delayed read of an ASYNC-copied snapshot: a newer copy is
                # already in flight, so this waits on a transfer that
                # finished ~check steps ago, never on the current step
                # graftcheck: disable=GC301 -- reads a finished async copy >=1 check-intervals old
                if bool(np.asarray(snapshots.popleft()).all()):
                    stop = True
                    break
            if stop:
                break
    # graftcheck: end-hot-region
    return state


class LoraMailbox:
    """In-flight weight-update mailbox shared by every engine (PipelineRL —
    see ``push_lora``). ``_swapped_lora`` carries a consumed swap across the
    WAVES of one round (each wave builds a fresh closure from the
    round-entry adapter, which would otherwise silently revert the swap);
    ``_reset_lora_mailbox_round`` runs at round entry so a new round's
    trainer-passed adapter supersedes the carry."""

    # single-slot pending mailbox: (adapter, version) written/consumed as
    # ONE reference so the learner thread's push can never be paired with a
    # stale partner field by the concurrently-consuming generation thread
    _pending: tuple | None = None
    _swapped_lora = None
    # the adapter (and its version) the latest consumed swap SUPERSEDED —
    # i.e. the policy's own previous LoRA version. The speculative
    # self-drafter runs it as the draft model (PipelineRL's observation
    # that recent-checkpoint weights stay near-on-policy makes it a
    # high-acceptance draft source for free), and the (step, version) swap
    # log above gives exact draft/target version bookkeeping. Retention is
    # OPT-IN (_track_prev_lora — set by engines running the self drafter):
    # only that drafter reads the slot, and unconditional retention would
    # pin a whole extra adapter version in device memory for the engine's
    # lifetime on runs that never consume it
    _track_prev_lora = False
    _prev_lora = None
    _prev_lora_version: int | None = None

    def _pending_mu(self) -> threading.Lock:
        # lazily per-instance (the mixin has no __init__); dict.setdefault
        # is atomic under the GIL, so two racing first-callers agree
        mu = self.__dict__.get("_pending_mu_lock")
        if mu is None:
            mu = self.__dict__.setdefault(
                "_pending_mu_lock", threading.Lock()
            )
        return mu

    def push_lora(self, lora, version: int | None = None) -> None:
        """In-flight weight update (PipelineRL-style): the next dispatched
        decode step onwards samples under this adapter, without waiting for
        the round to drain. Adapter shapes must match (the jitted step sees
        new VALUES, not new shapes — no recompile).

        Semantics: KV already resident stays as the OLD adapter computed it
        (the stale-KV regime in-flight updating accepts); post-swap tokens
        sample from the new adapter's forward over that cache. The captured
        per-token behavior logprob is the TRUE probability of that mixed
        sampling process, which is exactly what the PPO-clip ratio needs —
        enable via ``--inflight_weight_updates`` (requires clip_ratio > 0).

        ``version`` is the learner's weight_version for this adapter: the
        consumed swap records (step, version) pairs (``last_swap_steps`` /
        ``last_swap_versions``) so the trainer can tag every generated
        position with the policy version that sampled it
        (rollout/trajectory.py version tags)."""
        # push time rides in the same single-slot tuple (one reference —
        # the consuming thread can never pair it with a stale partner
        # field); the consume observes push→swap latency from it. The lock
        # orders the slot against discard_pending_at_or_below, which must
        # never clobber a newer push that lands mid-check.
        with self._pending_mu():
            self._pending = (lora, version, time.perf_counter())

    def discard_pending_at_or_below(self, version: int) -> None:
        """Drop a pending swap whose version is already covered by the
        adapter a round is about to open with (remote workers: the weight
        bus pushes every update into the mailbox so MID-round swaps work;
        the entry push would otherwise replay as a phantom step-0 swap).
        Atomic with ``push_lora``: a strictly newer push landing
        concurrently survives."""
        with self._pending_mu():
            pending = self._pending
            if (
                pending is not None and pending[1] is not None
                and int(pending[1]) <= int(version)
            ):
                self._pending = None

    def _take_pending_lora(self, lora_cell: list, dispatched: int) -> None:
        with self._pending_mu():
            pending, self._pending = self._pending, None
        if pending is not None:
            lora, version, pushed_t = pending
            # weight-sync observability (ISSUE 8): how long the learner's
            # push sat in the mailbox before a decode dispatch consumed it
            telemetry.hist_observe(
                obs.SWAP_LATENCY_MS,
                (time.perf_counter() - pushed_t) * 1e3,
            )
            if self._track_prev_lora:
                # the adapter being superseded becomes "the previous
                # version" — its own version is the last swap's (None
                # before any swap: the round-entry adapter's version is
                # the trainer's to know)
                self._prev_lora = lora_cell[0]
                self._prev_lora_version = (
                    self.last_swap_versions[-1] if self.last_swap_versions
                    else None
                )
            self._swapped_lora = lora
            lora_cell[0] = lora
            self.last_swap_steps.append(dispatched)
            self.last_swap_versions.append(version)

    def _round_entry_lora(self, lora):
        """Adapter a wave should open with: the in-round swap if one
        happened, else the caller's."""
        return self._swapped_lora if self._swapped_lora is not None else lora

    def _reset_lora_mailbox_round(self) -> None:
        self._swapped_lora = None


class GenerationEngine(LoraMailbox):
    """Compiled rollout engine bound to (model config, shapes, eos/pad ids).

    ``generate`` is the ``vllm_generate`` equivalent: prompts in, per-candidate
    token arrays + lengths out (decode to text happens host-side).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_prompt_tokens: int,
        max_new_tokens: int,
        eos_token_ids: Sequence[int],
        pad_token_id: int,
        lora_scale: float = 1.0,
        cache_dtype=jnp.bfloat16,
        # "int8": fused-dequant cache (paged parity). None = consult the
        # autotune plan DB (ExecutionPlan.kv_format; empty DB = "none",
        # byte-identical to the historical default); an explicit
        # "none"/"int8" always wins (the decode_scan_chunk convention)
        kv_quant: str | None = None,
        attn_impl: str = "reference",
        decode_chunk: int = 128,
        # None = consult the autotune plan DB (falls back to 0, the
        # historical default); an explicit int — including 0 — always wins
        scan_chunk: int | None = None,
        prompt_buckets: Sequence[int] | None = None,
        max_concurrent_rows: int = 0,  # 0 = unlimited (vLLM max_num_seqs)
        capture_logprobs: bool = False,  # record behavior logprobs (clip_ratio)
        cache_read_formulation: str | None = None,  # None = auto by scan_chunk
        autotune: bool = True,  # False pins the static defaults (no DB read)
        plan_db: str | None = None,  # plan-DB path; None = env/default path
        # expected concurrent candidate rows, for plan-key selection ONLY
        # (batch size arrives at generate()): callers that know the round
        # volume (bench) pass it so their own resolve and the engine's hit
        # the SAME DB entry; 0 = the any-rows entry
        plan_rows: int = 0,
    ):
        self.max_concurrent_rows = max_concurrent_rows
        self.capture_logprobs = capture_logprobs
        if scan_chunk is not None and scan_chunk < 0:
            raise ValueError(f"scan_chunk must be >= 0, got {scan_chunk}")
        if kv_quant not in (None, "none", "int8"):
            # validated BEFORE plan resolution so a typo'd kwarg fails with
            # the engine's own contract, not a plan-field error
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        if cache_read_formulation not in (None, "dot", "mulred"):
            raise ValueError(
                "cache_read_formulation must be None/'dot'/'mulred', got "
                f"{cache_read_formulation!r}")
        # Execution-plan resolution (distrl_llm_tpu/autotune): explicit
        # kwargs always win; a stored measured plan fills the rest; with no
        # DB entry the static defaults apply byte-identically. decode_path
        # is pinned to this class so bench/trace records stay honest.
        from distrl_llm_tpu.autotune import resolve_plan

        requested: dict[str, Any] = {"decode_path": "dense"}
        if scan_chunk is not None:
            requested["scan_chunk"] = scan_chunk
        if cache_read_formulation is not None:
            requested["cache_read_formulation"] = cache_read_formulation
        if prompt_buckets is not None:
            requested["prompt_buckets"] = tuple(prompt_buckets)
        if kv_quant is not None:
            # explicit "none" is a real pin (the int8-default A/B control),
            # not "unset" — the decode_scan_chunk convention
            requested["kv_format"] = kv_quant
        self.resolved_plan = resolve_plan(
            model_cfg=cfg, max_prompt_tokens=max_prompt_tokens,
            max_new_tokens=max_new_tokens, rows=plan_rows,
            requested=requested, db_path=plan_db, enabled=autotune,
        )
        plan = self.resolved_plan.plan
        scan_chunk = plan.scan_chunk
        if prompt_buckets is None and plan.prompt_buckets:
            # a DB plan must never crash a run (store.py's contract): a
            # stored bucket that doesn't fit THIS engine's geometry (e.g. a
            # cross-geometry hand-copied entry) is dropped with a warning,
            # where the same bucket passed explicitly would raise below
            fitting = tuple(
                b for b in plan.prompt_buckets if 0 < b <= max_prompt_tokens
            )
            if fitting != plan.prompt_buckets:
                _logger.warning(
                    "autotune plan buckets %s exceed max_prompt_tokens=%d — "
                    "keeping only %s (re-run tools/autotune.py for this "
                    "geometry)",
                    list(plan.prompt_buckets), max_prompt_tokens,
                    list(fitting),
                )
            prompt_buckets = fitting or None
        # plan-suggested top-p implementation; an explicit SamplingConfig
        # pin (top_p_impl / top_p_exact) still wins at generate() —
        # SamplingConfig.resolved_top_p_impl(plan_default)
        self.plan_top_p_impl = plan.top_p_impl
        self.scan_chunk = scan_chunk
        # Chunk-configured engines read the cache via multiply+reduce in BOTH
        # the chunk program and the host-dispatched steps (tail / guard
        # fallback): a dot_general over the scanned carry makes TPU layout
        # assignment insert per-leaf relayout copies that OOM the program
        # (see ops.attention.attention_cached), and using one formulation
        # everywhere keeps chunk-vs-host greedy decode bit-identical. The
        # explicit kwarg exists for parity tests and on-chip formulation
        # A/Bs; None picks the right one for the dispatch mode.
        self.cache_read_formulation = (
            plan.cache_read_formulation
            or ("mulred" if scan_chunk else "dot"))
        # buckets where the chunked program compiled WITHOUT double-buffering
        # the KV cache (memory_analysis guard) hold their compiled fn here;
        # buckets where it did are marked None and use the host loop
        self._chunk_compiled: dict[int, Any] = {}
        self.cfg = cfg
        self.max_prompt_tokens = max_prompt_tokens
        self.max_new_tokens = max_new_tokens
        self.max_total = max_prompt_tokens + max_new_tokens
        cfg.check_within_window(self.max_total)
        self.eos_ids = jnp.asarray(list(eos_token_ids), jnp.int32)
        self.pad_id = int(pad_token_id)
        self.lora_scale = lora_scale
        # post-resolution KV format: an explicit kwarg already rode the
        # requested dict (wins per-field); unset adopts the stored plan's
        # kv_format, defaulting to the historical "none"
        kv_quant = kv_quant if kv_quant is not None else (
            plan.kv_format or "none"
        )
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        # "int8" rides the cache_dtype static arg as a sentinel: _prefill
        # builds the scale-carrying cache and the forward's dense-cache
        # branch switches to attention_cached_quant
        self.cache_dtype = "int8" if kv_quant == "int8" else cache_dtype
        self.kv_quant = kv_quant
        self.attn_impl = attn_impl
        self.decode_chunk = decode_chunk
        # Length bucketing (SURVEY §2b N1 "static batch + length bucketing
        # first"): each generation round runs at the smallest bucket holding
        # its longest real prompt, cutting prefill FLOPs and every decode
        # step's KV length for short batches. One compile per bucket used.
        buckets = sorted(set(prompt_buckets or [])) or [max_prompt_tokens]
        if any(b <= 0 or b > max_prompt_tokens for b in buckets):
            raise ValueError(f"buckets must be in (0, {max_prompt_tokens}]: {buckets}")
        if buckets[-1] != max_prompt_tokens:
            buckets.append(max_prompt_tokens)
        self.prompt_buckets = buckets
        self._compiled: dict[int, tuple] = {}
        # concurrent generate() calls (hybrid rollout: actor + learner
        # submeshes decode in parallel threads) share the compiled-fn cache
        self._compile_mu = threading.Lock()
        # in-flight weight-update mailbox (LoraMailbox base): consumed-swap
        # steps and the learner weight_version pushed with each adapter
        self.last_swap_steps: list[int] = []
        self.last_swap_versions: list[int | None] = []
        # per-round prefill/decode timing + token counts (telemetry:
        # accumulate_round_stats); snapshotted by the trainer per round
        self.last_round_stats: dict | None = None

        # n and max_steps are static (shape-determining)
        self._decode_init = jax.jit(
            partial(_decode_init, pad_id=self.pad_id),
            static_argnames=("n", "max_steps"),
            # no cache donation: the candidate fan-out (jnp.repeat to B·n
            # rows) allocates fresh buffers the prefill cache can't alias
        )

    @property
    def scan_chunk_active(self) -> bool | None:
        """Whether chunked decode actually ran: True once a chunked program
        compiled AND passed the memory guard, False if every attempt fell
        back to the host loop, None before the first decode (or scan_chunk=0).
        Bench records report this so a fallback can't masquerade as a
        chunked measurement."""
        if not self.scan_chunk or not self._chunk_compiled:
            return None
        return any(v is not None for v in self._chunk_compiled.values())

    def bucket_for(self, prompt_mask) -> int:
        """The bucket a batch with this mask will run at: the smallest bucket
        holding the longest real prompt."""
        if len(self.prompt_buckets) == 1:
            return self.prompt_buckets[0]
        longest = int(np.asarray(prompt_mask).sum(axis=-1).max())
        return next(bb for bb in self.prompt_buckets if bb >= max(longest, 1))

    def is_warm(self, bucket: int) -> bool:
        """Whether this bucket's programs have been built (first use of a
        bucket pays XLA compilation — callers with hang detectors exempt cold
        buckets, trainer._call_engine)."""
        return bucket in self._compiled

    def _fns_for_bucket(self, bucket: int) -> tuple:
        """(prefill, decode_step) jits for one prompt bucket — the step is
        donated so the cache updates in place (verified zero HBM temp bytes
        via compile memory_analysis)."""
        with self._compile_mu:
            if bucket not in self._compiled:
                obs.note_compile("dense/bucket_fns", (bucket,))
                prefill = jax.jit(
                    partial(
                        _prefill, cfg=self.cfg, max_total=bucket + self.max_new_tokens,
                        lora_scale=self.lora_scale, cache_dtype=self.cache_dtype,
                        attn_impl=self.attn_impl,
                    )
                )
                step = jax.jit(
                    partial(
                        _decode_step, cfg=self.cfg, prompt_len=bucket,
                        pad_id=self.pad_id, lora_scale=self.lora_scale,
                        attn_impl=self.attn_impl,
                        capture_logprobs=self.capture_logprobs,
                        cache_read_formulation=self.cache_read_formulation,
                    ),
                    donate_argnames=("state",),
                    static_argnames=("top_p_impl",),
                )
                self._compiled[bucket] = (prefill, step)
            return self._compiled[bucket]

    def _chunk_fn_for_bucket(
        self, bucket: int, max_steps: int, params, lora, state, rng,
        temperature, top_p, top_p_impl: str,
    ):
        """Compiled K-steps-per-dispatch program for this (bucket, shapes)
        combination, or None where the host loop should be used instead.

        The program is explicitly lowered + compiled so its
        ``memory_analysis`` can be inspected BEFORE it ever runs: if the TPU
        compiler double-buffered the scan carry (temp bytes on the order of
        the KV cache — the failure mode that made the host-dispatched loop
        the default, see module docstring) the chunked program would OOM the
        very configs it is meant to speed up, so it is rejected and the wave
        falls back to one dispatch per step. Compile failures (e.g. a Mosaic
        lowering surprise on a new config) also fall back rather than kill
        the round."""
        bn = state.out.shape[0]
        # lora=None rounds and adapter rounds need separate cache entries
        # (Compiled executables raise on structure changes, see
        # lora_signature)
        key = (bucket, max_steps, top_p_impl, bn, lora_signature(lora))
        with self._compile_mu:
            if key in self._chunk_compiled:
                return self._chunk_compiled[key]
            fn = jax.jit(
                partial(
                    _decode_chunk, chunk=pick_chunk(self.scan_chunk, max_steps),
                    cfg=self.cfg, prompt_len=bucket,
                    pad_id=self.pad_id, lora_scale=self.lora_scale,
                    attn_impl=self.attn_impl, top_p_impl=top_p_impl,
                    capture_logprobs=self.capture_logprobs,
                    cache_read_formulation=self.cache_read_formulation,
                ),
                donate_argnames=("state",),
            )
            cache_bytes = sum(
                x.nbytes for x in jax.tree_util.tree_leaves(state.cache)
            )
            fusion_bytes = 0
            if self.cache_read_formulation == "mulred":
                # per-layer _gqa_mulred broadcast product at this bucket's
                # full window — the unfused-temp envelope (ADVICE r5)
                from distrl_llm_tpu.ops.attention import mulred_broadcast_bytes

                fusion_bytes = mulred_broadcast_bytes(
                    bn, self.cfg.num_kv_heads,
                    self.cfg.num_heads // self.cfg.num_kv_heads,
                    self.cfg.head_dim, bucket + self.max_new_tokens,
                )
            compiled = compile_chunk_guarded(
                fn, cache_bytes, f"scan_chunk={self.scan_chunk} bucket={bucket}",
                params, lora, state, rng, fusion_bytes=fusion_bytes,
                eos_ids=self.eos_ids,
                temperature=temperature, top_p=top_p,
            )
            self._chunk_compiled[key] = compiled
            return compiled

    def generate(
        self,
        params: Params,
        lora: Params | None,
        prompt_ids: np.ndarray,  # [B, P] left-padded to max_prompt_tokens
        prompt_mask: np.ndarray,
        sampling: SamplingConfig,
        rng: jax.Array,
    ) -> GenerationResult:
        # a new round supersedes any swap consumed during the previous one
        # (the trainer hands the freshest adapter at round entry)
        self._reset_lora_mailbox_round()
        self.last_round_stats = None  # waves of THIS round accumulate below
        return generate_in_waves(
            self._generate_wave, self.max_concurrent_rows, params, lora,
            prompt_ids, prompt_mask, sampling, rng, self.pad_id,
        )

    def _generate_wave(
        self, params, lora, prompt_ids, prompt_mask,
        sampling: SamplingConfig, rng: jax.Array,
    ) -> GenerationResult:
        b, p = prompt_ids.shape
        if p != self.max_prompt_tokens:
            raise ValueError(f"prompts must be padded to {self.max_prompt_tokens}, got {p}")
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        # an in-flight swap from an earlier wave of THIS round also covers
        # this wave's prefill (its rows haven't sampled yet)
        lora = self._round_entry_lora(lora)

        # bucket selection: smallest bucket holding the longest real prompt;
        # prompts are left-padded, so the bucket keeps the trailing columns
        bucket = self.bucket_for(prompt_mask)
        if bucket < p:
            prompt_ids = prompt_ids[:, p - bucket:]
            prompt_mask = prompt_mask[:, p - bucket:]
        prefill_fn, decode_step_fn = self._fns_for_bucket(bucket)

        prefill_tokens = int(np.asarray(prompt_mask).sum())
        t0 = time.perf_counter()
        with telemetry.span("engine/prefill", rows=b, bucket=bucket,
                            tokens=prefill_tokens):
            cache, key_mask, last_logits = prefill_fn(
                params, lora, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask)
            )
            # the block makes the prefill/decode timing split honest (the
            # decode loop's final readback syncs its side); it only forgoes
            # overlapping prefill device time with sub-ms host-side setup
            jax.block_until_ready(last_logits)
        t_prefill = time.perf_counter() - t0
        row_alive = jnp.asarray(prompt_mask).sum(axis=-1) > 0
        state = self._decode_init(
            cache, key_mask, last_logits, row_alive,
            n=sampling.n, max_steps=max_steps,
        )
        temperature = jnp.asarray(sampling.temperature, jnp.float32)
        top_p = jnp.asarray(sampling.top_p, jnp.float32)
        top_p_impl = sampling.resolved_top_p_impl(self.plan_top_p_impl)
        # measured bytes/token source (ISSUE 15; DISTRL_MEASURE_COST=1
        # only): file the step program's XLA cost_analysis once
        obs.maybe_record_step_cost(
            "decode_step/dense", decode_step_fn, params, lora, state, rng,
            eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
            top_p_impl=top_p_impl,
        )
        lora_cell = [lora]
        steps_seen = [0]
        # explicit enter/exit: the span must cover BOTH dispatch branches
        # and the final device→host readback that syncs the decode
        t1 = time.perf_counter()
        dec_span = telemetry.span("engine/decode", rows=b * sampling.n,
                                  bucket=bucket)
        dec_span.__enter__()

        chunk_fn = (
            self._chunk_fn_for_bucket(
                bucket, max_steps, params, lora, state, rng,
                temperature, top_p, top_p_impl,
            )
            # > 1, matching the paged engines: a scan-of-one program has no
            # fusion benefit but would still report scan_chunk_active=True
            if self.scan_chunk > 1 and max_steps > 1
            else None
        )
        if chunk_fn is not None:
            k = pick_chunk(self.scan_chunk, max_steps)

            def run_step(l, s):
                return decode_step_fn(
                    params, l, s, rng, eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p,
                    top_p_impl=top_p_impl,
                )

            step = make_swap_aware_chunk_step(
                self, lora_cell, steps_seen, k, max_steps, chunk_fn, lora,
                rebuild=lambda l, s: self._chunk_fn_for_bucket(
                    bucket, max_steps, params, l, s, rng,
                    temperature, top_p, top_p_impl,
                ),
                run_chunk=lambda fn, l, s: fn(
                    params, l, s, rng, eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p,
                ),
                run_step=run_step,
            )
            # one "step" per chunk; snapshot done flags every chunk
            # (check=1), then the shared non-divisor tail
            full, rem = divmod(max_steps, k)
            state = run_decode_loop(step, state, full, 1)
            state = run_nondivisor_tail(
                self, lora_cell, steps_seen, rem, state, run_step)
        else:

            def step(s):
                # in-flight weight-update mailbox: swap BEFORE sampling, so
                # the recorded swap step is the first position decoded under
                # the new adapter (dense decode: step index == position)
                self._take_pending_lora(lora_cell, steps_seen[0])
                steps_seen[0] += 1
                return decode_step_fn(
                    params, lora_cell[0], s, rng,
                    eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
                    top_p_impl=top_p_impl,
                )

            state = run_decode_loop(step, state, max_steps, self.decode_chunk)
        out = np.asarray(state.out).reshape(b, sampling.n, max_steps)
        lengths = np.asarray(state.lengths).reshape(b, sampling.n)
        logps = (
            np.asarray(state.logps).reshape(b, sampling.n, max_steps)
            if self.capture_logprobs else None
        )
        gen_tokens = int(lengths.sum())
        dec_span.set(tokens=gen_tokens, steps=steps_seen[0])
        dec_span.__exit__(None, None, None)
        self.last_round_stats = accumulate_round_stats(
            self.last_round_stats, prefill_s=t_prefill,
            prefill_tokens=prefill_tokens, prompt_rows=b,
            decode_s=time.perf_counter() - t1, gen_tokens=gen_tokens,
            gen_rows=b * sampling.n,
        )
        return GenerationResult(tokens=out, lengths=lengths, logprobs=logps)
