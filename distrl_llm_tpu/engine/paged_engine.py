"""Paged-KV generation engine: packed ragged decode (the full N1 core).

Where ``engine.GenerationEngine`` keeps a dense [B, K, hd, Smax] cache that
every decode step reads in full, this engine stores KV in PAGES and reads
each row's true [0, length) prefix only — vLLM's PagedAttention bandwidth
model (reference: requirements.txt:6, entered via ``policy.fast_generate``,
distributed_actor.py:148–150), built TPU-native:

* prompts are packed (left padding removed) during a jitted prefill, so a
  short prompt costs its own length, not ``max_prompt_tokens``;
* decode attention is jaxlib's Pallas ``paged_attention`` kernel on TPU (jnp
  reference elsewhere — ops/paged.py);
* candidates SHARE their prompt's full prompt pages (vLLM prefix sharing):
  the page table points each candidate's leading columns at a shared pool
  written once by prefill; only the partial last prompt page — extended in
  place by decode — is private per candidate. Prompt KV memory is ~B copies
  instead of B·n. The table is data-dependent but shape-static, so it rides
  as a traced array (an RL rollout round is a fixed batch, so vLLM's dynamic
  C++ block allocator reduces to this host-computed table);
* the host-dispatched donated decode-step loop, candidate fan-out after a
  shared prefill, and async early-exit snapshots all match the dense engine.

Parallelism note: this engine targets one rollout replica — a single chip or
a TP group (KV heads shard over "tp"). Data-parallel scale-out has two
paths: one engine per replica (the remote-worker fan-out,
distributed/remote_engine.py — vLLM's one-engine-per-GPU model), or ONE
wave-mode engine whose page pool is partitioned over the dp axis via
shard_map (engine/sharded_paged.py, reusing this module's jitted pieces as
the shard-local program).
"""

from __future__ import annotations

import threading
import time
from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.config import SamplingConfig
from distrl_llm_tpu.control.governor import CONTROL_SHED_GROUPS
from distrl_llm_tpu.engine.engine import (
    GenerationResult,
    LoraMailbox,
    accumulate_round_stats,
    cached_chunk_program,
    generate_in_waves,
    scan_steps_guarded,
    lora_signature,
    make_swap_aware_chunk_step,
    pool_nbytes,
    pick_chunk,
    run_decode_loop,
    run_nondivisor_tail,
)
from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.models.transformer import forward
from distrl_llm_tpu.ops.paged import (
    make_page_table,
    pages_per_seq,
)
from distrl_llm_tpu.ops.sampling import sample_with_logprob, token_logprob

# telemetry series owned by the paged engine (one owner per name —
# graftcheck GC2xx). ops/* attribute the Pallas grid-launch budget;
# engine/spec_* are the speculative-decoding accounting trace_report and
# the spec smoke read.
OPS_PAGED_GRID_STEPS = "ops/paged_grid_steps"              # counter
OPS_PAGED_US_PER_GRID_STEP = "ops/paged_us_per_grid_step"  # gauge
ENGINE_SPEC_DRAFT_RESIZES = "engine/spec_draft_resizes"    # counter
ENGINE_SPEC_ACCEPT_RATE = "engine/spec_accept_rate"        # gauge
ENGINE_SPEC_EMIT_TOKENS = "engine/spec_emit_tokens"        # hist (binned)
ENGINE_SPEC_VERIFY_GRID_STEPS = "engine/spec_verify_grid_steps"  # counter
# continuous-batching admission accounting (ISSUE 12): candidates admitted
# into freed slots AFTER the round's first dispatch (the backfill the fixed
# episode batch never gets), and lazy per-group prompt prefills run by the
# continuous-admission scheduler
ENGINE_BACKFILL_ADMITS = "engine/backfill_admits"          # counter
ENGINE_CONT_PREFILLS = "engine/cont_prefills"              # counter
# multi-turn episode continuation (ISSUE 17): slots resumed in place after
# the turn hook injected an observation, and the conversation-prefix tokens
# whose re-prefill that in-place resume avoided (KV stayed resident)
ENGINE_TURN_RESUMES = "engine/turn_resumes"                # counter
ENGINE_TURN_PREFILL_SAVED = "engine/turn_prefill_saved_tokens"  # counter

Params = dict[str, Any]


class _PagedDecodeState(NamedTuple):
    step: jax.Array  # []
    out: jax.Array  # [Bn, T]
    logps: jax.Array  # [Bn, T] raw-model logprob of each sampled token
    gen_lengths: jax.Array  # [Bn] generated token counts (incl. EOS)
    done: jax.Array  # [Bn] bool
    logits: jax.Array  # [Bn, V]
    seq_lengths: jax.Array  # [Bn] tokens resident in the cache per row
    k_pages: tuple  # L × [K, total_pages, ps, hd]
    v_pages: tuple


class _RefillState(NamedTuple):
    """Decode state for the SLOT-REFILL scheduler (continuous batching).

    R decode slots run concurrently; each slot holds one candidate (or the
    dead sentinel ``total``). Completed candidates' tokens live in the
    [total, T] ``out`` buffer indexed by candidate id, so a slot can be
    re-assigned mid-decode without disturbing finished output."""

    step: jax.Array  # []
    alive_steps: jax.Array  # [] sum over steps of alive-slot count
    out: jax.Array  # [total, T] pad-filled; scatter-written by candidate id
    logps_buf: jax.Array  # [total, T] behavior logprobs, scatter-written
    lengths_buf: jax.Array  # [total] per-candidate generated counts
    cand: jax.Array  # [R] candidate id per slot (== total → dead slot)
    done: jax.Array  # [R]
    logits: jax.Array  # [R, V]
    seq_lengths: jax.Array  # [R] tokens resident per slot
    gen_lengths: jax.Array  # [R] tokens generated by the current occupant
    page_indices: jax.Array  # [R, width] — rewritten per admit
    k_pages: tuple
    v_pages: tuple


def _pack_rows(ids: jax.Array, mask: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Left-padded [B, P] → packed [B, P] (first real token at column 0)."""
    b, p = ids.shape
    real_len = mask.sum(axis=-1).astype(jnp.int32)  # [B]
    shift = p - real_len  # left-pad amount per row
    cols = (jnp.arange(p)[None, :] + shift[:, None]) % p
    packed = jnp.take_along_axis(ids, cols, axis=1)
    packed_mask = (jnp.arange(p)[None, :] < real_len[:, None]).astype(mask.dtype)
    return packed * packed_mask, packed_mask, real_len



def _record_grid_telemetry(num_layers: int, steps: int, decode_s: float,
                           *, per_call: int, calls_per_step: int = 1):
    """Paged grid-overhead telemetry (the BASELINE r5 model: decode's cost
    floor is Pallas grid steps × ~1 µs/grid-step). ``per_call`` is the
    dispatch chain's trace-time analytic count for the CALLER's own
    geometry and LIVE row count (engines derive it from their exact
    dispatch-choice record — see ``_grid_steps_per_call`` — never from a
    process-global cache, which could hold another engine's geometry or a
    stale batch);
    ``calls_per_step`` is the op calls per layer per dispatched step (1
    for plain decode, draft_len+1 for the speculative verify fan-out).
    Total grid steps this round = per-call × calls/step × layers × steps.
    The realized µs/grid-step gauge is an upper bound (decode seconds also
    carry sampling and non-attention layers), but it makes the
    launch-overhead regime visible in every trace without a bench run."""
    if not per_call or not steps:
        return
    total = per_call * calls_per_step * num_layers * steps
    telemetry.counter_add(OPS_PAGED_GRID_STEPS, total)
    if decode_s > 0:
        telemetry.gauge_set(OPS_PAGED_US_PER_GRID_STEP, decode_s * 1e6 / total)


def _paged_prefill(params, lora, prompt_ids, prompt_mask, *, cfg: ModelConfig,
                   prompt_pages: int, page_size: int, lora_scale: float,
                   cache_dtype, attn_impl: str, kv_quant: str = "none"):
    """Pack prompts, run one forward over B rows, return per-prompt page
    tiles [K, B, prompt_pages, ps, hd] per layer + sampling logits."""
    b, p = prompt_ids.shape
    packed_ids, packed_mask, real_len = _pack_rows(prompt_ids, prompt_mask)
    pad_to = prompt_pages * page_size
    packed_ids = jnp.pad(packed_ids, ((0, 0), (0, pad_to - p)))
    packed_mask = jnp.pad(packed_mask, ((0, 0), (0, pad_to - p)))

    shape = (cfg.num_kv_heads, b * prompt_pages, page_size, cfg.head_dim)

    def make_pages():
        if kv_quant == "int8":
            # int8 KV: halves resident cache memory (see the bandwidth caveat
            # on ops/paged.py:quantize_pages)
            from distrl_llm_tpu.ops.paged import init_quantized_pages

            return init_quantized_pages(shape)
        return jnp.zeros(shape, cache_dtype)

    cache = {
        "k": tuple(make_pages() for _ in range(cfg.num_layers)),
        "v": tuple(make_pages() for _ in range(cfg.num_layers)),
        "lengths": real_len,
        "page_indices": jnp.asarray(
            make_page_table(b, pad_to, page_size)
        ),
    }
    positions = jnp.broadcast_to(
        jnp.arange(pad_to, dtype=jnp.int32)[None, :], (b, pad_to)
    )
    logits, cache = forward(
        params, cfg, packed_ids, attention_mask=packed_mask,
        positions=positions, lora=lora, lora_scale=lora_scale,
        kv_cache=cache, attn_impl=attn_impl, page_size=page_size,
        # each packed row's sampling logits sit at its LAST REAL position —
        # a per-row gather that also skips the [B, Ppad, V] lm_head
        logits_positions=jnp.maximum(real_len - 1, 0),
    )
    return cache["k"], cache["v"], logits[:, 0], real_len


def _grow_pool(pages, extra_pages: int):
    """Append ``extra_pages`` zeroed pages to a pool [K, S, ps, tail] →
    [K, S+extra, ps, tail] (quantized pools grow weight + scales alike)."""

    def grow(arr):
        kh, s_, ps, tail = arr.shape
        out = jnp.zeros((kh, s_ + extra_pages, ps, tail), arr.dtype)
        return out.at[:, :s_].set(arr)

    from distrl_llm_tpu.ops.paged import is_quantized_pages

    if is_quantized_pages(pages):
        return type(pages)(weight=grow(pages.weight), scales=grow(pages.scales))
    return grow(pages)


def _copy_pages(pages, src_idx, dst_idx, keep_mask=None):
    """``pages[:, dst_idx] = pages[:, src_idx]`` — the partial-prompt-page
    copy shared by fan-out and refill-admit. With ``keep_mask``, rows where
    it is False keep their current destination content (quantized pools copy
    weight + scales alike, preserving the (int8, scale) pairing)."""

    def cp(arr):
        tile = arr[:, src_idx]
        if keep_mask is not None:
            tile = jnp.where(keep_mask[None, :, None, None], tile, arr[:, dst_idx])
        return arr.at[:, dst_idx].set(tile)

    from distrl_llm_tpu.ops.paged import is_quantized_pages

    if is_quantized_pages(pages):
        return type(pages)(weight=cp(pages.weight), scales=cp(pages.scales))
    return cp(pages)


def _page_table_rows(prompt_of, full, priv0, *, prompt_pages: int,
                     private_pages: int):
    """Page-table rows [R, width] with shared prompt prefixes: column t of
    row r holds position block t — the prompt's shared full pages below
    ``full[r]``, the row's private pages after; trailing unused columns clamp
    to a valid private page (the jnp reference gathers the whole width)."""
    width = prompt_pages + private_pages
    col = jnp.arange(width)[None, :]
    shared_entry = prompt_of[:, None] * prompt_pages + col
    private_entry = jnp.minimum(
        priv0[:, None] + (col - full[:, None]),
        priv0[:, None] + private_pages - 1,
    )
    return jnp.where(col < full[:, None], shared_entry, private_entry).astype(
        jnp.int32
    )


def _cont_adopt(state, k_tiles, v_tiles, dst_idx, logits_buf, logits_row, g):
    """Adopt one lazily-prefilled group's prompt KV into the live pool
    arrays and publish its sampling logits (continuous admission).

    ``dst_idx`` [prompt_pages] is the pool-allocated chain padded with the
    scratch page — the tiles beyond the prompt's real chain carry prefill's
    pad-position garbage and land on scratch, which takes garbage writes by
    contract (duplicate scratch destinations are fine: whichever write wins
    is equally garbage). Quantized pools place weight + scales alike, so
    the (int8, scale) pairing survives adoption."""
    from distrl_llm_tpu.ops.paged import is_quantized_pages

    def place(pages, tiles):
        if is_quantized_pages(pages):
            return type(pages)(
                weight=pages.weight.at[:, dst_idx].set(tiles.weight),
                scales=pages.scales.at[:, dst_idx].set(tiles.scales),
            )
        return pages.at[:, dst_idx].set(tiles)

    state = state._replace(
        k_pages=tuple(place(p, t) for p, t in zip(state.k_pages, k_tiles)),
        v_pages=tuple(place(p, t) for p, t in zip(state.v_pages, v_tiles)),
    )
    return state, logits_buf.at[g].set(logits_row)


def _paged_fanout(prompt_k, prompt_v, last_logits, real_len, row_alive,
                  *, n: int, b: int, prompt_pages: int, private_pages: int,
                  page_size: int, max_steps: int):
    """Expand B prompts to B·n candidate rows with SHARED prompt prefixes.

    vLLM's prefix sharing, static-shape edition: every candidate's page table
    points its leading columns at the prompt's FULL pages in the shared pool
    (written once by prefill, never written again), and only the partial last
    prompt page — which decode tokens will extend in place — is copied per
    candidate into a private region alongside its decode pages. At the
    reference volume this drops prompt KV memory from B·n to ~B copies.

    Returns (state, page_indices): the table is data-DEPENDENT (each prompt's
    full-page count is real_len // page_size) but shape-static, so it rides
    as a traced array and never forces a recompile."""
    bn = b * n
    total_shared = b * prompt_pages

    full = real_len // page_size  # [B] full shared pages per prompt
    full_r = jnp.repeat(full, n)  # [Bn]
    prompt_of_row = jnp.repeat(jnp.arange(b), n)  # [Bn]
    priv0 = total_shared + jnp.arange(bn) * private_pages  # [Bn]

    page_indices = _page_table_rows(
        prompt_of_row, full_r, priv0,
        prompt_pages=prompt_pages, private_pages=private_pages,
    )

    # the partial prompt page each candidate must own privately (clamped for
    # page-aligned prompts, where the copy content is never read)
    src_partial = prompt_of_row * prompt_pages + jnp.repeat(
        jnp.minimum(full, prompt_pages - 1), n
    )

    def expand(pages):
        grown = _grow_pool(pages, bn * private_pages)
        return _copy_pages(grown, src_partial, priv0)

    k_pages = tuple(expand(x) for x in prompt_k)
    v_pages = tuple(expand(x) for x in prompt_v)
    state = _PagedDecodeState(
        step=jnp.zeros((), jnp.int32),
        out=jnp.zeros((bn, max_steps), jnp.int32),
        logps=jnp.zeros((bn, max_steps), jnp.float32),
        gen_lengths=jnp.zeros((bn,), jnp.int32),
        done=jnp.repeat(~row_alive, n, axis=0),
        logits=jnp.repeat(last_logits, n, axis=0),
        seq_lengths=jnp.repeat(real_len, n, axis=0),
        k_pages=k_pages,
        v_pages=v_pages,
    )
    return state, page_indices


def _paged_decode_step(params, lora, state: _PagedDecodeState, rng, page_indices,
                       *, cfg: ModelConfig, page_size: int, eos_ids, pad_id: int,
                       temperature, top_p, lora_scale: float, paged_impl: str,
                       pages_per_block: int = 0,
                       top_p_impl: str = "bisect", capture_logprobs: bool = False):
    """One donated decode step over the paged cache (host-loop dispatched,
    zero cache-sized temps — same design as engine._decode_step)."""
    s = state
    # fused sample+logprob when enabled (ops/sampling.py); done rows'
    # logprobs are zeroed below, so pre-substitution logprobs are
    # observably identical to the old post-substitution token_logprob
    tok, logp_s = sample_with_logprob(
        jax.random.fold_in(rng, s.step), s.logits, temperature, top_p,
        top_p_impl=top_p_impl, capture_logprob=capture_logprobs,
    )
    tok = jnp.where(s.done, pad_id, tok)
    out = jax.lax.dynamic_update_slice(s.out, tok[:, None], (0, s.step))
    if capture_logprobs:
        logp = jnp.where(s.done, 0.0, logp_s)
        logps = jax.lax.dynamic_update_slice(s.logps, logp[:, None], (0, s.step))
    else:
        logps = s.logps
    gen_lengths = s.gen_lengths + (~s.done).astype(jnp.int32)
    hit_eos = jnp.isin(tok, eos_ids)
    done = s.done | hit_eos

    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": s.seq_lengths,
        "page_indices": page_indices,
    }
    next_logits, cache = forward(
        params, cfg, tok[:, None],
        positions=s.seq_lengths[:, None],
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_impl=paged_impl,
        pages_per_block=pages_per_block,
    )
    seq_lengths = s.seq_lengths + (~s.done).astype(jnp.int32)
    return _PagedDecodeState(
        step=s.step + 1, out=out, logps=logps, gen_lengths=gen_lengths,
        done=done, logits=next_logits[:, 0], seq_lengths=seq_lengths,
        k_pages=cache["k"], v_pages=cache["v"],
    )


def _paged_decode_chunk(params, lora, state: _PagedDecodeState, rng,
                        page_indices, *, chunk: int,
                        cfg: ModelConfig, page_size: int, eos_ids,
                        pad_id: int, temperature, top_p, lora_scale: float,
                        paged_impl: str, pages_per_block: int = 0,
                        top_p_impl: str = "bisect",
                        capture_logprobs: bool = False):
    """``chunk`` wave-mode paged decode steps in ONE dispatch via
    ``lax.scan`` — the exact mirror of the dense engine's
    ``_decode_chunk`` (see its docstring for the tunnel dispatch-overhead
    rationale). The body is unguarded (a cond would double-buffer the
    page pools — scan_steps_guarded), so the HOST never dispatches a
    chunk crossing ``max_steps``; all-done steps are per-row no-ops."""
    def run(s):
        return _paged_decode_step(
            params, lora, s, rng, page_indices, cfg=cfg,
            page_size=page_size, eos_ids=eos_ids, pad_id=pad_id,
            temperature=temperature, top_p=top_p, lora_scale=lora_scale,
            paged_impl=paged_impl, pages_per_block=pages_per_block,
            top_p_impl=top_p_impl,
            capture_logprobs=capture_logprobs,
        )

    return scan_steps_guarded(run, state, chunk)


def _refill_init(prompt_k, prompt_v, *, b: int, r_slots: int, total: int,
                 max_steps: int, vocab: int, pool_pages: int,
                 prompt_pages: int, private_pages: int, pad_id: int,
                 shared_pages: int | None = None):
    """Empty R-slot decode state over the shared prompt pool: every slot is
    born dead; ``_refill_admit`` assigns occupants (including the first R).

    ``pool_pages`` is the decode page POOL size (the vLLM block pool behind
    ``gpu_memory_utilization`` / ``--actor_gpu_usage``): pages are owned by
    the host-side ``PagePool`` allocator, not statically partitioned per
    slot. Every slot's table starts pointed at the SCRATCH page (pool page
    0): decode steps run for dead slots too, and their garbage KV writes
    must land somewhere no live row ever reads — an all-zero table would
    alias physical page 0, a SHARED prefill page, and corrupt prompt 0's KV
    for every candidate (caught in review).

    ``shared_pages`` overrides the static prompt-region size (None = the
    historical ``b·prompt_pages``): continuous admission passes 0 — prompt
    chains are pool-allocated, ``prompt_k``/``prompt_v`` arrive as 0-page
    tiles, and the scratch page is physical page 0."""
    total_shared = b * prompt_pages if shared_pages is None else shared_pages
    width = prompt_pages + private_pages

    return _RefillState(
        step=jnp.zeros((), jnp.int32),
        alive_steps=jnp.zeros((), jnp.int32),
        out=jnp.full((total, max_steps), pad_id, jnp.int32),
        logps_buf=jnp.zeros((total, max_steps), jnp.float32),
        lengths_buf=jnp.zeros((total,), jnp.int32),
        cand=jnp.full((r_slots,), total, jnp.int32),
        done=jnp.ones((r_slots,), bool),
        logits=jnp.zeros((r_slots, vocab), jnp.float32),
        seq_lengths=jnp.zeros((r_slots,), jnp.int32),
        gen_lengths=jnp.zeros((r_slots,), jnp.int32),
        page_indices=jnp.full((r_slots, width), total_shared, jnp.int32),
        k_pages=tuple(_grow_pool(x, pool_pages) for x in prompt_k),
        v_pages=tuple(_grow_pool(x, pool_pages) for x in prompt_v),
    )


def _admit_tables(state, new_cand, admit_mask, real_len, dst_partial,
                  *, n: int, b: int, prompt_pages: int, page_size: int,
                  src_partial=None, copy_mask=None):
    """The admit work shared by the plain and speculative refill schedulers:
    merge slot assignments and build the partial-page recopy (the last,
    partial prompt page is extended in place by decode, so each admitted
    slot needs a private copy at the host-chosen ``dst_partial`` page).
    Page-TABLE rows are host-authored (engine/page_pool.py) and shipped via
    ``state._replace`` — the device no longer computes them.

    With ``src_partial``/``copy_mask`` the HOST authored the copy plan too
    (prefix sharing: the pool's copy-on-write splits name the pristine
    chain-tail source per slot, and page-aligned prompts need no copy at
    all); without them the source derives from the static prompt region
    exactly as it always has. Returns (cand, live_new, prompt_of, recopy)."""
    s = state
    total = b * n

    cand = jnp.where(admit_mask, new_cand, s.cand)
    live_new = new_cand < total
    prompt_of = jnp.clip(cand // n, 0, b - 1)
    if src_partial is None:
        full = real_len[prompt_of] // page_size  # [R] shared full pages
        src = prompt_of * prompt_pages + jnp.minimum(full, prompt_pages - 1)
        keep = admit_mask & live_new
    else:
        src = src_partial
        keep = copy_mask

    def recopy(pages):
        return _copy_pages(pages, src, dst_partial, keep_mask=keep)

    return cand, live_new, prompt_of, recopy


def _refill_admit(state: _RefillState, new_cand, admit_mask, last_logits,
                  real_len, dst_partial, src_partial=None, copy_mask=None,
                  *, n: int, b: int, prompt_pages: int, page_size: int):
    """Assign candidates to slots (vLLM's scheduler admitting waiting
    sequences into freed slots, static-shape edition). All shapes are
    static; which slots refill is data."""
    s = state
    cand, live_new, prompt_of, recopy = _admit_tables(
        s, new_cand, admit_mask, real_len, dst_partial, n=n, b=b,
        prompt_pages=prompt_pages, page_size=page_size,
        src_partial=src_partial, copy_mask=copy_mask,
    )

    return _RefillState(
        step=s.step,
        alive_steps=s.alive_steps,
        out=s.out,
        logps_buf=s.logps_buf,
        lengths_buf=s.lengths_buf,
        cand=cand,
        done=jnp.where(admit_mask, ~live_new, s.done),
        logits=jnp.where(admit_mask[:, None], last_logits[prompt_of], s.logits),
        seq_lengths=jnp.where(admit_mask, real_len[prompt_of], s.seq_lengths),
        gen_lengths=jnp.where(admit_mask, 0, s.gen_lengths),
        page_indices=s.page_indices,
        k_pages=tuple(recopy(x) for x in s.k_pages),
        v_pages=tuple(recopy(x) for x in s.v_pages),
    )


def _spec_resume_fixup(params, lora, state, slot, prefix_tok, prefix_len,
                       real_len_c, seq_row, first_logp, *, cfg: ModelConfig,
                       page_size: int, lora_scale: float):
    """Speculative-mode preemption resume: rebuild the evicted candidate's
    RESIDENT KV (its prefix minus the pending last token — spec slots carry
    ``last_tok`` emitted-but-not-resident, engine/speculative.py) via one
    chunked prefill, reseed the n-gram sequence buffer from the host-built
    row, and fast-forward the cursors. No logits to restore: the next spec
    step's verify forward consumes last_tok directly.

    The preceding re-admission (``_spec_admit``) sampled a FRESH first token
    and overwrote out[c, 0] / logps_buf[c, 0] / lengths_buf[c] — under
    sampling that token differs from the originally emitted prefix[0], so
    the buffers must be restored to the PREFIX the resident KV encodes
    (``first_logp`` is the original behavior logprob, read back by the host
    at preempt time). Pinned by the logprob-consistency regression in
    tests/test_paged_budget.py."""
    s = state
    t = prefix_tok.shape[0]
    resident = jnp.maximum(prefix_len - 1, 0)
    valid = (jnp.arange(t) < resident).astype(jnp.int32)[None, :]
    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": real_len_c[None],
        "page_indices": s.page_indices[slot][None],
    }
    positions = (real_len_c + jnp.arange(t, dtype=jnp.int32))[None, :]
    _, cache = forward(
        params, cfg, prefix_tok[None],
        attention_mask=valid, positions=positions,
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_chunked=True,
        logits_positions=jnp.zeros((1,), jnp.int32),
    )
    last = prefix_tok[jnp.maximum(prefix_len - 1, 0)]
    cand = s.cand[slot]
    return s._replace(
        done=s.done.at[slot].set(False),
        out=s.out.at[cand, 0].set(prefix_tok[0]),
        logps_buf=s.logps_buf.at[cand, 0].set(first_logp),
        lengths_buf=s.lengths_buf.at[cand].set(prefix_len),
        last_tok=s.last_tok.at[slot].set(last),
        seq_buf=s.seq_buf.at[slot].set(seq_row),
        gen_lengths=s.gen_lengths.at[slot].set(prefix_len),
        seq_lengths=s.seq_lengths.at[slot].set(real_len_c + resident),
        k_pages=cache["k"], v_pages=cache["v"],
    )


def _resume_fixup(params, lora, state: _RefillState, slot, prefix_tok,
                  prefix_len, real_len_c, *, cfg: ModelConfig, page_size: int,
                  lora_scale: float):
    """Rebuild a PREEMPTED candidate's KV by continuation (chunked) prefill
    and resume it in ``slot`` — vLLM's preempt-by-recompute. The slot was
    just admitted normally (prompt logits, gen 0); this pass re-runs the
    candidate's generated prefix through the model in ONE forward (KV writes
    to the slot's pages; attention over the dense-gathered context), then
    fast-forwards the slot's cursor to the prefix end. The prefix tokens /
    behavior logprobs already live in the out/logps buffers (scatter by
    candidate id — preemption never erased them)."""
    s = state
    t = prefix_tok.shape[0]
    valid = (jnp.arange(t) < prefix_len).astype(jnp.int32)[None, :]
    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": real_len_c[None],
        "page_indices": s.page_indices[slot][None],
    }
    positions = (real_len_c + jnp.arange(t, dtype=jnp.int32))[None, :]
    logits, cache = forward(
        params, cfg, prefix_tok[None],
        attention_mask=valid, positions=positions,
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_chunked=True,
        logits_positions=jnp.maximum(prefix_len - 1, 0)[None],
    )
    return s._replace(
        done=s.done.at[slot].set(False),
        logits=s.logits.at[slot].set(logits[0, 0]),
        gen_lengths=s.gen_lengths.at[slot].set(prefix_len),
        seq_lengths=s.seq_lengths.at[slot].set(real_len_c + prefix_len),
        k_pages=cache["k"], v_pages=cache["v"],
    )


def _turn_resume_fixup(params, lora, state: _RefillState, slot, obs_tok,
                       obs_len, cand_c, gen_len_c, seq_len_c, real_len_c,
                       *, cfg: ModelConfig, page_size: int, lora_scale: float,
                       max_steps: int, pad_id: int,
                       capture_logprobs: bool = False):
    """Continue a FINISHED candidate in place with environment-injected
    observation tokens (ISSUE 17 multi-turn episodes). Unlike ``_resume_fixup``
    the slot was never released: the whole conversation's KV (prompt + every
    prior turn, including the just-ended one) is still resident in the slot's
    pages, so this appends exactly the observation — one chunked forward over
    ``obs_len`` tokens instead of re-prefilling ``seq_len_c`` of context.

    The observation tokens are recorded in the candidate's out buffer (they
    are part of the answer the driver decodes) with behavior logprobs zeroed
    — the trainer's loss mask excludes env-injected spans, so the zeros are
    never consumed as behavior probabilities.

    Positions are clamped to the slot's page-table coverage
    (``real_len + max_steps`` tokens): masked padding lanes beyond ``obs_len``
    would otherwise scatter KV garbage past the table. Valid observation
    positions never reach the clamp — the host only resumes when
    ``gen_len + obs_len < max_steps`` — so the clamp target is only ever
    written by masked lanes whose KV is never attended to (attention is
    bounded by the cache lengths entry)."""
    s = state
    t = obs_tok.shape[0]
    steps = jnp.arange(t, dtype=jnp.int32)
    valid_vec = steps < obs_len
    valid = valid_vec.astype(jnp.int32)[None, :]
    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": seq_len_c[None],
        "page_indices": s.page_indices[slot][None],
    }
    positions = jnp.minimum(seq_len_c + steps, real_len_c + max_steps - 1)[None, :]
    obs_tok = jnp.where(valid_vec, obs_tok, pad_id)
    logits, cache = forward(
        params, cfg, obs_tok[None],
        attention_mask=valid, positions=positions,
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_chunked=True,
        logits_positions=jnp.maximum(obs_len - 1, 0)[None],
    )
    total_cols = s.out.shape[1]
    # out-of-range column sentinel drops the padding lanes, mirroring the
    # decode step's dead-slot scatter discipline
    col = jnp.where(valid_vec, gen_len_c + steps, total_cols)
    out = s.out.at[cand_c, col].set(obs_tok, mode="drop")
    if capture_logprobs:
        logps_buf = s.logps_buf.at[cand_c, col].set(
            jnp.zeros_like(col, dtype=s.logps_buf.dtype), mode="drop")
    else:
        logps_buf = s.logps_buf
    new_gen = gen_len_c + obs_len
    return s._replace(
        out=out, logps_buf=logps_buf,
        lengths_buf=s.lengths_buf.at[cand_c].set(new_gen),
        done=s.done.at[slot].set(False),
        logits=s.logits.at[slot].set(logits[0, 0]),
        gen_lengths=s.gen_lengths.at[slot].set(new_gen),
        seq_lengths=s.seq_lengths.at[slot].set(seq_len_c + obs_len),
        k_pages=cache["k"], v_pages=cache["v"],
    )


def _gather_page_tiles(k_pages, v_pages, src):
    """One physical page's KV tiles across all layers — [K, ps, hd] (or
    int8 weight+scales) per layer — as INDEPENDENT device buffers: jit
    outputs, never views into the state pools, so a background spill
    thread may hold them across the decode loop's donated-state dispatches
    (ISSUE 18 tier-2 transport; the PR 15 quant idiom — quantized pools
    gather weight + scales alike, so the round-trip is a pure memcpy and
    bit-exact by construction)."""
    from distrl_llm_tpu.ops.paged import is_quantized_pages

    def take(pages):
        if is_quantized_pages(pages):
            return type(pages)(
                weight=pages.weight[:, src], scales=pages.scales[:, src]
            )
        return pages[:, src]

    return (
        tuple(take(p) for p in k_pages),
        tuple(take(p) for p in v_pages),
    )


def _restore_page_tiles(state, k_tiles, v_tiles, dst):
    """Scatter one parked page's tiles back into the live pools at page
    ``dst`` (the `_cont_adopt` placement idiom, single-page edition)."""
    from distrl_llm_tpu.ops.paged import is_quantized_pages

    def put(pages, tile):
        if is_quantized_pages(pages):
            return type(pages)(
                weight=pages.weight.at[:, dst].set(tile.weight),
                scales=pages.scales.at[:, dst].set(tile.scales),
            )
        return pages.at[:, dst].set(tile)

    return state._replace(
        k_pages=tuple(put(p, t) for p, t in zip(state.k_pages, k_tiles)),
        v_pages=tuple(put(p, t) for p, t in zip(state.v_pages, v_tiles)),
    )


def _warm_prefill(params, lora, state: _RefillState, row_ext, suffix_tok,
                  suffix_len, start, logits_buf, g, *, cfg: ModelConfig,
                  page_size: int, lora_scale: float, pad_id: int):
    """Suffix-only group prefill through a radix-cache hit (ISSUE 18): the
    prompt's first ``start`` tokens are already resident in cached chain
    pages, so this forwards only the un-cached suffix — KV writes land in
    the chain's FRESH pages (the hit is capped below the last token, so no
    suffix position ever writes into a cached page) and the group's
    sampling logits come off the suffix's last real token.

    Runs the ``paged_prefix`` forward mode: suffix KV is written to pages,
    then attention goes through the SAME packed ``attention`` front door
    the cold `_paged_prefill` uses, over the row's dense-gathered packed
    window in compute dtype — so a warm group's logits and suffix KV are
    bit-identical to the cold prefill's (cached pages hold exact ``astype``
    round-trips of the in-flight k/v the cold path attended over).

    ``row_ext`` is the chain's table row padded with the scratch page plus
    ONE extra trailing scratch column: masked padding lanes clamp their
    positions to ``prompt_pages * page_size``, whose block index is exactly
    that extra column — their garbage KV lands on scratch, never in a page
    another admission could alias (the `_turn_resume_fixup` clamp
    discipline, aimed at scratch instead of the write ceiling because
    cached pages are immutable cross-group state). The ``paged_prefix``
    gather drops that trailing column, so the attention key window is
    exactly the cold packed width."""
    s = state
    t = suffix_tok.shape[0]
    prompt_pages = row_ext.shape[0] - 1
    steps = jnp.arange(t, dtype=jnp.int32)
    valid_vec = steps < suffix_len
    valid = valid_vec.astype(jnp.int32)[None, :]
    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": start[None],
        "page_indices": row_ext[None],
    }
    positions = jnp.where(
        valid_vec, start + steps, prompt_pages * page_size
    )[None, :]
    suffix_tok = jnp.where(valid_vec, suffix_tok, pad_id)
    logits, cache = forward(
        params, cfg, suffix_tok[None],
        attention_mask=valid, positions=positions,
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_prefix=True,
        logits_positions=jnp.maximum(suffix_len - 1, 0)[None],
    )
    s = s._replace(k_pages=cache["k"], v_pages=cache["v"])
    return s, logits_buf.at[g].set(logits[0, 0])


def _spill_resume_fixup(state: _RefillState, slot, logits_row, prefix_len,
                        real_len_c):
    """Cursor-only resume for a candidate whose KV pages were restored from
    the host spill store (ISSUE 18 tier 2): the preceding `_refill_admit`
    seated the slot with prompt logits and zeroed cursors, and the page
    restores already re-materialized the generated prefix's KV bit-exactly
    — so unlike `_resume_fixup` there is nothing to recompute, only the
    slot's logits row and cursors to fast-forward. The out/logps/lengths
    buffers are candidate-indexed and were never erased by preemption."""
    s = state
    return s._replace(
        done=s.done.at[slot].set(False),
        logits=s.logits.at[slot].set(logits_row),
        gen_lengths=s.gen_lengths.at[slot].set(prefix_len),
        seq_lengths=s.seq_lengths.at[slot].set(real_len_c + prefix_len),
    )


def _refill_decode_step(params, lora, state: _RefillState, rng,
                        *, cfg: ModelConfig, page_size: int, eos_ids,
                        pad_id: int, temperature, top_p, lora_scale: float,
                        paged_impl: str, max_steps: int,
                        pages_per_block: int = 0,
                        top_p_impl: str = "bisect",
                        capture_logprobs: bool = False):
    """One donated decode step over R slots. Differences from the wave step:
    output/length writes scatter by candidate id (``mode="drop"`` discards
    dead slots via the out-of-range sentinel), and a slot self-stops once its
    occupant has generated ``max_steps`` tokens (the host loop's step count
    bounds a WAVE's lifetime, not a slot's)."""
    s = state
    total = s.out.shape[0]
    alive = ~s.done
    # fused sample+logprob when enabled (ops/sampling.py); dead slots'
    # writes are dropped via the out-of-range sentinel either way, so the
    # pre-substitution logprob is observably identical
    tok, logp = sample_with_logprob(
        jax.random.fold_in(rng, s.step), s.logits, temperature, top_p,
        top_p_impl=top_p_impl, capture_logprob=capture_logprobs,
    )
    tok = jnp.where(s.done, pad_id, tok)
    row = jnp.where(alive, s.cand, total)  # `total` is out of range → dropped
    out = s.out.at[row, s.gen_lengths].set(tok, mode="drop")
    if capture_logprobs:
        logps_buf = s.logps_buf.at[row, s.gen_lengths].set(logp, mode="drop")
    else:
        logps_buf = s.logps_buf
    gen_lengths = s.gen_lengths + alive.astype(jnp.int32)
    lengths_buf = s.lengths_buf.at[row].set(gen_lengths, mode="drop")
    hit_eos = jnp.isin(tok, eos_ids) & alive
    done = s.done | hit_eos | (gen_lengths >= max_steps)

    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": s.seq_lengths,
        "page_indices": s.page_indices,
    }
    next_logits, cache = forward(
        params, cfg, tok[:, None],
        positions=s.seq_lengths[:, None],
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_impl=paged_impl,
        pages_per_block=pages_per_block,
    )
    seq_lengths = s.seq_lengths + alive.astype(jnp.int32)
    return _RefillState(
        step=s.step + 1,
        alive_steps=s.alive_steps + alive.sum().astype(jnp.int32),
        out=out, logps_buf=logps_buf,
        lengths_buf=lengths_buf, cand=s.cand,
        done=done, logits=next_logits[:, 0], seq_lengths=seq_lengths,
        gen_lengths=gen_lengths, page_indices=s.page_indices,
        k_pages=cache["k"], v_pages=cache["v"],
    )


def _refill_decode_chunk(params, lora, state: _RefillState, rng,
                         *, chunk: int, cfg: ModelConfig, page_size: int,
                         eos_ids, pad_id: int, temperature, top_p,
                         lora_scale: float, paged_impl: str, max_steps: int,
                         pages_per_block: int = 0,
                         top_p_impl: str = "bisect",
                         capture_logprobs: bool = False):
    """``chunk`` refill decode steps in ONE dispatch via ``lax.scan`` — the
    tunnel dispatch-overhead lever (engine.py::_decode_chunk has the full
    rationale; ~40 ms/dispatch over a network-tunneled PJRT client bounds
    decode throughput regardless of chip speed).

    Semantically identical to ``chunk`` host-dispatched steps: the host
    only ever intervenes (snapshot reads, admissions, grants, preemption)
    every ``check`` steps, and the caller sizes ``chunk`` as a DIVISOR of
    ``check`` (a non-divisor would stretch the host cadence past the
    budgeted pool's grant horizon), so no host decision point is ever
    skipped or delayed. Done slots are no-ops by construction (dropped
    writes, frozen lengths — see ``_refill_decode_step``), and all-done
    steps advance the per-step rng index exactly as host-dispatched
    steps would, so post-refill sampling sees identical fold_in indices.
    The body is deliberately unguarded: a ``lax.cond`` skip branch would
    double-buffer the carried page pools (scan_steps_guarded).

    Returns ``(state, done_copy, seq_lengths_copy)``: the host's
    admission/grant cadence consumes a (done, seq_lengths) snapshot at
    every ``check`` boundary, and the copies the old per-boundary
    ``jnp.copy`` dispatches made are FUSED into this program instead —
    fresh buffers (safe to read while ``state`` is donated into the
    next dispatch) at zero extra device round-trips, so a steady-state
    chunk leaves nothing on the host but the snapshot read itself."""
    def run(s):
        return _refill_decode_step(
            params, lora, s, rng, cfg=cfg, page_size=page_size,
            eos_ids=eos_ids, pad_id=pad_id, temperature=temperature,
            top_p=top_p, lora_scale=lora_scale, paged_impl=paged_impl,
            max_steps=max_steps, pages_per_block=pages_per_block,
            top_p_impl=top_p_impl,
            capture_logprobs=capture_logprobs,
        )

    state = scan_steps_guarded(run, state, chunk)
    return state, jnp.copy(state.done), jnp.copy(state.seq_lengths)


def _spec_decode_chunk(params, lora, state, rng, drafter_lora=None,
                       *, chunk: int, cfg: ModelConfig, page_size: int,
                       eos_ids, pad_id: int, temperature, top_p,
                       lora_scale: float, paged_impl: str, max_steps: int, draft_len: int, ngram_k: int,
                       drafter: str = "ngram", spec_verify: str = "fused",
                       hist_width: int = 0,
                       pages_per_block: int = 0,
                       top_p_impl: str = "bisect",
                       capture_logprobs: bool = False):
    """``chunk`` speculative decode steps in ONE dispatch — same contract
    as ``_refill_decode_chunk`` (chunk divides the host cadence; all-done
    steps run unguarded as per-slot no-ops and advance the rng step
    index): the spec step is fully functional (draft proposal, verify
    forward, rejection sampling, emission all device-side), so fusing
    steps changes nothing the host's admission/grant/preempt logic can
    observe. Each fused step still emits 1..d+1 tokens, so the
    dispatch-overhead amortization COMPOUNDS with speculative
    acceptance. Returns ``(state, done_copy, seq_lengths_copy,
    draft_total_copy, accept_total_copy)`` — the fused steady-state
    snapshot (see ``_refill_decode_chunk``) plus the acceptance accounting
    the adaptive draft-length controller consumes at the same boundaries
    (emit_hist stays in ``state``; round-end stats read it directly)."""
    def run(s):
        return _spec_step(
            params, lora, s, rng, drafter_lora, cfg=cfg, page_size=page_size,
            eos_ids=eos_ids, pad_id=pad_id, temperature=temperature,
            top_p=top_p, lora_scale=lora_scale, paged_impl=paged_impl,
            max_steps=max_steps, pages_per_block=pages_per_block,
            draft_len=draft_len, ngram_k=ngram_k,
            drafter=drafter, spec_verify=spec_verify, hist_width=hist_width,
            top_p_impl=top_p_impl, capture_logprobs=capture_logprobs,
        )

    state = scan_steps_guarded(run, state, chunk)
    return (
        state, jnp.copy(state.done), jnp.copy(state.seq_lengths),
        jnp.copy(state.draft_total), jnp.copy(state.accept_total),
    )


def _spec_init(prompt_k, prompt_v, *, b: int, r_slots: int, total: int,
               max_steps: int, buf_width: int, pool_pages: int,
               hist_width: int,
               prompt_pages: int, private_pages: int, pad_id: int,
               shared_pages: int | None = None):
    """Empty R-slot speculative decode state (engine/speculative.py)."""
    from distrl_llm_tpu.engine.speculative import SpecRefillState

    base = _refill_init(
        prompt_k, prompt_v, b=b, r_slots=r_slots, total=total,
        max_steps=max_steps, vocab=1, pool_pages=pool_pages,
        prompt_pages=prompt_pages, private_pages=private_pages, pad_id=pad_id,
        shared_pages=shared_pages,
    )
    return SpecRefillState(
        step=base.step, alive_steps=base.alive_steps,
        out=base.out, logps_buf=base.logps_buf,
        lengths_buf=base.lengths_buf,
        cand=base.cand, done=base.done,
        last_tok=jnp.zeros((r_slots,), jnp.int32),
        seq_buf=jnp.zeros((r_slots, buf_width), jnp.int32),
        seq_lengths=base.seq_lengths, gen_lengths=base.gen_lengths,
        page_indices=base.page_indices,
        k_pages=base.k_pages, v_pages=base.v_pages,
        emit_hist=jnp.zeros((hist_width,), jnp.int32),
        draft_total=jnp.zeros((), jnp.int32),
        accept_total=jnp.zeros((), jnp.int32),
    )


def _spec_admit(state, new_cand, admit_mask, last_logits, real_len,
                packed_ids, rng, temperature, top_p, dst_partial,
                src_partial=None, copy_mask=None,
                *, n: int, b: int, prompt_pages: int, page_size: int,
                eos_ids, top_p_impl: str = "bisect",
                capture_logprobs: bool = False):
    """Admit candidates into slots (speculative flavor): beyond the page-table
    work shared with ``_refill_admit``, sample each admitted slot's FIRST
    token from its prompt's prefill logits (the spec step carries a pending
    token, not logits), seed the n-gram sequence buffer with the packed
    prompt, and write that first token as generated output."""
    from distrl_llm_tpu.engine.speculative import SpecRefillState
    from distrl_llm_tpu.ops.sampling import sample_with_logprob

    s = state
    total = b * n
    cand, live_new, prompt_of, recopy = _admit_tables(
        s, new_cand, admit_mask, real_len, dst_partial, n=n, b=b,
        prompt_pages=prompt_pages, page_size=page_size,
        src_partial=src_partial, copy_mask=copy_mask,
    )

    # first token per admitted slot, from the prompt's last-position logits
    # (fused sample+logprob when enabled — ops/sampling.py; the rejection-
    # sampling accept path in _spec_step is untouched)
    tok0, logp0 = sample_with_logprob(
        rng, last_logits[prompt_of], temperature, top_p,
        top_p_impl=top_p_impl, capture_logprob=capture_logprobs,
    )
    hit_eos = jnp.isin(tok0, eos_ids)
    done = jnp.where(admit_mask, ~live_new | hit_eos, s.done)

    # n-gram buffer: packed prompt then tok0 at position real_len
    w = s.seq_buf.shape[1]
    p_len = packed_ids.shape[1]
    seq_rows = jnp.pad(packed_ids[prompt_of], ((0, 0), (0, w - p_len)))
    rl = real_len[prompt_of]
    seq_rows = jnp.where(
        jnp.arange(w)[None, :] == rl[:, None], tok0[:, None], seq_rows
    )
    seq_buf = jnp.where(admit_mask[:, None], seq_rows, s.seq_buf)

    # tok0 is generated output: out[cand, 0] and per-candidate length 1
    row = jnp.where(admit_mask & live_new, cand, total)
    out = s.out.at[row, 0].set(tok0, mode="drop")
    if capture_logprobs:
        logps_buf = s.logps_buf.at[row, 0].set(logp0, mode="drop")
    else:
        logps_buf = s.logps_buf
    lengths_buf = s.lengths_buf.at[row].set(1, mode="drop")

    return SpecRefillState(
        step=s.step,
        alive_steps=s.alive_steps,
        out=out,
        logps_buf=logps_buf,
        lengths_buf=lengths_buf,
        cand=cand,
        done=done,
        last_tok=jnp.where(admit_mask, tok0, s.last_tok),
        seq_buf=seq_buf,
        seq_lengths=jnp.where(admit_mask, real_len[prompt_of], s.seq_lengths),
        gen_lengths=jnp.where(admit_mask, 1, s.gen_lengths),
        page_indices=s.page_indices,
        k_pages=tuple(recopy(x) for x in s.k_pages),
        v_pages=tuple(recopy(x) for x in s.v_pages),
        emit_hist=s.emit_hist,
        draft_total=s.draft_total,
        accept_total=s.accept_total,
    )


def _self_draft(params, drafter_lora, state, step_rng, *, cfg: ModelConfig,
                page_size: int, lora_scale: float, paged_impl: str,
                pages_per_block: int, d: int, temperature, top_p,
                top_p_impl: str):
    """Online self-drafting: run the policy's own PREVIOUS LoRA version (the
    LoraMailbox swap log's superseded adapter) as the draft model — d
    autoregressive single-token decode steps over the SAME paged cache.

    The drafter's KV writes at positions seq_lengths..seq_lengths+d−1 are
    TRANSIENT: the verify forward re-processes the whole block under the
    target adapter and overwrites every one of them, so accepted positions
    always hold target-version KV (the drafter attending its own in-draft
    KV is the stale-KV mixture in-flight updating already embraces — and
    exactness never depends on it: the acceptance test consumes q as the
    distribution the draft was ACTUALLY sampled from, which this returns).

    Returns (draft [R, d], draft_probs q [R, d, V], k_pages, v_pages)."""
    from distrl_llm_tpu.engine.speculative import sampling_probs

    s = state
    k_pages, v_pages = s.k_pages, s.v_pages
    tok = s.last_tok
    drafts, qs = [], []
    for i in range(d):
        cache = {
            "k": k_pages, "v": v_pages,
            "lengths": s.seq_lengths + i,
            "page_indices": s.page_indices,
        }
        logits, cache = forward(
            params, cfg, tok[:, None],
            positions=(s.seq_lengths + i)[:, None],
            lora=drafter_lora, lora_scale=lora_scale,
            kv_cache=cache, page_size=page_size, paged_impl=paged_impl,
            pages_per_block=pages_per_block,
        )
        k_pages, v_pages = cache["k"], cache["v"]
        q_i = sampling_probs(
            logits[:, 0], temperature, top_p, top_p_impl=top_p_impl
        )  # [R, V] — the proposal distribution, exact (greedy → one-hot)
        tok = jax.random.categorical(
            jax.random.fold_in(step_rng, 7_000_000 + i),
            jnp.log(jnp.maximum(q_i, 1e-30)),
        ).astype(jnp.int32)
        drafts.append(tok)
        qs.append(q_i)
    return (
        jnp.stack(drafts, axis=1), jnp.stack(qs, axis=1), k_pages, v_pages,
    )


def _spec_step(params, lora, state, rng, drafter_lora=None, *,
               cfg: ModelConfig, page_size: int,
               eos_ids, pad_id: int, temperature, top_p, lora_scale: float,
               paged_impl: str, max_steps: int, draft_len: int, ngram_k: int,
               drafter: str = "ngram", spec_verify: str = "fused",
               hist_width: int = 0,
               pages_per_block: int = 0,
               top_p_impl: str = "bisect", capture_logprobs: bool = False):
    """One speculative decode step: propose d draft tokens (n-gram prompt
    lookup, or the previous-version policy itself — ``drafter``), verify
    [last_tok, draft] in one (d+1)-position forward whose attention runs as
    ONE fused blocked sweep (``spec_verify="fused"``; "unrolled" forces the
    per-position dispatch fan-out), accept by rejection sampling, and emit
    1..d+1 tokens (engine/speculative.py)."""
    from distrl_llm_tpu.engine.speculative import (
        SpecRefillState, propose_ngram_drafts, sampling_probs, spec_accept,
    )

    s = state
    total = s.out.shape[0]
    d = draft_len
    alive = ~s.done
    buf_len = s.seq_lengths + 1  # resident + the pending last_tok
    step_rng = jax.random.fold_in(rng, s.step)

    if drafter == "self":
        draft, draft_probs, k_pages, v_pages = _self_draft(
            params, drafter_lora if drafter_lora is not None else lora,
            s, step_rng, cfg=cfg, page_size=page_size,
            lora_scale=lora_scale, paged_impl=paged_impl,
            pages_per_block=pages_per_block, d=d,
            temperature=temperature, top_p=top_p, top_p_impl=top_p_impl,
        )
    else:
        draft = propose_ngram_drafts(s.seq_buf, buf_len, k=ngram_k, d=d)
        draft_probs = None
        k_pages, v_pages = s.k_pages, s.v_pages
    inputs = jnp.concatenate([s.last_tok[:, None], draft], axis=1)  # [R, d+1]
    positions = s.seq_lengths[:, None] + jnp.arange(d + 1)[None, :]
    cache = {
        "k": k_pages, "v": v_pages,
        "lengths": s.seq_lengths,
        "page_indices": s.page_indices,
    }
    logits, cache = forward(
        params, cfg, inputs, positions=positions,
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_impl=paged_impl,
        pages_per_block=pages_per_block, paged_verify=True,
        paged_verify_impl=spec_verify,
    )  # [R, d+1, V]
    probs = sampling_probs(logits, temperature, top_p, top_p_impl=top_p_impl)
    emit, n_emit, n_accept = spec_accept(step_rng, probs, draft, draft_probs)

    # EOS truncation: emission stops AT the first EOS among emitted tokens
    pos = jnp.arange(d + 1)[None, :]
    is_eos = jnp.isin(emit, eos_ids) & (pos < n_emit[:, None])
    any_eos = is_eos.any(axis=1)
    first_eos = jnp.argmax(is_eos, axis=1)
    n_emit = jnp.where(any_eos, first_eos + 1, n_emit)
    # cap at the per-candidate token budget
    room = jnp.maximum(max_steps - s.gen_lengths, 0)
    n_emit = jnp.minimum(n_emit, room)
    n_emit = jnp.where(alive, n_emit, 0)

    gen_lengths = s.gen_lengths + n_emit
    done = s.done | (alive & (any_eos | (gen_lengths >= max_steps)))

    # scatter the emitted tokens into out / seq_buf (static d+1 writes)
    out = s.out
    logps_buf = s.logps_buf
    seq_buf = s.seq_buf
    row = jnp.where(alive, s.cand, total)  # `total` → dropped
    for i in range(d + 1):
        live_i = i < n_emit
        row_i = jnp.where(live_i, row, total)
        out = out.at[row_i, s.gen_lengths + i].set(emit[:, i], mode="drop")
        if capture_logprobs:
            # behavior logprob on the RAW basis (same convention as every
            # other decode path): the verify logits at slot i judge/sample
            # emit[:, i]
            logp_i = token_logprob(logits[:, i], emit[:, i])
            logps_buf = logps_buf.at[row_i, s.gen_lengths + i].set(
                logp_i, mode="drop"
            )
        slot_i = jnp.where(live_i, jnp.arange(row.shape[0]), row.shape[0])
        seq_buf = seq_buf.at[slot_i, buf_len + i].set(emit[:, i], mode="drop")
    lengths_buf = s.lengths_buf.at[row].set(gen_lengths, mode="drop")

    last_tok = jnp.where(
        alive,
        jnp.take_along_axis(
            emit, jnp.clip(n_emit - 1, 0, d)[:, None], axis=1
        )[:, 0],
        s.last_tok,
    )
    seq_lengths = s.seq_lengths + n_emit
    # acceptance accounting: one [d_max+2]-bucket histogram increment per
    # step (device-side — the host reads it at snapshot boundaries / round
    # end, never per step). hist_width is the CONFIGURED max draft length's
    # width, so adaptive shrink (draft_len < max) changes no shapes.
    hw = hist_width or (d + 2)
    hist_inc = (
        (n_emit[:, None] == jnp.arange(hw)[None, :]) & alive[:, None]
    ).astype(jnp.int32).sum(axis=0)
    return SpecRefillState(
        step=s.step + 1,
        alive_steps=s.alive_steps + alive.sum().astype(jnp.int32),
        out=out, logps_buf=logps_buf,
        lengths_buf=lengths_buf, cand=s.cand,
        done=done, last_tok=last_tok, seq_buf=seq_buf,
        seq_lengths=seq_lengths, gen_lengths=gen_lengths,
        page_indices=s.page_indices,
        k_pages=cache["k"], v_pages=cache["v"],
        emit_hist=s.emit_hist + hist_inc,
        draft_total=s.draft_total + d * alive.sum().astype(jnp.int32),
        accept_total=(
            s.accept_total
            + jnp.where(alive, n_accept, 0).sum().astype(jnp.int32)
        ),
    )


class PagedGenerationEngine(LoraMailbox):
    """Drop-in for ``GenerationEngine`` with a packed paged KV cache."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_prompt_tokens: int,
        max_new_tokens: int,
        eos_token_ids: Sequence[int],
        pad_token_id: int,
        lora_scale: float = 1.0,
        cache_dtype=jnp.bfloat16,
        attn_impl: str = "reference",
        paged_impl: str = "auto",
        page_size: int = 128,
        decode_chunk: int = 128,
        # "none" | "int8" (per-token absmax KV cache, compact-scales Pallas
        # variants). None = consult the autotune plan DB
        # (ExecutionPlan.kv_format; empty DB = "none", byte-identical to
        # the historical default); an explicit value — including "none" —
        # always wins (the decode_scan_chunk convention)
        kv_quant: str | None = None,
        prompt_buckets: Sequence[int] | None = None,  # accepted for interface parity
        max_concurrent_rows: int = 0,  # 0 = unlimited (vLLM max_num_seqs)
        max_kv_pages: int = 0,  # refill decode-page pool size; 0 = worst-case
        scheduler: str = "waves",  # "waves" | "refill" (continuous batching)
        # prefix sharing (ISSUE 12): a group's N candidates ALIAS one
        # refcounted prompt-prefix page chain (copy-on-write tail split)
        # instead of each keeping a private partial-page copy against a
        # never-freed static region; finished groups' prompt pages recycle
        # into decode capacity. Refill scheduler only.
        prefix_sharing: bool = False,
        # continuous admission (ISSUE 12): replace the fixed-episode-batch
        # prefill with a group request queue — each group's prompt is
        # prefilled lazily into pool-allocated chain pages when freed slots
        # and page budget admit it, so short completions backfill
        # immediately. Implies prefix_sharing. None = consult the autotune
        # plan DB (cb_mode field; empty DB = off); an explicit bool —
        # including False — always wins (the decode_scan_chunk convention).
        continuous_admission: bool | None = None,
        # tiered KV cache (ISSUE 18). Tier 1: a cross-request radix prefix
        # index over the continuous-admission pool — admissions
        # longest-prefix-match their token ids against previously finished
        # chains, alias every matched full page refcounted, and prefill
        # only the un-cached suffix (SGLang RadixAttention-style; multi-turn
        # re-admission of a conversation's history costs zero prefill).
        # None = consult the autotune plan DB (prefix_cache field; empty
        # DB = off); an explicit bool — including False — always wins (the
        # decode_scan_chunk convention). Requires continuous admission.
        prefix_cache: bool | None = None,
        # Tier 2: evicted cache nodes and preempted chains park their KV
        # pages (int8 payload + scales travel as-is — the PR 15 quant
        # transport idiom) in a host-RAM page store on a background thread
        # and page back in on re-match/resume, bit-exact. Explicit-only:
        # host-memory geometry is a deployment fact, not a measured plan
        # field. Requires prefix_cache; speculative chains resume by
        # recompute instead (their draft state is not spillable).
        kv_spill: bool = False,
        kv_spill_host_mb: int = 0,  # host store byte cap; 0 = unbounded
        # speculative decoding (engine/speculative.py). None = consult the
        # autotune plan DB (spec_draft_len/spec_ngram_k/spec_drafter/
        # spec_verify plan fields; empty DB falls back to the historical
        # defaults: off / k=2 / "ngram" / "fused"); an explicit value —
        # including spec_draft=0 — always wins.
        spec_draft: int | None = None,  # >0: speculative, d draft tokens
        spec_ngram: int | None = None,  # lookup n-gram size
        spec_drafter: str | None = None,  # "ngram" | "self" (prev-LoRA policy)
        spec_verify: str | None = None,  # "fused" | "unrolled" verify sweep
        # acceptance-rate-driven draft-length adaptation: shrink the
        # effective d (halving, floor 1) when the accept-rate EMA says
        # drafts are being wasted, grow it back when acceptance recovers
        spec_adapt: bool = False,
        # None = consult the autotune plan DB (falls back to 0, the
        # historical default); an explicit int — including 0 — always wins
        scan_chunk: int | None = None,
        # blocked-kernel page collapse. None = consult the plan DB (falls
        # back to 0 — the kernel default); an explicit int, including 0,
        # always wins. Only consumed when the blocked kernel dispatches.
        pages_per_block: int | None = None,
        capture_logprobs: bool = False,  # record behavior logprobs (clip_ratio)
        autotune: bool = True,  # False pins the static defaults (no DB read)
        plan_db: str | None = None,  # plan-DB path; None = env/default path
        plan_rows: int = 0,  # expected rows for plan-KEY selection (0 = any)
    ):
        self.max_concurrent_rows = max_concurrent_rows
        self.capture_logprobs = capture_logprobs
        if scan_chunk is not None and scan_chunk < 0:
            raise ValueError(f"scan_chunk must be >= 0, got {scan_chunk}")
        if kv_quant not in (None, "none", "int8"):
            # validated BEFORE plan resolution so a typo'd kwarg fails with
            # the engine's own contract, not a plan-field error
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        if pages_per_block is not None and pages_per_block < 0:
            raise ValueError(
                f"pages_per_block must be >= 0, got {pages_per_block}"
            )
        # Execution-plan resolution (distrl_llm_tpu/autotune): explicit
        # kwargs win, a stored measured plan fills the rest, no DB entry =
        # the static defaults byte-identically. decode_path is pinned to
        # what this construction actually is (honest bench/trace records).
        from distrl_llm_tpu.autotune import (
            IMPL_TO_PAGED_KERNEL, PAGED_KERNEL_TO_IMPL, resolve_plan,
        )

        requested: dict[str, Any] = {}
        if spec_draft is not None:
            requested["decode_path"] = "speculative" if spec_draft else "paged"
            requested["spec_draft_len"] = spec_draft
        elif scheduler != "refill" or not max_concurrent_rows:
            # only a refill engine can host a stored speculative plan (it
            # needs the slot scheduler); everything else pins the paged
            # path so a spec DB entry is treated as a decode-path miss
            requested["decode_path"] = "paged"
        else:
            # refill with spec unpinned: this engine can host "paged" OR
            # "speculative" — which one is exactly what the DB decides.
            # The tuple is a CONSTRAINT, not a pin: a stored entry from any
            # OTHER path (e.g. dense) is still a wholesale miss — its
            # scan_chunk/top_p were never measured here — and with no
            # entry the first element ("paged") is the default path
            requested["decode_path"] = ("paged", "speculative")
        if spec_ngram is not None:
            requested["spec_ngram_k"] = spec_ngram
        if spec_drafter is not None:
            requested["spec_drafter"] = spec_drafter
        if spec_verify is not None:
            requested["spec_verify"] = spec_verify
        if scan_chunk is not None:
            requested["scan_chunk"] = scan_chunk
        if pages_per_block is not None:
            requested["pages_per_block"] = pages_per_block
        if continuous_admission is not None:
            # explicit bool pins the admission regime past any stored plan
            # (False is a real A/B control, not "unset")
            requested["cb_mode"] = (
                "continuous" if continuous_admission else "batch"
            )
        if kv_quant is not None:
            # explicit "none" is a real pin (the int8-default A/B control)
            requested["kv_format"] = kv_quant
        if prefix_cache is not None:
            # explicit False pins "off" past any stored plan (the cache-off
            # A/B control must never be silently re-armed by the DB)
            requested["prefix_cache"] = "on" if prefix_cache else "off"
        if kv_spill_host_mb < 0:
            raise ValueError(
                f"kv_spill_host_mb must be >= 0, got {kv_spill_host_mb}"
            )
        # the paged_kernel plan field and the paged_impl kwarg name the same
        # choice: any explicit non-"auto" kwarg wins over the DB ("kernel"/
        # "reference" have no plan spelling, so they pin the field to None —
        # a stored native-variant plan must not override them either)
        if paged_impl != "auto":
            requested["paged_kernel"] = IMPL_TO_PAGED_KERNEL.get(paged_impl)
        self.resolved_plan = resolve_plan(
            model_cfg=cfg, max_prompt_tokens=max_prompt_tokens,
            max_new_tokens=max_new_tokens, rows=plan_rows,
            requested=requested, db_path=plan_db, enabled=autotune,
        )
        scan_chunk = self.resolved_plan.plan.scan_chunk
        self.plan_top_p_impl = self.resolved_plan.plan.top_p_impl
        if paged_impl == "auto" and self.resolved_plan.plan.paged_kernel:
            # a measured plan picked a native kernel variant for this
            # geometry — adopt it (empty DB: field is None, "auto" stands)
            paged_impl = PAGED_KERNEL_TO_IMPL[
                self.resolved_plan.plan.paged_kernel
            ]
        self.pages_per_block = self.resolved_plan.plan.pages_per_block
        self.scan_chunk = scan_chunk
        self._chunk_compiled: dict = {}
        self._chunk_mu = threading.Lock()
        if scheduler not in ("waves", "refill"):
            raise ValueError(f"scheduler must be waves/refill, got {scheduler!r}")
        if scheduler == "refill" and not max_concurrent_rows:
            # without a cap the refill path never engages and rounds would
            # silently run as one unlimited wave while reporting "refill"
            raise ValueError(
                "scheduler='refill' requires max_concurrent_rows (the decode "
                "slot count)"
            )
        # spec knobs post-resolution: plan fields hold user > DB > default
        # (resolve_plan). A system-written speculative plan carries
        # decode_path="speculative", so on a non-refill engine it is
        # dropped WHOLESALE by resolve_plan's decode-path mismatch (this
        # constructor pins requested decode_path="paged" above) and never
        # reaches here; the warning branch below additionally guards
        # hand-edited/inconsistent DB entries (a "paged" entry carrying a
        # nonzero spec_draft_len) — a stored plan must never crash or
        # silently reshape a run, while the same value passed EXPLICITLY
        # still raises below.
        plan = self.resolved_plan.plan
        spec_explicit = spec_draft is not None
        spec_draft = (
            spec_draft if spec_explicit else plan.spec_draft_len
        )
        if spec_draft and not spec_explicit and scheduler != "refill":
            import logging

            logging.getLogger(__name__).warning(
                "autotune: stored plan wants speculative decoding "
                "(spec_draft_len=%d) but this engine runs the %s scheduler "
                "— ignoring the plan's spec fields",
                spec_draft, scheduler,
            )
            spec_draft = 0
        if spec_draft and scheduler != "refill":
            raise ValueError(
                "spec_draft (speculative decoding) runs on the refill "
                "scheduler — set scheduler='refill' and max_concurrent_rows"
            )
        spec_ngram = (
            spec_ngram if spec_ngram is not None
            else (plan.spec_ngram_k or 2)
        )
        if spec_draft < 0 or spec_draft > 16:
            raise ValueError(
                f"spec_draft must be in [0, 16] (draft blocks beyond 16 "
                f"positions waste verify width faster than they amortize "
                f"weight reads), got {spec_draft}"
            )
        if spec_ngram < 1:
            raise ValueError(f"bad spec config: d={spec_draft}, k={spec_ngram}")
        self.spec_draft = spec_draft
        self.spec_ngram = spec_ngram
        self.spec_drafter = spec_drafter or plan.spec_drafter or "ngram"
        if self.spec_drafter not in ("ngram", "self"):
            raise ValueError(
                f"spec_drafter must be ngram/self, got {self.spec_drafter!r}"
            )
        self.spec_verify = spec_verify or plan.spec_verify or "fused"
        if self.spec_verify not in ("fused", "unrolled"):
            raise ValueError(
                f"spec_verify must be fused/unrolled, got {self.spec_verify!r}"
            )
        if spec_adapt and not spec_draft:
            if spec_explicit:
                raise ValueError(
                    "spec_adapt adapts the speculative draft length — it "
                    "requires spec_draft > 0"
                )
            # unpinned spec_draft resolved to 0 (no speculative plan for
            # this geometry in the DB): the same command line must not
            # crash on one host and run on another — same never-crash
            # policy as the scheduler-mismatch branch above
            import logging

            logging.getLogger(__name__).warning(
                "spec_adapt requested but spec_draft resolved to 0 from "
                "this host's plan DB (no speculative plan stored for the "
                "geometry) — the draft-length controller is inert this run"
            )
            spec_adapt = False
        self.spec_adapt = spec_adapt
        # only the self drafter consumes the mailbox's superseded-adapter
        # slot; leave retention off otherwise (it pins an extra adapter
        # version in device memory for the engine's lifetime)
        self._track_prev_lora = bool(spec_draft) and self.spec_drafter == "self"
        # ---- continuous-batching admission + prefix sharing (ISSUE 12)
        cb_explicit = continuous_admission is not None
        cont = (
            continuous_admission if cb_explicit
            else plan.cb_mode == "continuous"
        )
        if cont and (scheduler != "refill" or not max_concurrent_rows):
            if cb_explicit:
                raise ValueError(
                    "continuous_admission runs on the refill scheduler — "
                    "set scheduler='refill' and max_concurrent_rows"
                )
            # a stored plan must never crash or silently reshape a run the
            # engine can't host (the spec-plan scheduler-mismatch policy)
            import logging

            logging.getLogger(__name__).warning(
                "autotune: stored plan wants continuous admission "
                "(cb_mode='continuous') but this engine runs the %s "
                "scheduler — ignoring the plan's cb_mode", scheduler,
            )
            cont = False
        if cont:
            # continuous admission allocates prompt chains from the
            # refcounted pool — it IS prefix sharing plus lazy prefill
            prefix_sharing = True
        if prefix_sharing and (scheduler != "refill" or not max_concurrent_rows):
            raise ValueError(
                "prefix_sharing shares prompt-prefix pages across the "
                "refill scheduler's slots — set scheduler='refill' and "
                "max_concurrent_rows"
            )
        self.prefix_sharing = bool(prefix_sharing)
        self.continuous_admission = bool(cont)
        # the scheduler self-description bench/telemetry record (the wave
        # path reports "waves" regardless; generate() stamps last_cb_mode
        # with what each round actually ran)
        self.cb_mode = (
            "waves" if scheduler == "waves" else (
                "continuous" if cont
                else ("refill_shared" if prefix_sharing else "refill")
            )
        )
        self.last_cb_mode: str | None = None
        # post-resolution KV format (explicit kwarg already won per-field
        # via the requested dict; unset adopts the stored plan, default
        # "none" — the historical behavior, byte-identical on an empty DB)
        kv_quant = kv_quant if kv_quant is not None else (
            plan.kv_format or "none"
        )
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        self.kv_quant = kv_quant
        # ---- tiered KV cache (ISSUE 18) resolution: tier 1 aliases cached
        # chains out of the continuous-admission pool, so it inherits the
        # cb_mode policy verbatim — explicit wins (including False), a
        # stored plan the engine can't host degrades with a warning, the
        # same value passed explicitly raises
        pc_explicit = prefix_cache is not None
        pcache = (
            prefix_cache if pc_explicit else plan.prefix_cache == "on"
        )
        if pcache and kv_quant == "int8":
            # int8 pages are QUANTIZED rewrites of the in-flight k/v, so a
            # warm suffix prefill over cached pages could never be
            # bit-identical to the packed cold prefill (which attends the
            # un-quantized in-flight values) — the cache's core contract
            if pc_explicit:
                raise ValueError(
                    "prefix_cache requires a lossless KV pool "
                    "(kv_quant='none'): int8 pages cannot reproduce the "
                    "cold prefill's attention inputs bit-exactly"
                )
            import logging

            logging.getLogger(__name__).warning(
                "autotune: stored plan wants the radix prefix cache "
                "(prefix_cache='on') but the KV pool is int8-quantized — "
                "ignoring the plan's prefix_cache"
            )
            pcache = False
        if pcache and not cont:
            if pc_explicit:
                raise ValueError(
                    "prefix_cache aliases cached prompt chains out of the "
                    "continuous-admission pool — set "
                    "continuous_admission=True (refill scheduler with "
                    "max_concurrent_rows)"
                )
            import logging

            logging.getLogger(__name__).warning(
                "autotune: stored plan wants the radix prefix cache "
                "(prefix_cache='on') but this engine does not run "
                "continuous admission — ignoring the plan's prefix_cache"
            )
            pcache = False
        self.prefix_cache = bool(pcache)
        if kv_spill and not self.prefix_cache:
            raise ValueError(
                "kv_spill parks KV pages through the tiered cache's host "
                "store — it requires prefix_cache=True"
            )
        if kv_spill and spec_draft:
            raise ValueError(
                "kv_spill restores raw decode cursors the speculative "
                "scheduler does not expose (draft history, acceptance "
                "state) — preempted speculative chains already resume by "
                "recompute; drop kv_spill or spec_draft"
            )
        self.kv_spill = bool(kv_spill)
        # honesty: the record in resolved_plan must describe what this
        # engine actually is (generate() routes on spec_draft/scheduler,
        # not on the plan record), including when the decode_path came
        # from DEFAULT_PLAN ("dense" — never true of this class)
        self.resolved_plan = self.resolved_plan._replace(
            plan=plan.replace(
                decode_path="speculative" if spec_draft else "paged",
                spec_draft_len=spec_draft,
                spec_ngram_k=spec_ngram if spec_draft else 0,
                spec_drafter=self.spec_drafter if spec_draft else None,
                spec_verify=self.spec_verify if spec_draft else None,
                # what actually runs: a degraded stored "continuous" plan
                # records "batch", an explicit pin keeps its spelling
                cb_mode=(
                    "continuous" if cont
                    else ("batch" if plan.cb_mode is not None else None)
                ),
                prefix_cache=(
                    "on" if pcache
                    else ("off" if plan.prefix_cache is not None else None)
                ),
            )
        )
        self.scheduler = scheduler
        self.cfg = cfg
        self.max_prompt_tokens = max_prompt_tokens
        self.max_new_tokens = max_new_tokens
        cfg.check_within_window(max_prompt_tokens + max_new_tokens)
        self.page_size = page_size
        self.prompt_pages = pages_per_seq(max_prompt_tokens, page_size)
        # per-candidate private region: the partial prompt page (extended in
        # place by decode) + decode pages; full prompt pages are SHARED.
        # Speculative verify writes up to spec_draft KVs PAST a row's final
        # position (the unaccepted tail of the last draft block) — they must
        # land in scratch pages, not clamp onto valid resident KV (review
        # finding: near-budget rows otherwise corrupt their own cache).
        self.private_pages = 1 + pages_per_seq(
            max_new_tokens + max(spec_draft, 0), page_size
        )
        # page BUDGET for the refill scheduler's decode pool (vLLM's block
        # pool behind gpu_memory_utilization / --actor_gpu_usage): 0 means
        # worst-case provisioning (one private region per slot — admission
        # can never stall and preemption never fires); a smaller budget makes
        # KV HBM scale with REALIZED lengths, with admission gated on free
        # pages and preempt-by-recompute under pressure
        # continuous admission allocates prompt chains FROM the pool, so the
        # single-sequence floor additionally carries one prompt chain
        # the tiered cache keeps warm chains resident in the SAME pool, so
        # its floor carries one extra prompt chain (mirrors budget.py's
        # kv_pool_pages(prefix_cache=True) clamp)
        pool_floor = 1 + self.private_pages + (
            self.prompt_pages if self.continuous_admission else 0
        ) + (self.prompt_pages if self.prefix_cache else 0)
        if max_kv_pages and max_kv_pages < pool_floor:
            raise ValueError(
                f"max_kv_pages={max_kv_pages} cannot fit one sequence "
                f"(need >= {pool_floor}: scratch + "
                f"{self.private_pages} private pages"
                + (f" + {self.prompt_pages} prompt-chain pages for "
                   f"continuous admission" if self.continuous_admission
                   else "")
                + (f" + {self.prompt_pages} resident-cache pages for "
                   f"prefix_cache" if self.prefix_cache else "")
                + ")"
            )
        if (
            max_kv_pages and self.continuous_admission
            and max_kv_pages < pool_floor + self.private_pages
        ):
            # above the hard floor but wedge-prone (ISSUE 19 satellite, the
            # BENCH_KV_PAGES<=16 gotcha): a budget that cannot hold the
            # head group's chain plus TWO private regions serializes every
            # admission behind a full drain, and a mid-round decline with
            # no live slot trips the wedge detector. Warn at build time
            # with the number, don't wait for the round to stall.
            import warnings

            warnings.warn(
                f"max_kv_pages={max_kv_pages} is wedge-prone under "
                f"continuous admission: the pool fits one sequence (floor "
                f"{pool_floor}) but cannot overlap the next admission's "
                f"private region — minimum comfortable budget is "
                f"{pool_floor + self.private_pages} pages "
                f"({pool_floor} floor + {self.private_pages} private)",
                RuntimeWarning, stacklevel=2,
            )
        self.max_kv_pages = max_kv_pages
        self.last_pool_stats: dict | None = None
        # request-level serving observability (ISSUE 13): when an owner
        # (trainer --serving_obs, worker --serving-obs, bench cb rows)
        # attaches a serving_obs.ServingLedger here, the refill/spec/
        # continuous loops emit per-group lifecycle events and the
        # admission audit at host chunk boundaries. None = every hook site
        # is one attribute check — the telemetry-off fast path and the
        # byte-identity pins are untouched (the ledger observes, never
        # schedules)
        self.serving_ledger: Any = None
        # closed-loop admission limits (ISSUE 14): when an owner (trainer
        # --control, worker --control, bench control A/B rows) attaches a
        # control.ControlLimits here, the continuous-admission loop
        # consults it — the HBM governor's chain-cap scale and the SLO
        # shedder's shed gate. None = one attribute check per admission
        # pass; a handle at its defaults makes byte-identical decisions
        # (pinned in tests/test_control.py)
        self.control_limits: Any = None
        # multi-turn episode continuation (ISSUE 17): when an owner (trainer
        # env driver, bench env arm) attaches a turn hook here, the refill
        # idle pass consults it before retiring a finished candidate —
        # ``hook(cand_id, gen_tokens) -> np.ndarray | None`` returns
        # observation tokens to append in place (KV chain stays resident) or
        # None to finish; ``hook.declined(cand_id)`` unwinds an accepted
        # observation the engine could not seat. None = one attribute check
        # per idle pass — single-turn rounds and byte-identity pins untouched
        self.turn_hook: Any = None
        # multi-tenant gateway identity (ISSUE 19): when a gateway owner
        # attaches per-round tenancy here, the continuous-admission loop
        # schedules by priority class. ``round_meta`` maps group index ->
        # {"tenant", "cls", "rank", "seq", "arrival_ts", "trace_ctx"};
        # ``quota_book`` is a gateway.TenantQuotaBook consulted (charge at
        # admission, credit at group finish) with the ``quota`` stall
        # reason on decline; ``stream_hook`` is ``fn(cand, token_list)``
        # called with newly visible tokens at host boundaries plus a
        # byte-complete final flush at round end. All three default None =
        # one attribute check per site — non-gateway rounds and the
        # byte-identity pins are untouched (pinned in tests/test_gateway.py)
        self.round_meta: Any = None
        self.quota_book: Any = None
        self.stream_hook: Any = None
        # per-round speculative stats (refill spec rounds only): drafter,
        # realized accept rate, tokens/verify-step, emit histogram, verify
        # kernel choice + grid steps, draft/target version bookkeeping
        self.last_spec_stats: dict | None = None
        # per-round prefill/decode timing + token counts (telemetry:
        # accumulate_round_stats); snapshotted by the trainer per round
        self.last_round_stats: dict | None = None
        # in-flight weight-update mailbox (LoraMailbox base)
        self.last_swap_steps: list[int] = []
        self.last_swap_versions: list[int | None] = []
        self.eos_ids = jnp.asarray(list(eos_token_ids), jnp.int32)
        self.pad_id = int(pad_token_id)
        self.lora_scale = lora_scale
        self.decode_chunk = decode_chunk
        self.paged_impl = paged_impl
        self.prompt_buckets = [max_prompt_tokens]
        # continuous admission builds per-layer 0-page tiles in this dtype
        # and reuses the jitted prefill at [1, P]
        self.cache_dtype = cache_dtype
        # tiered-KV engine state (ISSUE 18): the radix index and host page
        # store are ENGINE-owned — they outlive the per-round PagePool, so
        # warm prefixes survive into the next round's admissions; each
        # round's pool attaches to them and round end flushes residency to
        # the store (page ids are round-scoped, payloads are not). The
        # adapter identity guard invalidates the WHOLE cache whenever the
        # LoRA the KV was computed under changes (cached KV is only exact
        # for the adapter that wrote it — a strong reference keeps the
        # identity test sound against id() reuse).
        if self.prefix_cache:
            from distrl_llm_tpu.engine.page_pool import (
                HostPageStore, RadixCache,
            )

            self._radix = RadixCache(page_size)
            self._kv_store = HostPageStore(
                max_bytes=kv_spill_host_mb * 2**20
            )
        else:
            self._radix = None
            self._kv_store = None
        self._cache_lora_ref: Any = None

        self._prefill = jax.jit(
            partial(
                _paged_prefill, cfg=cfg, prompt_pages=self.prompt_pages,
                page_size=page_size, lora_scale=lora_scale,
                cache_dtype=cache_dtype, attn_impl=attn_impl, kv_quant=kv_quant,
            )
        )
        self._fanout = jax.jit(
            partial(
                _paged_fanout, prompt_pages=self.prompt_pages,
                private_pages=self.private_pages,
                page_size=page_size,
            ),
            static_argnames=("n", "b", "max_steps"),
        )
        self._decode_step = jax.jit(
            partial(
                _paged_decode_step, cfg=cfg, page_size=page_size,
                pad_id=self.pad_id, lora_scale=lora_scale, paged_impl=paged_impl,
                pages_per_block=self.pages_per_block,
                capture_logprobs=capture_logprobs,
            ),
            donate_argnames=("state",),
            static_argnames=("top_p_impl",),
        )
        self._refill_init = jax.jit(
            partial(
                _refill_init, prompt_pages=self.prompt_pages,
                private_pages=self.private_pages, pad_id=self.pad_id,
            ),
            static_argnames=(
                "b", "r_slots", "total", "max_steps", "vocab", "pool_pages",
                "shared_pages",
            ),
        )
        self._cont_adopt = jax.jit(
            _cont_adopt, donate_argnames=("state", "logits_buf"),
        )
        self._refill_admit = jax.jit(
            partial(
                _refill_admit, prompt_pages=self.prompt_pages,
                page_size=page_size,
            ),
            donate_argnames=("state",),
            static_argnames=("n", "b"),
        )
        self._resume_fixup = jax.jit(
            partial(
                _resume_fixup, cfg=cfg, page_size=page_size,
                lora_scale=lora_scale,
            ),
            donate_argnames=("state",),
        )
        self._spec_resume_fixup = jax.jit(
            partial(
                _spec_resume_fixup, cfg=cfg, page_size=page_size,
                lora_scale=lora_scale,
            ),
            donate_argnames=("state",),
        )
        self._turn_resume = jax.jit(
            partial(
                _turn_resume_fixup, cfg=cfg, page_size=page_size,
                lora_scale=lora_scale, pad_id=self.pad_id,
                capture_logprobs=capture_logprobs,
            ),
            donate_argnames=("state",),
            static_argnames=("max_steps",),
        )
        # tiered-KV programs (ISSUE 18): warm suffix prefill through a
        # radix hit, page spill/restore transport, spill-resume cursor
        # fast-forward. _gather_page deliberately does NOT donate — its
        # outputs must be independent buffers a host thread can park.
        self._warm_prefill = jax.jit(
            partial(
                _warm_prefill, cfg=cfg, page_size=page_size,
                lora_scale=lora_scale, pad_id=self.pad_id,
            ),
            donate_argnames=("state", "logits_buf"),
        )
        self._gather_page = jax.jit(_gather_page_tiles)
        self._restore_page = jax.jit(
            _restore_page_tiles, donate_argnames=("state",),
        )
        self._spill_fixup = jax.jit(
            _spill_resume_fixup, donate_argnames=("state",),
        )
        self._refill_step = jax.jit(
            partial(
                _refill_decode_step, cfg=cfg, page_size=page_size,
                pad_id=self.pad_id, lora_scale=lora_scale, paged_impl=paged_impl,
                pages_per_block=self.pages_per_block,
                capture_logprobs=capture_logprobs,
            ),
            donate_argnames=("state",),
            static_argnames=("max_steps", "top_p_impl"),
        )
        self._spec_init = jax.jit(
            partial(
                _spec_init, prompt_pages=self.prompt_pages,
                private_pages=self.private_pages, pad_id=self.pad_id,
            ),
            static_argnames=(
                "b", "r_slots", "total", "max_steps", "buf_width",
                "pool_pages", "hist_width", "shared_pages",
            ),
        )
        self._spec_admit = jax.jit(
            partial(
                _spec_admit, prompt_pages=self.prompt_pages,
                page_size=page_size,
                capture_logprobs=capture_logprobs,
            ),
            donate_argnames=("state",),
            static_argnames=("n", "b", "top_p_impl"),
        )
        self._spec_step = jax.jit(
            partial(
                _spec_step, cfg=cfg, page_size=page_size,
                pad_id=self.pad_id, lora_scale=lora_scale, paged_impl=paged_impl,
                pages_per_block=self.pages_per_block,
                drafter=self.spec_drafter, spec_verify=self.spec_verify,
                hist_width=self.spec_draft + 2,
                capture_logprobs=capture_logprobs,
            ),
            donate_argnames=("state",),
            static_argnames=("max_steps", "draft_len", "ngram_k", "top_p_impl"),
        )

    def _dispatch_key(self, verify_len: int = 0) -> tuple:
        """THIS engine's ``dispatch_choices`` key (decode when
        ``verify_len`` is 0, draft-block verify otherwise). One builder so
        the decode and verify lookups can't drift when the key grows a
        field — ``ops.paged.dispatch_choice_key`` owns the layout."""
        from distrl_llm_tpu.ops.paged import dispatch_choice_key

        return dispatch_choice_key(
            quantized=self.kv_quant == "int8",
            num_kv_heads=self.cfg.num_kv_heads,
            num_groups=self.cfg.num_heads // self.cfg.num_kv_heads,
            head_dim=self.cfg.head_dim,
            page_size=self.page_size,
            pps=self.prompt_pages + self.private_pages,
            impl=self.paged_impl, pages_per_block=self.pages_per_block,
            verify_len=verify_len,
        )

    def _grid_steps_per_call(self, rows: int) -> int:
        """Analytic grid-step count of ONE of this engine's paged-attention
        calls at ``rows`` concurrent rows. WHICH kernel ran is read from
        ``dispatch_choices`` under this engine's exact dispatch key (keyed
        by requested impl + geometry, so same-geometry engines pinned to
        different kernels never alias); the count itself is computed
        against the LIVE batch — it is batch-dependent and deliberately
        never cached at trace time. 0 until a decode step has traced, or
        on the reference path."""
        from distrl_llm_tpu.ops.paged import dispatch_choices, paged_grid_steps

        choice = dispatch_choices.get(self._dispatch_key())
        if not choice:
            return 0
        return paged_grid_steps(
            choice, batch=rows, num_kv_heads=self.cfg.num_kv_heads,
            pps=self.prompt_pages + self.private_pages,
            pages_per_block=self.pages_per_block,
        )

    def _verify_dispatch_choice(self, draft_len: int | None = None):
        """What the draft-block verify dispatch actually ran:
        "native_verify" (the fused one-sweep kernel) or "unrolled" (S
        per-position dispatches), read from ``dispatch_choices`` under this
        engine's verify-marked key. The adaptive controller can trace
        several draft lengths in one round, so without an explicit
        ``draft_len`` the lookup walks d down from the configured max and
        returns the first recorded decision. None until a verify step has
        traced."""
        from distrl_llm_tpu.ops.paged import dispatch_choices

        lens = (
            [draft_len] if draft_len
            else list(range(self.spec_draft, 0, -1))
        )
        for dl in lens:
            choice = dispatch_choices.get(self._dispatch_key(verify_len=dl + 1))
            if choice:
                return choice
        return None

    @property
    def scan_chunk_active(self) -> bool | None:
        """Whether chunked decode (wave or refill) actually ran — None
        before the first round (or scan_chunk off), False if every attempt
        fell back to per-step dispatch (bench honesty flag, same contract
        as the dense engine's)."""
        if self.scan_chunk <= 1 or not self._chunk_compiled:
            return None
        return any(v is not None for v in self._chunk_compiled.values())

    def _chunk_program(self, tag: str, chunk_fn_partial, chunk: int,
                       max_steps: int, top_p_impl: str,
                       params, lora, state, rng, extra_args,
                       temperature, top_p):
        """Compiled K-steps-per-dispatch program (wave or refill flavor —
        ``tag`` keys them apart) for these shapes, or None where per-step
        dispatch should be used (memory guard or compile failure —
        compile_chunk_guarded). ``extra_args`` are flavor-specific
        positional operands after ``rng`` (the wave step's page table, the
        spec step's drafter adapter) — their STRUCTURE is part of the key:
        compiled executables raise on a structurally different operand
        tree instead of retracing, so e.g. a spec program built with a
        None drafter must not be handed a real adapter pytree."""
        key = (tag, chunk, max_steps, top_p_impl,
               lora_signature(state), lora_signature(lora),
               lora_signature(extra_args))
        return cached_chunk_program(
            self._chunk_compiled, self._chunk_mu, key,
            jax.jit(chunk_fn_partial, donate_argnames=("state",)),
            pool_nbytes(state.k_pages, state.v_pages),
            f"{tag} scan_chunk={chunk}",
            params, lora, state, rng, *extra_args,
            eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
        )

    def _refill_chunk_fn(self, chunk: int, max_steps: int, top_p_impl: str,
                         params, lora, state, rng, temperature, top_p):
        return self._chunk_program(
            "refill",
            partial(
                _refill_decode_chunk, chunk=chunk, cfg=self.cfg,
                page_size=self.page_size, pad_id=self.pad_id,
                lora_scale=self.lora_scale, paged_impl=self.paged_impl,
                pages_per_block=self.pages_per_block,
                max_steps=max_steps, top_p_impl=top_p_impl,
                capture_logprobs=self.capture_logprobs,
            ),
            chunk, max_steps, top_p_impl, params, lora, state, rng, (),
            temperature, top_p,
        )

    def _spec_chunk_fn(self, chunk: int, max_steps: int, top_p_impl: str,
                       params, lora, state, rng, temperature, top_p,
                       drafter_lora=None, draft_len: int | None = None):
        d = self.spec_draft if draft_len is None else draft_len
        return self._chunk_program(
            # the effective draft length is a static shape choice (the
            # adaptive controller switches it mid-round) — distinct
            # programs, distinct cache keys
            f"spec:d{d}",
            partial(
                _spec_decode_chunk, chunk=chunk, cfg=self.cfg,
                page_size=self.page_size, pad_id=self.pad_id,
                lora_scale=self.lora_scale, paged_impl=self.paged_impl,
                pages_per_block=self.pages_per_block,
                max_steps=max_steps, draft_len=d,
                ngram_k=self.spec_ngram,
                drafter=self.spec_drafter, spec_verify=self.spec_verify,
                hist_width=self.spec_draft + 2,
                top_p_impl=top_p_impl,
                capture_logprobs=self.capture_logprobs,
            ),
            chunk, max_steps, top_p_impl, params, lora, state, rng,
            (drafter_lora,),
            temperature, top_p,
        )

    def _wave_chunk_fn(self, chunk: int, max_steps: int, top_p_impl: str,
                       params, lora, state, rng, page_indices,
                       temperature, top_p):
        return self._chunk_program(
            "wave",
            partial(
                _paged_decode_chunk, chunk=chunk,
                cfg=self.cfg, page_size=self.page_size,
                pad_id=self.pad_id, lora_scale=self.lora_scale,
                paged_impl=self.paged_impl,
                pages_per_block=self.pages_per_block,
                top_p_impl=top_p_impl,
                capture_logprobs=self.capture_logprobs,
            ),
            chunk, max_steps, top_p_impl, params, lora, state, rng,
            (page_indices,), temperature, top_p,
        )

    def bucket_for(self, prompt_mask) -> int:
        """Single-bucket engine (interface parity with GenerationEngine's
        warm-key tracking in trainer._call_engine)."""
        return self.max_prompt_tokens

    def generate(
        self,
        params: Params,
        lora: Params | None,
        prompt_ids: np.ndarray,  # [B, P] left-padded (trainer contract)
        prompt_mask: np.ndarray,
        sampling: SamplingConfig,
        rng: jax.Array,
    ) -> GenerationResult:
        total = prompt_ids.shape[0] * max(sampling.n, 1)
        # a new round supersedes any swap consumed during the previous one
        self._reset_lora_mailbox_round()
        # pool telemetry is per-round (only the refill path produces it):
        # without this reset a wave-path round would leave a previous
        # refill/eval round's stats for trainer/bench snapshots to misread
        self.last_pool_stats = None
        self.last_spec_stats = None
        self.last_round_stats = None  # waves/refill of THIS round accumulate
        if self.turn_hook is not None and (
            self.scheduler != "refill" or not self.max_concurrent_rows
            or self.spec_draft
        ):
            raise ValueError(
                "turn_hook (multi-turn episodes) requires the refill "
                "scheduler with max_concurrent_rows set and no spec_draft — "
                "turn continuation lives in the refill idle pass"
            )
        if (
            self.scheduler == "refill"
            and self.max_concurrent_rows
            # spec decode and prefix sharing live on the refill path — a
            # configured speculative or prefix-sharing engine must not
            # silently fall back to plain waves on a small batch (review
            # finding; continuous_admission implies prefix_sharing). A turn
            # hook forces refill too: turn continuation is an idle-pass
            # feature
            and (total > self.max_concurrent_rows or self.spec_draft
                 or self.prefix_sharing or self.turn_hook is not None)
        ):
            self.last_cb_mode = self.cb_mode
            return self._generate_refill(
                params, lora, prompt_ids, prompt_mask, sampling, rng
            )
        self.last_cb_mode = "waves"
        return generate_in_waves(
            self._generate_wave, self.max_concurrent_rows, params, lora,
            prompt_ids, prompt_mask, sampling, rng, self.pad_id,
        )

    def _generate_refill(
        self, params, lora, prompt_ids, prompt_mask,
        sampling: SamplingConfig, rng: jax.Array,
    ) -> GenerationResult:
        """Continuous batching: R decode slots, refilled per-candidate.

        Where ``generate_in_waves`` admits whole prompt groups and pays each
        wave's straggler tail, this scheduler keeps exactly
        ``max_concurrent_rows`` candidate rows decoding and admits a pending
        candidate into every slot whose occupant hit EOS — vLLM's continuous
        batching (requirements.txt:6) at fixed shapes: the decode program is
        compiled once for [R] rows regardless of batch size; WHICH candidate
        a slot serves is data (the page-table row + scatter indices). Host
        bookkeeping mirrors slot occupancy; per-slot epochs ignore stale
        async done-snapshots taken before a refill."""
        from collections import deque

        b, p = prompt_ids.shape
        if p != self.max_prompt_tokens:
            raise ValueError(
                f"prompts must be padded to {self.max_prompt_tokens}, got {p}"
            )
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        n = max(sampling.n, 1)
        total = b * n
        # small batches (spec routing) need no more slots than candidates
        r_slots = min(self.max_concurrent_rows, total)
        sharing = self.prefix_sharing
        continuous = self.continuous_admission
        # serving observability (ISSUE 13): one attribute read per round
        # when unarmed; armed, the loop emits per-group lifecycle events
        # and the admission audit at its existing host boundaries — the
        # ledger observes, it never changes a scheduling decision
        sl = self.serving_ledger
        suid: dict[int, int] = {}  # group -> serving-record uid
        # multi-turn turn hook (ISSUE 17): one attribute read per round when
        # unarmed; armed, the idle pass consults it before retiring a
        # finished candidate (try_turn_resume below)
        th = self.turn_hook
        # closed-loop admission limits (ISSUE 14): one attribute read per
        # round when unarmed; armed, admit_groups consults the governors'
        # chain-cap scale and shed gate at its existing decision points —
        # a handle at its defaults decides identically to None (pinned)
        limits = self.control_limits
        # gateway tenancy (ISSUE 19): one attribute read per round when
        # unarmed; armed, admit_groups orders by class-then-FIFO-with-aging,
        # the quota book gates admissions, preemption prefers low classes,
        # and the stream hook flushes tokens at host boundaries
        meta = self.round_meta
        qb = self.quota_book
        stream = self.stream_hook
        t_enqueue = time.time()

        real_len_h = np.asarray(prompt_mask).sum(axis=-1).astype(np.int64)
        row_alive = real_len_h > 0
        ps = self.page_size
        prefill_tokens = int(real_len_h.sum())
        if continuous:
            # lazy per-group prefill (continuous admission): the pool
            # arrays start with ZERO prompt pages — each group's prompt KV
            # is prefilled at [1, P] and adopted into pool-allocated chain
            # pages when the request queue admits it mid-round
            t_prefill = 0.0
            shape0 = (self.cfg.num_kv_heads, 0, ps, self.cfg.head_dim)
            if self.kv_quant == "int8":
                from distrl_llm_tpu.ops.paged import init_quantized_pages

                def _empty():
                    return init_quantized_pages(shape0)
            else:
                def _empty():
                    return jnp.zeros(shape0, self.cache_dtype)
            prompt_k = tuple(_empty() for _ in range(self.cfg.num_layers))
            prompt_v = tuple(_empty() for _ in range(self.cfg.num_layers))
            # per-group sampling logits, scatter-published by each adopt
            # (the admit paths index it by prompt id exactly as they index
            # the monolithic prefill's batched logits)
            last_logits = jnp.zeros((b, self.cfg.vocab_size), jnp.float32)
            real_len = jnp.asarray(real_len_h.astype(np.int32))
        else:
            t0 = time.perf_counter()
            with telemetry.span("engine/prefill", rows=b,
                                tokens=prefill_tokens):
                prompt_k, prompt_v, last_logits, real_len = self._prefill(
                    params, lora, jnp.asarray(prompt_ids),
                    jnp.asarray(prompt_mask)
                )
                jax.block_until_ready(last_logits)
            t_prefill = time.perf_counter() - t0
        t_decode0 = time.perf_counter()
        dec_span = telemetry.span("engine/refill_decode", slots=r_slots,
                                  candidates=total)
        dec_span.__enter__()

        temperature = jnp.asarray(sampling.temperature, jnp.float32)
        top_p = jnp.asarray(sampling.top_p, jnp.float32)
        top_p_impl = sampling.resolved_top_p_impl(self.plan_top_p_impl)

        # --- page pool (vLLM's budgeted block pool, host-authoritative) ----
        from distrl_llm_tpu.engine.page_pool import PagePool

        total_shared = b * self.prompt_pages
        width = self.prompt_pages + self.private_pages
        if continuous:
            # prompt chains live IN the pool: worst case = scratch + every
            # slot's private region + a chain per concurrently-active group
            # (slots span at most min(b, r_slots) groups) + one prefetched
            worst_pool = (
                1 + r_slots * self.private_pages
                + min(b, r_slots + 1) * self.prompt_pages
            )
            shared_static = 0
        else:
            worst_pool = 1 + r_slots * self.private_pages
            shared_static = total_shared
        pool_pages = (
            min(self.max_kv_pages, worst_pool) if self.max_kv_pages
            else worst_pool
        )
        budgeted = pool_pages < worst_pool
        # tiered KV cache (ISSUE 18): only a continuous-admission round can
        # host it (cached chains are pool pages) — __init__ enforces the
        # pairing, this flag just names the round-local arming
        cache_on = self.prefix_cache and continuous
        pool = PagePool(
            first_page=shared_static, n_pages=pool_pages, r_slots=r_slots,
            width=width, page_size=self.page_size,
            prompt_pages=self.prompt_pages, prefix_sharing=sharing,
            radix=self._radix if cache_on else None,
            store=self._kv_store if cache_on else None,
        )
        # round-local cache bookkeeping (all inert when the cache is off):
        # un-padded prompt token rows (radix keys + cache_chain retirement),
        # per-group hit sizes (serving-ledger provenance), and the round's
        # restore-latency samples (spill_restore_ms_p50)
        group_hit_tok: dict[int, int] = {}
        restore_ms: list[float] = []
        # live ("preempt", cand) host-store keys: candidate ids are round-
        # scoped, so any payload not consumed by a resume is dropped at
        # round end rather than leaking into the engine-lifetime store
        spilled_keys: set = set()
        if cache_on:
            # adapter identity guard: cached KV is only exact under the
            # adapter that wrote it — any change (each training round hands
            # the engine a new LoRA object) drops the whole cache. The
            # strong reference held in __init__ keeps `is` sound.
            if self._cache_lora_ref is not lora:
                pool.invalidate_cache()
                self._cache_lora_ref = lora
            real_toks = [
                np.asarray(prompt_ids[g])[
                    np.asarray(prompt_mask[g]) > 0
                ].astype(np.int32)
                for g in range(b)
            ]
            radix_snap0 = self._radix.snapshot()
        # cache writes stay legal until a mid-round weight swap is consumed
        # (chains prefilled before the swap must not enter the cache under
        # the post-swap adapter identity)
        cache_write = [cache_on]
        if sharing and not continuous:
            # adopt the monolithic prefill's static region as refcounted
            # prefix chains: ceil(rl/ps) live pages per prompt (full pages
            # + the pristine partial tail) held until the group finishes,
            # with each prompt's slack — and dead padding rows' whole
            # regions — reclaimed into the free list as decode capacity
            for g in range(b):
                base = g * self.prompt_pages
                region = list(range(base, base + self.prompt_pages))
                if not row_alive[g]:
                    pool.reclaim(region)
                    continue
                n_chain = max(-(-int(real_len_h[g]) // ps), 1)
                pool.register_prefix(
                    g, region[:n_chain], int(real_len_h[g]) // ps
                )
                pool.reclaim(region[n_chain:])
        # snapshot cadence: never longer than a short decode's whole run
        check = max(1, min(self.decode_chunk, 16, max_steps))
        # grant horizon: a slot's write frontier can advance for up to
        # 2·check steps of snapshot lag plus check steps until the next
        # grant pass (plain decode writes 1 token/step)
        lag_tokens = 3 * check
        # in-flight weight updates read the adapter through this cell
        lora_cell = [lora]
        # sampling-logits cell: continuous admission republishes it per
        # group adopt; the admit closures read it at call time
        logits_cell = [last_logits]

        def _admit_extras(src_partial, copy_mask):
            """Host-authored partial-page copy plan (prefix sharing): per-
            slot CoW sources + which admitted slots copy at all (page-
            aligned prompts skip the copy). Empty on unshared engines so
            the historical admit trace is untouched."""
            if not sharing:
                return ()
            sp = (
                np.full(r_slots, pool.scratch, np.int32)
                if src_partial is None else src_partial
            )
            cm = np.zeros(r_slots, bool) if copy_mask is None else copy_mask
            return (jnp.asarray(sp), jnp.asarray(cm))

        if self.spec_draft:
            # speculative mode: slots carry a pending token + sequence
            # buffer. Verify writes land up to spec_draft positions PAST the
            # frontier and emission is up to d+1 tokens/step, so the grant
            # horizon scales by (d+1) and covers the verify overhang
            d = self.spec_draft
            lag_tokens = 3 * check * (d + 1) + d
            write_ceiling_extra = d
            packed_ids, _, _ = _pack_rows(
                jnp.asarray(prompt_ids), jnp.asarray(prompt_mask)
            )
            buf_width = self.max_prompt_tokens + max_steps + self.spec_draft + 2
            state = self._spec_init(
                prompt_k, prompt_v, b=b, r_slots=r_slots, total=total,
                max_steps=max_steps, buf_width=buf_width,
                pool_pages=pool_pages, hist_width=d + 2,
                shared_pages=shared_static,
            )
            admit_seq = iter(range(1 << 30))
            # the self-drafter runs the policy's own PREVIOUS adapter
            # version (the LoraMailbox swap log's superseded slot); before
            # any swap has ever happened, the current adapter doubles as
            # its own drafter — q == p, near-total acceptance, exact.
            # the ngram drafter never reads the operand: keep it None so
            # every spec dispatch skips flattening a dead LoRA pytree and
            # the chunk-program signature stays swap-stable
            if self.spec_drafter == "self":
                drafter_cell = [
                    self._prev_lora if self._prev_lora is not None else lora
                ]
            else:
                drafter_cell = [None]
            drafter_version = (
                self._prev_lora_version
                if (self.spec_drafter == "self" and self._prev_lora is not None)
                else None
            )
            # effective draft length (the adaptive controller shrinks/grows
            # it between host boundaries; shapes sized for the max)
            d_cell = [d]
            d_switches = 0

            def step(s):
                return self._spec_step(
                    params, lora_cell[0], s, rng, drafter_cell[0],
                    eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p, max_steps=max_steps,
                    draft_len=d_cell[0], ngram_k=self.spec_ngram,
                    top_p_impl=top_p_impl,
                )

            def admit(s, new_cand, admit_mask, dst_partial,
                      src_partial=None, copy_mask=None):
                return self._spec_admit(
                    s, jnp.asarray(new_cand), jnp.asarray(admit_mask),
                    logits_cell[0], real_len, packed_ids,
                    jax.random.fold_in(rng, 100_000 + next(admit_seq)),
                    temperature, top_p, jnp.asarray(dst_partial),
                    *_admit_extras(src_partial, copy_mask),
                    n=n, b=b, eos_ids=self.eos_ids,
                    top_p_impl=top_p_impl,
                )

            def admit_last_pos(rl: int, plen: int) -> int:
                if not budgeted:
                    return rl + max_steps + d  # worst case: no grant passes
                # grow-as-you-go: cover the resumed prefix plus the spec
                # grant horizon, never past the ceiling + verify overhang
                return min(rl + max(plen, 1) + lag_tokens, rl + max_steps + d)
        else:
            write_ceiling_extra = 0
            state = self._refill_init(
                prompt_k, prompt_v, b=b, r_slots=r_slots, total=total,
                max_steps=max_steps, vocab=self.cfg.vocab_size,
                pool_pages=pool_pages, shared_pages=shared_static,
            )

            def step(s):
                return self._refill_step(
                    params, lora_cell[0], s, rng, eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p, max_steps=max_steps,
                    top_p_impl=top_p_impl,
                )

            def admit(s, new_cand, admit_mask, dst_partial,
                      src_partial=None, copy_mask=None):
                return self._refill_admit(
                    s, jnp.asarray(new_cand), jnp.asarray(admit_mask),
                    logits_cell[0], real_len, jnp.asarray(dst_partial),
                    *_admit_extras(src_partial, copy_mask), n=n, b=b,
                )

            def admit_last_pos(rl: int, plen: int) -> int:
                if not budgeted:
                    # worst-case pool: grant the full region at admit (no
                    # grant passes run, so nothing else would extend it)
                    return rl + max_steps
                # grow-as-you-go: cover the (resumed) prefix plus the grant
                # horizon, never past the sequence's hard ceiling
                return min(rl + plen + lag_tokens, rl + max_steps)

        if cache_on:
            # spill transport: MAIN-thread device gather into independent
            # buffers (jit outputs, never views into the donated state
            # pools) — the host store's worker thread only converts them.
            # The closure reads the loop's CURRENT `state` binding: every
            # pool path that can evict (alloc/admit/ensure/note_write) runs
            # between dispatches, while the binding holds live buffers.
            def _spill_payload(page):
                return self._gather_page(
                    state.k_pages, state.v_pages,
                    jnp.asarray(page, jnp.int32),
                )

            pool.spill_fn = _spill_payload
        # measured bytes/token source (ISSUE 15; DISTRL_MEASURE_COST=1
        # only): file the slot-step program's XLA cost_analysis once
        from distrl_llm_tpu import obs as _obs

        if self.spec_draft:
            _obs.maybe_record_step_cost(
                "decode_step/spec", self._spec_step, params, lora_cell[0],
                state, rng, drafter_cell[0], eos_ids=self.eos_ids,
                temperature=temperature, top_p=top_p, max_steps=max_steps,
                draft_len=d_cell[0], ngram_k=self.spec_ngram,
                top_p_impl=top_p_impl,
            )
        else:
            _obs.maybe_record_step_cost(
                "decode_step/refill", self._refill_step, params,
                lora_cell[0], state, rng, eos_ids=self.eos_ids,
                temperature=temperature, top_p=top_p, max_steps=max_steps,
                top_p_impl=top_p_impl,
            )
        # K-steps-per-dispatch (tunnel dispatch-overhead lever). K must
        # DIVIDE `check`: the host acts when since_host >= check, so a
        # non-divisor K stretches the effective cadence to ceil(check/K)·K
        # steps — past the grant horizon lag_tokens = 3·check, which on a
        # budgeted pool would let a slot's write frontier overrun its
        # granted pages and clamp-write onto resident KV (review finding).
        # With K | check, every host decision point runs at exactly its
        # per-step cadence and outputs are bit-identical.
        chunk_fn = None
        k_chunk = 1
        if self.spec_draft:
            def builder(k, ms, tpi, params_, lora_, state_, rng_, temp_, tp_):
                # late-bound drafter adapter and effective draft length: a
                # swap rotates the drafter, the controller resizes d — both
                # must reach any REBUILT program
                return self._spec_chunk_fn(
                    k, ms, tpi, params_, lora_, state_, rng_, temp_, tp_,
                    drafter_lora=drafter_cell[0], draft_len=d_cell[0],
                )
        else:
            builder = self._refill_chunk_fn
        # divisor-adjusted chunk size this round WANTS (0 = chunking off);
        # kept separate from k_chunk so a swap-driven fallback to per-step
        # dispatch can re-enable chunking when a later swap returns to a
        # signature whose program is cached
        k_conf = 0
        if self.scan_chunk > 1 and check > 1:
            k_conf = min(self.scan_chunk, check)
            while check % k_conf:
                k_conf -= 1
            if k_conf > 1:
                chunk_fn = builder(
                    k_conf, max_steps, top_p_impl, params, lora_cell[0],
                    state, rng, temperature, top_p,
                )
                k_chunk = k_conf if chunk_fn is not None else 1
            else:
                k_conf = 0
        # signature the chunk program was built for: an in-flight swap to a
        # structurally different adapter must refetch (compiled executables
        # raise on structure change instead of retracing — ADVICE r3). The
        # drafter operand is part of the signature: the first consumed swap
        # on a lora=None round rotates the drafter None→adapter with the
        # TARGET signature unchanged, so keying on the target alone would
        # feed a structurally new drafter tree to the stale executable
        def _chunk_round_sig():
            sig = lora_signature(lora_cell[0])
            if self.spec_draft:
                return (sig, lora_signature(drafter_cell[0]))
            return sig

        chunk_sig = _chunk_round_sig() if k_conf else None

        # dead prompts (batch padding) are never enqueued: their rows keep
        # pad tokens / zero length, same as wave mode's born-done rows.
        # Pending entries are candidate ids, or (cand, prefix, prefix_len)
        # for preempted candidates awaiting recompute.
        if continuous:
            # the request queue holds GROUPS awaiting their lazy prefill;
            # the candidate queue fills as admit_group() runs them
            pending: deque = deque()
            group_queue: deque = deque(g for g in range(b) if row_alive[g])
        else:
            pending = deque(c for c in range(total) if row_alive[c // n])
            group_queue = deque()
        finished = np.array([not row_alive[c // n] for c in range(total)])
        # per-group unfinished-candidate counts: a group's prefix-chain
        # hold drops only when its LAST candidate finishes (a preempted
        # candidate must still find the pristine chain on resume)
        group_left = np.array(
            [n if row_alive[g] else 0 for g in range(b)]
        )
        if sl is not None:
            # open one serving record per live group as it enters the
            # request queue (enqueue = round entry); the monolithic-
            # prefill path has every group's prompt KV resident before
            # any admission, so prefill-done lands here too
            for g in range(b):
                if row_alive[g]:
                    mg = meta.get(g) if meta is not None else None
                    suid[g] = sl.on_enqueue(
                        g, n=n, prompt_tokens=int(real_len_h[g]),
                        tenant=mg.get("tenant") if mg else None,
                        priority=mg.get("cls") if mg else None,
                        trace_ctx=mg.get("trace_ctx") if mg else None,
                        # gateway rounds stamp the request's true ARRIVAL
                        # time so queue_wait/TTFT include the open-queue
                        # wait, not just the in-round wait
                        ts=(
                            mg.get("arrival_ts") or t_enqueue
                        ) if mg else t_enqueue,
                    )
            if not continuous:
                for uid_g in suid.values():
                    sl.on_prefill_done(uid_g)
        groups_prefilled = 0
        backfill_admits = 0
        boundary_admits = 0  # admissions (slots + prefills) this host pass
        fill_declined: str | None = None  # fill_idle's head-of-line decline
        shed_groups_seen: set[int] = set()  # groups the shedder deferred
        # gateway round-local bookkeeping (ISSUE 19; all dead when meta is
        # None): per-group quota reservations, deterministic aging counters,
        # per-candidate streamed-token cursors, the declined head group's
        # class for the per-class stall attribution, and the per-class
        # shed/preempt action tally the bench artifact scores
        quota_charged: dict[int, int] = {}
        group_waited: dict[int, int] = {}
        stream_sent: dict[int, int] = {}
        decline_cls: str | None = None
        class_actions: dict[str, dict[str, int]] = {
            "shed": {}, "preempt": {},
        }
        # turn-resume declines for lack of max_new_tokens window (the
        # PR 17 CharTokenizer gotcha): (obs_tokens, needed_window) pairs,
        # warned once per round when EVERY resume was window-declined
        window_declines: list[tuple[int, int]] = []
        window_short = 0  # resumes never offered: no room for obs+1 at all

        def cls_of(g: int) -> str | None:
            mg = meta.get(g) if meta is not None else None
            return mg.get("cls") if mg else None

        def rank_of(g: int) -> int:
            mg = meta.get(g) if meta is not None else None
            return int(mg.get("rank", 0)) if mg else 0

        def eff_rank(g: int) -> int:
            # FIFO-with-aging (no starvation): every 16 passed-over
            # admission passes promote the group one class step toward
            # rank 0 — pass counters, never wall clock, so the schedule
            # is deterministic and replayable
            return max(0, rank_of(g) - group_waited.get(g, 0) // 16)
        dispatched = 0
        turn_resumes = 0  # in-place episode continuations (turn hook)
        turn_saved = 0  # resident-prefix tokens those resumes never re-prefilled
        host_cand = np.full(r_slots, total, np.int64)  # device `cand` mirror
        epoch = np.zeros(r_slots, np.int64)

        def mark_finished(c: int) -> None:
            if finished[c]:
                return
            finished[c] = True
            if sl is not None:
                sl.on_finish(suid.get(c // n), c)
            if qb is not None and quota_charged.get(c // n):
                g_q = c // n
                if bool(finished[g_q * n:(g_q + 1) * n].all()):
                    # the group's last candidate finished: release its
                    # tenant's token reservation (charge at admission,
                    # credit at close — the quota bounds in-flight
                    # footprint, not lifetime usage)
                    qb.credit(
                        (meta.get(g_q) or {}).get("tenant", ""),
                        quota_charged.pop(g_q),
                    )
            if sharing:
                g = c // n
                group_left[g] -= 1
                if group_left[g] == 0 and g in pool.chains:
                    if cache_write[0]:
                        # tiered cache (ISSUE 18): the finished chain's full
                        # pages become radix inventory instead of freeing —
                        # the next admission sharing this prefix aliases
                        # them with zero prefill. The mutable partial tail
                        # derefs as before; chain holds transfer in place.
                        pool.cache_chain(g, real_toks[g])
                    else:
                        # refcount hold drops; the chain pages free as the
                        # last slot references release (CoW release
                        # discipline)
                        pool.drop_prefix(g)

        # graftcheck: hot-region cont-admission
        def admit_group(g: int) -> bool:
            """Lazily prefill group ``g``'s prompt into pool-allocated
            chain pages ([1, P] reuse of the jitted prefill — bit-identical
            per row to the batched pass), adopt the tiles + logits into the
            live pool arrays, and enqueue the group's candidates."""
            nonlocal state, groups_prefilled, t_prefill, boundary_admits
            rl = int(real_len_h[g])
            n_chain = max(-(-rl // ps), 1)
            resident: list = []
            if cache_on:
                # tier-1 longest-prefix match (ISSUE 18), restoring any
                # spilled matched pages from the host store first
                nodes, _hit = pool.radix_match(real_toks[g])
                if nodes:
                    resident, uploads = pool.restore_nodes(nodes)
                    if uploads:
                        t0r = time.perf_counter()
                        for _node, page, payload in uploads:
                            k_t, v_t = payload
                            state = self._restore_page(
                                state, k_t, v_t,
                                jnp.asarray(page, jnp.int32),
                            )
                        jax.block_until_ready(state.k_pages[0])
                        ms = (time.perf_counter() - t0r) * 1e3
                        pool.note_restore_ms(ms)
                        restore_ms.append(ms)
            if resident:
                return admit_group_warm(g, resident, rl, n_chain)
            chain = pool.alloc_prefix(g, n_chain, rl // ps)
            if chain is None:
                return False
            if cache_on:
                group_hit_tok[g] = 0
            t0 = time.perf_counter()
            with telemetry.span("engine/prefill", rows=1, tokens=rl):
                k_t, v_t, logits_g, _rl = self._prefill(
                    params, lora_cell[0], prompt_ids_j[g:g + 1],
                    prompt_mask_j[g:g + 1],
                )
            dst = np.full(self.prompt_pages, pool.scratch, np.int32)
            dst[:n_chain] = chain
            state, logits_cell[0] = self._cont_adopt(
                state, k_t, v_t, jnp.asarray(dst), logits_cell[0],
                logits_g[0], jnp.asarray(g, jnp.int32),
            )
            # block before stopping the timer (the monolithic prefill
            # path's convention): under async dispatch the device-side
            # prefill would otherwise serialize into the decode stream and
            # be misattributed to decode_s. The measurement is an UPPER
            # bound — the wait can also absorb the drain of decode chunks
            # already queued — but decode absorbing prefill would bias the
            # fixed-vs-continuous A/B in the new mode's favor
            jax.block_until_ready(logits_cell[0])
            t_prefill += time.perf_counter() - t0
            groups_prefilled += 1
            boundary_admits += 1
            telemetry.counter_add(ENGINE_CONT_PREFILLS)
            if sl is not None:
                sl.on_prefill_done(suid.get(g))
            pending.extend(range(g * n, (g + 1) * n))
            return True

        def admit_group_warm(g: int, resident, rl: int,
                             n_chain: int) -> bool:
            """Radix-hit admission (ISSUE 18): alias the matched resident
            pages refcounted into group ``g``'s chain and forward ONLY the
            un-cached suffix — its KV writes land in the chain's fresh
            pages (the match is capped below the last token, so no write
            ever touches a cached page) and the group's sampling logits
            come off the suffix's last real token through the chunked
            paged forward, exactly the `_resume_fixup` shape."""
            nonlocal state, groups_prefilled, t_prefill, boundary_admits
            chain = pool.admit_cached(g, resident, n_chain, rl // ps)
            if chain is None:
                return False
            hit = len(resident) * ps
            group_hit_tok[g] = hit
            suffix = real_toks[g][hit:rl]
            t0 = time.perf_counter()
            with telemetry.span("engine/prefill", rows=1,
                                tokens=rl - hit):
                suf = np.full(self.prompt_pages * ps, self.pad_id,
                              np.int32)
                suf[:suffix.size] = suffix
                row_ext = np.full(self.prompt_pages + 1, pool.scratch,
                                  np.int32)
                row_ext[:n_chain] = chain
                state, logits_cell[0] = self._warm_prefill(
                    params, lora_cell[0], state, jnp.asarray(row_ext),
                    jnp.asarray(suf), jnp.asarray(suffix.size, jnp.int32),
                    jnp.asarray(hit, jnp.int32), logits_cell[0],
                    jnp.asarray(g, jnp.int32),
                )
                # same timer discipline as the cold adopt above: block so
                # the suffix forward is attributed to prefill, not decode
                jax.block_until_ready(logits_cell[0])
            t_prefill += time.perf_counter() - t0
            groups_prefilled += 1
            boundary_admits += 1
            telemetry.counter_add(ENGINE_CONT_PREFILLS)
            if sl is not None:
                sl.on_prefill_done(suid.get(g))
            pending.extend(range(g * n, (g + 1) * n))
            return True

        def try_admit_group(g: int) -> str | None:
            """One group's admission decision: the decline reason, or None
            when the group was admitted. Shared by the FIFO path and the
            gateway's class-ordered path — the checks and their order are
            identical, so non-gateway rounds decide exactly as before."""
            if limits is not None and limits.shed_active() and (
                pending or bool((host_cand < total).any())
            ) and rank_of(g) >= (
                limits.shed_floor() if meta is not None else 0
            ):
                # class-aware shed (ISSUE 19): the governor's shed floor
                # names the lowest rank still admitted — scavenger sheds
                # before batch, interactive never sheds at floor >= 1.
                # Without gateway identity every group is rank 0 and the
                # floor is pinned 0: the ISSUE 14 behavior, bit for bit
                if g not in shed_groups_seen:
                    # counted once per deferred group, however many
                    # passes decline it (the bench row's shed_groups)
                    shed_groups_seen.add(g)
                    telemetry.counter_add(CONTROL_SHED_GROUPS)
                    c_g = cls_of(g)
                    if c_g is not None:
                        class_actions["shed"][c_g] = (
                            class_actions["shed"].get(c_g, 0) + 1
                        )
                return "shed"
            if len(pending) >= r_slots:
                return "no_slots"
            cap = r_slots + 1
            if limits is not None:
                cap = limits.chain_cap(cap)
            if len(pool.chains) >= cap:
                return "chain_cap"
            if qb is not None and meta is not None and g not in quota_charged:
                # per-tenant token quota (ISSUE 19): reserve the group's
                # WORST-CASE footprint (prompt + full output window) before
                # touching pool state; a decline is the ``quota`` stall
                # reason. The charge sticks across declined passes (the
                # group stays queued) and credits back at group finish.
                mg = meta.get(g)
                if mg is not None:
                    # the window is the REQUEST's own budget when the meta
                    # carries one (the gateway caps each request below the
                    # round max) — this keeps the charge equal to what the
                    # gateway's submit-time quota check priced, so a request
                    # that entered the queue can always eventually admit
                    charge = int(real_len_h[g]) + n * min(
                        max_steps, int(mg.get("max_new", max_steps))
                    )
                    if not qb.try_charge(mg.get("tenant", ""), charge):
                        return "quota"
                    quota_charged[g] = charge
            n_chain = max(-(-int(real_len_h[g]) // ps), 1)
            if pool.free_pages < n_chain + self.private_pages:
                return "no_pages"
            if not admit_group(g):
                return "no_pages"
            return None

        def admit_groups() -> str | None:
            """Admission-ahead: keep the candidate queue stocked while the
            pool can afford the head group's chain AND a full private
            region on top (never starve a running slot's grants), capped at
            one prefetched chain beyond the slots' worst-case group spread
            (the worst_pool sizing above). Returns the head group's decline
            reason when the queue is left waiting (the admission audit's
            attribution, ISSUE 13), None when the queue drained.

            Control hooks (ISSUE 14): an armed SLO shedder declines new
            GROUP admissions with the ``shed`` reason — but only while the
            engine has live work to drain (shedding an otherwise-empty
            engine would wedge it, not protect it); the HBM governor's
            admission fraction scales the live-chain cap.

            Gateway rounds (ISSUE 19, ``meta`` armed): groups are visited
            in class-then-FIFO-with-aging order, and a POLICY decline
            (shed/quota) on one group skips ahead to the next — an
            interactive group never waits behind a shed scavenger. A
            RESOURCE decline (slots/pages/chain cap) still ends the pass
            head-of-line, exactly like the FIFO path, so pool pressure
            keeps its auditable ordering."""
            nonlocal decline_cls
            decline_cls = None
            if meta is None:
                while group_queue:
                    reason = try_admit_group(group_queue[0])
                    if reason is not None:
                        return reason
                    group_queue.popleft()
                return None
            while group_queue:
                order = sorted(
                    group_queue,
                    key=lambda g: (
                        eff_rank(g), (meta.get(g) or {}).get("seq", g),
                    ),
                )
                head_reason: str | None = None
                admitted_g: int | None = None
                for g in order:
                    reason = try_admit_group(g)
                    if reason is None:
                        admitted_g = g
                        break
                    if head_reason is None:
                        head_reason = reason
                        decline_cls = cls_of(g)
                    if reason not in ("shed", "quota"):
                        break  # resource decline: no skip-ahead past it
                if admitted_g is None:
                    for g in group_queue:
                        group_waited[g] = group_waited.get(g, 0) + 1
                    return head_reason
                group_queue.remove(admitted_g)
                for g in group_queue:
                    # passed-over groups age one pass per admission ahead
                    # of them (the deterministic starvation valve)
                    group_waited[g] = group_waited.get(g, 0) + 1
            return None
        # graftcheck: end-hot-region

        if continuous:
            prompt_ids_j = jnp.asarray(prompt_ids)
            prompt_mask_j = jnp.asarray(prompt_mask)

        def fill_idle(s, idle_slots):
            nonlocal backfill_admits, boundary_admits, fill_declined
            new_cand = np.full(r_slots, total, np.int32)
            admit_mask = np.zeros(r_slots, bool)
            dst_partial = np.full(r_slots, pool.scratch, np.int32)
            src_partial = np.full(r_slots, pool.scratch, np.int32)
            copy_mask = np.zeros(r_slots, bool)
            resumes = []
            for s_i in idle_slots:
                if not pending:
                    break
                entry = pending[0]
                c, prefix, plen, logp0 = (
                    entry if isinstance(entry, tuple) else (entry, None, 0, 0.0)
                )
                pr = c // n
                rl = int(real_len_h[pr])
                # admission gated on FREE PAGES (vLLM's can_allocate); the
                # queue is FIFO — a head-of-line candidate that doesn't fit
                # blocks the rest rather than being starved by skip-ahead.
                # Under prefix sharing the slot ALIASES its group's chain
                # (refcount++) and first_write=rl names the imminent first
                # decode write, so the pool's copy-on-write split of the
                # partial tail page runs as part of this admission — the
                # engine registers chains rather than passing donor slots
                # because a pending candidate must outlive its siblings
                # (the chain hold persists until the group finishes)
                if not pool.admit(
                    int(s_i), pr, rl, admit_last_pos(rl, plen),
                    first_write=rl if sharing else None,
                ):
                    fill_declined = "no_pages"
                    break
                pending.popleft()
                boundary_admits += 1
                new_cand[s_i] = c
                admit_mask[s_i] = True
                dst_partial[s_i] = pool.owned[int(s_i)][0]
                if sl is not None:
                    # admission event with the pool's chain-alias facts:
                    # how much of the prompt this slot aliases and whether
                    # a CoW tail split rides this admit dispatch — read
                    # BEFORE take_copy drains the queued copy source
                    alias = pool.slot_alias_info(int(s_i))
                    sl.on_admit(
                        suid.get(pr), cand=c, slot=int(s_i),
                        shared_pages=int(alias["shared_pages"]),
                        cow=bool(alias["cow_queued"]),
                        backfill=dispatched > 0, resumed=bool(plen),
                        prefix_hit_tokens=group_hit_tok.get(pr, 0),
                    )
                if sharing:
                    src = pool.take_copy(int(s_i))
                    if src is not None:
                        src_partial[s_i] = src
                        copy_mask[s_i] = True
                if plen:
                    resumes.append((int(s_i), prefix, plen, rl, c // n, logp0))
            if admit_mask.any():
                s = admit(s, new_cand, admit_mask, dst_partial,
                          src_partial, copy_mask)
                host_cand[admit_mask] = new_cand[admit_mask]
                epoch[admit_mask] += 1
                if dispatched:
                    # mid-round backfill: the admissions a fixed episode
                    # batch would have left idle
                    k_admit = int(admit_mask.sum())
                    backfill_admits += k_admit
                    telemetry.counter_add(ENGINE_BACKFILL_ADMITS, k_admit)
                # admitted slots' table rows must reach the device before
                # their first decode step (and before any resume fixup)
                s = s._replace(page_indices=jnp.asarray(pool.table))
                for s_i, prefix, plen, rl, pr, logp0 in resumes:
                    if self.kv_spill:
                        payload = self._kv_store.get(
                            ("preempt", int(new_cand[s_i]))
                        )
                        if payload is not None:
                            # tier-2 resume (ISSUE 18): the preempt spill
                            # parked the slot's written pages + logits row
                            # host-side — reload them bit-exactly into the
                            # freshly granted pages (same block order) and
                            # fast-forward the cursors; nothing recomputes.
                            # Payload aged out of the store's byte cap →
                            # fall through to the recompute fixup below.
                            t0r = time.perf_counter()
                            owned = pool.owned[int(s_i)]
                            nv = int(payload["n_valid"])
                            for pg, (k_t, v_t) in zip(
                                owned[:nv], payload["tiles"]
                            ):
                                s = self._restore_page(
                                    s, k_t, v_t,
                                    jnp.asarray(pg, jnp.int32),
                                )
                            s = self._spill_fixup(
                                s, jnp.asarray(s_i, jnp.int32),
                                jnp.asarray(payload["logits"]),
                                jnp.asarray(plen, jnp.int32),
                                jnp.asarray(rl, jnp.int32),
                            )
                            jax.block_until_ready(s.logits)
                            ms = (time.perf_counter() - t0r) * 1e3
                            pool.note_restore_ms(ms)
                            pool.note_restored(nv)
                            restore_ms.append(ms)
                            # the restored content goes stale the moment
                            # decode continues — drop the host copy
                            self._kv_store.drop(
                                ("preempt", int(new_cand[s_i]))
                            )
                            spilled_keys.discard(
                                ("preempt", int(new_cand[s_i]))
                            )
                            continue
                    if self.spec_draft:
                        # host-rebuilt n-gram buffer: packed prompt + prefix
                        buf_w = s.seq_buf.shape[1]
                        row = np.zeros(buf_w, np.int32)
                        real = np.asarray(prompt_ids[pr])[
                            np.asarray(prompt_mask[pr]) > 0
                        ]
                        row[:rl] = real
                        row[rl:rl + plen] = prefix[:plen]
                        s = self._spec_resume_fixup(
                            params, lora_cell[0], s, jnp.asarray(s_i, jnp.int32),
                            jnp.asarray(prefix, jnp.int32),
                            jnp.asarray(plen, jnp.int32),
                            jnp.asarray(rl, jnp.int32),
                            jnp.asarray(row),
                            jnp.asarray(logp0, jnp.float32),
                        )
                    else:
                        s = self._resume_fixup(
                            params, lora_cell[0], s, jnp.asarray(s_i, jnp.int32),
                            jnp.asarray(prefix, jnp.int32),
                            jnp.asarray(plen, jnp.int32),
                            jnp.asarray(rl, jnp.int32),
                        )
            return s

        def preempt(s_i: int):
            """Evict slot ``s_i``'s occupant: requeue it (with its generated
            prefix, for recompute) at the FRONT of the queue, free its pages,
            and kill the slot on device via a dead-sentinel admit."""
            nonlocal state
            c = int(host_cand[s_i])
            # blocking read of the slot's CURRENT truth (the snapshot lags):
            # preemption is rare, the sync is the cost of exactness
            if bool(np.asarray(state.done[s_i])):
                mark_finished(c)  # finished while we deliberated
            else:
                plen = int(np.asarray(state.lengths_buf[c]))
                if plen:
                    prefix = np.asarray(state.out[c]).astype(np.int32)
                    # spec re-admission re-samples a first token and clobbers
                    # logps_buf[c, 0]; carry the ORIGINAL behavior logprob so
                    # the resume fixup can restore it
                    logp0 = (
                        float(np.asarray(state.logps_buf[c, 0]))
                        if self.capture_logprobs else 0.0
                    )
                    if self.kv_spill:
                        # tier-2 spill (ISSUE 18): park the slot's WRITTEN
                        # pages (its CoW tail + decode pages, block order)
                        # and its logits row host-side BEFORE releasing the
                        # pages — resume becomes a bit-exact page reload
                        # instead of a recompute forward. Gathers dispatch
                        # here on the main thread; the store thread only
                        # converts the finished buffers.
                        rl_p = int(real_len_h[c // n])
                        owned = pool.owned[s_i]
                        n_valid = min(
                            (rl_p + plen - 1) // ps - rl_p // ps + 1,
                            len(owned),
                        )
                        self._kv_store.put(
                            ("preempt", c),
                            {
                                "tiles": [
                                    self._gather_page(
                                        state.k_pages, state.v_pages,
                                        jnp.asarray(pg, jnp.int32),
                                    )
                                    for pg in owned[:n_valid]
                                ],
                                "logits": state.logits[s_i],
                                "n_valid": np.int64(n_valid),
                            },
                        )
                        pool.note_spilled(n_valid)
                        spilled_keys.add(("preempt", c))
                    pending.appendleft((c, prefix, plen, logp0))
                else:
                    pending.appendleft(c)
                pool.preemptions += 1
                if sl is not None:
                    sl.on_preempt(suid.get(c // n), c)
                c_g = cls_of(c // n)
                if c_g is not None:
                    class_actions["preempt"][c_g] = (
                        class_actions["preempt"].get(c_g, 0) + 1
                    )
            pool.release(s_i)
            kill_cand = np.full(r_slots, total, np.int32)
            kill_mask = np.zeros(r_slots, bool)
            kill_mask[s_i] = True
            dstp = np.full(r_slots, pool.scratch, np.int32)
            state = admit(state, kill_cand, kill_mask, dstp)
            host_cand[s_i] = total
            epoch[s_i] += 1

        def try_turn_resume(s_i: int, c: int) -> bool:
            """Multi-turn continuation (ISSUE 17): before retiring a finished
            candidate, offer its completion to the turn hook. If the hook
            returns observation tokens and the slot has token room and pages,
            append them in place — the slot keeps its occupant AND its pages,
            so the whole conversation prefix (``seq_len`` tokens of resident
            KV) is never re-prefilled. Returns True when the slot resumed
            (the idle pass must then NOT release/finish it). Declines —
            size, page pressure — unwind via ``hook.declined`` so the driver
            can close the episode as truncated; declining instead of
            preempting victims keeps turn continuation strictly lower
            priority than first-turn progress."""
            nonlocal state, budget, turn_resumes, turn_saved, window_short
            # blocking read of the candidate's CURRENT truth: done is
            # monotone per epoch, so the occupant has truly finished; turn
            # boundaries are rare relative to decode steps, same cost
            # argument as preempt()
            gen_len = int(np.asarray(state.lengths_buf[c]))
            if gen_len + 2 > max_steps:
                # no room for even one observation + one decode token: the
                # hook is never consulted, the driver scores the final turn
                # from the result tensors
                window_short += 1
                return False
            tokens = np.asarray(state.out[c][:gen_len]).astype(np.int32)
            obs = th(c, tokens)
            if obs is None:
                return False
            obs = np.asarray(obs, np.int32).ravel()
            t_obs = int(obs.size)
            if t_obs == 0 or gen_len + t_obs + 1 > max_steps:
                if t_obs > 0:
                    # window decline, not an empty observation: remember
                    # what WOULD have fit for the round-end diagnostic
                    window_declines.append((t_obs, gen_len + t_obs + 1))
                th.declined(c)
                return False
            rl = int(real_len_h[c // n])
            seq_len = rl + gen_len
            if pool.ensure(s_i, admit_last_pos(rl, gen_len + t_obs)):
                th.declined(c)
                return False
            # any pages ensure granted must reach the device BEFORE the
            # fixup's chunked forward scatters observation KV
            state = state._replace(page_indices=jnp.asarray(pool.table))
            obs_pad = np.full(max_steps, self.pad_id, np.int32)
            obs_pad[:t_obs] = obs
            state = self._turn_resume(
                params, lora_cell[0], state, jnp.asarray(s_i, jnp.int32),
                jnp.asarray(obs_pad), jnp.asarray(t_obs, jnp.int32),
                jnp.asarray(c, jnp.int32), jnp.asarray(gen_len, jnp.int32),
                jnp.asarray(seq_len, jnp.int32), jnp.asarray(rl, jnp.int32),
                max_steps=max_steps,
            )
            # queued snapshots were taken while this slot's done flag was
            # set — the epoch bump stops them retiring the resumed occupant
            epoch[s_i] += 1
            # each resume spends up to one more notice-latency window of
            # idle slot-steps before the occupant's next EOS is seen
            budget += 2 * check
            turn_resumes += 1
            turn_saved += seq_len
            telemetry.counter_add(ENGINE_TURN_RESUMES)
            telemetry.counter_add(ENGINE_TURN_PREFILL_SAVED, float(seq_len))
            return True

        def serving_boundary(group_decline: str | None, had_idle: bool,
                             wedged: bool = False) -> None:
            """One admission-audit + occupancy sample per admission pass
            (ISSUE 13; only called with the ledger armed). A pass that
            admitted nothing while work waited is attributed to exactly
            one stall reason — the smoke asserts the reason counts sum to
            the declined passes, so an unattributable decline surfaces as
            a failure, not a silent gap."""
            nonlocal boundary_admits, fill_declined
            waiting = len(pending) + n * len(group_queue)
            reason = None
            if waiting and not boundary_admits:
                if wedged:
                    reason = "budget_wedge"
                elif group_decline is not None:
                    reason = group_decline
                elif fill_declined is not None and had_idle:
                    reason = fill_declined
                else:
                    # every slot is busy (or the pass offered no idle
                    # slot): the queue waits on decode progress
                    reason = "no_slots"
            cls = None
            if reason is not None and meta is not None:
                # class attribution (ISSUE 19): the declined head's class —
                # the group admit_groups declined, else the pending head's
                # group, else the queue head
                if reason == group_decline and decline_cls is not None:
                    cls = decline_cls
                elif pending:
                    e0 = pending[0]
                    c0 = e0[0] if isinstance(e0, tuple) else e0
                    cls = cls_of(c0 // n)
                elif group_queue:
                    cls = cls_of(group_queue[0])
            sl.on_boundary(
                live_slots=int((host_cand < total).sum()),
                queue_depth=waiting,
                free_pages=pool.free_pages,
                admitted=boundary_admits,
                reason=reason,
                cls=cls,
            )
            boundary_admits = 0
            fill_declined = None

        group_decline = admit_groups() if continuous else None
        state = fill_idle(state, range(r_slots))
        if sl is not None:
            serving_boundary(group_decline, had_idle=True)

        snapshots: deque = deque()
        # each slot serves ≤ ceil(total/R) occupants × max_steps, plus up to
        # 2·check admission lag per handoff (the async snapshot pipeline); a
        # budgeted pool can additionally serialize candidates (admission
        # stalls) and recompute preempted prefixes, so its backstop is the
        # fully-serial bound — continuous admission takes the serial bound
        # too (its chains gate admission like a budget does)
        occupancies = -(-total // r_slots)
        budget = (max_steps + 2 * check) * (
            2 * (total + 2) if (budgeted or continuous) else occupancies + 2
        )
        since_host = 0
        stalled_boundaries = 0
        # speculative accounting for the grid-cost artifacts, accumulated
        # PER DISPATCH in per-layer units so a round that mixes dispatch
        # regimes stays exact (the adaptive controller can resize d
        # mid-round, and the fused-verify probe can pass at one draft
        # length but not another — a single round-end choice would then
        # misattribute every step): each dispatched step costs one fused
        # sweep when its verify traced "native_verify", else (d_eff+1)
        # per-position decode calls; the self drafter adds d_eff plain
        # decode calls per step either way
        verify_grid_units = 0
        draft_call_steps = 0
        grid_units_key: tuple | None = None
        grid_units_step = 0
        spec_prev_acc = 0
        spec_prev_draft = 0
        spec_ema: float | None = None
        # graftcheck: hot-region refill/spec
        while dispatched < budget and not finished.all():
            prev_lora = lora_cell[0]
            self._take_pending_lora(lora_cell, dispatched)
            if lora_cell[0] is not prev_lora:
                if self.spec_draft and self.spec_drafter == "self":
                    # the consumed swap made the superseded adapter "the
                    # previous version" — rotate it into the drafter slot
                    # (usually a value swap, but None→adapter on a
                    # lora=None round's second swap changes structure:
                    # _chunk_round_sig covers the drafter so that case
                    # rebuilds). Read the MAILBOX's slot, not a local
                    # snapshot: _take_pending_lora owns the
                    # superseded-adapter bookkeeping (value + version)
                    drafter_cell[0] = self._prev_lora
                    drafter_version = self._prev_lora_version
                if cache_on:
                    # consumed in-flight weight swap: KV cached under the
                    # superseded adapter is no longer exact — drop the whole
                    # cache and stop caching for the rest of the round
                    # (chains prefilled pre-swap must not be retired into
                    # the cache under the new identity). Already-admitted
                    # chains keep decoding on their pre-swap KV, exactly
                    # as the cache-off engine does.
                    pool.invalidate_cache()
                    cache_write[0] = False
                if k_conf:
                    sig = _chunk_round_sig()
                    if sig != chunk_sig:
                        # rebuild at k_conf (not k_chunk): a swap whose
                        # program fell back drops to per-step, and a later
                        # swap back to a cached signature re-enables
                        # chunked dispatch
                        chunk_fn = builder(
                            k_conf, max_steps, top_p_impl, params,
                            lora_cell[0], state, rng, temperature, top_p,
                        )
                        chunk_sig = sig
                        k_chunk = k_conf if chunk_fn is not None else 1
            # chunk dispatch only at k_conf-aligned offsets: a mid-interval
            # re-enable (swap back to a cached signature while running
            # per-step) would otherwise stretch one host-decision interval
            # past `check` steps — past the grant horizon on a budgeted
            # pool, the clamp-write overrun the K | check invariant exists
            # to prevent (see the cadence comment above)
            fused_snap = None
            fused_spec = None
            if chunk_fn is not None and since_host % k_conf == 0:
                if self.spec_draft:
                    state, done_c, seq_c, dtot_c, acc_c = chunk_fn(
                        params, lora_cell[0], state, rng, drafter_cell[0],
                        eos_ids=self.eos_ids,
                        temperature=temperature, top_p=top_p,
                    )
                    fused_spec = (dtot_c, acc_c)
                else:
                    state, done_c, seq_c = chunk_fn(
                        params, lora_cell[0], state, rng,
                        eos_ids=self.eos_ids,
                        temperature=temperature, top_p=top_p,
                    )
                fused_snap = (done_c, seq_c)
                dispatched += k_chunk
                since_host += k_chunk
                n_new = k_chunk
            else:
                state = step(state)
                dispatched += 1
                since_host += 1
                n_new = 1
            if self.spec_draft:
                # by the time a dispatch returns, its verify decision is
                # in dispatch_choices (jit traces synchronously on first
                # call) — a dict lookup per host iteration, no device
                # sync. The per-step unit count is recomputed only when
                # (d, verify decision) changes: the analytic grid model
                # is pure Python the launch-bound loop shouldn't repay
                # every iteration
                vchoice_now = self._verify_dispatch_choice(d_cell[0])
                if (d_cell[0], vchoice_now) != grid_units_key:
                    grid_units_key = (d_cell[0], vchoice_now)
                    if vchoice_now and (
                        vchoice_now.split("!")[0] == "native_verify"
                    ):
                        from distrl_llm_tpu.ops.paged import paged_grid_steps

                        grid_units_step = paged_grid_steps(
                            "native_verify", batch=r_slots,
                            num_kv_heads=self.cfg.num_kv_heads,
                            pps=self.prompt_pages + self.private_pages,
                            pages_per_block=self.pages_per_block,
                        )
                    else:
                        grid_units_step = (
                            (d_cell[0] + 1)
                            * self._grid_steps_per_call(r_slots)
                        )
                verify_grid_units += n_new * grid_units_step
                if self.spec_drafter == "self":
                    draft_call_steps += n_new * d_cell[0]
            if since_host < check:
                continue
            since_host = 0
            if self.spec_draft and self.spec_adapt:
                # draft-length controller: between-boundary accept-rate
                # deltas feed an EMA; persistent waste shrinks the
                # effective d (halving, floor 1), recovery grows it back
                # toward the configured max. The d choice depends only on
                # PAST acceptance data, so per-step output distributions
                # are untouched (any d is exact). Reads are one tiny
                # device sync per boundary — the knob is opt-in.
                if fused_spec is not None:
                    dtot_now, atot_now = fused_spec
                else:
                    dtot_now = jnp.copy(state.draft_total)
                    atot_now = jnp.copy(state.accept_total)
                # sampler acceptance (accept_total), NOT emit-derived: a
                # final emitted token that was itself an accepted draft
                # (an accepted EOS, a budget-clamped tail) must not read
                # as a rejection and bias the EMA toward shrinking d
                # graftcheck: disable=GC301 -- opt-in spec_adapt controller: one tiny read per host boundary, not per step
                acc_now = int(np.asarray(atot_now))
                # graftcheck: disable=GC301 -- same boundary read as the line above
                dtot_h = int(np.asarray(dtot_now))
                d_acc = acc_now - spec_prev_acc
                d_draft = dtot_h - spec_prev_draft
                spec_prev_acc, spec_prev_draft = acc_now, dtot_h
                if d_draft > 0:
                    rate = d_acc / d_draft
                    spec_ema = (
                        rate if spec_ema is None
                        else 0.5 * spec_ema + 0.5 * rate
                    )
                    new_d = d_cell[0]
                    if spec_ema < 0.35 and d_cell[0] > 1:
                        new_d = max(1, d_cell[0] // 2)
                    elif spec_ema > 0.75 and d_cell[0] < self.spec_draft:
                        new_d = min(self.spec_draft, d_cell[0] * 2)
                    if new_d != d_cell[0]:
                        d_cell[0] = new_d
                        d_switches += 1
                        telemetry.counter_add(ENGINE_SPEC_DRAFT_RESIZES)
                        if k_conf:
                            chunk_fn = builder(
                                k_conf, max_steps, top_p_impl, params,
                                lora_cell[0], state, rng, temperature, top_p,
                            )
                            k_chunk = k_conf if chunk_fn is not None else 1
            if fused_snap is not None:
                # chunked steady state: the (done, seq) copies rode INSIDE
                # the decode dispatch (_refill_decode_chunk's fused
                # snapshot) — a boundary with no admissions or preemptions
                # costs zero extra device round-trips
                done_snap, seq_snap = fused_snap
            else:
                done_snap = jnp.copy(state.done)
                seq_snap = jnp.copy(state.seq_lengths)
            try:
                done_snap.copy_to_host_async()
                seq_snap.copy_to_host_async()
            except AttributeError:
                pass
            snapshots.append(
                (done_snap, seq_snap, epoch.copy(), host_cand.copy())
            )
            if len(snapshots) <= 1:
                continue
            done_snap, seq_snap, snap_epoch, snap_cand = snapshots.popleft()
            # delayed reads of ASYNC-copied snapshots dispatched one host
            # boundary ago — the copy already completed while the last
            # `check` decode steps ran
            # graftcheck: disable=GC301 -- reads a finished async copy one boundary old
            done_h = np.asarray(done_snap)
            # graftcheck: disable=GC301 -- same delayed snapshot as the line above
            seq_h = np.asarray(seq_snap)
            if sl is not None:
                # first-token detection off the same boundary snapshot: a
                # slot whose resident length moved past its occupant's
                # prompt has generated (boundary-granular — the loop's own
                # cadence, no extra device sync; a candidate that finished
                # between boundaries backfills at finish)
                for s_i in range(r_slots):
                    c_s = int(snap_cand[s_i])
                    if (
                        c_s < total and snap_epoch[s_i] == epoch[s_i]
                        and int(seq_h[s_i]) > int(real_len_h[c_s // n])
                    ):
                        sl.on_first_token(suid.get(c_s // n))
            if stream is not None:
                # gateway streaming (ISSUE 19): flush each live slot's
                # newly visible tokens off the boundary snapshot. The
                # snapshot's seq count is one boundary old, so the first
                # ``gen`` output positions are already written and
                # immutable — reading them from the CURRENT out buffer is
                # exact. One small blocking gather per streaming slot per
                # boundary, gateway-armed rounds only (opt-in cost); the
                # round-end flush below guarantees byte-complete streams
                # regardless of boundary cadence
                for s_i in range(r_slots):
                    c_s = int(snap_cand[s_i])
                    if c_s >= total or snap_epoch[s_i] != epoch[s_i]:
                        continue
                    gen = int(seq_h[s_i]) - int(real_len_h[c_s // n])
                    sent = stream_sent.get(c_s, 0)
                    if gen > sent:
                        # graftcheck: disable=GC301 -- gateway-armed rounds only: streamed positions are immutable once written
                        toks = np.asarray(state.out[c_s][sent:gen])
                        stream_sent[c_s] = gen
                        stream(c_s, [int(t) for t in toks])
            # a done flag is only believed if the slot hasn't been refilled
            # since the snapshot was dispatched (done is monotone per epoch)
            idle = [
                int(s_i) for s_i in np.nonzero(done_h)[0]
                if snap_epoch[s_i] == epoch[s_i]
            ]
            for s_i in idle:
                c = snap_cand[s_i]
                if (
                    th is not None and c < total and not finished[c]
                    and try_turn_resume(int(s_i), int(c))
                ):
                    # episode continues in place: occupant, pages and KV all
                    # kept — do not release or retire the slot
                    continue
                if pool.owned[s_i] or pool.shared[s_i]:
                    pool.release(s_i)  # frees pages + redirects to scratch
                if c < total:
                    # after the slot release so a completed group's chain
                    # pages free the moment the hold drops
                    mark_finished(int(c))
                host_cand[s_i] = total
            table_dirty = bool(idle)
            if budgeted:
                # grant pass: extend every occupied slot's pages to cover its
                # write frontier through the next grant window (spec: the
                # verify overhang rides in lag_tokens/write_ceiling_extra);
                # preempt the least-advanced occupant when the pool runs dry
                idle_set = set(idle)
                for s_i in range(r_slots):
                    if host_cand[s_i] >= total or s_i in idle_set:
                        continue
                    if snap_epoch[s_i] != epoch[s_i]:
                        continue  # admitted post-snapshot; admit grant covers
                    rl = int(real_len_h[int(host_cand[s_i]) // n])
                    target = min(
                        int(seq_h[s_i]) + lag_tokens,
                        rl + max_steps + write_ceiling_extra,
                    )
                    while pool.ensure(s_i, target):
                        occupied = [
                            v for v in range(r_slots)
                            if host_cand[v] < total and v != s_i
                            and snap_epoch[v] == epoch[v]
                        ]
                        if occupied and meta is not None:
                            # class-aware preemption (ISSUE 19): evict the
                            # highest-rank (lowest-priority) occupant first
                            # — scavenger before batch before interactive —
                            # least progress within a class. Non-gateway
                            # rounds keep the pure least-progress victim
                            victim = min(
                                occupied,
                                key=lambda v: (
                                    -rank_of(int(host_cand[v]) // n),
                                    int(seq_h[v])
                                    - int(real_len_h[int(host_cand[v]) // n]),
                                ),
                            )
                        elif occupied:
                            victim = min(
                                occupied,
                                key=lambda v: int(seq_h[v])
                                - int(real_len_h[int(host_cand[v]) // n]),
                            )
                        else:
                            victim = s_i  # nothing else to evict: self-evict
                        preempt(victim)
                        if victim == s_i:
                            break
                    table_dirty = True
            boundary_marks = pool.total_admissions + groups_prefilled
            group_decline = None
            if continuous and group_queue:
                # freed pages (released slots, dropped chains) may now fit
                # the next queued group's prefill — the backfill that
                # replaces the fixed episode batch
                group_decline = admit_groups()
            idle_free = [s for s in idle if host_cand[s] >= total]
            if pending:
                state = fill_idle(state, idle_free)
                table_dirty = True
            if table_dirty:
                state = state._replace(page_indices=jnp.asarray(pool.table))
            if pool.self_check:
                pool.check_invariants()
            wedged = False
            if continuous:
                # wedge detector: every slot dead, work still queued, and
                # this boundary neither prefilled nor admitted — decode
                # steps can free nothing, so give the one-boundary snapshot
                # lag a few rounds of grace and then name the stall instead
                # of silently spinning the step budget down
                if (
                    pool.total_admissions + groups_prefilled == boundary_marks
                    and (pending or group_queue)
                    and all(host_cand[v] >= total for v in range(r_slots))
                ):
                    stalled_boundaries += 1
                    wedged = True
                    if stalled_boundaries > 4:
                        # name the MINIMUM VIABLE page budget (ISSUE 19
                        # satellite): what the head of the queue needs to
                        # admit — chain pages for its prompt plus the full
                        # private region the admission gate reserves — so
                        # the fix is a number, not a bisection
                        if group_queue:
                            g_h = group_queue[0]
                            rl_h = int(real_len_h[g_h])
                        else:
                            e0 = pending[0]
                            c0 = e0[0] if isinstance(e0, tuple) else e0
                            rl_h = int(real_len_h[c0 // n])
                        need = (
                            max(-(-rl_h // ps), 1) + self.private_pages
                        )
                        raise RuntimeError(
                            f"continuous admission wedged: "
                            f"{int(finished.sum())}/{total} finished, "
                            f"{len(pending)} pending candidates + "
                            f"{len(group_queue)} queued groups, no live "
                            f"slot, and the pool ({pool.free_pages} free / "
                            f"{pool.universe_pages}) cannot admit the head "
                            f"— the page budget cannot make progress. "
                            f"Minimum viable budget for the head request: "
                            f"{need} pages (ceil(prompt {rl_h} / page_size "
                            f"{ps}) chain + {self.private_pages} private) — "
                            f"raise max_kv_pages / BENCH_KV_PAGES to at "
                            f"least {need}"
                        )
                else:
                    stalled_boundaries = 0
            if sl is not None:
                serving_boundary(
                    group_decline, had_idle=len(idle_free) > 0,
                    wedged=wedged,
                )
        # graftcheck: end-hot-region

        # final blocking read closes the snapshot lag on the last occupants
        # (mark_finished, not a bare flag write: the serving ledger's
        # finish events and the sharing chain drops stay exactly-once)
        done_h = np.asarray(state.done)
        for s_i in np.nonzero(done_h)[0]:
            c = host_cand[s_i]
            if c < total:
                mark_finished(int(c))
        alive_h = int(np.asarray(state.alive_steps))
        if cache_on:
            # park every resident cached page host-side: device page ids
            # are round-scoped, so the tree survives between rounds as a
            # host-resident index and the next round restores matched
            # prefixes from the store. Unconsumed preempt payloads drop
            # (candidate ids are round-scoped too).
            pool.flush_cache()
            for key in spilled_keys:
                self._kv_store.drop(key)
            radix_snap1 = self._radix.snapshot()
            radix_delta = {
                k: radix_snap1[k] - radix_snap0[k] for k in radix_snap1
            }
        self.last_pool_stats = {
            "pool_pages": pool_pages,
            "worst_case_pages": worst_pool,
            "peak_pages_used": pool.peak_pages_used,
            "preemptions": pool.preemptions,
            "budgeted": budgeted,
            # continuous-batching self-description (ISSUE 12, read by bench
            # rollout rows + tools/cb_smoke.py): which admission regime ran,
            # how much of the prompt segment was physically shared, and how
            # much mid-round backfill the fixed batch would have idled away
            "cb_mode": self.cb_mode,
            "cow_splits": pool.cow_splits,
            "pages_shared_frac": (
                round(pool.peak_shared_pages
                      / max(pool.peak_pages_used, 1), 4)
                if sharing else None
            ),
            "prefill_shared_frac": (
                round(pool.prefix_admissions
                      / max(pool.total_admissions, 1), 4)
                if sharing else None
            ),
            "backfill_admissions": backfill_admits,
            "groups_prefilled": groups_prefilled if continuous else None,
            # closed-loop control self-description (ISSUE 14): how many
            # groups the SLO shedder deferred at least once this round
            # (None = no ControlLimits attached, the controllers-off row)
            "shed_groups": (
                len(shed_groups_seen) if limits is not None else None
            ),
            # multi-tenant gateway rounds (ISSUE 19): per-class shed/preempt
            # action tally — the bench artifact's "actions land on low
            # classes" contract reads this (None = no gateway identity)
            "class_actions": (
                {k: dict(v) for k, v in class_actions.items()}
                if meta is not None else None
            ),
            "slot_idle_frac": (
                round(1.0 - alive_h / (r_slots * dispatched), 4)
                if dispatched else None
            ),
            # multi-turn episode continuation (ISSUE 17): in-place turn
            # resumes and the conversation-prefix tokens they kept resident
            # (None = no turn hook armed, the single-turn row)
            "turn_resumes": turn_resumes if th is not None else None,
            "turn_prefill_saved_tokens": (
                turn_saved if th is not None else None
            ),
            # tiered KV cache (ISSUE 18): per-round radix-counter deltas +
            # this round's spill/restore latency (None on the cache-off
            # control — the bench contract's honest-null discipline)
            "prefix_cache": bool(cache_on),
            "radix_hit_rate": (
                round(
                    radix_delta["hit_tok"]
                    / max(radix_delta["lookup_tok"], 1), 4,
                ) if cache_on else None
            ),
            "prefill_tok_saved": (
                radix_delta["prefill_tok_saved"] if cache_on else None
            ),
            "radix_evictions": (
                radix_delta["evictions"] if cache_on else None
            ),
            "spilled_pages": (
                radix_delta["spilled_pages"] if cache_on else None
            ),
            "restored_pages": (
                radix_delta["restored_pages"] if cache_on else None
            ),
            "spill_restore_ms_p50": (
                round(float(np.percentile(restore_ms, 50)), 3)
                if cache_on and restore_ms else None
            ),
        }
        if not finished.all():
            missing = int((~finished).sum())
            raise RuntimeError(
                f"refill scheduler exhausted its step budget ({budget}) with "
                f"{missing}/{total} candidates unfinished — this is a bug"
            )
        if th is not None and turn_resumes == 0 and (
            window_declines or window_short
        ):
            # multi-turn window exhaustion (ISSUE 19 satellite, the PR 17
            # gotcha): every turn resume this round was declined for lack
            # of max_new_tokens window — the run silently degraded to
            # single-turn. One warning naming the observed observation
            # length and the minimum viable window.
            import warnings

            if window_declines:
                obs_max = max(t for t, _ in window_declines)
                need_w = max(w for _, w in window_declines)
                detail = (
                    f"observed observation length up to {obs_max} tokens; "
                    f"minimum viable max_new_tokens window: {need_w}"
                )
            else:
                detail = (
                    f"every finished candidate was within 2 tokens of the "
                    f"window, so no observation could seat at all "
                    f"(max_new_tokens={max_steps})"
                )
            warnings.warn(
                f"multi-turn window exhausted: all "
                f"{len(window_declines) + window_short} turn continuations "
                f"this round were declined for max_new_tokens room — the "
                f"round degraded to single-turn. {detail} (need "
                f"gen_len + obs_tokens + 1 <= max_new_tokens)",
                RuntimeWarning, stacklevel=2,
            )
        out = np.asarray(state.out).reshape(b, n, max_steps)
        lengths = np.asarray(state.lengths_buf).reshape(b, n)
        if stream is not None:
            # byte-complete final flush: whatever the boundary cadence
            # missed (fast finishes, the last chunk) streams here from the
            # already-host result tensors before the round returns
            for c in range(total):
                ln = int(lengths[c // n, c % n])
                sent = stream_sent.get(c, 0)
                if ln > sent:
                    stream_sent[c] = ln
                    stream(
                        c,
                        [int(t) for t in out[c // n, c % n, sent:ln]],
                    )
        if sl is not None:
            # realized token counts close each serving record (TPOT needs
            # them); the closed records stream to the JSONL here
            for g, uid_g in suid.items():
                sl.note_tokens(uid_g, int(lengths[g].sum()))
        logps = (
            np.asarray(state.logps_buf).reshape(b, n, max_steps)
            if self.capture_logprobs else None
        )
        gen_tokens = int(lengths.sum())
        if self.spec_draft:
            # acceptance accounting off the device-carried histogram: one
            # read at round end, zero per-step host traffic
            hist_h = np.asarray(state.emit_hist)
            drafted = int(np.asarray(state.draft_total))
            steps_alive = int(hist_h.sum())
            emit_tokens = int((hist_h * np.arange(hist_h.size)).sum())
            # sampler acceptance: pre-truncation accepted prefix lengths
            # (accept_total) over drafted — the emit-derived count
            # (emit_tokens - steps_alive) under-counts steps whose final
            # emitted token was an accepted draft (EOS/budget truncation)
            accepted = int(np.asarray(state.accept_total))
            accept_rate = accepted / drafted if drafted else 0.0
            tokens_per_verify_step = (
                emit_tokens / steps_alive if steps_alive else 0.0
            )
            telemetry.gauge_set(ENGINE_SPEC_ACCEPT_RATE, accept_rate)
            for n_val in range(hist_h.size):
                telemetry.hist_observe(
                    ENGINE_SPEC_EMIT_TOKENS, float(n_val),
                    count=int(hist_h[n_val]),
                )
            # grid cost: accumulated per dispatch in per-layer units (one
            # fused sweep vs (d_eff+1) per-position calls, read off the
            # step's own verify dispatch record — exact even when the
            # adaptive controller mixed regimes mid-round); scaled by
            # layer count here. vchoice is the SUMMARY spelling for the
            # stats record (the configured d's decision).
            vchoice = self._verify_dispatch_choice()
            verify_grid = verify_grid_units * self.cfg.num_layers
            draft_grid = (
                self._grid_steps_per_call(r_slots)
                * self.cfg.num_layers * draft_call_steps
            )
            if verify_grid:
                telemetry.counter_add(
                    ENGINE_SPEC_VERIFY_GRID_STEPS, verify_grid
                )
            self.last_spec_stats = {
                "drafter": self.spec_drafter,
                "spec_draft": self.spec_draft,
                "draft_len_final": d_cell[0],
                "draft_len_switches": d_switches,
                "accept_rate": round(accept_rate, 4),
                "tokens_per_verify_step": round(tokens_per_verify_step, 4),
                "emit_hist": hist_h.tolist(),
                "drafted": drafted,
                "verify_impl": vchoice,
                "verify_grid_steps": verify_grid,
                "draft_grid_steps": draft_grid,
                "drafter_version": drafter_version,
                "target_version": (
                    self.last_swap_versions[-1]
                    if self.last_swap_versions else None
                ),
            }
        dec_span.set(tokens=gen_tokens, steps=dispatched,
                     preemptions=pool.preemptions,
                     **(
                         {
                             "spec_drafter": self.spec_drafter,
                             "spec_accept_rate": self.last_spec_stats[
                                 "accept_rate"],
                             "tokens_per_verify_step": self.last_spec_stats[
                                 "tokens_per_verify_step"],
                         }
                         if self.spec_draft else {}
                     ))
        dec_span.__exit__(None, None, None)
        decode_s = time.perf_counter() - t_decode0
        if continuous:
            # lazy group prefills ran inside the decode loop; decode
            # throughput must not absorb their time
            decode_s = max(decode_s - t_prefill, 1e-9)
        if self.spec_draft:
            # aggregate attention grid steps (verify + drafter) — computed
            # directly, since the fused verify sweep and the drafter's
            # decode calls have different per-call counts
            _record_grid_telemetry(
                1, 1, decode_s, per_call=verify_grid + draft_grid,
            )
        else:
            _record_grid_telemetry(
                self.cfg.num_layers, dispatched, decode_s,
                per_call=self._grid_steps_per_call(r_slots),
            )
        self.last_round_stats = accumulate_round_stats(
            self.last_round_stats, prefill_s=t_prefill,
            prefill_tokens=prefill_tokens, prompt_rows=b,
            decode_s=decode_s, gen_tokens=gen_tokens,
            gen_rows=total,
        )
        return GenerationResult(
            tokens=out, lengths=lengths, steps_dispatched=dispatched,
            alive_slot_steps=alive_h,
            logprobs=logps,
        )

    def _generate_wave(
        self, params, lora, prompt_ids, prompt_mask,
        sampling: SamplingConfig, rng: jax.Array,
    ) -> GenerationResult:
        b, p = prompt_ids.shape
        if p != self.max_prompt_tokens:
            raise ValueError(f"prompts must be padded to {self.max_prompt_tokens}, got {p}")
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        n = sampling.n
        # an in-flight swap from an earlier wave of THIS round also covers
        # this wave's prefill (its rows haven't sampled yet)
        lora = self._round_entry_lora(lora)

        prefill_tokens = int(np.asarray(prompt_mask).sum())
        t0 = time.perf_counter()
        with telemetry.span("engine/prefill", rows=b, tokens=prefill_tokens):
            prompt_k, prompt_v, last_logits, real_len = self._prefill(
                params, lora, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask)
            )
            jax.block_until_ready(last_logits)
        t_prefill = time.perf_counter() - t0
        row_alive = jnp.asarray(prompt_mask).sum(axis=-1) > 0
        t1 = time.perf_counter()
        dec_span = telemetry.span("engine/decode", rows=b * n)
        dec_span.__enter__()
        state, page_indices = self._fanout(
            prompt_k, prompt_v, last_logits, real_len, row_alive,
            n=n, b=b, max_steps=max_steps,
        )

        temperature = jnp.asarray(sampling.temperature, jnp.float32)
        top_p = jnp.asarray(sampling.top_p, jnp.float32)
        top_p_impl = sampling.resolved_top_p_impl(self.plan_top_p_impl)
        # measured bytes/token source (ISSUE 15; DISTRL_MEASURE_COST=1 only)
        from distrl_llm_tpu import obs as _obs

        _obs.maybe_record_step_cost(
            "decode_step/paged", self._decode_step, params, lora, state,
            rng, page_indices, eos_ids=self.eos_ids, temperature=temperature,
            top_p=top_p, top_p_impl=top_p_impl,
        )
        lora_cell = [lora]
        steps_seen = [0]

        chunk_fn = (
            self._wave_chunk_fn(
                pick_chunk(self.scan_chunk, max_steps), max_steps, top_p_impl,
                params, lora, state, rng, page_indices, temperature, top_p,
            )
            if self.scan_chunk > 1 and max_steps > 1
            else None
        )
        if chunk_fn is not None:
            k = pick_chunk(self.scan_chunk, max_steps)

            def run_step(l, s):
                return self._decode_step(
                    params, l, s, rng, page_indices, eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p,
                    top_p_impl=top_p_impl,
                )

            step = make_swap_aware_chunk_step(
                self, lora_cell, steps_seen, k, max_steps, chunk_fn, lora,
                rebuild=lambda l, s: self._wave_chunk_fn(
                    k, max_steps, top_p_impl, params, l, s, rng,
                    page_indices, temperature, top_p,
                ),
                run_chunk=lambda fn, l, s: fn(
                    params, l, s, rng, page_indices, eos_ids=self.eos_ids,
                    temperature=temperature, top_p=top_p,
                ),
                run_step=run_step,
            )
            # floor chunks + shared non-divisor tail (run_nondivisor_tail
            # has the cadence invariant)
            full, rem = divmod(max_steps, k)
            state = run_decode_loop(step, state, full, 1)
            state = run_nondivisor_tail(
                self, lora_cell, steps_seen, rem, state, run_step)
        else:

            def step(s):
                self._take_pending_lora(lora_cell, steps_seen[0])
                steps_seen[0] += 1
                return self._decode_step(
                    params, lora_cell[0], s, rng, page_indices,
                    eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
                    top_p_impl=top_p_impl,
                )

            state = run_decode_loop(step, state, max_steps, self.decode_chunk)
        out = np.asarray(state.out).reshape(b, n, max_steps)
        lengths = np.asarray(state.gen_lengths).reshape(b, n)
        logps = (
            np.asarray(state.logps).reshape(b, n, max_steps)
            if self.capture_logprobs else None
        )
        gen_tokens = int(lengths.sum())
        dec_span.set(tokens=gen_tokens, steps=steps_seen[0])
        dec_span.__exit__(None, None, None)
        decode_s = time.perf_counter() - t1
        _record_grid_telemetry(
            self.cfg.num_layers, steps_seen[0], decode_s,
            per_call=self._grid_steps_per_call(b * n),
        )
        self.last_round_stats = accumulate_round_stats(
            self.last_round_stats, prefill_s=t_prefill,
            prefill_tokens=prefill_tokens, prompt_rows=b,
            decode_s=decode_s, gen_tokens=gen_tokens,
            gen_rows=b * n,
        )
        return GenerationResult(tokens=out, lengths=lengths, logprobs=logps)
