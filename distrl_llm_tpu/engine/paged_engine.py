"""Paged-KV generation engine: packed ragged decode (the full N1 core).

Where ``engine.GenerationEngine`` keeps a dense [B, K, hd, Smax] cache that
every decode step reads in full, this engine stores KV in PAGES and reads
each row's true [0, length) prefix only — vLLM's PagedAttention bandwidth
model (reference: requirements.txt:6, entered via ``policy.fast_generate``,
distributed_actor.py:148–150), built TPU-native:

* prompts are packed (left padding removed) during a jitted prefill, so a
  short prompt costs its own length, not ``max_prompt_tokens``;
* decode attention is jaxlib's Pallas ``paged_attention`` kernel on TPU (jnp
  reference elsewhere — ops/paged.py);
* candidates SHARE their prompt's full prompt pages (vLLM prefix sharing):
  the page table points each candidate's leading columns at a shared pool
  written once by prefill; only the partial last prompt page — extended in
  place by decode — is private per candidate. Prompt KV memory is ~B copies
  instead of B·n. The table is data-dependent but shape-static, so it rides
  as a traced array (an RL rollout round is a fixed batch, so vLLM's dynamic
  C++ block allocator reduces to this host-computed table);
* the host-dispatched donated decode-step loop, candidate fan-out after a
  shared prefill, and async early-exit snapshots all match the dense engine.

Parallelism note: this engine targets one rollout replica — a single chip or
a TP group (KV heads shard over "tp"). Data-parallel scale-out runs one
engine per replica (the remote-worker fan-out, distributed/remote_engine.py),
matching vLLM's one-engine-per-GPU model; the shared page pool deliberately
interleaves prompts, so slicing it across a dp axis needs a pool-partitioned
shard_map design (future work — the dense engine covers GSPMD dp today).
"""

from __future__ import annotations

from functools import partial
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distrl_llm_tpu.config import SamplingConfig
from distrl_llm_tpu.engine.engine import (
    GenerationResult,
    generate_in_waves,
    run_decode_loop,
)
from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.models.transformer import forward
from distrl_llm_tpu.ops.paged import (
    make_page_table,
    pages_per_seq,
)
from distrl_llm_tpu.ops.sampling import sample

Params = dict[str, Any]


class _PagedDecodeState(NamedTuple):
    step: jax.Array  # []
    out: jax.Array  # [Bn, T]
    gen_lengths: jax.Array  # [Bn] generated token counts (incl. EOS)
    done: jax.Array  # [Bn] bool
    logits: jax.Array  # [Bn, V]
    seq_lengths: jax.Array  # [Bn] tokens resident in the cache per row
    k_pages: tuple  # L × [K, total_pages, ps, hd]
    v_pages: tuple


def _pack_rows(ids: jax.Array, mask: jax.Array) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Left-padded [B, P] → packed [B, P] (first real token at column 0)."""
    b, p = ids.shape
    real_len = mask.sum(axis=-1).astype(jnp.int32)  # [B]
    shift = p - real_len  # left-pad amount per row
    cols = (jnp.arange(p)[None, :] + shift[:, None]) % p
    packed = jnp.take_along_axis(ids, cols, axis=1)
    packed_mask = (jnp.arange(p)[None, :] < real_len[:, None]).astype(mask.dtype)
    return packed * packed_mask, packed_mask, real_len


def _paged_prefill(params, lora, prompt_ids, prompt_mask, *, cfg: ModelConfig,
                   prompt_pages: int, page_size: int, lora_scale: float,
                   cache_dtype, attn_impl: str, kv_quant: str = "none"):
    """Pack prompts, run one forward over B rows, return per-prompt page
    tiles [K, B, prompt_pages, ps, hd] per layer + sampling logits."""
    b, p = prompt_ids.shape
    packed_ids, packed_mask, real_len = _pack_rows(prompt_ids, prompt_mask)
    pad_to = prompt_pages * page_size
    packed_ids = jnp.pad(packed_ids, ((0, 0), (0, pad_to - p)))
    packed_mask = jnp.pad(packed_mask, ((0, 0), (0, pad_to - p)))

    shape = (cfg.num_kv_heads, b * prompt_pages, page_size, cfg.head_dim)

    def make_pages():
        if kv_quant == "int8":
            # int8 KV: halves resident cache memory (see the bandwidth caveat
            # on ops/paged.py:quantize_pages)
            from distrl_llm_tpu.ops.paged import init_quantized_pages

            return init_quantized_pages(shape)
        return jnp.zeros(shape, cache_dtype)

    cache = {
        "k": tuple(make_pages() for _ in range(cfg.num_layers)),
        "v": tuple(make_pages() for _ in range(cfg.num_layers)),
        "lengths": real_len,
        "page_indices": jnp.asarray(
            make_page_table(b, pad_to, page_size)
        ),
    }
    positions = jnp.broadcast_to(
        jnp.arange(pad_to, dtype=jnp.int32)[None, :], (b, pad_to)
    )
    logits, cache = forward(
        params, cfg, packed_ids, attention_mask=packed_mask,
        positions=positions, lora=lora, lora_scale=lora_scale,
        kv_cache=cache, attn_impl=attn_impl, page_size=page_size,
        # each packed row's sampling logits sit at its LAST REAL position —
        # a per-row gather that also skips the [B, Ppad, V] lm_head
        logits_positions=jnp.maximum(real_len - 1, 0),
    )
    return cache["k"], cache["v"], logits[:, 0], real_len


def _paged_fanout(prompt_k, prompt_v, last_logits, real_len, row_alive,
                  *, n: int, b: int, prompt_pages: int, private_pages: int,
                  page_size: int, max_steps: int):
    """Expand B prompts to B·n candidate rows with SHARED prompt prefixes.

    vLLM's prefix sharing, static-shape edition: every candidate's page table
    points its leading columns at the prompt's FULL pages in the shared pool
    (written once by prefill, never written again), and only the partial last
    prompt page — which decode tokens will extend in place — is copied per
    candidate into a private region alongside its decode pages. At the
    reference volume this drops prompt KV memory from B·n to ~B copies.

    Returns (state, page_indices): the table is data-DEPENDENT (each prompt's
    full-page count is real_len // page_size) but shape-static, so it rides
    as a traced array and never forces a recompile."""
    bn = b * n
    total_shared = b * prompt_pages
    width = prompt_pages + private_pages

    full = real_len // page_size  # [B] full shared pages per prompt
    full_r = jnp.repeat(full, n)  # [Bn]
    prompt_of_row = jnp.repeat(jnp.arange(b), n)  # [Bn]
    priv0 = total_shared + jnp.arange(bn) * private_pages  # [Bn]

    # column t of row r holds position block t: shared pages below full_r,
    # private pages after; trailing unused columns clamp to a valid private
    # page (the jnp reference gathers the whole table width)
    col = jnp.arange(width)[None, :]
    shared_entry = prompt_of_row[:, None] * prompt_pages + col
    private_entry = jnp.minimum(
        priv0[:, None] + (col - full_r[:, None]),
        priv0[:, None] + private_pages - 1,
    )
    page_indices = jnp.where(
        col < full_r[:, None], shared_entry, private_entry
    ).astype(jnp.int32)

    # the partial prompt page each candidate must own privately (clamped for
    # page-aligned prompts, where the copy content is never read)
    src_partial = prompt_of_row * prompt_pages + jnp.repeat(
        jnp.minimum(full, prompt_pages - 1), n
    )

    def expand_arr(pages):  # [K, B·pp, ps, tail] → [K, shared+Bn·priv, ps, tail]
        kh, _, ps, tail = pages.shape
        out = jnp.zeros(
            (kh, total_shared + bn * private_pages, ps, tail), pages.dtype
        )
        out = out.at[:, :total_shared].set(pages)
        out = out.at[:, priv0].set(pages[:, src_partial])
        return out

    def expand(pages):
        from distrl_llm_tpu.ops.paged import is_quantized_pages

        if is_quantized_pages(pages):  # int8 KV: expand weight + scales alike
            return type(pages)(
                weight=expand_arr(pages.weight), scales=expand_arr(pages.scales)
            )
        return expand_arr(pages)

    k_pages = tuple(expand(x) for x in prompt_k)
    v_pages = tuple(expand(x) for x in prompt_v)
    state = _PagedDecodeState(
        step=jnp.zeros((), jnp.int32),
        out=jnp.zeros((bn, max_steps), jnp.int32),
        gen_lengths=jnp.zeros((bn,), jnp.int32),
        done=jnp.repeat(~row_alive, n, axis=0),
        logits=jnp.repeat(last_logits, n, axis=0),
        seq_lengths=jnp.repeat(real_len, n, axis=0),
        k_pages=k_pages,
        v_pages=v_pages,
    )
    return state, page_indices


def _paged_decode_step(params, lora, state: _PagedDecodeState, rng, page_indices,
                       *, cfg: ModelConfig, page_size: int, eos_ids, pad_id: int,
                       temperature, top_p, lora_scale: float, paged_impl: str,
                       top_p_impl: str = "bisect"):
    """One donated decode step over the paged cache (host-loop dispatched,
    zero cache-sized temps — same design as engine._decode_step)."""
    s = state
    tok = sample(jax.random.fold_in(rng, s.step), s.logits, temperature, top_p,
                 top_p_impl=top_p_impl)
    tok = jnp.where(s.done, pad_id, tok)
    out = jax.lax.dynamic_update_slice(s.out, tok[:, None], (0, s.step))
    gen_lengths = s.gen_lengths + (~s.done).astype(jnp.int32)
    hit_eos = jnp.isin(tok, eos_ids)
    done = s.done | hit_eos

    cache = {
        "k": s.k_pages, "v": s.v_pages,
        "lengths": s.seq_lengths,
        "page_indices": page_indices,
    }
    next_logits, cache = forward(
        params, cfg, tok[:, None],
        positions=s.seq_lengths[:, None],
        lora=lora, lora_scale=lora_scale,
        kv_cache=cache, page_size=page_size, paged_impl=paged_impl,
    )
    seq_lengths = s.seq_lengths + (~s.done).astype(jnp.int32)
    return _PagedDecodeState(
        step=s.step + 1, out=out, gen_lengths=gen_lengths, done=done,
        logits=next_logits[:, 0], seq_lengths=seq_lengths,
        k_pages=cache["k"], v_pages=cache["v"],
    )


class PagedGenerationEngine:
    """Drop-in for ``GenerationEngine`` with a packed paged KV cache."""

    def __init__(
        self,
        cfg: ModelConfig,
        *,
        max_prompt_tokens: int,
        max_new_tokens: int,
        eos_token_ids: Sequence[int],
        pad_token_id: int,
        lora_scale: float = 1.0,
        cache_dtype=jnp.bfloat16,
        attn_impl: str = "reference",
        paged_impl: str = "auto",
        page_size: int = 128,
        decode_chunk: int = 128,
        kv_quant: str = "none",  # "none" | "int8" (per-token absmax KV cache)
        prompt_buckets: Sequence[int] | None = None,  # accepted for interface parity
        max_concurrent_rows: int = 0,  # 0 = unlimited (vLLM max_num_seqs)
    ):
        self.max_concurrent_rows = max_concurrent_rows
        if kv_quant not in ("none", "int8"):
            raise ValueError(f"kv_quant must be none/int8, got {kv_quant!r}")
        self.cfg = cfg
        self.max_prompt_tokens = max_prompt_tokens
        self.max_new_tokens = max_new_tokens
        self.page_size = page_size
        self.prompt_pages = pages_per_seq(max_prompt_tokens, page_size)
        # per-candidate private region: the partial prompt page (extended in
        # place by decode) + decode pages; full prompt pages are SHARED
        self.private_pages = 1 + pages_per_seq(max_new_tokens, page_size)
        self.eos_ids = jnp.asarray(list(eos_token_ids), jnp.int32)
        self.pad_id = int(pad_token_id)
        self.lora_scale = lora_scale
        self.decode_chunk = decode_chunk
        self.prompt_buckets = [max_prompt_tokens]

        self._prefill = jax.jit(
            partial(
                _paged_prefill, cfg=cfg, prompt_pages=self.prompt_pages,
                page_size=page_size, lora_scale=lora_scale,
                cache_dtype=cache_dtype, attn_impl=attn_impl, kv_quant=kv_quant,
            )
        )
        self._fanout = jax.jit(
            partial(
                _paged_fanout, prompt_pages=self.prompt_pages,
                private_pages=self.private_pages,
                page_size=page_size,
            ),
            static_argnames=("n", "b", "max_steps"),
        )
        self._decode_step = jax.jit(
            partial(
                _paged_decode_step, cfg=cfg, page_size=page_size,
                pad_id=self.pad_id, lora_scale=lora_scale, paged_impl=paged_impl,
            ),
            donate_argnames=("state",),
            static_argnames=("top_p_impl",),
        )

    def bucket_for(self, prompt_mask) -> int:
        """Single-bucket engine (interface parity with GenerationEngine's
        warm-key tracking in trainer._call_engine)."""
        return self.max_prompt_tokens

    def generate(
        self,
        params: Params,
        lora: Params | None,
        prompt_ids: np.ndarray,  # [B, P] left-padded (trainer contract)
        prompt_mask: np.ndarray,
        sampling: SamplingConfig,
        rng: jax.Array,
    ) -> GenerationResult:
        return generate_in_waves(
            self._generate_wave, self.max_concurrent_rows, params, lora,
            prompt_ids, prompt_mask, sampling, rng, self.pad_id,
        )

    def _generate_wave(
        self, params, lora, prompt_ids, prompt_mask,
        sampling: SamplingConfig, rng: jax.Array,
    ) -> GenerationResult:
        b, p = prompt_ids.shape
        if p != self.max_prompt_tokens:
            raise ValueError(f"prompts must be padded to {self.max_prompt_tokens}, got {p}")
        max_steps = min(sampling.max_tokens, self.max_new_tokens)
        n = sampling.n

        prompt_k, prompt_v, last_logits, real_len = self._prefill(
            params, lora, jnp.asarray(prompt_ids), jnp.asarray(prompt_mask)
        )
        row_alive = jnp.asarray(prompt_mask).sum(axis=-1) > 0
        state, page_indices = self._fanout(
            prompt_k, prompt_v, last_logits, real_len, row_alive,
            n=n, b=b, max_steps=max_steps,
        )

        temperature = jnp.asarray(sampling.temperature, jnp.float32)
        top_p = jnp.asarray(sampling.top_p, jnp.float32)
        top_p_impl = "exact" if sampling.top_p_exact else "bisect"
        state = run_decode_loop(
            lambda s: self._decode_step(
                params, lora, s, rng, page_indices,
                eos_ids=self.eos_ids, temperature=temperature, top_p=top_p,
                top_p_impl=top_p_impl,
            ),
            state, max_steps, self.decode_chunk,
        )
        out = np.asarray(state.out).reshape(b, n, max_steps)
        lengths = np.asarray(state.gen_lengths).reshape(b, n)
        return GenerationResult(tokens=out, lengths=lengths)
