from distrl_llm_tpu.engine.engine import GenerationEngine, GenerationResult  # noqa: F401
