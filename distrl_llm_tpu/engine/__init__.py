from distrl_llm_tpu.engine.engine import GenerationEngine, GenerationResult  # noqa: F401
from distrl_llm_tpu.engine.page_pool import PagePool  # noqa: F401
from distrl_llm_tpu.engine.paged_engine import PagedGenerationEngine  # noqa: F401
from distrl_llm_tpu.engine.sharded_paged import ShardedPagedEngine  # noqa: F401
