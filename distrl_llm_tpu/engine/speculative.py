"""Speculative decoding for the paged refill engine: prompt-lookup drafts.

vLLM-class capability beyond the reference's configuration (its vLLM 0.7.2
ships speculative decoding; the reference never enables it — this build does,
TPU-first). Math-RL rollouts repeat prompt material (numbers, expressions,
format tags), so an n-gram lookup over the row's OWN sequence proposes the
next ``d`` tokens for free ("prompt lookup decoding" / vLLM's ngram
speculator): find the latest earlier occurrence of the last ``k`` tokens and
draft whatever followed it. The model then VERIFIES the whole draft block in
one forward — QKV/MLP/lm_head matmuls batch over [R, d+1] positions, which is
exactly where single-token decode is weight-bandwidth-bound — and a
rejection-sampling acceptance keeps the output distribution IDENTICAL to
plain sampling (exact equality under greedy, tested):

* draft q is a point mass, so token t_i is accepted with probability
  p_i(t_i) under the model's post-temperature/top-p distribution;
* the first rejected position resamples from the residual
  norm(p_i − onehot(t_i)) — unbiased for one-hot proposals;
* if the whole draft survives, one bonus token samples from the final
  distribution, so a step emits between 1 and d+1 tokens.

Cache bookkeeping rides the paged refill machinery: the verify forward
writes d+1 KVs at per-row offsets (transformer.forward(paged_verify=True));
rejected positions hold garbage ABOVE the row's valid length and are
overwritten before they can be read. All shapes are static; acceptance
counts are data.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from distrl_llm_tpu.ops.sampling import TOP_P_IMPLS


def sampling_probs(
    logits: jax.Array,  # [..., V]
    temperature,
    top_p,
    top_p_impl: str = "bisect",
) -> jax.Array:
    """The categorical distribution ``ops.sampling.sample`` draws from,
    as explicit probabilities (greedy → one-hot argmax). The acceptance test
    must use THIS distribution — not raw softmax — or speculative sampling
    would silently change semantics vs plain decoding."""
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    # shared impl registry: draft/verify sampling must use the SAME
    # filter as the main decode sampler for every impl string
    filtered = TOP_P_IMPLS[top_p_impl](logits.astype(jnp.float32) / t, top_p)
    probs = jax.nn.softmax(filtered, axis=-1)
    greedy = jax.nn.one_hot(
        jnp.argmax(logits, axis=-1), logits.shape[-1], dtype=jnp.float32
    )
    is_greedy = jnp.asarray(temperature, jnp.float32) == 0.0
    return jnp.where(is_greedy, greedy, probs)


def propose_ngram_drafts(
    seq_buf: jax.Array,  # [R, W] the row's full token sequence so far
    buf_len: jax.Array,  # [R] valid tokens in seq_buf
    *,
    k: int,
    d: int,
) -> jax.Array:
    """Prompt-lookup proposal: the latest j < buf_len−k with
    seq_buf[j:j+k] == the last k tokens; draft = the d tokens that followed.
    Rows with no match draft their last token repeated (a cheap guess the
    verifier simply rejects when wrong). Returns [R, d] int32."""
    r, w = seq_buf.shape
    tail_idx = jnp.clip(
        buf_len[:, None] - k + jnp.arange(k)[None, :], 0, w - 1
    )
    tail = jnp.take_along_axis(seq_buf, tail_idx, axis=1)  # [R, k]

    n_win = w - k + 1
    match = jnp.ones((r, n_win), bool)
    for i in range(k):
        match = match & (seq_buf[:, i : i + n_win] == tail[:, i : i + 1])
    j = jnp.arange(n_win)[None, :]
    match = match & (j < (buf_len - k)[:, None])  # strictly before the tail
    found = match.any(axis=1)
    last_j = (n_win - 1) - jnp.argmax(match[:, ::-1], axis=1)  # [R]

    cont_idx = jnp.clip(
        last_j[:, None] + k + jnp.arange(d)[None, :], 0, w - 1
    )
    cont = jnp.take_along_axis(seq_buf, cont_idx, axis=1)  # [R, d]
    last_tok_idx = jnp.clip(buf_len - 1, 0, w - 1)
    last_tok = jnp.take_along_axis(seq_buf, last_tok_idx[:, None], axis=1)
    return jnp.where(found[:, None], cont, jnp.broadcast_to(last_tok, cont.shape))


def spec_accept(
    rng: jax.Array,
    probs: jax.Array,  # [R, d+1, V] — probs[:, i] judges draft[:, i]; [:, d] = bonus
    draft: jax.Array,  # [R, d]
    draft_probs: jax.Array | None = None,  # [R, d, V] full proposal dists q
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Speculative rejection sampling. Returns (emit [R, d+1], n_emit [R],
    n_accept [R]): emit[:, :n_emit] are this step's new tokens — the
    accepted draft prefix followed by one resampled/bonus token; n_emit ∈
    [1, d+1]. ``n_accept`` ∈ [0, d] is the SAMPLER's accepted prefix
    length (n_emit − 1 before any EOS/budget truncation the caller
    applies) — the unbiased drafter-quality measure the accept-rate
    accounting consumes; deriving it from the post-truncation emit count
    would under-count a final emitted token that was itself an accepted
    draft (e.g. an accepted EOS).

    Without ``draft_probs`` the proposal is treated as a POINT MASS (the
    n-gram drafter's regime): token t_i is accepted with probability
    p_i(t_i), and the residual zeroes exactly the drafted token — the
    original one-hot algebra, bit-for-bit.

    With ``draft_probs`` this is standard full-distribution speculative
    sampling (the self-drafter's regime — q is the previous-version
    policy's own sampling distribution): accept t_i with probability
    min(1, p_i(t_i) / q_i(t_i)) — implemented as ``u · q < p`` so a
    zero-q never divides — and resample the first rejection from the
    residual norm(max(p_i − q_i, 0)). Both branches leave the output
    distribution IDENTICAL to plain sampling from p (the rejection-sampling
    identity; pinned empirically by tests/test_speculative.py). The
    one-hot path is the q = onehot(t_i) special case: u·1 < p(t_i) and
    max(p − onehot, 0) = p with the drafted token zeroed."""
    r, dp1, v = probs.shape
    d = dp1 - 1
    u = jax.random.uniform(jax.random.fold_in(rng, 0), (r, d))
    p_draft = jnp.take_along_axis(probs[:, :d], draft[..., None], axis=-1)[..., 0]
    if draft_probs is None:
        accept = u < p_draft  # [R, d]
    else:
        q_draft = jnp.take_along_axis(
            draft_probs, draft[..., None], axis=-1
        )[..., 0]
        accept = u * q_draft < p_draft  # u < p/q, division-free
    m = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)  # [R] prefix len

    rows = jnp.arange(r)
    final_probs = probs[rows, m]  # [R, V] — dist at the first rejected / bonus slot
    rejected = m < d
    if draft_probs is None:
        drop = jnp.take_along_axis(
            draft, jnp.minimum(m, d - 1)[:, None], axis=1
        )[:, 0]
        onehot_drop = jax.nn.one_hot(drop, v, dtype=bool)
        final_probs = jnp.where(
            rejected[:, None] & onehot_drop, 0.0, final_probs
        )
    else:
        q_at = draft_probs[rows, jnp.minimum(m, d - 1)]  # [R, V]
        resid = jnp.maximum(final_probs - q_at, 0.0)
        # p ≤ q everywhere ⇒ p == q ⇒ the residual is empty; any
        # acceptance test would have passed, so the event has measure
        # zero under exact arithmetic — guard the float-rounding case by
        # falling back to p itself (still exact: p == q there)
        resid_ok = resid.sum(axis=-1, keepdims=True) > 0
        final_probs = jnp.where(
            rejected[:, None], jnp.where(resid_ok, resid, final_probs),
            final_probs,
        )
    final_probs = final_probs / jnp.maximum(
        final_probs.sum(axis=-1, keepdims=True), 1e-20
    )
    final_tok = jax.random.categorical(
        jax.random.fold_in(rng, 1), jnp.log(jnp.maximum(final_probs, 1e-30))
    ).astype(jnp.int32)

    pos = jnp.arange(dp1)[None, :]
    draft_padded = jnp.pad(draft, ((0, 0), (0, 1)))
    emit = jnp.where(pos < m[:, None], draft_padded, 0)
    emit = jnp.where(pos == m[:, None], final_tok[:, None], emit)
    return (
        emit.astype(jnp.int32), (m + 1).astype(jnp.int32),
        m.astype(jnp.int32),
    )


class SpecRefillState(NamedTuple):
    """Refill decode state for speculative mode. Differences from
    ``_RefillState``: no carried logits — the carried quantity is
    ``last_tok`` (emitted but not yet resident in the KV cache; the next
    verify forward processes it as its first input) — plus each slot's full
    token sequence for the n-gram lookup."""

    step: jax.Array
    alive_steps: jax.Array  # [] sum over steps of alive-slot count
    out: jax.Array  # [total, T]
    logps_buf: jax.Array  # [total, T] behavior logprobs (raw log_softmax)
    lengths_buf: jax.Array  # [total]
    cand: jax.Array  # [R]
    done: jax.Array  # [R]
    last_tok: jax.Array  # [R] pending token (counted in gen_lengths, not in cache)
    seq_buf: jax.Array  # [R, W] prompt + generated tokens
    seq_lengths: jax.Array  # [R] tokens RESIDENT in the cache
    gen_lengths: jax.Array  # [R] generated tokens incl. last_tok
    page_indices: jax.Array  # [R, width]
    k_pages: tuple
    v_pages: tuple
    # acceptance accounting, carried ON DEVICE so the host pays no extra
    # round-trips: emit_hist[n] counts the alive slot-steps that emitted
    # exactly n tokens (n ∈ [0, d_max+1]; width is static at the CONFIGURED
    # max draft length so the adaptive controller can shrink d without a
    # shape change), draft_total sums alive·d_eff, accept_total sums the
    # SAMPLER's accepted prefix lengths (spec_accept's n_accept — pre-EOS/
    # budget truncation, so accept_rate = accept_total/draft_total is the
    # unbiased drafter-quality measure; emit-derived counts would
    # under-count rows whose final emitted token was an accepted draft,
    # e.g. an accepted EOS) — together they give the accept rate,
    # tokens/verify-step, and the emit distribution (engine/spec_*
    # telemetry + the bench row's spec fields)
    emit_hist: jax.Array  # [d_max+2] i32
    draft_total: jax.Array  # [] i32
    accept_total: jax.Array  # [] i32
