"""Name -> environment class registry used by config validation and CLIs."""

from __future__ import annotations

from .code_env import CodeToolEnv
from .math_env import MathSingleTurnEnv
from .verifier_env import VerifierFeedbackEnv

ENV_REGISTRY: dict[str, type] = {
    MathSingleTurnEnv.name: MathSingleTurnEnv,
    CodeToolEnv.name: CodeToolEnv,
    VerifierFeedbackEnv.name: VerifierFeedbackEnv,
}


def env_names() -> tuple[str, ...]:
    return tuple(sorted(ENV_REGISTRY))


def get_env_class(name: str) -> type:
    try:
        return ENV_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown environment {name!r}; available: {', '.join(env_names())}"
        ) from None
