"""Single-turn math environment — the legacy scoring path behind the protocol.

``env="math"`` is the default and routes the trainer down the *exact*
pre-environment code path (the env driver is never armed, rewards come from
``RewardComputer`` over whole groups), so training stays byte-identical to
pre-ISSUE-17 HEAD — that pin lives in ``tests/test_rollout_modes.py``. This
class exists so the math task is still expressible through the protocol
(tests, ``env_smoke``) and so per-completion scoring matches the batch
``reward_function`` contract exactly: one step, reward = format column,
accuracy in ``info``.
"""

from __future__ import annotations

from typing import Any

from ..rewards import make_reward_function
from .base import EnvStep


class MathSingleTurnEnv:
    """One completion, one regex-scored step, done."""

    name = "math"

    def __init__(self, format_scorer: str = "soft", max_turns: int = 1):
        del max_turns  # always single-turn
        self._reward_fn = make_reward_function(format_scorer)
        self._task: dict[str, Any] | None = None
        self._stepped = False

    def reset(self, task: dict[str, Any]) -> str:
        self._task = dict(task)
        self._stepped = False
        return str(task.get("problem", ""))

    def step(self, completion: str) -> EnvStep:
        if self._task is None:
            raise RuntimeError("step() before reset()")
        if self._stepped:
            raise RuntimeError("math env is single-turn; episode already done")
        self._stepped = True
        row = self._reward_fn([completion], [str(self._task.get("solution", ""))])
        fmt, acc = float(row[0, 0]), float(row[0, 1])
        return EnvStep(
            observation=None,
            reward=fmt,
            done=True,
            info={"accuracy": acc},
        )
