"""Environment protocol for multi-turn agentic rollouts (ISSUE 17).

An :class:`Environment` owns the task-side half of an episode: it hands the
rollout driver the first observation (the prompt), scores each policy turn,
and decides whether to inject a new observation (tool output, verifier
critique) or end the episode. The engine half — keeping the conversation's KV
chain resident across turns — lives in ``engine/paged_engine.py`` behind the
``turn_hook`` attribute; the glue is ``env/driver.py``.

Contract:

* ``reset(task) -> str`` — first observation. For the shipped envs this is
  ``task["problem"]`` verbatim so prompt encoding stays on the trainer's
  existing path (byte-identity for the single-turn math env).
* ``step(completion) -> EnvStep`` — consume one policy turn. ``observation``
  is the text to inject before the next turn, or ``None`` when the episode is
  over. ``reward`` is the *per-turn shaped reward* (format / tool-use /
  improvement — column 0 of the (n, 2) reward contract); terminal accuracy
  rides in ``info["accuracy"]`` so column 1 keeps its meaning.

Environments are cheap, single-use, and stateful: the driver builds one
instance per candidate per round. They run on the host between engine turns,
so they must never touch JAX.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Protocol, runtime_checkable


@dataclass
class EnvStep:
    """Result of consuming one policy turn.

    ``observation``: text injected as the next turn's context (loss-masked),
    or ``None`` when the episode is done. ``reward``: per-turn shaped reward
    (format/progress — never terminal accuracy, which belongs in
    ``info["accuracy"]``). ``info`` carries provenance: ``tool_call_id`` for
    tool envs, ``verdict`` for verifier envs, ``accuracy`` on terminal steps.
    """

    observation: str | None
    reward: float
    done: bool
    info: dict[str, Any] = field(default_factory=dict)


@dataclass
class TurnRecord:
    """One policy turn inside an episode, in answer-token coordinates.

    ``policy_span`` is the half-open [start, end) of the tokens the policy
    generated this turn; ``env_span`` covers the environment-injected
    observation that followed (``None`` on the final turn). Spans index into
    the engine's per-candidate answer buffer, so the loss mask, lineage
    provenance, and per-turn version tags all share one coordinate system.
    """

    index: int
    policy_span: tuple[int, int]
    env_span: tuple[int, int] | None
    reward: float
    tool_call_id: str | None
    info: dict[str, Any] = field(default_factory=dict)


@dataclass
class EpisodeState:
    """Driver-side record of one candidate's episode across turns."""

    task: dict[str, Any]
    turns: list[TurnRecord] = field(default_factory=list)
    done: bool = False
    truncated: bool = False
    accuracy: float = 0.0

    @property
    def total_reward(self) -> float:
        return float(sum(t.reward for t in self.turns))

    @property
    def num_turns(self) -> int:
        return len(self.turns)


@runtime_checkable
class Environment(Protocol):
    """Minimal protocol every pluggable environment implements."""

    name: str

    def reset(self, task: dict[str, Any]) -> str:
        """Begin an episode; return the first observation (the prompt)."""
        ...

    def step(self, completion: str) -> EnvStep:
        """Consume one policy completion; return the next observation."""
        ...
