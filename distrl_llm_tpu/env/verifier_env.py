"""Verifier-feedback environment: critique, re-prompt, reward improvement.

Each turn the verifier extracts the model's answer, checks it against the
solution, and — when wrong and turn budget remains — injects a critique and
asks the model to try again. The per-turn reward is the *improvement* in the
verifier's format score over the previous attempt (first turn: the score
itself), so a policy that tightens its formatting across turns earns
positive per-turn rewards while a degrading one pays for it. Terminal
accuracy rides in ``info["accuracy"]`` as usual.

With the turn hook in the paged engine, each retry continues the same KV
chain — the critique is appended to the resident conversation, not
re-prefilled.
"""

from __future__ import annotations

from typing import Any

from ..rewards import correctness_reward, extract_xml_answer, make_format_scorer
from .base import EnvStep


class VerifierFeedbackEnv:
    """Multi-turn verifier loop: wrong answers get a critique and a retry."""

    name = "verifier"

    def __init__(self, format_scorer: str = "soft", max_turns: int = 4):
        self.max_turns = max(1, int(max_turns))
        self._fmt = make_format_scorer(format_scorer)
        self._task: dict[str, Any] | None = None
        self._turn = 0
        self._prev_score: float | None = None

    def reset(self, task: dict[str, Any]) -> str:
        self._task = dict(task)
        self._turn = 0
        self._prev_score = None
        return str(task.get("problem", ""))

    def step(self, completion: str) -> EnvStep:
        if self._task is None:
            raise RuntimeError("step() before reset()")
        self._turn += 1
        score = float(self._fmt([completion])[0])
        reward = score if self._prev_score is None else score - self._prev_score
        self._prev_score = score
        acc = float(
            correctness_reward([completion], [str(self._task.get("solution", ""))])[0]
        )
        if acc == 1.0 or self._turn >= self.max_turns:
            return EnvStep(
                None, reward, True,
                {"accuracy": acc, "verdict": "correct" if acc == 1.0 else "incorrect"},
            )
        answer = extract_xml_answer(completion) or "<missing>"
        critique = (
            f"\nVerifier: answer {answer!r} is incorrect. Re-check your reasoning "
            "and reply again with <think>...</think> then <answer>...</answer>.\n"
        )
        return EnvStep(
            critique, reward, False,
            {"tool_call_id": f"verify-{self._turn}", "verdict": "incorrect"},
        )
