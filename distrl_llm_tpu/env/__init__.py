"""Pluggable multi-turn environments (ISSUE 17).

Public surface: the :class:`Environment` protocol and episode dataclasses
(`base`), the three shipped environments, the name registry consumed by
config validation and the CLIs, and :class:`EnvRolloutDriver` — the engine
turn-hook implementation the trainer arms for ``env != "math"`` runs.
"""

from .base import Environment, EnvStep, EpisodeState, TurnRecord
from .code_env import CodeToolEnv, run_sandboxed
from .driver import EnvRolloutDriver, EnvRoundResult, EnvRoundStats
from .math_env import MathSingleTurnEnv
from .registry import ENV_REGISTRY, env_names, get_env_class
from .verifier_env import VerifierFeedbackEnv

__all__ = [
    "ENV_REGISTRY",
    "CodeToolEnv",
    "Environment",
    "EnvRolloutDriver",
    "EnvRoundResult",
    "EnvRoundStats",
    "EnvStep",
    "EpisodeState",
    "MathSingleTurnEnv",
    "TurnRecord",
    "VerifierFeedbackEnv",
    "env_names",
    "get_env_class",
    "run_sandboxed",
]
