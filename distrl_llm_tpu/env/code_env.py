"""Sandboxed code-execution tool environment.

The model emits ``<tool>...</tool>`` blocks containing Python; the
environment runs the last block in a restricted subprocess and feeds the
captured output back as the next turn's observation (wrapped in
``<output>`` tags, loss-masked by the driver). The episode ends when the
model commits to an ``<answer>`` or the turn budget runs out, at which point
accuracy is scored exactly like the math task.

Sandbox restrictions (stdlib only — no new dependencies):

* interpreter isolation: ``python -I`` (implies ``-E``/``-s``: no env vars,
  no user site, no cwd on ``sys.path``), empty environment, tmpdir cwd;
* resource rlimits via ``preexec_fn``: CPU seconds, address space, file
  size, process count — plus a wall-clock timeout that kills the child;
* output truncation to ``output_limit`` characters before it is tokenized,
  so a print-loop cannot blow up the next turn's observation.

This is defense against *accidents* (infinite loops, fork bombs, giant
prints) during RL rollouts of a policy we are training, not a security
boundary against an adversary with root.
"""

from __future__ import annotations

import re
import subprocess
import sys
import tempfile
from typing import Any

from ..rewards import correctness_reward, make_format_scorer
from .base import EnvStep

_TOOL_RE = re.compile(r"<tool>(.*?)</tool>", re.DOTALL)

_CPU_SECONDS = 2
_ADDRESS_SPACE = 512 << 20  # 512 MiB
_FILE_SIZE = 1 << 20  # 1 MiB
_MAX_PROCS = 16


def _sandbox_rlimits() -> None:  # pragma: no cover - runs in the child
    import resource

    resource.setrlimit(resource.RLIMIT_CPU, (_CPU_SECONDS, _CPU_SECONDS))
    resource.setrlimit(resource.RLIMIT_FSIZE, (_FILE_SIZE, _FILE_SIZE))
    for limit, value in (
        (resource.RLIMIT_AS, _ADDRESS_SPACE),
        (getattr(resource, "RLIMIT_NPROC", None), _MAX_PROCS),
    ):
        if limit is None:
            continue
        try:
            resource.setrlimit(limit, (value, value))
        except (ValueError, OSError):
            pass  # some kernels/uids refuse; the wall timeout still holds


def run_sandboxed(code: str, timeout_s: float = 5.0, output_limit: int = 256) -> str:
    """Run ``code`` in the restricted subprocess; return its (truncated) output."""
    try:
        proc = subprocess.run(
            [sys.executable, "-I", "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env={},
            cwd=tempfile.gettempdir(),
            preexec_fn=_sandbox_rlimits,
        )
        out = proc.stdout if proc.returncode == 0 else proc.stdout + proc.stderr
    except subprocess.TimeoutExpired:
        out = "<tool timeout>"
    except Exception as exc:  # sandbox setup failure, not model output
        out = f"<tool error: {type(exc).__name__}>"
    out = out.strip()
    if not out:
        return "<no output>"
    return out[:output_limit]


class CodeToolEnv:
    """Multi-turn tool env: run ``<tool>`` blocks, round-trip the output."""

    name = "code"

    def __init__(
        self,
        format_scorer: str = "soft",
        max_turns: int = 4,
        tool_timeout_s: float = 5.0,
        output_limit: int = 256,
    ):
        self.max_turns = max(1, int(max_turns))
        self.tool_timeout_s = float(tool_timeout_s)
        self.output_limit = int(output_limit)
        self._fmt = make_format_scorer(format_scorer)
        self._task: dict[str, Any] | None = None
        self._turn = 0
        self._tool_seq = 0

    def reset(self, task: dict[str, Any]) -> str:
        self._task = dict(task)
        self._turn = 0
        self._tool_seq = 0
        return str(task.get("problem", ""))

    def _terminal(self, completion: str, fmt: float) -> EnvStep:
        assert self._task is not None
        acc = float(
            correctness_reward([completion], [str(self._task.get("solution", ""))])[0]
        )
        return EnvStep(None, fmt, True, {"accuracy": acc})

    def step(self, completion: str) -> EnvStep:
        if self._task is None:
            raise RuntimeError("step() before reset()")
        self._turn += 1
        fmt = float(self._fmt([completion])[0])
        if "<answer>" in completion or self._turn >= self.max_turns:
            return self._terminal(completion, fmt)
        blocks = _TOOL_RE.findall(completion)
        if blocks:
            self._tool_seq += 1
            tool_call_id = f"tool-{self._tool_seq}"
            out = run_sandboxed(blocks[-1], self.tool_timeout_s, self.output_limit)
            obs = f"\n<output>\n{out}\n</output>\n"
            # small bonus for a well-formed tool call: shaped, not accuracy
            return EnvStep(
                obs, fmt + 0.05, False,
                {"tool_call_id": tool_call_id, "tool_output": out},
            )
        obs = "\nNo <answer> given. Use <tool>...</tool> to compute, then reply in <answer>...</answer> tags.\n"
        return EnvStep(obs, fmt, False, {})
