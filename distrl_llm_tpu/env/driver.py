"""Multi-turn rollout driver: the glue between environments and the engine.

The paged engine exposes a ``turn_hook`` (engine/paged_engine.py): when a
candidate's generation hits EOS/length with the hook armed, the refill loop
calls ``hook(cand_id, gen_tokens)`` from its idle pass. Returning an array of
observation tokens makes the engine *resume the same slot* — the observation
is appended to the resident KV chain (one chunked forward over the
observation tokens, no re-prefill of the conversation prefix) and decoding
continues. Returning ``None`` lets the candidate finish normally. The engine
calls ``hook.declined(cand_id)`` if it accepted an observation but could not
seat it (no token room / no pages), so the driver can unwind the phantom
env span.

:class:`EnvRolloutDriver` is that hook. Per round it owns one environment
instance per candidate, tracks per-turn token spans in answer-token
coordinates, times ``env.step``, and after the engine returns assembles:

* a ``loss_mask`` ``[rows, max_new_tokens]`` — 1 on policy-generated spans,
  0 on environment-injected tokens (observations never train);
* per-group ``(n, 2)`` rewards — column 0 the summed per-turn shaped
  rewards, column 1 terminal accuracy — matching the legacy contract;
* per-candidate turn provenance (turn index, spans, tool-call id) that the
  trainer folds into trajectory metadata for lineage.

GRPO groups close when *all* candidates finish regardless of turn count:
the driver never blocks a group open — the engine's existing per-candidate
finish accounting handles heterogeneous turn counts, which is exactly what
keeps mixed-length episodes free of dead slots.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from distrl_llm_tpu import telemetry

from .base import EpisodeState, TurnRecord
from .registry import get_env_class

# env/* telemetry series (single defining owner — graftcheck GC2xx)
ENV_TURNS = "env/turns"  # histogram: policy turns per finished episode
ENV_STEP_MS = "env/step_ms"  # histogram: env.step wall time
ENV_TOOL_CALLS = "env/tool_calls"  # counter: sandboxed tool executions
ENV_EPISODES = "env/episodes"  # counter: episodes finished
ENV_RESUME_DECLINED = "env/resume_declined"  # counter: engine declined a turn


@dataclass
class _Episode:
    env: Any
    state: EpisodeState
    synthetic: bool = False  # batch-padding row: never stepped, never scored
    prev_len: int = 0  # answer-token cursor: where the current turn starts


@dataclass
class EnvRoundStats:
    env_name: str
    turns_mean: float
    turns_max: int
    env_step_ms_p50: float
    tool_calls: int
    resume_declined: int


@dataclass
class EnvRoundResult:
    loss_mask: np.ndarray  # [rows, max_new_tokens] int32
    group_rewards: list[np.ndarray]  # per group: (n, 2) float64
    turns: np.ndarray  # [rows] int32 policy-turn counts
    turn_provenance: list[list[dict[str, Any]]]  # per candidate row
    stats: EnvRoundStats
    episodes: list[EpisodeState] = field(default_factory=list)
    # per candidate row: the full conversation transcript in answer-token
    # coordinates (policy spans + injected observations, up to the final
    # cursor). A caller continuing the conversation in a LATER round
    # composes its next prompt as ``prompt_ids + history[c]``; with the
    # tiered KV cache on (ISSUE 18) the engine's admit-time radix match
    # aliases every full page the retired conversation left behind, so
    # re-admitting the history costs zero prefill for the cached prefix.
    history: list[np.ndarray] = field(default_factory=list)


class EnvRolloutDriver:
    """Per-trainer driver; ``begin_round`` arms it as the engine turn hook."""

    def __init__(
        self,
        env_name: str,
        tokenizer: Any,
        *,
        max_turns: int,
        max_new_tokens: int,
        format_scorer: str = "soft",
        env_kwargs: dict[str, Any] | None = None,
    ):
        self.env_name = env_name
        self.tokenizer = tokenizer
        self.max_turns = max(1, int(max_turns))
        self.max_new_tokens = int(max_new_tokens)
        self.format_scorer = format_scorer
        self.env_kwargs = dict(env_kwargs or {})
        self._cls = get_env_class(env_name)
        self._episodes: list[_Episode] = []
        self._n = 0
        self._step_ms: list[float] = []
        self._tool_calls = 0
        self._declined = 0

    # -- round lifecycle ----------------------------------------------------

    def begin_round(
        self, problems: list[str], solutions: list[str], n_candidates: int
    ) -> "EnvRolloutDriver":
        """Build one env per candidate row (group-major, ``row = g*n + i``).

        ``problems`` may include batch-padding entries (empty strings); those
        rows get synthetic already-done episodes so the hook ends them on
        first contact without ever running an environment.
        """
        self._episodes = []
        self._n = int(n_candidates)
        self._step_ms = []
        self._tool_calls = 0
        self._declined = 0
        for problem, solution in zip(problems, solutions):
            task = {"problem": problem, "solution": solution}
            synthetic = problem == ""
            for _ in range(n_candidates):
                env = self._cls(
                    format_scorer=self.format_scorer,
                    max_turns=self.max_turns,
                    **self.env_kwargs,
                )
                state = EpisodeState(task=dict(task))
                if synthetic:
                    state.done = True
                else:
                    env.reset(task)
                self._episodes.append(
                    _Episode(env=env, state=state, synthetic=synthetic)
                )
        return self

    # -- engine turn-hook contract ------------------------------------------

    def __call__(self, cand_id: int, gen_tokens: np.ndarray) -> np.ndarray | None:
        """Consume one finished turn; return observation tokens or ``None``."""
        ep = self._episodes[cand_id]
        if ep.state.done:
            return None
        gen_len = int(len(gen_tokens))
        step = self._step_env(ep, gen_tokens, gen_len)
        if step.done or step.observation is None:
            self._finish_episode(ep, step.info)
            return None
        if len(ep.state.turns) >= self.max_turns:
            # env wanted another turn but the budget is spent
            self._finish_episode(ep, step.info, truncated=True)
            return None
        obs_ids = self._encode(step.observation)
        if obs_ids.size == 0:
            self._finish_episode(ep, step.info, truncated=True)
            return None
        ep.state.turns[-1].env_span = (gen_len, gen_len + int(obs_ids.size))
        ep.prev_len = gen_len + int(obs_ids.size)
        return obs_ids

    def declined(self, cand_id: int) -> None:
        """Engine could not seat the observation we just returned."""
        ep = self._episodes[cand_id]
        if ep.state.turns and ep.state.turns[-1].env_span is not None:
            ep.prev_len = ep.state.turns[-1].policy_span[1]
            ep.state.turns[-1].env_span = None
        self._declined += 1
        telemetry.counter_add(ENV_RESUME_DECLINED)
        self._finish_episode(ep, {}, truncated=True)

    # -- internals ----------------------------------------------------------

    def _encode(self, text: str) -> np.ndarray:
        try:
            ids = self.tokenizer.encode(text, add_special_tokens=False)
        except TypeError:
            ids = self.tokenizer.encode(text)
        return np.asarray(ids, dtype=np.int32)

    def _decode(self, tokens: np.ndarray) -> str:
        try:
            return self.tokenizer.decode(tokens, skip_special_tokens=True)
        except TypeError:
            return self.tokenizer.decode(tokens)

    def _step_env(self, ep: _Episode, gen_tokens: np.ndarray, gen_len: int):
        completion = self._decode(np.asarray(gen_tokens[ep.prev_len:gen_len]))
        t0 = time.perf_counter()
        step = ep.env.step(completion)
        dt_ms = (time.perf_counter() - t0) * 1e3
        self._step_ms.append(dt_ms)
        telemetry.hist_observe(ENV_STEP_MS, dt_ms)
        tool_call_id = step.info.get("tool_call_id")
        if tool_call_id is not None and "tool_output" in step.info:
            self._tool_calls += 1
            telemetry.counter_add(ENV_TOOL_CALLS)
        ep.state.turns.append(
            TurnRecord(
                index=len(ep.state.turns),
                policy_span=(ep.prev_len, gen_len),
                env_span=None,
                reward=float(step.reward),
                tool_call_id=tool_call_id,
                info=dict(step.info),
            )
        )
        return step

    def _finish_episode(
        self, ep: _Episode, info: dict[str, Any], truncated: bool = False
    ) -> None:
        ep.state.done = True
        ep.state.truncated = truncated
        ep.state.accuracy = float(info.get("accuracy", 0.0))
        telemetry.counter_add(ENV_EPISODES)
        telemetry.hist_observe(ENV_TURNS, float(ep.state.num_turns))

    # -- post-round assembly ------------------------------------------------

    def finish_round(self, tokens: np.ndarray, lengths: np.ndarray) -> EnvRoundResult:
        """Score stragglers and assemble masks/rewards/provenance.

        A candidate the engine finished without consulting the hook (final
        blocking sweep, or an engine without the turn hook armed) still owes
        its last turn to the environment — score it here from the result
        tensors.
        """
        rows = len(self._episodes)
        width = self.max_new_tokens
        for c, ep in enumerate(self._episodes):
            if ep.state.done:
                continue
            gen_len = int(lengths[c])
            if gen_len > ep.prev_len or not ep.state.turns:
                step = self._step_env(ep, np.asarray(tokens[c][:gen_len]), gen_len)
                self._finish_episode(
                    ep, step.info, truncated=not (step.done or step.observation is None)
                )
            else:
                self._finish_episode(ep, {}, truncated=True)

        loss_mask = np.zeros((rows, width), dtype=np.int32)
        turns = np.zeros(rows, dtype=np.int32)
        history: list[np.ndarray] = []
        for c, ep in enumerate(self._episodes):
            # transcript = everything up to the last turn's end (the final
            # cursor is max(policy/env span ends) — lengths[c] can run past
            # it when the engine decoded beyond the last consulted turn)
            end = int(lengths[c])
            for turn in ep.state.turns:
                end = max(end, turn.policy_span[1])
                if turn.env_span is not None:
                    end = max(end, turn.env_span[1])
            row = np.asarray(tokens[c])
            history.append(row[: min(end, row.shape[0])].astype(np.int32))
        provenance: list[list[dict[str, Any]]] = []
        group_rewards: list[np.ndarray] = []
        for c, ep in enumerate(self._episodes):
            turns[c] = ep.state.num_turns
            cand_turns: list[dict[str, Any]] = []
            for turn in ep.state.turns:
                s, e = turn.policy_span
                loss_mask[c, max(0, s):min(width, e)] = 1
                cand_turns.append(
                    {
                        "turn": turn.index,
                        "tool_call_id": turn.tool_call_id,
                        "policy_span": [int(turn.policy_span[0]), int(turn.policy_span[1])],
                        "env_span": (
                            None if turn.env_span is None
                            else [int(turn.env_span[0]), int(turn.env_span[1])]
                        ),
                        "reward": float(turn.reward),
                    }
                )
            provenance.append(cand_turns)
        n = max(1, self._n)
        for g in range(rows // n):
            block = self._episodes[g * n:(g + 1) * n]
            rew = np.zeros((n, 2), dtype=np.float64)
            for i, ep in enumerate(block):
                rew[i, 0] = ep.state.total_reward
                rew[i, 1] = ep.state.accuracy
            group_rewards.append(rew)

        real = [ep for ep in self._episodes if not ep.synthetic]
        counts = [ep.state.num_turns for ep in real] or [0]
        stats = EnvRoundStats(
            env_name=self.env_name,
            turns_mean=float(np.mean(counts)),
            turns_max=int(np.max(counts)),
            env_step_ms_p50=float(np.median(self._step_ms)) if self._step_ms else 0.0,
            tool_calls=self._tool_calls,
            resume_declined=self._declined,
        )
        return EnvRoundResult(
            loss_mask=loss_mask,
            group_rewards=group_rewards,
            turns=turns,
            turn_provenance=provenance,
            stats=stats,
            episodes=[ep.state for ep in self._episodes],
            history=history,
        )
