"""distrl_llm_tpu — a TPU-native distributed RL framework for LLM fine-tuning.

Built from scratch in JAX/XLA/Pallas/pjit with the capabilities of
BY571/DistRL-LLM: data-parallel rollout workers sample many candidate
completions per prompt through a jit-compiled generation engine, rule-based
rewards score them on the host, and LoRA learners apply policy-gradient or
GRPO updates with gradient averaging over ICI collectives. Roles
(generator/learner) are partitions of one ``jax.sharding.Mesh`` rather than
processes; weight sync is a device-to-device transfer rather than an
adapter file on a shared filesystem.
"""

__version__ = "0.1.0"

from distrl_llm_tpu.config import MeshConfig, SamplingConfig, TrainConfig  # noqa: F401
