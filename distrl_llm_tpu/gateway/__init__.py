"""Multi-tenant serving gateway (ISSUE 19): streaming HTTP front-end,
priority-class scheduling, per-tenant token quotas, and an open-loop
traffic harness over the continuous-batching engine.

The package is pure attach-pattern glue: nothing here imports JAX at
module scope, and an engine with no gateway attached is byte-identical to
pre-gateway HEAD (the hooks are ``is not None`` checks, pinned in
tests/test_gateway.py).

* :mod:`.scheduler` — priority classes, the class-then-FIFO-with-aging
  request queue, :class:`TenantQuotaBook`, and the single-owner
  ``gateway/*`` telemetry series.
* :mod:`.service` — :class:`GatewayService`: the engine-facing loop that
  forms rounds from the open queue, attaches ``round_meta`` /
  ``quota_book`` / ``stream_hook``, and demuxes streamed tokens back to
  per-request subscribers.
* :mod:`.server` — :class:`GatewayServer`: ``POST /v1/generate`` chunked
  streaming on the obs.MetricsServer ThreadingHTTPServer pattern.
* :mod:`.traffic` — seeded open-loop arrival processes (Poisson/burst)
  with long-tail length distributions, JSONL-replayable.
"""

from distrl_llm_tpu.gateway.scheduler import (  # noqa: F401
    CLASS_RANK,
    DEFAULT_CLASS,
    PRIORITY_CLASSES,
    GatewayRequest,
    RequestQueue,
    TenantQuotaBook,
    parse_gateway_classes,
    parse_tenant_quota,
)
