"""Open-loop traffic synthesis + replay for the serving gateway
(ISSUE 19).

Production-shaped load is heterogeneous in BOTH dimensions Laminar
measures (PAPERS.md): arrival times (Poisson steady state punctuated by
bursts) and lengths (long-tail — a few huge prompts/outputs dominate
the page pool). This module synthesizes such traces deterministically
from a seed, persists them as JSONL so a bench round and a regression
bisect replay the SAME arrivals, and drives them at the gateway
OPEN-LOOP: each request fires at its scheduled offset whether or not
earlier requests completed — under overload the queue grows, which is
the point (a closed-loop client self-throttles and can never show the
p99 cliff).

Client-side latency is recorded per class alongside the server-side
ledger: TTFT here is "POST sent → first streamed chunk", including HTTP
and queue time the server-side number can't see."""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from typing import Any
from urllib.parse import urlsplit

# long-tail defaults (lognormal, tokens): most prompts small, a heavy
# tail capped by the caller's engine window
DEFAULT_PROMPT_MU = 2.5      # median ~12 tokens
DEFAULT_PROMPT_SIGMA = 0.8
DEFAULT_OUTPUT_MU = 2.0      # median ~7 tokens
DEFAULT_OUTPUT_SIGMA = 0.7


def synthesize(
    *,
    seed: int,
    n_requests: int,
    rate_rps: float,
    process: str = "poisson",
    burst_every_s: float = 2.0,
    burst_size: int = 8,
    class_mix: dict[str, float] | None = None,
    tenants: tuple[str, ...] = ("acme", "globex"),
    prompt_mu: float = DEFAULT_PROMPT_MU,
    prompt_sigma: float = DEFAULT_PROMPT_SIGMA,
    max_prompt_tokens: int = 64,
    output_mu: float = DEFAULT_OUTPUT_MU,
    output_sigma: float = DEFAULT_OUTPUT_SIGMA,
    max_new_tokens: int = 32,
) -> list[dict[str, Any]]:
    """Deterministic arrival trace: ``n_requests`` dicts with offset ``t``
    (seconds from replay start, nondecreasing), tenant, class, prompt
    length and output budget. ``process``: "poisson" (exponential
    inter-arrivals at ``rate_rps``) or "burst" (the same Poisson base with
    ``burst_size`` extra back-to-back arrivals every ``burst_every_s`` —
    the overload shape the r19 artifact drives)."""
    if process not in ("poisson", "burst"):
        raise ValueError(
            f"unknown arrival process {process!r} (poisson|burst)"
        )
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    mix = dict(class_mix or {
        "interactive": 0.4, "batch": 0.4, "scavenger": 0.2,
    })
    classes = sorted(mix)
    weights = [float(mix[c]) for c in classes]
    rng = random.Random(int(seed))
    out: list[dict[str, Any]] = []
    t = 0.0
    next_burst = burst_every_s
    while len(out) < n_requests:
        t += rng.expovariate(rate_rps)
        burst = 1
        if process == "burst" and t >= next_burst:
            burst += int(burst_size)
            next_burst += burst_every_s
        for _ in range(burst):
            if len(out) >= n_requests:
                break
            cls = rng.choices(classes, weights=weights)[0]
            p_len = max(1, min(
                int(rng.lognormvariate(prompt_mu, prompt_sigma)),
                int(max_prompt_tokens),
            ))
            o_len = max(1, min(
                int(rng.lognormvariate(output_mu, output_sigma)),
                int(max_new_tokens),
            ))
            out.append({
                "t": round(t, 6),
                "tenant": rng.choice(list(tenants)),
                "cls": cls,
                "prompt_len": p_len,
                "max_new_tokens": o_len,
            })
    return out


def save_trace(path: str, arrivals: list[dict[str, Any]]) -> None:
    with open(path, "w") as f:
        for a in arrivals:
            f.write(json.dumps(a) + "\n")


def load_trace(path: str) -> list[dict[str, Any]]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _percentile(vals: list[float], q: float) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    idx = min(int(len(s) * q / 100.0), len(s) - 1)
    return s[idx]


class _ClientRecord:
    __slots__ = ("cls", "ttft_ms", "e2e_ms", "gen_tokens", "error",
                 "streamed_ok")

    def __init__(self, cls: str):
        self.cls = cls
        self.ttft_ms: float | None = None
        self.e2e_ms: float | None = None
        self.gen_tokens = 0
        self.error: str | None = None
        self.streamed_ok: bool | None = None


def _one_request(url_parts, arrival: dict[str, Any],
                 rec: _ClientRecord, prompt_char: str,
                 timeout_s: float) -> None:
    t0 = time.time()
    try:
        conn = http.client.HTTPConnection(
            url_parts.hostname, url_parts.port, timeout=timeout_s
        )
        body = json.dumps({
            "prompt": prompt_char * int(arrival["prompt_len"]),
            "max_new_tokens": int(arrival["max_new_tokens"]),
        })
        conn.request(
            "POST", "/v1/generate", body=body,
            headers={
                "Content-Type": "application/json",
                "X-Tenant": str(arrival.get("tenant", "anon")),
                "X-Priority": str(arrival.get("cls", "batch")),
            },
        )
        resp = conn.getresponse()
        if resp.status != 200:
            rec.error = f"HTTP {resp.status}: {resp.read()[:200]!r}"
            return
        streamed: list[int] = []
        final: dict | None = None
        # http.client transparently de-chunks; one JSON doc per line
        for raw in resp:
            line = raw.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "error" in doc:
                rec.error = str(doc["error"])
                return
            if doc.get("done"):
                final = doc
                break
            if doc.get("tokens"):
                if rec.ttft_ms is None:
                    rec.ttft_ms = (time.time() - t0) * 1e3
                streamed.extend(int(t) for t in doc["tokens"])
        rec.e2e_ms = (time.time() - t0) * 1e3
        if final is None:
            rec.error = "stream ended without done line"
            return
        if rec.ttft_ms is None:
            # everything arrived in the final flush: TTFT = e2e
            rec.ttft_ms = rec.e2e_ms
        rec.gen_tokens = int(final.get("gen_tokens", 0))
        # byte-complete contract: the streamed chunks, concatenated,
        # ARE the final token list (the smoke asserts all(streamed_ok))
        rec.streamed_ok = streamed == [
            int(t) for t in final.get("tokens", ())
        ]
        conn.close()
    except Exception as e:  # noqa: BLE001 — a failed request is a row,
        # not a harness crash
        rec.error = f"{type(e).__name__}: {e}"


def replay(url: str, arrivals: list[dict[str, Any]], *,
           prompt_char: str = "a", timeout_s: float = 120.0,
           speedup: float = 1.0) -> dict[str, Any]:
    """Drive an arrival trace at the gateway open-loop: each request
    fires on its own thread at ``t / speedup`` seconds after start,
    never waiting for earlier completions. Returns the per-class
    client-side summary (TTFT/e2e p50/p99, errors, stream integrity)."""
    parts = urlsplit(url)
    records = [_ClientRecord(str(a.get("cls", "batch"))) for a in arrivals]
    threads: list[threading.Thread] = []
    t_start = time.time()
    for arrival, rec in zip(arrivals, records):
        delay = float(arrival.get("t", 0.0)) / max(speedup, 1e-9)
        wait = t_start + delay - time.time()
        if wait > 0:
            time.sleep(wait)
        th = threading.Thread(
            target=_one_request,
            args=(parts, arrival, rec, prompt_char, timeout_s),
            daemon=True,
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=timeout_s)
    wall_s = time.time() - t_start
    by_class: dict[str, dict[str, Any]] = {}
    for rec in records:
        cls = by_class.setdefault(rec.cls, {
            "n": 0, "errors": 0, "ttft_ms": [], "e2e_ms": [],
            "gen_tokens": 0, "stream_incomplete": 0,
        })
        cls["n"] += 1
        if rec.error is not None:
            cls["errors"] += 1
            continue
        cls["gen_tokens"] += rec.gen_tokens
        if rec.ttft_ms is not None:
            cls["ttft_ms"].append(rec.ttft_ms)
        if rec.e2e_ms is not None:
            cls["e2e_ms"].append(rec.e2e_ms)
        if rec.streamed_ok is False:
            cls["stream_incomplete"] += 1
    summary: dict[str, Any] = {
        "requests": len(records),
        "wall_s": round(wall_s, 3),
        "arrival_rate_rps": (
            round(len(records) / wall_s, 3) if wall_s > 0 else None
        ),
        "by_class": {},
    }
    for cls, agg in sorted(by_class.items()):
        summary["by_class"][cls] = {
            "n": agg["n"],
            "errors": agg["errors"],
            "stream_incomplete": agg["stream_incomplete"],
            "gen_tokens": agg["gen_tokens"],
            "ttft_p50_ms": _percentile(agg["ttft_ms"], 50),
            "ttft_p99_ms": _percentile(agg["ttft_ms"], 99),
            "e2e_p50_ms": _percentile(agg["e2e_ms"], 50),
            "e2e_p99_ms": _percentile(agg["e2e_ms"], 99),
        }
    return summary
