"""GatewayServer: the streaming HTTP front-end (ISSUE 19).

Same pattern as :class:`obs.MetricsServer` — a ThreadingHTTPServer on
127.0.0.1 (``port=0`` auto-assigns, read ``.port``) with one daemon serve
thread — plus chunked response streaming, which the metrics endpoint
never needed: ``POST /v1/generate`` answers HTTP/1.1 with
``Transfer-Encoding: chunked`` and writes one JSON line per token batch
as the engine streams it, ending with a ``done`` line carrying the full
result. An operator fronts it; nothing here needs to be internet-facing.

Request contract::

    POST /v1/generate
    X-Tenant: acme            (optional; default "anon")
    X-Priority: interactive   (optional; interactive|batch|scavenger,
                               default "batch")
    {"prompt": "...", "max_new_tokens": 32, "temperature": 0.0}

Response: ``application/x-ndjson`` chunks —
``{"tokens": [...], "text": "..."}`` per streamed batch, then
``{"done": true, "rid": ..., "gen_tokens": ..., "trace_id": ...,
"dispatch_id": ...}``. Errors before streaming starts are plain JSON
with an HTTP error code; errors mid-stream land as a final
``{"error": ...}`` line (the status line is already on the wire).

``GET /v1/stats`` → gateway/service/quota counters; ``GET /healthz`` →
``ok``."""

from __future__ import annotations

import json
import logging
import threading
from typing import Any

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.gateway.scheduler import (
    DEFAULT_CLASS,
    GATEWAY_REJECTED,
)

log = logging.getLogger("distrl.gateway")


class GatewayServer:
    """HTTP front-end over one :class:`GatewayService`."""

    def __init__(self, service, port: int = 0, host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _Handler(BaseHTTPRequestHandler):
            # chunked responses need HTTP/1.1 framing; http.server
            # defaults to 1.0 where chunked is illegal
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: ARG002 — quiet
                pass

            def _send(self, code: int, ctype: str, body: bytes) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _chunk(self, doc: dict) -> None:
                payload = (json.dumps(doc) + "\n").encode()
                self.wfile.write(
                    f"{len(payload):x}\r\n".encode() + payload + b"\r\n"
                )
                self.wfile.flush()

            def _end_chunks(self) -> None:
                self.wfile.write(b"0\r\n\r\n")
                self.wfile.flush()

            def do_GET(self):  # noqa: N802 — http.server contract
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send(200, "text/plain", b"ok\n")
                    elif path == "/v1/stats":
                        self._send(
                            200, "application/json",
                            json.dumps(server.service.stats()).encode(),
                        )
                    else:
                        self._send(404, "text/plain", b"not found\n")
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):  # noqa: N802 — http.server contract
                path = self.path.split("?", 1)[0]
                if path != "/v1/generate":
                    self._send(404, "text/plain", b"not found\n")
                    return
                try:
                    n_body = int(self.headers.get("Content-Length") or 0)
                    doc = json.loads(self.rfile.read(n_body) or b"{}")
                    req = server.service.submit(
                        doc.get("prompt"),
                        prompt_ids=doc.get("prompt_ids"),
                        tenant=self.headers.get("X-Tenant", "anon"),
                        cls=(
                            self.headers.get("X-Priority", DEFAULT_CLASS)
                            .strip().lower()
                        ),
                        max_new_tokens=doc.get("max_new_tokens"),
                        temperature=doc.get("temperature"),
                    )
                except (ValueError, KeyError, TypeError) as e:
                    # submit() already counted GATEWAY_REJECTED for policy
                    # rejections; malformed JSON lands here too
                    if not isinstance(e, ValueError):
                        telemetry.counter_add(GATEWAY_REJECTED)
                    self._send(
                        400, "application/json",
                        json.dumps({"error": str(e)}).encode(),
                    )
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                try:
                    while True:
                        kind, payload = req.events.get()
                        if kind == "tokens":
                            self._chunk({
                                "tokens": payload,
                                "text": server.service._decode(payload),
                            })
                        elif kind == "done":
                            self._chunk(dict(payload, done=True))
                            break
                        else:  # "error"
                            self._chunk({"error": payload})
                            break
                    self._end_chunks()
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client went away mid-stream; the round finishes

        self.service = service
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="gateway-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001 — idempotent teardown
            pass

    def stats(self) -> dict[str, Any]:
        return self.service.stats()
