"""Priority classes, the gateway request queue, and per-tenant token
quotas (ISSUE 19).

Three fixed priority classes order all gateway work:

    interactive (rank 0)  >  batch (rank 1)  >  scavenger (rank 2)

Rank is the scheduling currency everywhere class-awareness appears —
admission ordering prefers LOW rank, preemption victims and load-shed
prefer HIGH rank. Ordering within a class is FIFO **with aging**: every
scheduling pass a request is passed over bumps a deterministic wait
counter, and each ``AGE_PASSES`` passes promote its *effective* rank one
step toward 0 — a scavenger request cannot starve behind an endless
interactive stream (no wall clock in the policy: counters only, so the
decision is replayable).

:class:`TenantQuotaBook` is the admission-side token budget: each tenant
holds at most ``quota`` reserved tokens across its in-flight groups
(reservation = prompt + the worst-case output window, credited back at
group close). A quota-declined admission is a first-class stall reason
(``quota`` in serving_obs.STALL_REASONS) so the conservation contract
``sum(stalls) == declined`` extends rather than breaks.

This module is the single owner of every ``gateway/*`` telemetry series
(graftcheck GC202); per-class and per-tenant breakdowns derive with the
constant-prefix pattern (``f"{GATEWAY_REQUESTS}/{cls}"``)."""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Any

from distrl_llm_tpu import telemetry

# ------------------------------------------------------------- class model

PRIORITY_CLASSES = ("interactive", "batch", "scavenger")
CLASS_RANK = {c: i for i, c in enumerate(PRIORITY_CLASSES)}
DEFAULT_CLASS = "batch"

# scheduling passes a queued request must be passed over before its
# effective rank promotes one step (deterministic aging — pass counts,
# never wall clock)
AGE_PASSES = 16

# ------------------------------------------------------------ series names
# (single-owner gateway/* constants, GC202; schema pinned in
# tests/test_telemetry.py. Per-class / per-tenant breakdowns derive as
# f"{CONST}/<suffix>" — constant-prefix derivation, GC201-legal)

GATEWAY_REQUESTS = "gateway/requests"              # counter (+ /<class>)
GATEWAY_REJECTED = "gateway/rejected"              # counter: HTTP 4xx/5xx
GATEWAY_QUEUE_DEPTH = "gateway/queue_depth"        # gauge
GATEWAY_ROUNDS = "gateway/rounds"                  # counter: engine rounds
GATEWAY_STREAMED_TOKENS = "gateway/streamed_tokens"  # counter
GATEWAY_QUOTA_DENIALS = "gateway/quota_denials"    # counter (+ /<tenant>)
GATEWAY_QUOTA_RESERVED = "gateway/quota_reserved"  # gauge  (+ /<tenant>)
GATEWAY_AGED_PROMOTIONS = "gateway/aged_promotions"  # counter

# tenant names become telemetry-series suffixes and JSONL fields: clamp to
# the series alphabet so a hostile header can't mint malformed series
_TENANT_RE = re.compile(r"[^a-z0-9_]+")


def sanitize_tenant(name: str) -> str:
    s = _TENANT_RE.sub("_", str(name or "anon").lower()).strip("_")
    if not s or not s[0].isalpha():
        s = "t_" + s if s else "anon"
    return s[:48]


# --------------------------------------------------------------- CLI parse


def parse_gateway_classes(spec: str | None) -> tuple[str, ...]:
    """``--gateway_classes`` value → ordered class subset. Empty/None means
    all three; unknown names are config errors, not silent drops."""
    if not spec:
        return PRIORITY_CLASSES
    out = []
    for tok in str(spec).split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        if tok not in CLASS_RANK:
            raise ValueError(
                f"unknown gateway class {tok!r} "
                f"(expected a subset of {PRIORITY_CLASSES})"
            )
        if tok not in out:
            out.append(tok)
    if not out:
        return PRIORITY_CLASSES
    # preserve priority order regardless of spelling order
    return tuple(sorted(out, key=CLASS_RANK.__getitem__))


def parse_tenant_quota(spec: str | None) -> dict[str, int]:
    """``--tenant_quota`` value → {tenant: max reserved tokens}. Grammar:
    ``tenant=tokens[,tenant=tokens...]``; the pseudo-tenant ``default``
    caps every tenant not named explicitly. Empty/None = unlimited."""
    if not spec:
        return {}
    book: dict[str, int] = {}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad --tenant_quota entry {part!r} "
                "(expected tenant=tokens)"
            )
        name, val = part.split("=", 1)
        tokens = int(val)
        if tokens < 1:
            raise ValueError(
                f"--tenant_quota for {name.strip()!r} must be >= 1, "
                f"got {tokens}"
            )
        book[sanitize_tenant(name)] = tokens
    return book


# ----------------------------------------------------------------- request


@dataclass
class GatewayRequest:
    """One client request as the gateway sees it. ``seq`` is the FIFO
    arrival stamp; ``waited_passes`` is the aging counter the queue owns."""

    rid: int
    tenant: str
    cls: str
    prompt_ids: Any            # np.ndarray [P] (already tokenized, padded)
    prompt_len: int
    max_new_tokens: int
    temperature: float = 0.0
    seq: int = 0
    arrival_ts: float = 0.0
    trace_ctx: dict | None = None   # (trace_id, dispatch_id) lineage stamp
    waited_passes: int = 0
    # per-request event stream the HTTP handler drains: ("tokens", text),
    # ("done", payload) or ("error", message)
    events: Any = field(default=None, repr=False)

    @property
    def rank(self) -> int:
        return CLASS_RANK[self.cls]

    def effective_rank(self) -> int:
        """Aged rank: every AGE_PASSES passed-over passes promote one step
        toward interactive; never below 0."""
        return max(0, self.rank - self.waited_passes // AGE_PASSES)


class RequestQueue:
    """Class-then-FIFO-with-aging open queue. Thread-safe; ``pop_batch``
    is the single scheduling decision point so ordering stays auditable:
    sort key ``(effective_rank, seq)`` — class first, arrival order
    within class, with deterministic aging as the starvation valve."""

    def __init__(self, classes: tuple[str, ...] = PRIORITY_CLASSES):
        self.classes = tuple(classes)
        self._mu = threading.Lock()
        self._items: list[GatewayRequest] = []
        self._seq = 0

    def push(self, req: GatewayRequest) -> None:
        with self._mu:
            self._seq += 1
            req.seq = self._seq
            self._items.append(req)
            telemetry.counter_add(GATEWAY_REQUESTS)
            telemetry.counter_add(f"{GATEWAY_REQUESTS}/{req.cls}")
            telemetry.gauge_set(GATEWAY_QUEUE_DEPTH, float(len(self._items)))

    def __len__(self) -> int:
        with self._mu:
            return len(self._items)

    def pop_batch(self, max_groups: int) -> list[GatewayRequest]:
        """Take up to ``max_groups`` requests in scheduling order; every
        request left behind ages one pass (only when a pass actually
        passed it over — an empty pop ages nobody)."""
        with self._mu:
            if not self._items or max_groups < 1:
                return []
            order = sorted(
                self._items, key=lambda r: (r.effective_rank(), r.seq)
            )
            take = order[:max_groups]
            taken = set(id(r) for r in take)
            for r in self._items:
                if id(r) not in taken:
                    before = r.effective_rank()
                    r.waited_passes += 1
                    if r.effective_rank() < before:
                        telemetry.counter_add(GATEWAY_AGED_PROMOTIONS)
            self._items = [r for r in self._items if id(r) not in taken]
            telemetry.gauge_set(GATEWAY_QUEUE_DEPTH, float(len(self._items)))
            return take


# ------------------------------------------------------------------- quota


class TenantQuotaBook:
    """Per-tenant reserved-token budget, charged at admission and credited
    at group close. The reservation is the WORST-CASE footprint (prompt +
    full output window) — the quota bounds what a tenant can pin, not what
    it happened to emit. Thread-safe: the engine's admission loop and the
    gateway's submit path both touch it.

    ``try_charge`` is the only decision point; a False return is exactly
    one ``quota`` admission stall when the engine declines on it."""

    def __init__(self, quotas: dict[str, int] | None = None):
        self.quotas = dict(quotas or {})
        self.default = self.quotas.get("default")
        self._mu = threading.Lock()
        self.reserved: dict[str, int] = {}
        self.denials: dict[str, int] = {}

    def limit_for(self, tenant: str) -> int | None:
        lim = self.quotas.get(tenant, self.default)
        return None if lim is None else int(lim)

    def try_charge(self, tenant: str, tokens: int) -> bool:
        tenant = sanitize_tenant(tenant)
        tokens = int(tokens)
        with self._mu:
            lim = self.limit_for(tenant)
            held = self.reserved.get(tenant, 0)
            if lim is not None and held + tokens > lim:
                self.denials[tenant] = self.denials.get(tenant, 0) + 1
                telemetry.counter_add(GATEWAY_QUOTA_DENIALS)
                telemetry.counter_add(f"{GATEWAY_QUOTA_DENIALS}/{tenant}")
                return False
            self.reserved[tenant] = held + tokens
            telemetry.gauge_set(GATEWAY_QUOTA_RESERVED,
                                float(sum(self.reserved.values())))
            telemetry.gauge_set(f"{GATEWAY_QUOTA_RESERVED}/{tenant}",
                                float(self.reserved[tenant]))
            return True

    def credit(self, tenant: str, tokens: int) -> None:
        tenant = sanitize_tenant(tenant)
        with self._mu:
            held = self.reserved.get(tenant, 0)
            self.reserved[tenant] = max(0, held - int(tokens))
            telemetry.gauge_set(GATEWAY_QUOTA_RESERVED,
                                float(sum(self.reserved.values())))
            telemetry.gauge_set(f"{GATEWAY_QUOTA_RESERVED}/{tenant}",
                                float(self.reserved[tenant]))

    def reset(self) -> None:
        """Drop every reservation (a failed engine round can never reach
        its group-finish credits — the service resets so the book cannot
        wedge future rounds; denial counters survive)."""
        with self._mu:
            self.reserved.clear()
            telemetry.gauge_set(GATEWAY_QUOTA_RESERVED, 0.0)

    def stats(self) -> dict[str, Any]:
        with self._mu:
            return {
                "reserved": dict(self.reserved),
                "denials": dict(self.denials),
                "quotas": dict(self.quotas),
            }
