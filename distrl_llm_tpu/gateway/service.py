"""GatewayService: the engine-facing half of the serving gateway
(ISSUE 19).

The service owns an open request queue fed by :mod:`.server` (or any
in-process producer — the smoke and bench drive it directly) and a
round-forming loop: drain up to ``max_groups_per_round`` requests in
class-then-FIFO-with-aging order, attach the round's tenancy to the
engine (``round_meta`` / ``quota_book`` / ``stream_hook``), run ONE
``engine.generate`` round under the engine lock, and demux streamed
tokens back to each request's subscriber queue.

Rounds stay the engine's batching unit — open-loop realism comes from
the queue (arrivals never wait for completions) and from stamping each
request's true ``arrival_ts`` into the serving ledger, so TTFT /
queue-wait include the open-queue wait, not just the in-round wait.
Each request is one group with ``n=1`` candidates; its ``(trace_id,
dispatch_id)`` lineage context is allocated at arrival via
``telemetry.next_dispatch_context()`` — the SAME allocation path the
trainer's dispatches use, so gateway requests render in Perfetto and
join ``lineage_report`` / ``serving_report`` rows for free."""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Any

import numpy as np

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.gateway.scheduler import (
    CLASS_RANK,
    DEFAULT_CLASS,
    GATEWAY_REJECTED,
    GATEWAY_ROUNDS,
    GATEWAY_STREAMED_TOKENS,
    PRIORITY_CLASSES,
    GatewayRequest,
    RequestQueue,
    TenantQuotaBook,
    sanitize_tenant,
)


class GatewayService:
    """Round-forming loop between the request queue and one paged engine.

    ``engine_lock`` serializes gateway rounds against any other owner of
    the same engine (the worker's ``generate`` op); pass the worker's lock
    when sharing, or let the service own a private one."""

    def __init__(self, engine, params, tokenizer, *, lora=None,
                 classes: tuple[str, ...] = PRIORITY_CLASSES,
                 quota: dict[str, int] | None = None,
                 serving_ledger=None,
                 control_limits=None,
                 max_groups_per_round: int = 8,
                 temperature: float = 0.0,
                 top_p: float = 1.0,
                 default_max_new_tokens: int | None = None,
                 seed: int = 0,
                 engine_lock: threading.Lock | None = None,
                 poll_s: float = 0.005):
        if not getattr(engine, "continuous_admission", False):
            raise ValueError(
                "GatewayService requires a paged engine with "
                "continuous_admission (the request-queue scheduler is the "
                "gateway's admission plane)"
            )
        if getattr(engine, "spec_draft", 0):
            # the turn-hook precedent: the speculative sub-path drives its
            # own admission/stream cadence and does not consult the
            # gateway's round hooks — reject rather than silently lose the
            # class policy and streaming
            raise ValueError(
                "GatewayService does not support speculative decoding "
                "(spec_draft) — the gateway's scheduling and streaming "
                "hooks ride the plain refill boundaries"
            )
        self.engine = engine
        self.params = params
        self.lora = lora
        self.tokenizer = tokenizer
        self.classes = tuple(classes)
        self.quota_book = TenantQuotaBook(quota)
        self.serving_ledger = serving_ledger
        self.control_limits = control_limits
        self.max_groups_per_round = max(1, int(max_groups_per_round))
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.default_max_new_tokens = (
            int(default_max_new_tokens)
            if default_max_new_tokens else int(engine.max_new_tokens)
        )
        self.seed = int(seed)
        self.engine_lock = engine_lock or threading.Lock()
        self.poll_s = float(poll_s)
        self.queue = RequestQueue(self.classes)
        self.rounds = 0
        self.completed = 0
        self.failed = 0
        # run-cumulative per-class shed/preempt group tallies (the engine's
        # last_pool_stats only covers one round) — the bench's
        # shed_frac_by_class reads this
        self.class_actions: dict[str, dict[str, int]] = {
            "shed": {}, "preempt": {},
        }
        self.completed_by_class: dict[str, int] = {}
        self._rid = 0
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ producer

    def submit(self, prompt: str | None = None, *,
               prompt_ids=None,
               tenant: str = "anon", cls: str = DEFAULT_CLASS,
               max_new_tokens: int | None = None,
               temperature: float | None = None,
               arrival_ts: float | None = None) -> GatewayRequest:
        """Enqueue one request; returns the request whose ``events`` queue
        streams ``("tokens", [ids])`` chunks then one ``("done", result)``
        or ``("error", message)``. Tokenizes ``prompt`` when ``prompt_ids``
        is not given; prompts longer than the engine window keep their
        TAIL (the recent context)."""
        if cls not in CLASS_RANK:
            telemetry.counter_add(GATEWAY_REJECTED)
            raise ValueError(
                f"unknown priority class {cls!r} "
                f"(expected one of {PRIORITY_CLASSES})"
            )
        if cls not in self.classes:
            telemetry.counter_add(GATEWAY_REJECTED)
            raise ValueError(
                f"priority class {cls!r} is not served by this gateway "
                f"(serving {self.classes})"
            )
        if prompt_ids is None:
            if prompt is None:
                telemetry.counter_add(GATEWAY_REJECTED)
                raise ValueError("request needs prompt or prompt_ids")
            prompt_ids = self.tokenizer.encode(
                str(prompt), add_special_tokens=False
            )
        ids = np.asarray(prompt_ids, np.int32).ravel()
        if ids.size == 0:
            telemetry.counter_add(GATEWAY_REJECTED)
            raise ValueError("empty prompt")
        p_max = int(self.engine.max_prompt_tokens)
        if ids.size > p_max:
            ids = ids[-p_max:]
        window = min(
            int(max_new_tokens or self.default_max_new_tokens),
            int(self.engine.max_new_tokens),
        )
        lim = self.quota_book.limit_for(sanitize_tenant(tenant))
        if lim is not None and int(ids.size) + window > lim:
            # a footprint the tenant's quota can NEVER hold would stall in
            # the engine queue forever — reject at the door instead (the
            # engine charges exactly prompt + window, see try_admit_group)
            telemetry.counter_add(GATEWAY_REJECTED)
            raise ValueError(
                f"request footprint {int(ids.size) + window} tokens "
                f"(prompt {int(ids.size)} + window {window}) exceeds "
                f"tenant {sanitize_tenant(tenant)!r} quota {lim} — it "
                "could never admit; shrink the prompt or max_new_tokens"
            )
        with self._mu:
            self._rid += 1
            rid = self._rid
        req = GatewayRequest(
            rid=rid, tenant=sanitize_tenant(tenant), cls=cls,
            prompt_ids=ids, prompt_len=int(ids.size),
            max_new_tokens=window,
            temperature=(
                self.temperature if temperature is None
                else float(temperature)
            ),
            arrival_ts=time.time() if arrival_ts is None else arrival_ts,
            # lineage stamp at ARRIVAL: the same counter the trainer's
            # dispatches draw from — one allocation path (ISSUE 16)
            trace_ctx=telemetry.next_dispatch_context(),
            events=queue_mod.Queue(),
        )
        self.queue.push(req)
        self._wake.set()
        return req

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "GatewayService":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="gateway-service", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout_s: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def drain(self, timeout_s: float = 60.0) -> bool:
        """Block until the open queue and in-flight round are empty (the
        replay harness's end-of-run barrier). False on timeout."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._mu:
                busy = self._rid > self.completed + self.failed
            if not busy and len(self.queue) == 0:
                return True
            time.sleep(self.poll_s)
        return False

    # ---------------------------------------------------------- round loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.queue.pop_batch(self.max_groups_per_round)
            if not batch:
                self._wake.wait(timeout=self.poll_s)
                self._wake.clear()
                continue
            try:
                self._run_round(batch)
            except Exception as e:  # noqa: BLE001 — a failed round fails
                # its requests, not the gateway: subscribers get the error
                # and the loop keeps serving
                with self._mu:
                    self.failed += len(batch)
                # reservations charged by the dead round never reach their
                # group-finish credit — reset so the book can't wedge
                self.quota_book.reset()
                for req in batch:
                    req.events.put(("error", f"{type(e).__name__}: {e}"))

    def _run_round(self, batch: list[GatewayRequest]) -> None:
        import jax

        from distrl_llm_tpu.config import SamplingConfig

        engine = self.engine
        b = len(batch)
        p_max = int(engine.max_prompt_tokens)
        pad = int(engine.pad_id)
        prompt_ids = np.full((b, p_max), pad, np.int32)
        prompt_mask = np.zeros((b, p_max), np.int32)
        meta: dict[int, dict[str, Any]] = {}
        for g, req in enumerate(batch):
            ids = req.prompt_ids
            prompt_ids[g, p_max - ids.size:] = ids  # left-pad (trainer contract)
            prompt_mask[g, p_max - ids.size:] = 1
            meta[g] = {
                "tenant": req.tenant, "cls": req.cls,
                "rank": CLASS_RANK[req.cls], "seq": req.seq,
                "arrival_ts": req.arrival_ts,
                "trace_ctx": req.trace_ctx,
                "max_new": req.max_new_tokens,
            }
        round_max = max(req.max_new_tokens for req in batch)
        sampling = SamplingConfig(
            max_tokens=round_max,
            temperature=max(r.temperature for r in batch),
            top_p=self.top_p, n=1,
        )
        streamed: dict[int, int] = {}

        def stream_hook(cand: int, toks: list[int]) -> None:
            req = batch[cand]
            sent = streamed.get(cand, 0)
            room = req.max_new_tokens - sent
            if room <= 0:
                return
            toks = toks[:room]
            streamed[cand] = sent + len(toks)
            telemetry.counter_add(GATEWAY_STREAMED_TOKENS, len(toks))
            req.events.put(("tokens", [int(t) for t in toks]))

        with self.engine_lock:
            self.rounds += 1
            telemetry.counter_add(GATEWAY_ROUNDS)
            engine.round_meta = meta
            engine.quota_book = self.quota_book
            engine.stream_hook = stream_hook
            prev_ledger = engine.serving_ledger
            if self.serving_ledger is not None:
                engine.serving_ledger = self.serving_ledger
            prev_limits = engine.control_limits
            if self.control_limits is not None:
                engine.control_limits = self.control_limits
            try:
                result = engine.generate(
                    self.params, self.lora, prompt_ids, prompt_mask,
                    sampling, jax.random.PRNGKey(self.seed + self.rounds),
                )
            finally:
                # detach-pattern discipline: the engine leaves the round
                # exactly as a non-gateway owner would find it
                engine.round_meta = None
                engine.quota_book = None
                engine.stream_hook = None
                engine.serving_ledger = prev_ledger
                engine.control_limits = prev_limits
        stats = engine.last_pool_stats or {}
        for kind, per_cls in (stats.get("class_actions") or {}).items():
            agg = self.class_actions.setdefault(kind, {})
            for cls_name, cnt in per_cls.items():
                agg[cls_name] = agg.get(cls_name, 0) + int(cnt)
        for g, req in enumerate(batch):
            ln = min(int(result.lengths[g, 0]), req.max_new_tokens)
            toks = [int(t) for t in result.tokens[g, 0, :ln]]
            with self._mu:
                self.completed += 1
                self.completed_by_class[req.cls] = (
                    self.completed_by_class.get(req.cls, 0) + 1
                )
            req.events.put(("done", {
                "rid": req.rid,
                "tenant": req.tenant,
                "cls": req.cls,
                "tokens": toks,
                "text": self._decode(toks),
                "gen_tokens": ln,
                "prompt_tokens": req.prompt_len,
                "trace_id": (req.trace_ctx or {}).get("trace_id"),
                "dispatch_id": (req.trace_ctx or {}).get("dispatch_id"),
                "class_actions": stats.get("class_actions"),
            }))

    def _decode(self, toks: list[int]) -> str:
        try:
            return self.tokenizer.decode(toks, skip_special_tokens=True)
        except TypeError:
            return self.tokenizer.decode(toks)

    def stats(self) -> dict[str, Any]:
        with self._mu:
            return {
                "rounds": self.rounds,
                "submitted": self._rid,
                "completed": self.completed,
                "completed_by_class": dict(self.completed_by_class),
                "failed": self.failed,
                "queue_depth": len(self.queue),
                "class_actions": {
                    k: dict(v) for k, v in self.class_actions.items()
                },
                "quota": self.quota_book.stats(),
            }
