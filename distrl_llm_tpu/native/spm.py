"""ctypes binding + HF-format loading for the C++ sentencepiece Unigram core.

The sentencepiece half of the N7 parity component (SURVEY §2b: "HF Rust
tokenizers ... or sentencepiece-C++ where the model uses it"): Gemma-family
checkpoints tokenize with a sentencepiece Unigram model, serialized by HF
into tokenizer.json as ``{"model": {"type": "Unigram", ...}}``. The Viterbi
encode/decode hot path is C++ (csrc/spm_tokenizer.cc); this module parses the
JSON, applies the normalizer chain (Prepend/Replace — the only normalizers
sentencepiece-converted tokenizers carry), and exposes the framework's
tokenizer protocol. Differential-tested against the Rust ``tokenizers``
Unigram implementation in tests/test_native_spm.py.
"""

from __future__ import annotations

import ctypes
import json
import re
from typing import Any, Sequence

from distrl_llm_tpu.native.build import build_library

_BYTE_PIECE = re.compile(r"^<0x([0-9A-Fa-f]{2})>$")
_SPACE = "▁"  # ▁ — sentencepiece's whitespace escape


def _parse_normalizer(tokenizer_json: dict[str, Any]) -> list[tuple[str, str, str]]:
    """Flatten the normalizer spec into ("prepend"|"replace", a, b) ops.

    Sentencepiece-converted tokenizers use exactly Prepend("▁") (Llama's
    add_dummy_prefix) and Replace(" "→"▁") (whitespace escaping), possibly
    inside a Sequence. Anything else raises — silently skipping a normalizer
    would desync ids from the Rust implementation."""
    ops: list[tuple[str, str, str]] = []

    def walk(node):
        if not node:
            return
        kind = node.get("type")
        if kind == "Sequence":
            for sub in node.get("normalizers", []):
                walk(sub)
        elif kind == "Prepend":
            ops.append(("prepend", node["prepend"], ""))
        elif kind == "Replace":
            pat = node.get("pattern", {})
            if "String" not in pat:
                raise ValueError(f"unsupported Replace pattern: {pat}")
            ops.append(("replace", pat["String"], node["content"]))
        else:
            raise ValueError(f"unsupported normalizer for Unigram: {kind}")

    walk(tokenizer_json.get("normalizer"))
    return ops


def serialize_hf_unigram(tokenizer_json: dict[str, Any]) -> bytes:
    """HF tokenizer.json dict → the C core's model format (.cc header)."""
    model = tokenizer_json["model"]
    if model.get("type") != "Unigram":
        raise ValueError(f"not a Unigram model: {model.get('type')!r}")
    vocab: list = model["vocab"]  # [[piece, score], ...], id = index
    byte_fallback = bool(model.get("byte_fallback", False))
    unk_id = int(model.get("unk_id") or 0)
    added = tokenizer_json.get("added_tokens", [])

    size = max(
        len(vocab), max((t["id"] + 1 for t in added), default=0)
    )
    pieces: list[str] = [""] * size
    scores: list[float] = [0.0] * size
    for i, (piece, score) in enumerate(vocab):
        pieces[i] = piece
        scores[i] = float(score)
    special_ids = []
    for tok in added:
        pieces[tok["id"]] = tok["content"]
        if tok.get("special", True):
            special_ids.append(tok["id"])

    lines = [f"{size} {unk_id} {int(byte_fallback)} {len(special_ids)}"]
    for piece, score in zip(pieces, scores):
        mm = _BYTE_PIECE.match(piece) if byte_fallback else None
        bv = int(mm.group(1), 16) if mm else -1
        lines.append(f"{piece.encode('utf-8').hex()} {score!r} {bv}")
    lines += [str(i) for i in special_ids]
    return ("\n".join(lines) + "\n").encode("utf-8")


class _Lib:
    _inst = None

    @classmethod
    def get(cls):
        if cls._inst is None:
            lib = ctypes.CDLL(build_library("spm_tokenizer.cc"))
            lib.spm_create.restype = ctypes.c_void_p
            lib.spm_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.spm_free.argtypes = [ctypes.c_void_p]
            lib.spm_encode.restype = ctypes.c_int64
            lib.spm_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ]
            lib.spm_decode.restype = ctypes.c_int64
            lib.spm_decode.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
            ]
            cls._inst = lib
        return cls._inst


class NativeSPMTokenizer:
    """Sentencepiece Unigram with the C++ core; drop-in for the framework's
    tokenizer protocol (encode / decode / apply_chat_template / *_token_id).
    """

    def __init__(
        self,
        serialized_model: bytes,
        *,
        eos_token_id: int,
        pad_token_id: int | None = None,
        chat_template: str | None = None,
        normalizer_ops: Sequence[tuple[str, str, str]] = (),
        eos_token_ids: Sequence[int] | None = None,
    ):
        self._lib = _Lib.get()
        self._h = self._lib.spm_create(serialized_model, len(serialized_model))
        if not self._h:
            raise ValueError("malformed sentencepiece model data")
        self.eos_token_id = eos_token_id
        self.pad_token_id = (
            pad_token_id if pad_token_id is not None else eos_token_id
        )
        self.chat_template = chat_template
        self._norm_ops = list(normalizer_ops)
        if eos_token_ids:
            self.eos_token_ids = list(eos_token_ids)

    @classmethod
    def from_hf_file(cls, path: str, **kw) -> "NativeSPMTokenizer":
        with open(path, encoding="utf-8") as f:
            return cls.from_hf_dict(json.load(f), **kw)

    @classmethod
    def from_hf_dict(cls, tj: dict, **kw) -> "NativeSPMTokenizer":
        data = serialize_hf_unigram(tj)
        kw.setdefault("normalizer_ops", _parse_normalizer(tj))
        specials = {t["content"]: t["id"] for t in tj.get("added_tokens", [])}
        if "eos_token_id" not in kw:
            for name in ("<eos>", "</s>", "<end_of_turn>", "<|endoftext|>"):
                if name in specials:
                    kw["eos_token_id"] = specials[name]
                    break
            else:
                raise ValueError(
                    "no conventional EOS token found among special tokens "
                    f"{sorted(specials)}; pass eos_token_id explicitly"
                )
        if "eos_token_ids" not in kw and "<end_of_turn>" in specials:
            # Gemma chat turns end with <end_of_turn>, not <eos> — rollouts
            # must stop on either (the HF path exposes the same pair)
            kw["eos_token_ids"] = sorted(
                {kw["eos_token_id"], specials["<end_of_turn>"]}
            )
        if "pad_token_id" not in kw and "<pad>" in specials:
            kw["pad_token_id"] = specials["<pad>"]
        return cls(data, **kw)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.spm_free(h)
            self._h = None

    def _normalize(self, text: str) -> str:
        for kind, a, b in self._norm_ops:
            if kind == "prepend":
                # HF Prepend is UNCONDITIONAL on non-empty text (verified
                # against the Rust lib: "▁hi" → "▁▁hi"), and applies per
                # added-token-free segment — for the framework's inputs
                # (whole prompts) once at the start is the same thing
                if text:
                    text = a + text
            else:
                text = text.replace(a, b)
        return text

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        raw = self._normalize(text).encode("utf-8")
        cap = max(16, 4 * len(raw) + 16)
        buf = (ctypes.c_int32 * cap)()
        n = self._lib.spm_encode(self._h, raw, len(raw), buf, cap)
        if n < 0:
            raise RuntimeError("encode failed")
        if n > cap:  # can't happen (≤1 id per byte + specials), but be safe
            buf = (ctypes.c_int32 * n)()
            n = self._lib.spm_encode(self._h, raw, len(raw), buf, n)
        return list(buf[:n])

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        arr = (ctypes.c_int32 * len(ids))(*[int(i) for i in ids])
        cap = 16
        for _ in range(2):
            out = ctypes.create_string_buffer(cap)
            n = self._lib.spm_decode(
                self._h, arr, len(ids), int(skip_special_tokens), out, cap
            )
            if n < 0:
                raise RuntimeError("decode failed")
            if n <= cap:
                text = out.raw[:n].decode("utf-8", errors="replace")
                return text.replace(_SPACE, " ")
            cap = n
        raise RuntimeError("decode buffer negotiation failed")


# chat rendering is model-format-independent (Jinja over the checkpoint's
# template); borrow the BPE wrapper's implementation wholesale
from distrl_llm_tpu.native.tokenizer import NativeBPETokenizer as _BPE  # noqa: E402

NativeSPMTokenizer.apply_chat_template = _BPE.apply_chat_template
