"""ctypes binding + HF-format loading for the C++ byte-level BPE tokenizer.

The N7 parity component (SURVEY §2b): the reference tokenizes through HF's
Rust tokenizers (`load_correct_tokenizer`, train_distributed.py:46;
`batch_encode_plus`, distributed_actor.py:217/:222). Here the hot encode/
decode path is C++ (csrc/bpe_tokenizer.cc); this module

* converts an HF ``tokenizer.json`` into the C core's raw-bytes model format
  (undoing the GPT-2 byte→unicode remapping of byte-level BPE vocabularies),
* exposes a ``NativeBPETokenizer`` with the small tokenizer protocol the rest
  of the framework uses (encode/decode/apply_chat_template/pad & eos ids —
  see distrl_llm_tpu/tokenizer.py).
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Any, Sequence

from distrl_llm_tpu.native.build import build_library


def _gpt2_byte_decoder() -> dict[str, int]:
    """Inverse of the GPT-2 bytes_to_unicode table used by byte-level BPE."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


_BYTE_DECODER = _gpt2_byte_decoder()


def token_to_bytes(token: str) -> bytes:
    """Map a byte-level-BPE vocab token (unicode-remapped) to raw bytes."""
    try:
        return bytes(_BYTE_DECODER[ch] for ch in token)
    except KeyError:
        # not byte-remapped (added/special tokens) — use UTF-8 of the literal
        return token.encode("utf-8")


def _detect_pretok_kind(tokenizer_json: dict[str, Any]) -> int:
    """0 = GPT-2 pattern, 1 = Qwen2/cl100k pattern (the default for the model
    families this framework trains). Detection keys off the digit-chunking
    alternative ``\\p{N}{1,3}`` that distinguishes the cl100k-style regex."""
    pt = tokenizer_json.get("pre_tokenizer") or {}
    frags: list[str] = []

    def collect(node):
        if isinstance(node, dict):
            pat = node.get("pattern")
            if isinstance(pat, dict) and "Regex" in pat:
                frags.append(pat["Regex"])
            for v in node.values():
                collect(v)
        elif isinstance(node, list):
            for v in node:
                collect(v)

    collect(pt)
    pattern = " ".join(frags)
    if pattern:
        return 1 if "{1,3}" in pattern else 0

    # No explicit Regex. A ByteLevel pre_tokenizer with use_regex (the
    # tokenizers default is true) splits with its BUILT-IN GPT-2 pattern;
    # only regex-less configs (use_regex false everywhere, as Qwen2-style
    # Sequence[Split, ByteLevel(use_regex=false)] files always pair with an
    # explicit Split) default to the modern cl100k rules.
    uses_builtin_gpt2 = []

    def check_bytelevel(node):
        if isinstance(node, dict):
            if node.get("type") == "ByteLevel":
                uses_builtin_gpt2.append(node.get("use_regex", True))
            for v in node.values():
                check_bytelevel(v)
        elif isinstance(node, list):
            for v in node:
                check_bytelevel(v)

    check_bytelevel(pt)
    if any(uses_builtin_gpt2):
        return 0
    return 1


def serialize_hf_tokenizer(tokenizer_json: dict[str, Any]) -> bytes:
    """HF tokenizer.json dict → the C core's model format (see .cc header)."""
    model = tokenizer_json["model"]
    vocab: dict[str, int] = model["vocab"]
    merges = model.get("merges", [])
    added = tokenizer_json.get("added_tokens", [])

    size = max(vocab.values(), default=-1) + 1
    for tok in added:
        size = max(size, tok["id"] + 1)
    id_to_bytes: list[bytes] = [b""] * size
    for tok, i in vocab.items():
        id_to_bytes[i] = token_to_bytes(tok)
    special_ids = []
    for tok in added:
        id_to_bytes[tok["id"]] = tok["content"].encode("utf-8")
        if tok.get("special", True):
            special_ids.append(tok["id"])

    kind = _detect_pretok_kind(tokenizer_json)
    lines = [f"{size} {len(merges)} {len(special_ids)} {kind}"]
    lines += [t.hex() for t in id_to_bytes]
    for m in merges:
        l, r = m if isinstance(m, (list, tuple)) else m.split(" ", 1)
        lines.append(f"{token_to_bytes(l).hex()} {token_to_bytes(r).hex()}")
    lines += [str(i) for i in special_ids]
    return ("\n".join(lines) + "\n").encode("utf-8")


class _Lib:
    _inst = None

    @classmethod
    def get(cls):
        if cls._inst is None:
            lib = ctypes.CDLL(build_library("bpe_tokenizer.cc"))
            lib.bpe_create.restype = ctypes.c_void_p
            lib.bpe_create.argtypes = [ctypes.c_char_p, ctypes.c_int64]
            lib.bpe_free.argtypes = [ctypes.c_void_p]
            lib.bpe_encode.restype = ctypes.c_int64
            lib.bpe_encode.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ]
            lib.bpe_decode.restype = ctypes.c_int64
            lib.bpe_decode.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64, ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
            ]
            cls._inst = lib
        return cls._inst


class NativeBPETokenizer:
    """Byte-level BPE with the C++ core; drop-in for the framework's
    tokenizer protocol (encode / decode / apply_chat_template / *_token_id).
    """

    def __init__(
        self,
        serialized_model: bytes,
        *,
        eos_token_id: int,
        pad_token_id: int | None = None,
        chat_template: str | None = None,
        nfc_normalize: bool = True,
    ):
        self._lib = _Lib.get()
        self._h = self._lib.bpe_create(serialized_model, len(serialized_model))
        if not self._h:
            raise ValueError("malformed tokenizer model data")
        self.eos_token_id = eos_token_id
        self.pad_token_id = pad_token_id if pad_token_id is not None else eos_token_id
        self.chat_template = chat_template
        # Qwen2-family tokenizer.json carries an NFC normalizer; GPT-2's has
        # none. Normalization runs host-side in Python (unicodedata) — the C
        # core sees NFC bytes.
        self._nfc = nfc_normalize

    @classmethod
    def from_hf_file(cls, path: str, **kw) -> "NativeBPETokenizer":
        with open(path, encoding="utf-8") as f:
            return cls.from_hf_dict(json.load(f), **kw)

    @classmethod
    def from_hf_dict(cls, tj: dict, **kw) -> "NativeBPETokenizer":
        data = serialize_hf_tokenizer(tj)
        if "nfc_normalize" not in kw:
            kw["nfc_normalize"] = "NFC" in json.dumps(tj.get("normalizer") or {})
        if "eos_token_id" not in kw:
            # conventional names only; a silently-wrong eos breaks generation
            # termination (rollouts would always run to max_tokens), so an
            # unrecognized vocabulary must fail loudly (ADVICE r1)
            specials = {t["content"]: t["id"] for t in tj.get("added_tokens", [])}
            for name in ("<|im_end|>", "</s>", "<|eot_id|>", "<|endoftext|>"):
                if name in specials:
                    kw["eos_token_id"] = specials[name]
                    break
            else:
                raise ValueError(
                    "no conventional EOS token found among special tokens "
                    f"{sorted(specials)}; pass eos_token_id explicitly"
                )
        return cls(data, **kw)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.bpe_free(h)
            self._h = None

    def encode(self, text: str, add_special_tokens: bool = False) -> list[int]:
        if self._nfc:
            import unicodedata

            text = unicodedata.normalize("NFC", text)
        raw = text.encode("utf-8")
        cap = max(16, len(raw) + 16)
        buf = (ctypes.c_int32 * cap)()
        n = self._lib.bpe_encode(self._h, raw, len(raw), buf, cap)
        if n < 0:
            raise RuntimeError("encode failed")
        if n > cap:  # can't happen (≤1 id per byte + specials), but be safe
            buf = (ctypes.c_int32 * n)()
            n = self._lib.bpe_encode(self._h, raw, len(raw), buf, n)
        return list(buf[:n])

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        arr = (ctypes.c_int32 * len(ids))(*[int(i) for i in ids])
        cap = 16
        for _ in range(2):
            out = ctypes.create_string_buffer(cap)
            n = self._lib.bpe_decode(
                self._h, arr, len(ids), int(skip_special_tokens), out, cap
            )
            if n < 0:
                raise RuntimeError("decode failed")
            if n <= cap:
                return out.raw[:n].decode("utf-8", errors="replace")
            cap = n
        raise RuntimeError("decode buffer negotiation failed")

    def apply_chat_template(
        self, messages, tokenize: bool = False, add_generation_prompt: bool = True,
        chat_template: str | None = None,
    ):
        """Chat rendering (helper.py:15–19 relies on the HF template). A
        Jinja template (from tokenizer_config.json or the caller) renders via
        jinja2 when available; otherwise explicit ChatML — the Qwen2 format
        the reference's models use."""
        template = chat_template or self.chat_template
        if template:
            try:
                import jinja2

                env = jinja2.Environment(keep_trailing_newline=True)
                env.globals["raise_exception"] = lambda msg: (_ for _ in ()).throw(
                    ValueError(msg)
                )
                text = env.from_string(template).render(
                    messages=messages,
                    add_generation_prompt=add_generation_prompt,
                    eos_token="",
                    bos_token="",
                )
                return self.encode(text) if tokenize else text
            except Exception as e:  # noqa: BLE001 — template quirks → ChatML fallback
                import logging

                logging.getLogger(__name__).warning(
                    "chat template failed to render (%s: %s); falling back to "
                    "ChatML — WRONG for non-ChatML checkpoints", type(e).__name__, e,
                )
        parts = []
        for m in messages:
            parts.append(f"<|im_start|>{m['role']}\n{m['content']}<|im_end|>\n")
        if add_generation_prompt:
            parts.append("<|im_start|>assistant\n")
        text = "".join(parts)
        return self.encode(text) if tokenize else text
