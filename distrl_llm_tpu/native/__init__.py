"""Native (C++) host components.

The TPU compute path is JAX/XLA/Pallas; the host-side runtime pieces that the
reference delegates to native dependencies are C++ here:

* ``native.tokenizer`` — byte-level BPE encode/decode (the Rust HF-tokenizers
  equivalent, SURVEY §2b N7).
* ``native.build`` — tiny build cache: compiles each .cc to a shared library
  with g++ on first use and memoizes by source hash.
"""

from distrl_llm_tpu.native.build import build_library, native_available  # noqa: F401
