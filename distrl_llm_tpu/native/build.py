"""Compile-on-first-use build cache for the C++ host components.

pybind11 is not available in this environment, so native code exposes a plain
C ABI and Python binds it with ctypes (environment constraint — see repo
docs). Libraries are compiled with g++ into a per-source-hash cache dir, so
editing a .cc transparently rebuilds and stale binaries are never loaded.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import tempfile

_CSRC = os.path.join(os.path.dirname(__file__), "csrc")


def native_available() -> bool:
    return shutil.which("g++") is not None


def _cache_dir() -> str:
    d = os.environ.get(
        "DISTRL_NATIVE_CACHE",
        os.path.join(tempfile.gettempdir(), "distrl_llm_tpu_native"),
    )
    os.makedirs(d, exist_ok=True)
    return d


def build_library(source_name: str) -> str:
    """Compile csrc/<source_name> to a shared library; return its path.

    Memoized by source content hash — a changed source compiles to a new
    path, an unchanged one is reused across processes.
    """
    src = os.path.join(_CSRC, source_name)
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    for name in sorted(os.listdir(_CSRC)):  # local headers feed the digest too
        if name.endswith(".h"):
            with open(os.path.join(_CSRC, name), "rb") as f:
                h.update(f.read())
    digest = h.hexdigest()[:16]
    stem = os.path.splitext(source_name)[0]
    out = os.path.join(_cache_dir(), f"{stem}-{digest}.so")
    if os.path.exists(out):
        return out
    if not native_available():
        raise RuntimeError("g++ not found; native components unavailable")
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed:\n{proc.stderr}")
    os.replace(tmp, out)  # atomic vs concurrent builders
    return out
