// Sentencepiece Unigram tokenizer core (C ABI, ctypes-bound).
//
// The N7 parity component for sentencepiece model families (SURVEY §2b:
// "HF Rust tokenizers ... or sentencepiece-C++ where the model uses it"):
// Gemma-style checkpoints tokenize with a sentencepiece Unigram model, which
// HF serializes into tokenizer.json as {"model": {"type": "Unigram",
// "unk_id": ..., "vocab": [[piece, score], ...], "byte_fallback": ...}}.
// This file implements the encode/decode hot path; Python
// (native/spm.py) parses the JSON, applies the (trivial) normalizer chain,
// and feeds the serialized model below.
//
// Semantics pinned by differential tests against the Rust `tokenizers`
// Unigram implementation (tests/test_native_spm.py):
// * Viterbi segmentation over UNICODE characters maximizing the sum of
//   piece log-probs; pieces participate by their literal text (including
//   the "<0xNN>" byte pieces — matching the Rust trie).
// * Unknown characters score min_vocab_score - 10 (the kUnkPenalty both
//   sentencepiece and the Rust port use); consecutive unknown characters
//   FUSE into one unk token.
// * With byte_fallback, a fused unknown run is expanded POST-Viterbi into
//   its UTF-8 bytes' "<0xNN>" piece ids (observed: a known piece wins over
//   a byte expansion regardless of score — byte pieces are fallback, not
//   lattice competitors).
// * Added/special tokens match verbatim on the incoming text, earliest
//   occurrence first (longest wins on ties) — same contract as the BPE
//   core (bpe_tokenizer.cc).
//
// Serialized model format (line-based, like the BPE core's):
//   line 0:            V unk_id byte_fallback S
//   lines 1..V:        hex(piece_utf8) score byte_value   (byte_value -1 when
//                      the piece is not a "<0xNN>" byte piece)
//   lines V+1..V+S:    special token id (decimal)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct SpmModel {
  std::vector<std::string> id_to_piece;
  std::vector<float> scores;
  std::vector<int> byte_value;             // -1 unless "<0xNN>" piece
  std::unordered_map<std::string, uint32_t> piece_to_id;
  int32_t byte_piece_id[256];              // -1 when absent
  std::vector<uint32_t> special_ids;
  std::vector<std::string> specials;
  int32_t unk_id = 0;
  bool byte_fallback = false;
  bool all_bytes_present = false;
  float unk_score = 0.0f;                  // min_score - 10
  size_t max_piece_bytes = 1;
};

bool unhex(const std::string& in, std::string* out) {
  if (in.size() % 2) return false;
  out->clear();
  out->reserve(in.size() / 2);
  for (size_t i = 0; i < in.size(); i += 2) {
    auto nib = [](char c) -> int {
      if (c >= '0' && c <= '9') return c - '0';
      if (c >= 'a' && c <= 'f') return c - 'a' + 10;
      if (c >= 'A' && c <= 'F') return c - 'A' + 10;
      return -1;
    };
    int hi = nib(in[i]), lo = nib(in[i + 1]);
    if (hi < 0 || lo < 0) return false;
    out->push_back(static_cast<char>((hi << 4) | lo));
  }
  return true;
}

// UTF-8 char length from the lead byte (invalid lead bytes advance 1 so the
// lattice always makes progress on malformed input).
inline size_t char_len(unsigned char b) {
  if (b < 0x80) return 1;
  if ((b & 0xE0) == 0xC0) return 2;
  if ((b & 0xF0) == 0xE0) return 3;
  if ((b & 0xF8) == 0xF0) return 4;
  return 1;
}

// Viterbi over one ordinary-text segment (no specials inside).
void encode_segment(const SpmModel* m, const std::string& s,
                    std::vector<uint32_t>* out) {
  if (s.empty()) return;
  // char boundaries: starts[0..n_chars], starts[n_chars] == s.size()
  std::vector<uint32_t> starts;
  for (size_t i = 0; i < s.size();) {
    starts.push_back(static_cast<uint32_t>(i));
    i += char_len(static_cast<unsigned char>(s[i]));
    if (i > s.size()) i = s.size();
  }
  const size_t n = starts.size();
  starts.push_back(static_cast<uint32_t>(s.size()));

  constexpr float NEG = -1e30f;
  constexpr int32_t UNK_BP = -2;
  std::vector<float> best(n + 1, NEG);
  std::vector<uint32_t> prev(n + 1, 0);
  std::vector<int32_t> via(n + 1, -1);  // piece id, or UNK_BP for unk edge
  best[0] = 0.0f;
  std::string key;
  for (size_t i = 0; i < n; i++) {
    if (best[i] <= NEG) continue;
    // unk edge: one character
    float u = best[i] + m->unk_score;
    if (u > best[i + 1]) { best[i + 1] = u; prev[i + 1] = i; via[i + 1] = UNK_BP; }
    // vocab pieces starting at this character
    for (size_t j = i + 1; j <= n; j++) {
      size_t blen = starts[j] - starts[i];
      if (blen > m->max_piece_bytes) break;
      key.assign(s, starts[i], blen);
      auto it = m->piece_to_id.find(key);
      if (it == m->piece_to_id.end()) continue;
      float v = best[i] + m->scores[it->second];
      if (v > best[j]) { best[j] = v; prev[j] = i; via[j] = static_cast<int32_t>(it->second); }
    }
  }
  // backtrack
  std::vector<std::pair<int32_t, uint32_t>> rev;  // (piece id / UNK_BP, char idx)
  for (size_t j = n; j > 0;) {
    rev.emplace_back(via[j], prev[j]);
    j = prev[j];
  }
  // emit in order, fusing consecutive unk chars; with byte_fallback the
  // fused run expands into its bytes' pieces
  size_t unk_run_begin = 0, unk_run_end = 0;
  bool in_unk = false;
  auto flush_unk = [&]() {
    if (!in_unk) return;
    in_unk = false;
    if (m->byte_fallback && m->all_bytes_present) {
      for (size_t b = unk_run_begin; b < unk_run_end; b++)
        out->push_back(static_cast<uint32_t>(
            m->byte_piece_id[static_cast<unsigned char>(s[b])]));
    } else {
      out->push_back(static_cast<uint32_t>(m->unk_id));
    }
  };
  for (auto it = rev.rbegin(); it != rev.rend(); ++it) {
    int32_t piece = it->first;
    size_t char_i = it->second;
    if (piece == UNK_BP) {
      if (!in_unk) { in_unk = true; unk_run_begin = starts[char_i]; }
      unk_run_end = starts[char_i + 1];
    } else {
      flush_unk();
      out->push_back(static_cast<uint32_t>(piece));
    }
  }
  flush_unk();
}

}  // namespace

extern "C" {

void* spm_create(const char* data, int64_t len) {
  std::string s(data, static_cast<size_t>(len));
  auto* m = new SpmModel();
  size_t pos = 0;
  auto next_line = [&](std::string* line) -> bool {
    if (pos >= s.size()) return false;
    size_t e = s.find('\n', pos);
    if (e == std::string::npos) e = s.size();
    line->assign(s, pos, e - pos);
    pos = e + 1;
    return true;
  };
  std::string line;
  if (!next_line(&line)) { delete m; return nullptr; }
  long v = 0, unk = 0, bf = 0, sp = 0;
  if (sscanf(line.c_str(), "%ld %ld %ld %ld", &v, &unk, &bf, &sp) != 4 ||
      v <= 0 || unk < 0 || unk >= v || bf < 0 || bf > 1 || sp < 0) {
    delete m; return nullptr;
  }
  m->unk_id = static_cast<int32_t>(unk);
  m->byte_fallback = bf == 1;
  m->id_to_piece.resize(v);
  m->scores.resize(v);
  m->byte_value.assign(v, -1);
  for (int i = 0; i < 256; i++) m->byte_piece_id[i] = -1;
  float min_score = 0.0f;
  for (long i = 0; i < v; i++) {
    if (!next_line(&line)) { delete m; return nullptr; }
    size_t s1 = line.find(' ');
    size_t s2 = (s1 == std::string::npos) ? s1 : line.find(' ', s1 + 1);
    if (s2 == std::string::npos) { delete m; return nullptr; }
    std::string raw;
    if (!unhex(line.substr(0, s1), &raw)) { delete m; return nullptr; }
    float score = strtof(line.c_str() + s1 + 1, nullptr);
    long bv = strtol(line.c_str() + s2 + 1, nullptr, 10);
    if (bv < -1 || bv > 255) { delete m; return nullptr; }
    m->id_to_piece[i] = raw;
    m->scores[i] = score;
    m->byte_value[i] = static_cast<int>(bv);
    if (bv >= 0 && m->byte_piece_id[bv] < 0)
      m->byte_piece_id[bv] = static_cast<int32_t>(i);
    // first occurrence wins on duplicate pieces (matches the Rust trie)
    m->piece_to_id.emplace(raw, static_cast<uint32_t>(i));
    if (score < min_score) min_score = score;
    if (raw.size() > m->max_piece_bytes) m->max_piece_bytes = raw.size();
  }
  m->unk_score = min_score - 10.0f;
  bool all = true;
  for (int i = 0; i < 256; i++) all = all && m->byte_piece_id[i] >= 0;
  m->all_bytes_present = all;
  for (long i = 0; i < sp; i++) {
    if (!next_line(&line)) { delete m; return nullptr; }
    long id = strtol(line.c_str(), nullptr, 10);
    if (id < 0 || id >= v) { delete m; return nullptr; }
    m->special_ids.push_back(static_cast<uint32_t>(id));
    m->specials.push_back(m->id_to_piece[id]);
  }
  return m;
}

void spm_free(void* h) { delete static_cast<SpmModel*>(h); }

// Encode UTF-8 text (already normalized by the caller). Special tokens match
// verbatim, earliest first, longest on ties. Returns the id count (only
// max_out are written), or -1.
int64_t spm_encode(void* h, const char* text, int64_t len, int32_t* out,
                   int64_t max_out) {
  auto* m = static_cast<SpmModel*>(h);
  if (!m) return -1;
  std::string s(text, static_cast<size_t>(len));
  std::vector<uint32_t> ids;
  size_t start = 0;
  while (start < s.size()) {
    size_t best_pos = std::string::npos, best_len = 0;
    uint32_t best_id = 0;
    for (size_t k = 0; k < m->specials.size(); k++) {
      size_t p = s.find(m->specials[k], start);
      if (p == std::string::npos) continue;
      if (p < best_pos || (p == best_pos && m->specials[k].size() > best_len)) {
        best_pos = p;
        best_len = m->specials[k].size();
        best_id = m->special_ids[k];
      }
    }
    if (best_pos == std::string::npos) {
      encode_segment(m, s.substr(start), &ids);
      break;
    }
    if (best_pos > start)
      encode_segment(m, s.substr(start, best_pos - start), &ids);
    ids.push_back(best_id);
    start = best_pos + best_len;
  }
  int64_t nn = static_cast<int64_t>(ids.size());
  for (int64_t i = 0; i < nn && i < max_out; i++)
    out[i] = static_cast<int32_t>(ids[i]);
  return nn;
}

// Decode ids to raw bytes: byte pieces contribute their byte (sentencepiece
// ByteFallback+Fuse), other pieces their literal text. The Python wrapper
// does the final UTF-8 decode and "▁"→" " replacement. Returns the byte
// count (only max_out written), or -1.
int64_t spm_decode(void* h, const int32_t* ids, int64_t n, int skip_special,
                   char* out, int64_t max_out) {
  auto* m = static_cast<SpmModel*>(h);
  if (!m) return -1;
  std::string s;
  for (int64_t i = 0; i < n; i++) {
    uint32_t id = static_cast<uint32_t>(ids[i]);
    if (id >= m->id_to_piece.size()) continue;
    if (skip_special) {
      bool is_sp = false;
      for (uint32_t sid : m->special_ids)
        if (sid == id) { is_sp = true; break; }
      if (is_sp) continue;
    }
    if (m->byte_value[id] >= 0)
      s.push_back(static_cast<char>(m->byte_value[id]));
    else
      s += m->id_to_piece[id];
  }
  int64_t bytes = static_cast<int64_t>(s.size());
  if (bytes > 0)
    memcpy(out, s.data(), static_cast<size_t>(std::min(bytes, max_out)));
  return bytes;
}

}  // extern "C"
