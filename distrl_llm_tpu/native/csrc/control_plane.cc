// Control-plane transport — C++ TCP core with a plain C ABI (ctypes-bound).
//
// The N5 equivalent (SURVEY §2b): the reference's driver↔worker RPC runs on
// Ray's C++ core (raylet/GCS/gRPC — ray.init at distributed_actor.py:543,
// actor .remote dispatch at distributed_trainer.py:190–197, ray.get barriers
// with timeouts at :200/:333). This file is the native transport under our
// multi-process runtime: length-prefixed typed frames over TCP with
// poll()-based deadlines. gRPC itself is not in this environment (no
// grpc++/protoc plugin); the Python layer (distrl_llm_tpu/distributed/)
// builds the RPC semantics — request ids, dispatch/collect, health checks,
// shard resubmission — on these primitives.
//
// Frame wire format (little-endian):
//   [u32 magic 0xC0DE17A1][u8 type][u64 req_id][u64 payload_len][payload]
//
// All calls are blocking with explicit millisecond deadlines; fds are plain
// sockets so one process can serve/poll many connections from Python threads.

#include <arpa/inet.h>
#include <cerrno>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xC0DE17A1;

#pragma pack(push, 1)
struct FrameHeader {
  uint32_t magic;
  uint8_t type;
  uint64_t req_id;
  uint64_t len;
};
#pragma pack(pop)

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Fully send len bytes before an absolute deadline. The deadline is TOTAL —
// each poll gets only the remaining budget, so a peer trickling bytes cannot
// extend the transfer indefinitely.
bool send_all(int fd, const char* data, int64_t len, int timeout_ms) {
  const int64_t deadline = now_ms() + timeout_ms;
  int64_t off = 0;
  while (off < len) {
    int64_t remaining = deadline - now_ms();
    if (remaining <= 0) return false;
    struct pollfd p = {fd, POLLOUT, 0};
    int r = poll(&p, 1, static_cast<int>(remaining));
    if (r < 0 && errno == EINTR) continue;  // signal (e.g. SIGTERM drain)
    if (r <= 0) return false;
    ssize_t n = ::send(fd, data + off, static_cast<size_t>(len - off),
                       MSG_NOSIGNAL);
    if (n <= 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      return false;
    }
    off += n;
  }
  return true;
}

// Fully receive len bytes before an absolute deadline (total, as above).
bool recv_all(int fd, char* buf, int64_t len, int timeout_ms) {
  const int64_t deadline = now_ms() + timeout_ms;
  int64_t off = 0;
  while (off < len) {
    int64_t remaining = deadline - now_ms();
    if (remaining <= 0) return false;
    struct pollfd p = {fd, POLLIN, 0};
    int r = poll(&p, 1, static_cast<int>(remaining));
    if (r < 0 && errno == EINTR) continue;  // signal (e.g. SIGTERM drain)
    if (r <= 0) return false;
    ssize_t n = ::recv(fd, buf + off, static_cast<size_t>(len - off), 0);
    if (n <= 0) {
      if (n < 0 && (errno == EINTR || errno == EAGAIN)) continue;
      return false;  // peer closed or hard error
    }
    off += n;
  }
  return true;
}

}  // namespace

extern "C" {

// Listen on 127.0.0.1:port (port 0 = ephemeral). Returns server fd or -1.
int64_t cp_listen(int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(fd, 64) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

// Bound port of a listening fd (for port 0 ephemeral binds). -1 on error.
int cp_bound_port(int64_t server_fd) {
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (getsockname(static_cast<int>(server_fd),
                  reinterpret_cast<sockaddr*>(&addr), &len) != 0)
    return -1;
  return ntohs(addr.sin_port);
}

// Accept one connection. Returns conn fd, -1 on timeout, -2 on error.
int64_t cp_accept(int64_t server_fd, int timeout_ms) {
  struct pollfd p = {static_cast<int>(server_fd), POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);
  if (r == 0) return -1;
  // EINTR reports as a timeout so the Python accept loop regains control
  // (and runs its signal handlers — the SIGTERM drain path) instead of
  // treating a delivered signal as a transport error.
  if (r < 0) return errno == EINTR ? -1 : -2;
  int fd = accept(static_cast<int>(server_fd), nullptr, nullptr);
  if (fd < 0) return -2;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Connect to host:port with a real deadline (non-blocking connect + poll;
// the kernel's default SYN retry window is ~2 min, far past any RPC budget).
// Returns conn fd or -1.
int64_t cp_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portbuf[16];
  snprintf(portbuf, sizeof(portbuf), "%d", port);
  if (getaddrinfo(host, portbuf, &hints, &res) != 0 || !res) return -1;
  int fd = socket(res->ai_family, res->ai_socktype, res->ai_protocol);
  if (fd < 0) {
    freeaddrinfo(res);
    return -1;
  }
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = connect(fd, res->ai_addr, res->ai_addrlen);
  freeaddrinfo(res);
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      close(fd);
      return -1;
    }
    struct pollfd p = {fd, POLLOUT, 0};
    if (poll(&p, 1, timeout_ms) <= 0) {
      close(fd);
      return -1;
    }
    int err = 0;
    socklen_t elen = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen) != 0 || err != 0) {
      close(fd);
      return -1;
    }
  }
  fcntl(fd, F_SETFL, flags);  // back to blocking; frame ops poll explicitly
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Send one frame. Returns 0 ok, -1 failure.
int cp_send(int64_t fd, int type, uint64_t req_id, const char* data,
            int64_t len, int timeout_ms) {
  FrameHeader h{kMagic, static_cast<uint8_t>(type), req_id,
                static_cast<uint64_t>(len)};
  if (!send_all(static_cast<int>(fd), reinterpret_cast<const char*>(&h),
                sizeof(h), timeout_ms))
    return -1;
  if (len > 0 && !send_all(static_cast<int>(fd), data, len, timeout_ms))
    return -1;
  return 0;
}

// Receive a frame header. Returns 0 ok (fills type/req_id/len), -1 timeout,
// -2 closed/protocol error.
int cp_recv_header(int64_t fd, int* type, uint64_t* req_id, int64_t* len,
                   int timeout_ms) {
  FrameHeader h{};
  // peek-poll first so a clean timeout does not consume partial bytes
  struct pollfd p = {static_cast<int>(fd), POLLIN, 0};
  int r = poll(&p, 1, timeout_ms);
  if (r == 0) return -1;
  // EINTR → timeout, not connection death: the Python serve loop must get
  // control back to run signal handlers (SIGTERM drain) without the
  // connection being torn down underneath the driver.
  if (r < 0) return errno == EINTR ? -1 : -2;
  if (!recv_all(static_cast<int>(fd), reinterpret_cast<char*>(&h), sizeof(h),
                timeout_ms))
    return -2;
  if (h.magic != kMagic) return -2;
  *type = h.type;
  *req_id = h.req_id;
  *len = static_cast<int64_t>(h.len);
  return 0;
}

// Receive exactly len payload bytes. Returns 0 ok, -1 failure.
int cp_recv_payload(int64_t fd, char* buf, int64_t len, int timeout_ms) {
  if (len == 0) return 0;
  return recv_all(static_cast<int>(fd), buf, len, timeout_ms) ? 0 : -1;
}

void cp_close(int64_t fd) {
  if (fd >= 0) close(static_cast<int>(fd));
}

}  // extern "C"
