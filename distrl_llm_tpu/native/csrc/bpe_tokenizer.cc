// Byte-level BPE tokenizer — C++ core with a plain C ABI (ctypes-bound).
//
// TPU-native parity component for the reference's Rust HF tokenizer
// (SURVEY §2b N7: `load_correct_tokenizer` at train_distributed.py:46,
// `batch_encode_plus` at distributed_actor.py:217/:222). The host-side
// tokenize/decode of every rollout runs here instead of through Python
// string code. Rust is not available in this environment, so the native
// component is C++ (SURVEY §2b note).
//
// Model format: the Python wrapper (distrl_llm_tpu/native/tokenizer.py)
// converts an HF tokenizer.json (unicode-remapped byte-level tokens) into a
// raw-bytes serialization:
//
//   line 0:            V M S            (vocab size, merge count, special count)
//   next V lines:      <hex-bytes>      (token id = line index)
//   next M lines:      <hexL> <hexR>    (merge rank = line index)
//   next S lines:      <id>             (special token ids; matched verbatim
//                                        before pretokenization)
//
// Algorithm parity with the byte-level BPE the Rust crate implements:
//   1. split text on special tokens (longest match first);
//   2. GPT-2-style pretokenization (contractions / letter runs / digit runs /
//      punctuation runs, with a leading-space convention). "Letter" follows
//      ASCII classes plus any byte >= 0x80 (UTF-8 continuation), an
//      approximation of the \p{L} unicode classes that is exact for ASCII
//      and groups multibyte scripts into runs;
//   3. per pretoken, greedy lowest-rank pair merging over the merge table
//      (with a pretoken result cache, as the Rust implementation keeps).
//
// Decode is id -> byte-sequence concatenation (skipping specials on request).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>
#include <mutex>

namespace {

struct PairHash {
  size_t operator()(const std::pair<uint32_t, uint32_t>& p) const {
    return (static_cast<size_t>(p.first) << 32) ^ p.second;
  }
};

struct Tokenizer {
  std::vector<std::string> id_to_tok;                       // id -> raw bytes
  std::unordered_map<std::string, uint32_t> tok_to_id;      // raw bytes -> id
  std::unordered_map<std::pair<uint32_t, uint32_t>, uint32_t, PairHash>
      merge_rank;                                           // (idL,idR) -> rank
  std::unordered_map<std::pair<uint32_t, uint32_t>, uint32_t, PairHash>
      merge_result;                                         // (idL,idR) -> id
  std::vector<std::string> specials;                        // raw special strings
  std::vector<uint32_t> special_ids;
  std::unordered_map<std::string, std::vector<uint32_t>> cache;  // pretoken memo
  std::mutex cache_mu;
};

bool is_ascii_letter(uint8_t b) {
  return (b >= 'a' && b <= 'z') || (b >= 'A' && b <= 'Z');
}
bool is_letterish(uint8_t b) { return is_ascii_letter(b) || b >= 0x80; }
bool is_digit(uint8_t b) { return b >= '0' && b <= '9'; }
bool is_space(uint8_t b) { return b == ' ' || b == '\t' || b == '\n' || b == '\r'; }

// GPT-2 pattern: 's|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+
std::vector<std::string> pretokenize(const std::string& text) {
  std::vector<std::string> out;
  size_t i = 0, n = text.size();
  while (i < n) {
    // contractions
    if (text[i] == '\'' && i + 1 < n) {
      size_t len = 0;
      const char* two[] = {"'s", "'t", "'m", "'d"};
      const char* three[] = {"'re", "'ve", "'ll"};
      for (const char* c : three)
        if (i + 3 <= n && text.compare(i, 3, c) == 0) len = 3;
      if (!len)
        for (const char* c : two)
          if (i + 2 <= n && text.compare(i, 2, c) == 0) len = 2;
      if (len) { out.emplace_back(text.substr(i, len)); i += len; continue; }
    }
    size_t start = i;
    bool leading_space = false;
    if (text[i] == ' ' && i + 1 < n &&
        (is_letterish(text[i + 1]) || is_digit(text[i + 1]) ||
         (!is_space(text[i + 1]) && text[i + 1] != ' '))) {
      leading_space = true;
      i++;
    }
    if (i < n && is_letterish(text[i])) {
      while (i < n && is_letterish(text[i])) i++;
      out.emplace_back(text.substr(start, i - start));
      continue;
    }
    if (i < n && is_digit(text[i])) {
      while (i < n && is_digit(text[i])) i++;
      out.emplace_back(text.substr(start, i - start));
      continue;
    }
    if (i < n && !is_space(text[i])) {  // punctuation run (apostrophes that
      // did not start a contraction are ordinary punctuation, as in the
      // greedy [^\s\p{L}\p{N}]+ alternative)
      while (i < n && !is_space(text[i]) && !is_letterish(text[i]) &&
             !is_digit(text[i]))
        i++;
      out.emplace_back(text.substr(start, i - start));
      continue;
    }
    if (leading_space) { i = start; }  // space not followed by token content
    // whitespace runs: \s+(?!\S) keeps trailing ws together; emit maximal run
    // minus one if a non-space follows (that space prefixes the next token)
    size_t ws_start = i;
    while (i < n && is_space(text[i])) i++;
    if (i < n && i - ws_start > 1 && text[i - 1] == ' ') {
      out.emplace_back(text.substr(ws_start, i - ws_start - 1));
      i--;  // final space joins the next pretoken
    } else if (i > ws_start) {
      out.emplace_back(text.substr(ws_start, i - ws_start));
    }
  }
  return out;
}

std::vector<uint32_t> bpe_merge(Tokenizer* t, const std::string& piece) {
  {
    std::lock_guard<std::mutex> g(t->cache_mu);
    auto it = t->cache.find(piece);
    if (it != t->cache.end()) return it->second;
  }
  // initial symbols: single bytes (every byte has a vocab entry in byte-level BPE)
  std::vector<uint32_t> ids;
  ids.reserve(piece.size());
  for (unsigned char b : piece) {
    auto it = t->tok_to_id.find(std::string(1, b));
    if (it == t->tok_to_id.end()) return {};  // malformed vocab: no byte fallback
    ids.push_back(it->second);
  }
  while (ids.size() > 1) {
    uint32_t best_rank = UINT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < ids.size(); i++) {
      auto it = t->merge_rank.find({ids[i], ids[i + 1]});
      if (it != t->merge_rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == UINT32_MAX) break;
    uint32_t merged = t->merge_result[{ids[best_i], ids[best_i + 1]}];
    ids[best_i] = merged;
    ids.erase(ids.begin() + best_i + 1);
  }
  {
    std::lock_guard<std::mutex> g(t->cache_mu);
    if (t->cache.size() < (1u << 20)) t->cache.emplace(piece, ids);
  }
  return ids;
}

void encode_ordinary(Tokenizer* t, const std::string& text,
                     std::vector<uint32_t>* out) {
  for (const auto& piece : pretokenize(text)) {
    auto whole = t->tok_to_id.find(piece);
    if (whole != t->tok_to_id.end()) {
      out->push_back(whole->second);
      continue;
    }
    auto ids = bpe_merge(t, piece);
    out->insert(out->end(), ids.begin(), ids.end());
  }
}

int hexval(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

bool unhex(const std::string& h, std::string* out) {
  if (h.size() % 2) return false;
  out->clear();
  out->reserve(h.size() / 2);
  for (size_t i = 0; i < h.size(); i += 2) {
    int a = hexval(h[i]), b = hexval(h[i + 1]);
    if (a < 0 || b < 0) return false;
    out->push_back(static_cast<char>((a << 4) | b));
  }
  return true;
}

}  // namespace

extern "C" {

// Parse the serialized model (format in the header comment). Returns an
// opaque handle or null on malformed input.
void* bpe_create(const char* data, int64_t len) {
  std::string s(data, static_cast<size_t>(len));
  auto* t = new Tokenizer();
  size_t pos = 0;
  auto next_line = [&](std::string* line) -> bool {
    if (pos >= s.size()) return false;
    size_t e = s.find('\n', pos);
    if (e == std::string::npos) e = s.size();
    line->assign(s, pos, e - pos);
    pos = e + 1;
    return true;
  };
  std::string line;
  if (!next_line(&line)) { delete t; return nullptr; }
  long v = 0, m = 0, sp = 0;
  if (sscanf(line.c_str(), "%ld %ld %ld", &v, &m, &sp) != 3 || v <= 0) {
    delete t; return nullptr;
  }
  t->id_to_tok.resize(v);
  for (long i = 0; i < v; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    std::string raw;
    if (!unhex(line, &raw)) { delete t; return nullptr; }
    t->id_to_tok[i] = raw;
    t->tok_to_id.emplace(raw, static_cast<uint32_t>(i));
  }
  for (long i = 0; i < m; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    size_t sep = line.find(' ');
    if (sep == std::string::npos) { delete t; return nullptr; }
    std::string l, r;
    if (!unhex(line.substr(0, sep), &l) || !unhex(line.substr(sep + 1), &r)) {
      delete t; return nullptr;
    }
    auto li = t->tok_to_id.find(l), ri = t->tok_to_id.find(r),
         mi = t->tok_to_id.find(l + r);
    if (li == t->tok_to_id.end() || ri == t->tok_to_id.end() ||
        mi == t->tok_to_id.end())
      continue;  // merge over tokens pruned from the vocab
    std::pair<uint32_t, uint32_t> key{li->second, ri->second};
    if (!t->merge_rank.count(key)) {
      t->merge_rank.emplace(key, static_cast<uint32_t>(i));
      t->merge_result.emplace(key, mi->second);
    }
  }
  for (long i = 0; i < sp; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    long id = strtol(line.c_str(), nullptr, 10);
    if (id < 0 || id >= v) { delete t; return nullptr; }
    t->special_ids.push_back(static_cast<uint32_t>(id));
    t->specials.push_back(t->id_to_tok[id]);
  }
  return t;
}

void bpe_free(void* h) { delete static_cast<Tokenizer*>(h); }

// Encode UTF-8 text. Special tokens in the text are matched verbatim.
// Returns the number of ids produced (may exceed max_out; only max_out are
// written), or -1 on error.
int64_t bpe_encode(void* h, const char* text, int64_t len, int32_t* out,
                   int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  if (!t) return -1;
  std::string s(text, static_cast<size_t>(len));
  std::vector<uint32_t> ids;
  size_t start = 0;
  while (start < s.size()) {
    // find earliest special-token occurrence (ties: longest special wins)
    size_t best_pos = std::string::npos, best_len = 0;
    uint32_t best_id = 0;
    for (size_t k = 0; k < t->specials.size(); k++) {
      size_t p = s.find(t->specials[k], start);
      if (p == std::string::npos) continue;
      if (p < best_pos ||
          (p == best_pos && t->specials[k].size() > best_len)) {
        best_pos = p;
        best_len = t->specials[k].size();
        best_id = t->special_ids[k];
      }
    }
    if (best_pos == std::string::npos) {
      encode_ordinary(t, s.substr(start), &ids);
      break;
    }
    if (best_pos > start)
      encode_ordinary(t, s.substr(start, best_pos - start), &ids);
    ids.push_back(best_id);
    start = best_pos + best_len;
  }
  int64_t n = static_cast<int64_t>(ids.size());
  for (int64_t i = 0; i < n && i < max_out; i++)
    out[i] = static_cast<int32_t>(ids[i]);
  return n;
}

// Decode ids to UTF-8 bytes. skip_special drops special ids. Returns byte
// count (may exceed max_out; only max_out bytes are written), or -1.
int64_t bpe_decode(void* h, const int32_t* ids, int64_t n, int skip_special,
                   char* out, int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  if (!t) return -1;
  std::string s;
  for (int64_t i = 0; i < n; i++) {
    uint32_t id = static_cast<uint32_t>(ids[i]);
    if (id >= t->id_to_tok.size()) continue;
    if (skip_special) {
      bool is_sp = false;
      for (uint32_t sid : t->special_ids)
        if (sid == id) { is_sp = true; break; }
      if (is_sp) continue;
    }
    s += t->id_to_tok[id];
  }
  int64_t bytes = static_cast<int64_t>(s.size());
  if (bytes > 0) memcpy(out, s.data(), static_cast<size_t>(std::min(bytes, max_out)));
  return bytes;
}

}  // extern "C"
