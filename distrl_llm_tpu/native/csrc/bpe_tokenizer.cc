// Byte-level BPE tokenizer — C++ core with a plain C ABI (ctypes-bound).
//
// TPU-native parity component for the reference's Rust HF tokenizer
// (SURVEY §2b N7: `load_correct_tokenizer` at train_distributed.py:46,
// `batch_encode_plus` at distributed_actor.py:217/:222). The host-side
// tokenize/decode of every rollout runs here instead of through Python
// string code. Rust is not available in this environment, so the native
// component is C++ (SURVEY §2b note).
//
// Model format: the Python wrapper (distrl_llm_tpu/native/tokenizer.py)
// converts an HF tokenizer.json (unicode-remapped byte-level tokens) into a
// raw-bytes serialization:
//
//   line 0:            V M S [P]        (vocab size, merge count, special
//                                        count, pretokenizer kind: 0 = GPT-2
//                                        pattern, 1 = Qwen2/cl100k pattern;
//                                        default 1)
//   next V lines:      <hex-bytes>      (token id = line index)
//   next M lines:      <hexL> <hexR>    (merge rank = line index)
//   next S lines:      <id>             (special token ids; matched verbatim
//                                        before pretokenization)
//
// Algorithm parity with the byte-level BPE the Rust crate implements:
//   1. split text on special tokens (longest match first);
//   2. pretokenization with the checkpoint's actual regex, evaluated over
//      decoded UTF-8 codepoints with real \p{L}/\p{N} class tables
//      (unicode_tables.h, generated from unicodedata):
//        P=1 (Qwen2/Llama-3 family, the models this framework trains):
//          (?i:'s|'t|'re|'ve|'m|'ll|'d)|[^\r\n\p{L}\p{N}]?\p{L}+|\p{N}{1,3}
//          | ?[^\s\p{L}\p{N}]+[\r\n]*|\s*[\r\n]+|\s+(?!\S)|\s+
//        P=0 (GPT-2):
//          's|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+
//          |\s+(?!\S)|\s+
//      Alternatives are ordered (leftmost alternation wins), each greedy —
//      matching onig's behavior for these patterns; the \s+(?!\S) lookahead
//      is the standard "maximal run minus trailing space" rule. Differential
//      tests against the Rust implementation: tests/test_native_tokenizer.py;
//   3. per pretoken, greedy lowest-rank pair merging over the merge table
//      (with a pretoken result cache, as the Rust implementation keeps).
//
// Decode is id -> byte-sequence concatenation (skipping specials on request).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>
#include <mutex>

#include "unicode_tables.h"

namespace {

struct PairHash {
  size_t operator()(const std::pair<uint32_t, uint32_t>& p) const {
    return (static_cast<size_t>(p.first) << 32) ^ p.second;
  }
};

struct Tokenizer {
  std::vector<std::string> id_to_tok;                       // id -> raw bytes
  std::unordered_map<std::string, uint32_t> tok_to_id;      // raw bytes -> id
  std::unordered_map<std::pair<uint32_t, uint32_t>, uint32_t, PairHash>
      merge_rank;                                           // (idL,idR) -> rank
  std::unordered_map<std::pair<uint32_t, uint32_t>, uint32_t, PairHash>
      merge_result;                                         // (idL,idR) -> id
  std::vector<std::string> specials;                        // raw special strings
  std::vector<uint32_t> special_ids;
  int pretok_kind = 1;                                      // 0 gpt2, 1 qwen2
  std::unordered_map<std::string, std::vector<uint32_t>> cache;  // pretoken memo
  std::mutex cache_mu;
};

// ---------------------------------------------------------------- unicode ---

bool in_ranges(uint32_t cp, const uint32_t (*ranges)[2], size_t n) {
  size_t lo = 0, hi = n;
  while (lo < hi) {
    size_t mid = (lo + hi) / 2;
    if (cp < ranges[mid][0]) hi = mid;
    else if (cp > ranges[mid][1]) lo = mid + 1;
    else return true;
  }
  return false;
}

bool is_letter(uint32_t cp) { return in_ranges(cp, kUnicodeL, kUnicodeL_len); }
bool is_number(uint32_t cp) { return in_ranges(cp, kUnicodeN, kUnicodeN_len); }

// onig's \s in Unicode mode (the class the HF pretokenizer regex uses)
bool is_space_cp(uint32_t cp) {
  switch (cp) {
    case 0x09: case 0x0A: case 0x0B: case 0x0C: case 0x0D: case 0x20:
    case 0x85: case 0xA0: case 0x1680: case 0x2028: case 0x2029:
    case 0x202F: case 0x205F: case 0x3000:
      return true;
    default:
      return cp >= 0x2000 && cp <= 0x200A;
  }
}

// Decode one UTF-8 codepoint at byte offset i; returns codepoint and writes
// its byte length. Invalid bytes decode as single-byte codepoints (byte-level
// BPE always has a byte fallback, so this only affects class membership).
uint32_t utf8_next(const std::string& s, size_t i, size_t* len) {
  uint8_t b0 = static_cast<uint8_t>(s[i]);
  size_t n = s.size();
  if (b0 < 0x80) { *len = 1; return b0; }
  auto cont = [&](size_t k) {
    return i + k < n && (static_cast<uint8_t>(s[i + k]) & 0xC0) == 0x80;
  };
  if ((b0 & 0xE0) == 0xC0 && cont(1)) {
    *len = 2;
    return ((b0 & 0x1Fu) << 6) | (static_cast<uint8_t>(s[i + 1]) & 0x3Fu);
  }
  if ((b0 & 0xF0) == 0xE0 && cont(1) && cont(2)) {
    *len = 3;
    return ((b0 & 0x0Fu) << 12) | ((static_cast<uint8_t>(s[i + 1]) & 0x3Fu) << 6) |
           (static_cast<uint8_t>(s[i + 2]) & 0x3Fu);
  }
  if ((b0 & 0xF8) == 0xF0 && cont(1) && cont(2) && cont(3)) {
    *len = 4;
    return ((b0 & 0x07u) << 18) | ((static_cast<uint8_t>(s[i + 1]) & 0x3Fu) << 12) |
           ((static_cast<uint8_t>(s[i + 2]) & 0x3Fu) << 6) |
           (static_cast<uint8_t>(s[i + 3]) & 0x3Fu);
  }
  *len = 1;
  return b0;
}

// ----------------------------------------------------------- pretokenizer ---

// Case-insensitive contraction match at byte offset i ('s 't 're 've 'm 'll
// 'd). GPT-2's pattern is case-SENSITIVE; Qwen2's has the (?i:) group.
size_t match_contraction(const std::string& s, size_t i, bool case_insensitive) {
  size_t n = s.size();
  if (s[i] != '\'' || i + 1 >= n) return 0;
  auto low = [&](size_t k) {
    char c = s[i + k];
    if (!case_insensitive) return c;
    return (c >= 'A' && c <= 'Z') ? static_cast<char>(c - 'A' + 'a') : c;
  };
  char c1 = low(1);
  if (i + 2 < n) {
    char c2 = low(2);
    if ((c1 == 'r' && c2 == 'e') || (c1 == 'v' && c2 == 'e') ||
        (c1 == 'l' && c2 == 'l'))
      return 3;
  }
  if (c1 == 's' || c1 == 't' || c1 == 'm' || c1 == 'd') return 2;
  return 0;
}

// The Qwen2 / cl100k-style pattern (tokenizer.json pre_tokenizer regex):
//   (?i:'s|'t|'re|'ve|'m|'ll|'d)          contractions, any case
//   [^\r\n\p{L}\p{N}]?\p{L}+              optional joiner char + letter run
//   \p{N}{1,3}                            digits chunked 1-3 at a time
//   ' ?[^\s\p{L}\p{N}]+[\r\n]*'           symbol run absorbing newlines
//   \s*[\r\n]+                            whitespace ending in newlines
//   \s+(?!\S)                             trailing whitespace
//   \s+
std::vector<std::string> pretokenize_qwen2(const std::string& text) {
  std::vector<std::string> out;
  size_t i = 0, n = text.size();
  while (i < n) {
    size_t clen = match_contraction(text, i, /*case_insensitive=*/true);
    if (clen) { out.emplace_back(text.substr(i, clen)); i += clen; continue; }

    size_t len0;
    uint32_t cp0 = utf8_next(text, i, &len0);

    // [^\r\n\p{L}\p{N}]?\p{L}+
    {
      size_t j = i, jl = len0;
      uint32_t c = cp0;
      bool joiner = false;
      if (c != '\r' && c != '\n' && !is_letter(c) && !is_number(c)) {
        joiner = true;
        j += jl;
        if (j < n) c = utf8_next(text, j, &jl);
      }
      if (j < n && is_letter(c)) {
        size_t end = j;
        while (end < n) {
          size_t l;
          uint32_t cc = utf8_next(text, end, &l);
          if (!is_letter(cc)) break;
          end += l;
        }
        size_t start = joiner ? i : j;
        out.emplace_back(text.substr(start, end - start));
        i = end;
        continue;
      }
    }

    // \p{N}{1,3}
    if (is_number(cp0)) {
      size_t end = i, count = 0;
      while (end < n && count < 3) {
        size_t l;
        uint32_t cc = utf8_next(text, end, &l);
        if (!is_number(cc)) break;
        end += l;
        count++;
      }
      out.emplace_back(text.substr(i, end - i));
      i = end;
      continue;
    }

    // ' ?[^\s\p{L}\p{N}]+[\r\n]*'
    {
      size_t j = i;
      if (text[j] == ' ') j++;
      if (j < n) {
        size_t l;
        uint32_t cc = utf8_next(text, j, &l);
        if (!is_space_cp(cc) && !is_letter(cc) && !is_number(cc)) {
          size_t end = j;
          while (end < n) {
            uint32_t c2 = utf8_next(text, end, &l);
            if (is_space_cp(c2) || is_letter(c2) || is_number(c2)) break;
            end += l;
          }
          while (end < n && (text[end] == '\r' || text[end] == '\n')) end++;
          out.emplace_back(text.substr(i, end - i));
          i = end;
          continue;
        }
      }
    }

    // \s*[\r\n]+  — greedy: maximal whitespace run truncated at its LAST
    // newline (the [\r\n]+ suffix); fails if the run contains no newline
    if (is_space_cp(cp0)) {
      size_t end = i, l, last_nl_end = 0, last_len = 0;
      while (end < n) {
        uint32_t cc = utf8_next(text, end, &l);
        if (!is_space_cp(cc)) break;
        end += l;
        last_len = l;
        if (cc == '\r' || cc == '\n') last_nl_end = end;
      }
      if (last_nl_end > i) {
        out.emplace_back(text.substr(i, last_nl_end - i));
        i = last_nl_end;
        continue;
      }
      // \s+(?!\S) then \s+ : maximal run; drop the last space if a non-space
      // follows (it joins the next pretoken via the joiner/space alternatives)
      if (end < n && end - i > last_len) {
        out.emplace_back(text.substr(i, end - i - last_len));
        i = end - last_len;
      } else {
        out.emplace_back(text.substr(i, end - i));
        i = end;
      }
      continue;
    }

    // unreachable fallback: emit the codepoint as its own pretoken
    out.emplace_back(text.substr(i, len0));
    i += len0;
  }
  return out;
}

// GPT-2 pattern: 's|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+
std::vector<std::string> pretokenize_gpt2(const std::string& text) {
  std::vector<std::string> out;
  size_t i = 0, n = text.size();
  while (i < n) {
    size_t clen = match_contraction(text, i, /*case_insensitive=*/false);
    if (clen) { out.emplace_back(text.substr(i, clen)); i += clen; continue; }

    // ' ?' prefix shared by the letter/number/symbol alternatives
    size_t j = i;
    if (text[j] == ' ' && j + 1 < n) j++;
    if (j < n) {
      size_t l;
      uint32_t c = utf8_next(text, j, &l);
      auto run = [&](bool (*cls)(uint32_t)) {
        size_t end = j;
        while (end < n) {
          size_t ll;
          uint32_t cc = utf8_next(text, end, &ll);
          if (!cls(cc)) break;
          end += ll;
        }
        out.emplace_back(text.substr(i, end - i));
        i = end;
      };
      if (is_letter(c)) { run(is_letter); continue; }
      if (is_number(c)) { run(is_number); continue; }
      if (!is_space_cp(c)) {
        size_t end = j;
        while (end < n) {
          size_t ll;
          uint32_t cc = utf8_next(text, end, &ll);
          if (is_space_cp(cc) || is_letter(cc) || is_number(cc)) break;
          end += ll;
        }
        out.emplace_back(text.substr(i, end - i));
        i = end;
        continue;
      }
    }

    // whitespace: \s+(?!\S) | \s+
    size_t end = i, last_len = 0;
    while (end < n) {
      size_t l;
      uint32_t cc = utf8_next(text, end, &l);
      if (!is_space_cp(cc)) break;
      end += l;
      last_len = l;
    }
    if (end < n && end - i > last_len) {
      out.emplace_back(text.substr(i, end - i - last_len));
      i = end - last_len;
    } else {
      out.emplace_back(text.substr(i, end - i));
      i = end;
    }
  }
  return out;
}

std::vector<std::string> pretokenize(const Tokenizer* t, const std::string& text) {
  return t->pretok_kind == 0 ? pretokenize_gpt2(text) : pretokenize_qwen2(text);
}

std::vector<uint32_t> bpe_merge(Tokenizer* t, const std::string& piece) {
  {
    std::lock_guard<std::mutex> g(t->cache_mu);
    auto it = t->cache.find(piece);
    if (it != t->cache.end()) return it->second;
  }
  // initial symbols: single bytes (every byte has a vocab entry in byte-level BPE)
  std::vector<uint32_t> ids;
  ids.reserve(piece.size());
  for (unsigned char b : piece) {
    auto it = t->tok_to_id.find(std::string(1, b));
    if (it == t->tok_to_id.end()) return {};  // malformed vocab: no byte fallback
    ids.push_back(it->second);
  }
  while (ids.size() > 1) {
    uint32_t best_rank = UINT32_MAX;
    size_t best_i = 0;
    for (size_t i = 0; i + 1 < ids.size(); i++) {
      auto it = t->merge_rank.find({ids[i], ids[i + 1]});
      if (it != t->merge_rank.end() && it->second < best_rank) {
        best_rank = it->second;
        best_i = i;
      }
    }
    if (best_rank == UINT32_MAX) break;
    uint32_t merged = t->merge_result[{ids[best_i], ids[best_i + 1]}];
    ids[best_i] = merged;
    ids.erase(ids.begin() + best_i + 1);
  }
  {
    std::lock_guard<std::mutex> g(t->cache_mu);
    if (t->cache.size() < (1u << 20)) t->cache.emplace(piece, ids);
  }
  return ids;
}

void encode_ordinary(Tokenizer* t, const std::string& text,
                     std::vector<uint32_t>* out) {
  for (const auto& piece : pretokenize(t, text)) {
    auto whole = t->tok_to_id.find(piece);
    if (whole != t->tok_to_id.end()) {
      out->push_back(whole->second);
      continue;
    }
    auto ids = bpe_merge(t, piece);
    out->insert(out->end(), ids.begin(), ids.end());
  }
}

int hexval(char c) {
  if (c >= '0' && c <= '9') return c - '0';
  if (c >= 'a' && c <= 'f') return c - 'a' + 10;
  if (c >= 'A' && c <= 'F') return c - 'A' + 10;
  return -1;
}

bool unhex(const std::string& h, std::string* out) {
  if (h.size() % 2) return false;
  out->clear();
  out->reserve(h.size() / 2);
  for (size_t i = 0; i < h.size(); i += 2) {
    int a = hexval(h[i]), b = hexval(h[i + 1]);
    if (a < 0 || b < 0) return false;
    out->push_back(static_cast<char>((a << 4) | b));
  }
  return true;
}

}  // namespace

extern "C" {

// Parse the serialized model (format in the header comment). Returns an
// opaque handle or null on malformed input.
void* bpe_create(const char* data, int64_t len) {
  std::string s(data, static_cast<size_t>(len));
  auto* t = new Tokenizer();
  size_t pos = 0;
  auto next_line = [&](std::string* line) -> bool {
    if (pos >= s.size()) return false;
    size_t e = s.find('\n', pos);
    if (e == std::string::npos) e = s.size();
    line->assign(s, pos, e - pos);
    pos = e + 1;
    return true;
  };
  std::string line;
  if (!next_line(&line)) { delete t; return nullptr; }
  long v = 0, m = 0, sp = 0, pk = 1;
  int fields = sscanf(line.c_str(), "%ld %ld %ld %ld", &v, &m, &sp, &pk);
  if (fields < 3 || v <= 0 || pk < 0 || pk > 1) {
    delete t; return nullptr;
  }
  t->pretok_kind = static_cast<int>(pk);
  t->id_to_tok.resize(v);
  for (long i = 0; i < v; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    std::string raw;
    if (!unhex(line, &raw)) { delete t; return nullptr; }
    t->id_to_tok[i] = raw;
    t->tok_to_id.emplace(raw, static_cast<uint32_t>(i));
  }
  for (long i = 0; i < m; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    size_t sep = line.find(' ');
    if (sep == std::string::npos) { delete t; return nullptr; }
    std::string l, r;
    if (!unhex(line.substr(0, sep), &l) || !unhex(line.substr(sep + 1), &r)) {
      delete t; return nullptr;
    }
    auto li = t->tok_to_id.find(l), ri = t->tok_to_id.find(r),
         mi = t->tok_to_id.find(l + r);
    if (li == t->tok_to_id.end() || ri == t->tok_to_id.end() ||
        mi == t->tok_to_id.end())
      continue;  // merge over tokens pruned from the vocab
    std::pair<uint32_t, uint32_t> key{li->second, ri->second};
    if (!t->merge_rank.count(key)) {
      t->merge_rank.emplace(key, static_cast<uint32_t>(i));
      t->merge_result.emplace(key, mi->second);
    }
  }
  for (long i = 0; i < sp; i++) {
    if (!next_line(&line)) { delete t; return nullptr; }
    long id = strtol(line.c_str(), nullptr, 10);
    if (id < 0 || id >= v) { delete t; return nullptr; }
    t->special_ids.push_back(static_cast<uint32_t>(id));
    t->specials.push_back(t->id_to_tok[id]);
  }
  return t;
}

void bpe_free(void* h) { delete static_cast<Tokenizer*>(h); }

// Encode UTF-8 text. Special tokens in the text are matched verbatim.
// Returns the number of ids produced (may exceed max_out; only max_out are
// written), or -1 on error.
int64_t bpe_encode(void* h, const char* text, int64_t len, int32_t* out,
                   int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  if (!t) return -1;
  std::string s(text, static_cast<size_t>(len));
  std::vector<uint32_t> ids;
  size_t start = 0;
  while (start < s.size()) {
    // find earliest special-token occurrence (ties: longest special wins)
    size_t best_pos = std::string::npos, best_len = 0;
    uint32_t best_id = 0;
    for (size_t k = 0; k < t->specials.size(); k++) {
      size_t p = s.find(t->specials[k], start);
      if (p == std::string::npos) continue;
      if (p < best_pos ||
          (p == best_pos && t->specials[k].size() > best_len)) {
        best_pos = p;
        best_len = t->specials[k].size();
        best_id = t->special_ids[k];
      }
    }
    if (best_pos == std::string::npos) {
      encode_ordinary(t, s.substr(start), &ids);
      break;
    }
    if (best_pos > start)
      encode_ordinary(t, s.substr(start, best_pos - start), &ids);
    ids.push_back(best_id);
    start = best_pos + best_len;
  }
  int64_t n = static_cast<int64_t>(ids.size());
  for (int64_t i = 0; i < n && i < max_out; i++)
    out[i] = static_cast<int32_t>(ids[i]);
  return n;
}

// Decode ids to UTF-8 bytes. skip_special drops special ids. Returns byte
// count (may exceed max_out; only max_out bytes are written), or -1.
int64_t bpe_decode(void* h, const int32_t* ids, int64_t n, int skip_special,
                   char* out, int64_t max_out) {
  auto* t = static_cast<Tokenizer*>(h);
  if (!t) return -1;
  std::string s;
  for (int64_t i = 0; i < n; i++) {
    uint32_t id = static_cast<uint32_t>(ids[i]);
    if (id >= t->id_to_tok.size()) continue;
    if (skip_special) {
      bool is_sp = false;
      for (uint32_t sid : t->special_ids)
        if (sid == id) { is_sp = true; break; }
      if (is_sp) continue;
    }
    s += t->id_to_tok[id];
  }
  int64_t bytes = static_cast<int64_t>(s.size());
  if (bytes > 0) memcpy(out, s.data(), static_cast<size_t>(std::min(bytes, max_out)));
  return bytes;
}

}  // extern "C"
