"""Dataset preparation and R1-style prompting.

Parity with the reference's helper.py:3–23 and train_distributed.py:38–48:
MATH-500 "test" split, answer→solution rename, 90/10 split, system+user chat
template with ``add_generation_prompt=True``. Works with any HF tokenizer that
carries a chat template; falls back to a plain template for test tokenizers.
"""

from __future__ import annotations

from typing import Any, Iterator, Mapping, Sequence

import numpy as np

# Reference system prompt, verbatim contract (helper.py:3–9).
R1_PREPROMPT = (
    "A conversation between User and Assistant. The user asks a question, and the Assistant solves it.\n"
    "The assistant first thinks about the reasoning process and then provides the user with the answer.\n"
    "The response must follow this format:\n"
    "<think> reasoning process here </think>\n"
    "<answer> answer here </answer>\n"
)

_FALLBACK_TEMPLATE = (
    "{% for message in messages %}"
    "<|im_start|>{{ message['role'] }}\n{{ message['content'] }}<|im_end|>\n"
    "{% endfor %}"
    "{% if add_generation_prompt %}<|im_start|>assistant\n{% endif %}"
)


def build_chat_prompt(tokenizer, problem: str, preprompt: str = "", postprompt: str = "") -> str:
    """One problem → chat-templated prompt string (helper.py:12–21: system =
    preprompt, user = problem + ' ' + postprompt, generation prompt appended)."""
    messages = [
        {"role": "system", "content": preprompt},
        {"role": "user", "content": problem + " " + postprompt},
    ]
    kwargs = {}
    # Template-less tokenizers (tiny test tokenizers) get a ChatML-style
    # fallback passed per-call — the tokenizer object is never mutated.
    if getattr(tokenizer, "chat_template", None) is None:
        kwargs["chat_template"] = _FALLBACK_TEMPLATE
    return tokenizer.apply_chat_template(
        messages, add_generation_prompt=True, tokenize=False, **kwargs
    )


def process_dataset(tokenizer, dataset, preprompt: str = "", postprompt: str = ""):
    """Map the ``problem`` column through the chat template (helper.py:11–23).

    Accepts either an HF ``datasets.Dataset`` (uses .map) or a plain
    dict-of-lists (returns a new dict) so tests need no datasets dependency.
    """

    def _map(examples: Mapping[str, Sequence[str]]) -> dict[str, list[str]]:
        return {
            "problem": [
                build_chat_prompt(tokenizer, p, preprompt, postprompt)
                for p in examples["problem"]
            ]
        }

    if hasattr(dataset, "map"):
        return dataset.map(_map, batched=True)
    out = dict(dataset)
    out.update(_map(dataset))
    return out


def prepare_math500(dataset_name: str, tokenizer, test_size: float = 0.1, seed: int | None = None):
    """Load + split + template MATH-500 the way the reference CLI does
    (train_distributed.py:38–48): 'test' split only, answer→solution rename,
    train_test_split(0.1), chat templating on both splits."""
    from datasets import load_dataset  # deferred: heavy import

    raw = load_dataset(dataset_name)["test"]
    raw = raw.map(lambda x: {"solution": x["answer"]})
    raw = raw.remove_columns(["answer"])
    split = raw.train_test_split(test_size=test_size, seed=seed)
    train = process_dataset(tokenizer, split["train"], R1_PREPROMPT, "")
    test = process_dataset(tokenizer, split["test"], R1_PREPROMPT, "")
    return train, test


def extract_gsm8k_solution(answer: str) -> str:
    """GSM8K gold answers end with ``#### <number>`` — the graded solution is
    that number with thousands separators stripped (the community-standard
    extraction; the reward's exact-match contract then works unchanged)."""
    tail = answer.rsplit("####", 1)[-1] if "####" in answer else answer
    return tail.strip().replace(",", "").replace("$", "")


def prepare_gsm8k(dataset_name: str, tokenizer, test_size: float = 0.1,
                  seed: int | None = None):
    """Load + template GSM8K (BASELINE config 3's dataset). Unlike MATH-500
    (a single 'test' split the reference carves 90/10,
    train_distributed.py:44), GSM8K ships dedicated splits — training on its
    official 1,319-row test set would contaminate every published-accuracy
    comparison, so RL trains on the 7,473-row TRAIN split and evaluates on
    the untouched test split (``test_size`` is unused here; kept for the
    dispatcher's uniform signature)."""
    from datasets import load_dataset  # deferred: heavy import

    raw = load_dataset(dataset_name, "main")

    def remap(ds):
        ds = ds.map(
            lambda x: {
                "problem": x["question"],
                "solution": extract_gsm8k_solution(x["answer"]),
            }
        )
        return ds.remove_columns(
            [c for c in ("question", "answer") if c in ds.column_names]
        )

    train = process_dataset(tokenizer, remap(raw["train"]), R1_PREPROMPT, "")
    test = process_dataset(tokenizer, remap(raw["test"]), R1_PREPROMPT, "")
    return train, test


def prepare_dataset(dataset_name: str, tokenizer, test_size: float = 0.1,
                    seed: int | None = None):
    """Dispatch on the dataset id: GSM8K-style (question/#### answer) or
    MATH-500-style (problem/answer) preparation."""
    if "gsm8k" in dataset_name.lower():
        return prepare_gsm8k(dataset_name, tokenizer, test_size, seed)
    return prepare_math500(dataset_name, tokenizer, test_size, seed)


class DictDataset:
    """Minimal dict-of-lists dataset with the iteration surface the Trainer
    uses (``shuffle()`` / ``iter(batch_size)`` — distributed_trainer.py:245–246).
    Lets the trainer run on plain Python data (tests, offline hosts) and makes
    HF datasets optional rather than load-bearing."""

    def __init__(self, data: Mapping[str, Sequence[Any]], seed: int | None = None):
        lengths = {k: len(v) for k, v in data.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self.data = {k: list(v) for k, v in data.items()}
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return len(next(iter(self.data.values()), []))

    def __getitem__(self, key: str) -> list[Any]:
        return self.data[key]

    def shuffle(self, seed: int | None = None) -> "DictDataset":
        """Seedable like HF ``Dataset.shuffle(seed=...)`` — the trainer seeds
        each episode's shuffle deterministically so a mid-episode resume can
        re-derive the same batch order and skip what was already trained."""
        rng = np.random.default_rng(seed) if seed is not None else self._rng
        perm = rng.permutation(len(self))
        shuffled = {k: [v[i] for i in perm] for k, v in self.data.items()}
        out = DictDataset(shuffled)
        out._rng = self._rng
        return out

    def iter(self, batch_size: int) -> Iterator[dict[str, list[Any]]]:
        for start in range(0, len(self), batch_size):
            yield {k: v[start : start + batch_size] for k, v in self.data.items()}

    @staticmethod
    def wrap(dataset) -> "DictDataset | Any":
        """Pass HF datasets through untouched; wrap mappings."""
        if hasattr(dataset, "iter") and hasattr(dataset, "shuffle"):
            return dataset
        return DictDataset(dataset)
