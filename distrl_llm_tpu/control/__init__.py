"""Closed-loop control subsystem (ISSUE 14): bounded governors that act on
the observability plane.

PRs 8/10/13 made the system *measure* everything — HBM watermarks, policy
lag, TTFT/queue-wait SLOs, per-worker throughput — but every control knob
stayed a static CLI flag and the Sentinel could only dump a flight-recorder
bundle and keep going. This package closes the loops:

* :mod:`~distrl_llm_tpu.control.governor` — the framework: bounded
  actuators (hard min/max clamps), hysteretic deadband governors with
  per-governor cooldowns and a sustained-headroom regrow dwell, a global
  per-run actuation budget, and the :class:`ControlLimits` handle the paged
  engine's admission loop consults (one attribute check when absent).
* :mod:`~distrl_llm_tpu.control.controllers` — the six concrete
  controllers: HBM admission governor, SLO load-shedder, staleness
  governor, worker-health actor, the nan-loss rollback, and the
  autoscaling governor (ISSUE 20) steering the elastic fleet's target
  pool size.

Everything defaults OFF behind ``--control`` / per-controller flags; a run
with controllers off is byte-identical to one without this package (the
engine hook is ``control_limits is None``). Every actuation is bounded,
counted (``control/*`` series), recorded in the flight-recorder ring, and
stamped as a Perfetto instant — the chaos gates in tests/test_control.py
and tools/control_smoke.py prove each loop converges and never oscillates.
"""

from distrl_llm_tpu.control.governor import (
    CONTROL_ACTIONS,
    CONTROL_BUDGET_EXHAUSTED,
    CONTROL_COOLDOWN_SKIPS,
    CONTROL_NAN_ROLLBACKS,
    CONTROL_SHED_ACTIVE,
    CONTROL_SHED_GROUPS,
    CONTROL_TRIGGER_ESCALATIONS,
    CONTROL_VALUE,
    BoundedActuator,
    ControlAction,
    ControlLimits,
    ControlRuntime,
    Governor,
)
from distrl_llm_tpu.control.controllers import (
    AutoscaleGovernor,
    HbmGovernor,
    NanRollbackController,
    SloShedGovernor,
    StalenessGovernor,
    WorkerHealthGovernor,
    attach_staleness,
    build_runtime,
    injected_nan_step,
)

__all__ = [
    "CONTROL_ACTIONS",
    "CONTROL_BUDGET_EXHAUSTED",
    "CONTROL_COOLDOWN_SKIPS",
    "CONTROL_NAN_ROLLBACKS",
    "CONTROL_SHED_ACTIVE",
    "CONTROL_SHED_GROUPS",
    "CONTROL_TRIGGER_ESCALATIONS",
    "CONTROL_VALUE",
    "BoundedActuator",
    "ControlAction",
    "ControlLimits",
    "ControlRuntime",
    "Governor",
    "AutoscaleGovernor",
    "HbmGovernor",
    "NanRollbackController",
    "SloShedGovernor",
    "StalenessGovernor",
    "WorkerHealthGovernor",
    "attach_staleness",
    "build_runtime",
    "injected_nan_step",
]
