"""Governor/actuator framework: bounded, hysteretic, cooldown-guarded
closed-loop control over the observability plane (ISSUE 14).

Design contract — every actuation is:

* **bounded** — actuators carry hard min/max clamps; a governor can never
  push a knob past them, whatever its signal does;
* **hysteretic** — a deadband ``[low, high]`` separates the shrink and
  regrow regions, and regrow additionally requires a *sustained-headroom
  dwell* (``dwell_steps`` consecutive below-band observations), so a signal
  hovering at the threshold cannot ping-pong the knob;
* **cooldown-guarded** — at most one action per governor per
  ``cooldown_steps`` steps (suppressions are counted, never silent);
* **budgeted** — a global per-run actuation budget on the runtime; an
  exhausted budget freezes every knob at its current (clamped) value and
  counts the suppression;
* **observable** — every action bumps ``control/actions``, lands a
  ``control_action`` record in the flight-recorder ring, sets the
  ``control/value/<actuator>`` gauge, and emits a Perfetto instant, so
  ``tools/trace_report.py`` renders a "control:" section.

The :class:`ControlLimits` handle is the engine-facing half: the paged
engine's continuous-admission loop consults it (chain cap scale + shed
flag) behind a single ``is not None`` attribute check, so a run without
controllers is byte-identical to one without this module.
"""

from __future__ import annotations

import logging
import math
import threading
from dataclasses import dataclass
from typing import Any, Callable, Mapping

from distrl_llm_tpu import telemetry

log = logging.getLogger(__name__)

# ------------------------------------------------------------- series names
# (pinned, with their types, in tests/test_telemetry.py; graftcheck GC2xx:
# this module is the single owner of every control/* name — the engine's
# shed counter and the trainer's rollback path reference these constants)

CONTROL_ACTIONS = "control/actions"              # counter: applied actuations
CONTROL_TRIGGER_ESCALATIONS = "control/trigger_escalations"  # counter:
#                                       sentinel trigger → governor handoffs
CONTROL_COOLDOWN_SKIPS = "control/cooldown_skips"  # counter: suppressed by
#                                                    a governor cooldown
CONTROL_BUDGET_EXHAUSTED = "control/budget_exhausted"  # counter: suppressed
#                                                 by the global run budget
CONTROL_SHED_GROUPS = "control/shed_groups"      # counter: groups whose
#                        admission the SLO shedder deferred at least once
#                        (emitted by the paged engine's admission loop)
CONTROL_SHED_ACTIVE = "control/shed_active"      # gauge: 0/1 shed state
CONTROL_NAN_ROLLBACKS = "control/nan_rollbacks"  # counter: restored steps
# per-actuator current-value gauges, derived as f"{CONTROL_VALUE}/<name>"
# (constant-prefix derivation, the serving/admission_stalls pattern)
CONTROL_VALUE = "control/value"

CONTROL_ACTION_INSTANT = "control/action"        # Perfetto instant name


@dataclass
class ControlAction:
    """One applied (or suppressed) actuation — the flight-recorder record
    and the unit the chaos gates count."""

    step: int
    controller: str
    actuator: str
    kind: str          # shrink | regrow | engage | release | quarantine | rollback
    old: float | None
    new: float | None
    reason: str
    trigger: str | None = None  # sentinel trigger that escalated, if any

    def to_dict(self) -> dict[str, Any]:
        return {
            "step": self.step, "controller": self.controller,
            "actuator": self.actuator, "kind": self.kind,
            "old": self.old, "new": self.new, "reason": self.reason,
            "trigger": self.trigger,
        }


class ControlLimits:
    """Thread-safe admission limits shared between governors (writers) and
    the paged engine's continuous-admission loop (reader).

    At defaults (``admission_frac=1.0``, ``shed=False``) every read is the
    identity — an engine holding a default handle makes byte-identical
    admission decisions to one holding ``None`` (pinned in
    tests/test_control.py)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._admission_frac = 1.0
        self._shed = False
        # class-aware shed floor (ISSUE 19): the lowest priority RANK the
        # shed gate still blocks — 0 blocks every class (the pre-gateway
        # behavior, and the only value non-gateway rounds ever read), 2
        # blocks only scavenger. Consulted by the engine ONLY on rounds
        # carrying gateway identity, so the default handle stays the
        # identity for everything else.
        self._shed_floor = 0

    # ---- governor side -----------------------------------------------

    @property
    def admission_frac(self) -> float:
        with self._mu:
            return self._admission_frac

    def set_admission_frac(self, frac: float) -> None:
        with self._mu:
            self._admission_frac = min(max(float(frac), 0.0), 1.0)

    def set_shed(self, active: bool, floor: int = 0) -> None:
        with self._mu:
            self._shed = bool(active)
            self._shed_floor = max(0, int(floor)) if active else 0

    # ---- engine side -------------------------------------------------

    def chain_cap(self, base: int) -> int:
        """The continuous-admission live prefix-chain cap, scaled by the
        HBM governor's admission fraction (never below 1 — the engine must
        always be able to make progress)."""
        with self._mu:
            frac = self._admission_frac
        return max(1, math.ceil(base * frac))

    def shed_active(self) -> bool:
        with self._mu:
            return self._shed

    def shed_floor(self) -> int:
        """Lowest priority rank the active shed gate blocks (0 = all
        classes; meaningful only while ``shed_active()``)."""
        with self._mu:
            return self._shed_floor


@dataclass
class BoundedActuator:
    """One clamped knob. ``apply(new_value)`` pushes the value into the
    plant (a ControlLimits field, a StalenessPolicy attribute, a buffer
    watermark); ``shrink``/``regrow`` compute the next candidate value —
    the clamp is enforced here, not trusted to the governor."""

    name: str
    value: float
    min_value: float
    max_value: float
    apply: Callable[[float], None]
    shrink: Callable[[float], float]
    regrow: Callable[[float], float]
    integer: bool = False

    def clamp(self, v: float) -> float:
        v = min(max(v, self.min_value), self.max_value)
        return float(int(v)) if self.integer else v


class ControlRuntime:
    """One per process: owns the registered governors, the global actuation
    budget, the action log, and the sentinel trigger → governor map."""

    def __init__(self, *, budget: int = 64, recorder=None,
                 limits: ControlLimits | None = None):
        if budget < 1:
            raise ValueError(f"control budget must be >= 1, got {budget}")
        self.budget = int(budget)
        self.recorder = recorder  # obs.FlightRecorder | None
        self.limits = limits
        self.governors: list[Any] = []
        self._trigger_map: dict[str, Any] = {}
        self.actions: list[ControlAction] = []  # applied only, bounded
        self.actions_taken = 0
        self._budget_warned = False
        self._mu = threading.Lock()
        # the nan-loss rollback controller is step-inline (the trainer
        # consults it between the train step and the weight push), not a
        # per-step governor — it hangs here so one handle owns the budget
        self.nan: Any = None

    # ------------------------------------------------------------ registry

    def register(self, governor, *, triggers: tuple[str, ...] = ()) -> None:
        self.governors.append(governor)
        for trig in triggers:
            self._trigger_map[trig] = governor

    def governor(self, name: str):
        for g in self.governors:
            if getattr(g, "name", None) == name:
                return g
        return None

    # ------------------------------------------------------------- budget

    def budget_left(self) -> int:
        with self._mu:
            return max(self.budget - self.actions_taken, 0)

    def _consume_budget(self) -> bool:
        with self._mu:
            if self.actions_taken >= self.budget:
                telemetry.counter_add(CONTROL_BUDGET_EXHAUSTED)
                if not self._budget_warned:
                    self._budget_warned = True
                    log.warning(
                        "control actuation budget (%d) exhausted — every "
                        "knob frozen at its current value for the rest of "
                        "the run", self.budget,
                    )
                return False
            self.actions_taken += 1
            return True

    # ------------------------------------------------------------- acting

    def act(self, action: ControlAction,
            apply: Callable[[], None] | None = None,
            free: bool = False) -> bool:
        """Apply one actuation under the global budget. Returns True when
        the action was applied (False = budget-suppressed; the plant is
        untouched). Every applied action is counted, ring-recorded, traced
        as an instant, and logged in one line.

        ``free=True`` bypasses the budget WITHOUT consuming it — reserved
        for actions that restore the safe/default state (the shed
        RELEASE): an exhausted budget must freeze knobs where they are,
        never pin the system in a degraded mode it can no longer leave.
        Free actions are bounded by the budgeted actions that created the
        state they undo (a release per engage), so they cannot run away."""
        if not free and not self._consume_budget():
            return False
        if apply is not None:
            apply()
        with self._mu:
            self.actions.append(action)
            if len(self.actions) > 4096:  # bounded in-memory log
                del self.actions[:2048]
        telemetry.counter_add(CONTROL_ACTIONS)
        if action.new is not None:
            telemetry.gauge_set(
                f"{CONTROL_VALUE}/{action.actuator}", float(action.new)
            )
        telemetry.emit_instant(CONTROL_ACTION_INSTANT, **action.to_dict())
        if self.recorder is not None:
            self.recorder.record("control_action", action.to_dict())
        log.warning(
            "control action [%s] %s.%s %s -> %s at step %d (%s)",
            action.kind, action.controller, action.actuator,
            action.old, action.new, action.step, action.reason,
        )
        return True

    def note_cooldown_skip(self) -> None:
        telemetry.counter_add(CONTROL_COOLDOWN_SKIPS)

    # -------------------------------------------------------------- steps

    def on_step(self, step: int, metrics: Mapping[str, Any]) -> list[ControlAction]:
        """One control pass over the step's metrics record — the trainer
        calls this right after ``obs.on_step`` (the worker pump calls it
        between generation rounds)."""
        applied: list[ControlAction] = []
        for gov in self.governors:
            try:
                applied.extend(gov.step(step, metrics, self) or ())
            except Exception:  # noqa: BLE001 — a governor bug must degrade
                # to "knob stays put", never take the training loop down
                log.warning(
                    "governor %s failed on step %d",
                    getattr(gov, "name", gov), step, exc_info=True,
                )
        return applied

    def on_trigger(self, trigger: str, step: int,
                   extra: Mapping[str, Any] | None = None) -> bool:
        """Sentinel trigger escalation (exactly once per trigger per run —
        the Sentinel's own fire-once contract). Returns True when a
        registered governor acted on it; False leaves the trigger
        dump-only (the PR 8 contract for un-armed controllers)."""
        gov = self._trigger_map.get(trigger)
        if gov is None:
            return False
        telemetry.counter_add(CONTROL_TRIGGER_ESCALATIONS)
        try:
            return bool(gov.on_trigger(trigger, step, self, extra or {}))
        except Exception:  # noqa: BLE001 — same degrade-don't-crash rule
            log.warning(
                "trigger escalation %r -> %s failed", trigger,
                getattr(gov, "name", gov), exc_info=True,
            )
            return False


def cooldown_ok(gov, step: int, runtime: ControlRuntime) -> bool:
    """THE cooldown check, shared by every governor shape (the deadband
    base below and the stateful shed/worker-health controllers): one
    owner of the suppress-and-count semantics, so the governors cannot
    drift apart. ``gov`` needs ``_last_action_step`` and
    ``cooldown_steps``."""
    if (
        gov._last_action_step is not None
        and step - gov._last_action_step < gov.cooldown_steps
    ):
        runtime.note_cooldown_skip()
        return False
    return True


class Governor:
    """Deadband + hysteresis + cooldown base for scalar-signal governors.

    Subclasses implement :meth:`read` (the signal, or None when there is no
    observation this step). Semantics per step:

    * signal **above** ``high`` → shrink every actuator one step (subject
      to the cooldown and the runtime budget); the regrow dwell resets.
    * signal **below** ``low`` for ``dwell_steps`` consecutive
      observations → regrow one step (the sustained-headroom dwell); the
      dwell restarts after every regrow action.
    * signal **inside** the deadband → hold (hysteresis: neither shrink
      nor dwell credit), so a breach recovers to *stable*, not to the edge
      of the next breach.
    """

    # escalation semantics for sentinel triggers: one immediate shrink
    ESCALATE_KIND = "shrink"

    def __init__(self, name: str, *, actuators: list[BoundedActuator],
                 high: float, low: float, cooldown_steps: int = 2,
                 dwell_steps: int = 3):
        if low > high:
            raise ValueError(
                f"deadband low ({low}) must be <= high ({high})"
            )
        if cooldown_steps < 0 or dwell_steps < 1:
            raise ValueError(
                "cooldown_steps must be >= 0 and dwell_steps >= 1"
            )
        self.name = name
        self.actuators = actuators
        self.high = float(high)
        self.low = float(low)
        self.cooldown_steps = int(cooldown_steps)
        self.dwell_steps = int(dwell_steps)
        self._last_action_step: int | None = None
        self._ok_run = 0
        self.last_signal: float | None = None

    # ------------------------------------------------------------- signal

    def read(self, step: int, metrics: Mapping[str, Any]) -> float | None:
        raise NotImplementedError

    # ------------------------------------------------------------ helpers

    def _cooled(self, step: int, runtime: ControlRuntime) -> bool:
        return cooldown_ok(self, step, runtime)

    def _move(self, step: int, runtime: ControlRuntime, kind: str,
              reason: str, trigger: str | None = None) -> list[ControlAction]:
        """One shrink/regrow pass over every actuator (they move in
        lockstep — a governor's knobs express one decision, so the move
        is all-or-nothing: a budget that cannot cover every pending knob
        applies NONE of them, rather than leaving the knobs permanently
        diverged when the exhausted budget then freezes everything)."""
        moves: list[tuple[BoundedActuator, float]] = []
        for act in self.actuators:
            fn = act.shrink if kind == "shrink" else act.regrow
            new = act.clamp(fn(act.value))
            if new != act.value:  # at the clamp already = no move needed
                moves.append((act, new))
        if not moves:
            return []
        if runtime.budget_left() < len(moves):
            telemetry.counter_add(CONTROL_BUDGET_EXHAUSTED)
            return []
        applied: list[ControlAction] = []
        for act, new in moves:
            action = ControlAction(
                step=step, controller=self.name, actuator=act.name,
                kind=kind, old=act.value, new=new, reason=reason,
                trigger=trigger,
            )
            old = act.value

            def push(act=act, new=new):
                act.value = new
                act.apply(new)

            if runtime.act(action, apply=push):
                applied.append(action)
            else:
                # cannot happen single-threaded (the reservation above);
                # defensive against a concurrent budget consumer
                act.value = old
                break
        if applied:
            self._last_action_step = step
            self._ok_run = 0
        return applied

    # --------------------------------------------------------------- step

    def step(self, step: int, metrics: Mapping[str, Any],
             runtime: ControlRuntime) -> list[ControlAction]:
        v = self.read(step, metrics)
        if v is None:
            return []
        self.last_signal = v
        if v > self.high:
            self._ok_run = 0
            if not self._cooled(step, runtime):
                return []
            return self._move(
                step, runtime, "shrink",
                f"signal {v:.4g} > high {self.high:.4g}",
            )
        if v < self.low:
            self._ok_run += 1
            if self._ok_run < self.dwell_steps:
                return []
            if any(a.value < a.max_value for a in self.actuators):
                if not self._cooled(step, runtime):
                    return []
                return self._move(
                    step, runtime, "regrow",
                    f"signal {v:.4g} < low {self.low:.4g} for "
                    f"{self._ok_run} steps (dwell {self.dwell_steps})",
                )
            return []
        # inside the deadband: hysteresis hold — no shrink, no dwell credit
        self._ok_run = 0
        return []

    def on_trigger(self, trigger: str, step: int, runtime: ControlRuntime,
                   extra: Mapping[str, Any]) -> bool:
        """Sentinel escalation: one immediate bounded shrink, still subject
        to the cooldown and the budget (an escalation is urgent, not
        exempt)."""
        self._ok_run = 0
        if not self._cooled(step, runtime):
            return False
        return bool(self._move(
            step, runtime, self.ESCALATE_KIND,
            f"sentinel trigger {trigger!r}", trigger=trigger,
        ))
