"""The six concrete controllers of the self-healing runtime (ISSUE 14,
ISSUE 20).

Each one closes a loop the observability plane already measures:

========================  =============================  ====================
signal                    actuator                       bounds
========================  =============================  ====================
HBM peak / limit          continuous-admission chain     admission_frac in
(obs.hbm_stats, the       cap scale (ControlLimits.      [0.1, 1.0]; shrink
DISTRL_OBS_FAKE_HBM       admission_frac)                x0.5, regrow +0.25
hook in tests)                                           after the dwell
serving TTFT/queue-wait   admit_groups shed gate         shed bounded by
vs the PR 13 SLOs         (ControlLimits.shed; the       shed_max_steps,
                          engine declines with the       release after the
                          "shed" reason)                 recovery dwell
lineage/policy_lag_ms     effective max_staleness +      K in [1, configured
p90 vs the lag target     buffer high watermark          K]; watermark >=
                                                         2x batch pull
per-worker tok/s vs its   DriverClient.quarantine_       never below
own EMA                   worker (PR 5 rejoin loop       min_healthy; per-
                          probes + re-admits)            worker cooldown
non-finite loss           restore last-good (adapter,    max_rollbacks per
                          opt state, version) snapshot   run
serving queue-wait /      FleetSupervisor.scale_to       target in
learner idle (up), per-   (add_worker cold joins /       [fleet_min,
worker tok/s (down)       retire_worker drains)          fleet_max]; +-1 per
                                                         action, dwell down
========================  =============================  ====================

Every controller rides the governor framework's cooldown/budget/clamp
discipline and is chaos-gated in tests/test_control.py (seeded breach →
bounded actuation → signal back inside the deadband → no oscillation
across the dwell window) plus tools/control_smoke.py end-to-end.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Mapping

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.control.governor import (
    CONTROL_BUDGET_EXHAUSTED,
    CONTROL_NAN_ROLLBACKS,
    CONTROL_SHED_ACTIVE,
    BoundedActuator,
    ControlAction,
    ControlLimits,
    ControlRuntime,
    Governor,
    cooldown_ok,
)

log = logging.getLogger(__name__)

# seeded chaos injection for the nan-loss rollback gate (the sentinel's
# DISTRL_SENTINEL_INJECT fakes the *metric*; the rollback controller acts
# on the *actual* loss, so its gate needs the loss itself poisoned): the
# trainer reads this once and overrides the realized loss with NaN at the
# named step — tools/control_smoke.py's rollback gate drives it
CONTROL_INJECT_NAN_ENV = "DISTRL_CONTROL_INJECT_NAN"


def injected_nan_step() -> int | None:
    """Step at which the chaos harness poisons the realized loss, or None."""
    spec = os.environ.get(CONTROL_INJECT_NAN_ENV)
    if not spec:
        return None
    try:
        return int(spec)
    except ValueError:
        log.warning(
            "ignoring %s=%r (expected an integer step)",
            CONTROL_INJECT_NAN_ENV, spec,
        )
        return None


# ------------------------------------------------------------ HBM governor


class HbmGovernor(Governor):
    """Shrinks the continuous-admission chain cap under HBM pressure.

    Signal: device ``bytes_in_use / bytes_limit`` from
    :func:`obs.hbm_stats` (honors the ``DISTRL_OBS_FAKE_HBM`` test hook);
    None on backends without memory stats — the governor is then inert.
    LIVE bytes, not ``peak_bytes_in_use``: the peak is a lifetime
    high-watermark that never resets, so steering on it would turn one
    recovered spike (an XLA compile workspace, say) into a permanent
    one-way ratchet — shrink forever, regrow never. The sentinel's
    ``hbm_breach`` keeps the peak (an incident HAPPENED is exactly its
    semantics); this governor needs the signal that tracks recovery.
    Deadband defaults sit BELOW the sentinel's 0.95 threshold, so the
    governor degrades gracefully before the incident trigger would fire;
    the ``hbm_breach`` escalation is the immediate shrink when it fires
    anyway."""

    def __init__(self, limits: ControlLimits, *, high: float = 0.85,
                 low: float = 0.70, min_frac: float = 0.1,
                 cooldown_steps: int = 2, dwell_steps: int = 3,
                 stats_fn: Callable[[], Mapping[str, float] | None] | None = None):
        self.limits = limits
        if stats_fn is None:
            from distrl_llm_tpu import obs as obs_mod

            stats_fn = obs_mod.hbm_stats
        self._stats_fn = stats_fn
        super().__init__(
            "hbm",
            actuators=[BoundedActuator(
                name="admission_frac", value=1.0,
                min_value=float(min_frac), max_value=1.0,
                apply=limits.set_admission_frac,
                shrink=lambda v: v * 0.5,
                regrow=lambda v: v + 0.25,
            )],
            high=high, low=low,
            cooldown_steps=cooldown_steps, dwell_steps=dwell_steps,
        )

    def read(self, step: int, metrics: Mapping[str, Any]) -> float | None:
        stats = self._stats_fn()
        if not stats or not stats.get("bytes_limit"):
            return None
        live = stats.get("bytes_in_use")
        if live is None:
            # fall back only when the KEY is absent (an honest 0.0 live
            # reading must not resurrect the never-regrowing peak):
            # backends exposing only the peak get a conservative signal
            # rather than a blind governor
            live = stats.get("peak_bytes_in_use", 0.0)
        return float(live) / float(stats["bytes_limit"])


# ---------------------------------------------------------- SLO load-shed


class SloShedGovernor:
    """Throttles ``admit_groups`` when serving latency breaches the PR 13
    SLOs: while shed is engaged the continuous-admission loop declines new
    group admissions with the ``shed`` reason (candidates of already-
    admitted groups keep filling slots, so the engine drains rather than
    starves).

    Signal: the step's worst observed ``serving/ttft_ms`` /
    ``serving/queue_wait_ms`` (the per-step registry hist max, or the
    fleet-folded worker max — the same keys the sentinel's SLO triggers
    read), normalized by its SLO. Engage above 1.0; release after the
    signal stays under ``release_frac`` for ``dwell_steps`` consecutive
    steps, or unconditionally after ``shed_max_steps`` (shed is a bounded
    action, never a permanent starvation mode). A step with no latency
    observation counts as healthy while shed (no new admissions means no
    new samples — that IS the recovery)."""

    ESCALATE_KIND = "engage"

    def __init__(self, limits: ControlLimits, *,
                 slo_ttft_ms: float | None = None,
                 slo_queue_wait_ms: float | None = None,
                 release_frac: float = 0.7, cooldown_steps: int = 2,
                 dwell_steps: int = 2, shed_max_steps: int = 8,
                 class_aware: bool = False):
        if slo_ttft_ms is None and slo_queue_wait_ms is None:
            raise ValueError(
                "SloShedGovernor needs at least one SLO "
                "(slo_ttft_ms / slo_queue_wait_ms) to steer on"
            )
        if not 0.0 < release_frac <= 1.0:
            raise ValueError(
                f"release_frac must be in (0, 1], got {release_frac}"
            )
        if shed_max_steps < 1:
            raise ValueError(
                f"shed_max_steps must be >= 1, got {shed_max_steps}"
            )
        self.name = "slo_shed"
        self.limits = limits
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_queue_wait_ms = slo_queue_wait_ms
        self.release_frac = float(release_frac)
        self.cooldown_steps = int(cooldown_steps)
        self.dwell_steps = int(dwell_steps)
        self.shed_max_steps = int(shed_max_steps)
        # class-aware shedding (ISSUE 19, gateway rounds): engage at floor
        # 2 (scavenger only) and escalate to floor 1 (batch too) if the
        # breach persists one dwell past the engage — NEVER floor 0, so
        # interactive admissions are untouchable by this governor. Without
        # class_aware the floor is pinned 0 (the pre-gateway semantics,
        # and what non-gateway rounds read regardless).
        self.class_aware = bool(class_aware)
        self.shed_floor = 0
        self.shed = False
        self._shed_since: int | None = None
        self._ok_run = 0
        self._last_action_step: int | None = None
        self.last_signal: float | None = None
        telemetry.gauge_set(CONTROL_SHED_ACTIVE, 0.0)

    def read(self, step: int, metrics: Mapping[str, Any]) -> float | None:
        from distrl_llm_tpu.serving_obs import (
            FLEET_SERVING_QUEUE_WAIT_MAX_MS,
            FLEET_SERVING_TTFT_MAX_MS,
            SERVING_QUEUE_WAIT_MS,
            SERVING_TTFT_MS,
        )

        ratios: list[float] = []
        for slo, keys in (
            (self.slo_ttft_ms,
             (SERVING_TTFT_MS + "_max", FLEET_SERVING_TTFT_MAX_MS)),
            (self.slo_queue_wait_ms,
             (SERVING_QUEUE_WAIT_MS + "_max",
              FLEET_SERVING_QUEUE_WAIT_MAX_MS)),
        ):
            if slo is None:
                continue
            vals = [float(metrics[k]) for k in keys
                    if metrics.get(k) is not None]
            if vals:
                ratios.append(max(vals) / float(slo))
        return max(ratios) if ratios else None

    def _cooled(self, step: int, runtime: ControlRuntime) -> bool:
        return cooldown_ok(self, step, runtime)

    def _transition(self, step: int, runtime: ControlRuntime,
                    engage: bool, reason: str,
                    trigger: str | None = None) -> list[ControlAction]:
        action = ControlAction(
            step=step, controller=self.name, actuator="shed",
            kind="engage" if engage else "release",
            old=float(self.shed), new=float(engage), reason=reason,
            trigger=trigger,
        )

        def push():
            self.shed = engage
            self.shed_floor = (
                2 if (engage and self.class_aware) else 0
            )
            self.limits.set_shed(engage, floor=self.shed_floor)
            telemetry.gauge_set(CONTROL_SHED_ACTIVE, float(engage))

        # a RELEASE restores the default state and is budget-FREE: an
        # exhausted budget blocking it would leave shed engaged forever —
        # the exact permanent-starvation mode shed_max_steps exists to
        # prevent (the engage that created the state paid the budget)
        if runtime.act(action, apply=push, free=not engage):
            self._last_action_step = step
            self._shed_since = step if engage else None
            self._ok_run = 0
            return [action]
        return []

    def step(self, step: int, metrics: Mapping[str, Any],
             runtime: ControlRuntime) -> list[ControlAction]:
        v = self.read(step, metrics)
        self.last_signal = v
        if not self.shed:
            if v is not None and v > 1.0 and self._cooled(step, runtime):
                return self._transition(
                    step, runtime, True,
                    f"latency at {v:.3g}x its SLO",
                )
            return []
        # shed engaged: bounded duration first, then the recovery dwell
        if (
            self._shed_since is not None
            and step - self._shed_since >= self.shed_max_steps
        ):
            return self._transition(
                step, runtime, False,
                f"shed_max_steps ({self.shed_max_steps}) reached",
            )
        if v is None or v < self.release_frac:
            self._ok_run += 1
            if self._ok_run >= self.dwell_steps and self._cooled(
                step, runtime
            ):
                return self._transition(
                    step, runtime, False,
                    f"latency back under {self.release_frac:.2g}x SLO for "
                    f"{self._ok_run} steps",
                )
        else:
            self._ok_run = 0
            if (
                self.class_aware and self.shed_floor == 2
                and v is not None and v > 1.0
                and self._shed_since is not None
                and step - self._shed_since >= self.dwell_steps
                and self._cooled(step, runtime)
            ):
                # persistent breach with scavenger already shed: widen to
                # batch (floor 1). Interactive stays admitted — floor 0 is
                # unreachable for a class-aware shedder.
                action = ControlAction(
                    step=step, controller=self.name, actuator="shed",
                    kind="engage", old=2.0, new=1.0,
                    reason=f"latency still {v:.3g}x SLO with scavenger "
                           f"shed: widening shed to batch",
                )

                def widen():
                    self.shed_floor = 1
                    self.limits.set_shed(True, floor=1)

                if runtime.act(action, apply=widen):
                    self._last_action_step = step
                    return [action]
        return []

    def on_trigger(self, trigger: str, step: int, runtime: ControlRuntime,
                   extra: Mapping[str, Any]) -> bool:
        """ttft_blowup / queue_wait_blowup escalation: immediate engage."""
        if self.shed:
            return False  # already shedding — the trigger adds nothing
        if not self._cooled(step, runtime):
            return False
        return bool(self._transition(
            step, runtime, True, f"sentinel trigger {trigger!r}",
            trigger=trigger,
        ))


# ------------------------------------------------------ staleness governor


class StalenessGovernor(Governor):
    """Adapts the async regime's effective staleness bound and buffer
    backpressure from the realized ``lineage/policy_lag_ms`` distribution
    (async mode only; the drop/downweight admission semantics are
    untouched — only the effective K and the high watermark move, both
    clamped inside their configured values).

    Signal: the step's ``lineage/policy_lag_ms_p90`` from the registry
    snapshot riding the metrics record (None on steps where no lag closed
    — the dwell holds). High lag shrinks K (fresher admissions) and the
    buffer's high watermark (less queued backlog — the backlog IS most of
    the lag); sustained low lag regrows both toward their configured
    values."""

    def __init__(self, policy, buffer, *, lag_target_ms: float,
                 batch_size: int, cooldown_steps: int = 2,
                 dwell_steps: int = 3):
        if lag_target_ms <= 0:
            raise ValueError(
                f"lag_target_ms must be > 0, got {lag_target_ms}"
            )
        self.policy = policy
        self.buffer = buffer
        k_max = int(policy.max_staleness)
        wm_max = int(buffer.high_watermark)
        # the buffer floor keeps the documented async invariant: a
        # get_batch(batch_size) must stay satisfiable below the
        # backpressure gate, or learner and producer deadlock
        wm_min = min(max(2 * int(batch_size), 1), wm_max)

        def apply_k(v: float) -> None:
            policy.max_staleness = int(v)

        def apply_wm(v: float) -> None:
            buffer.set_watermarks(int(v))

        super().__init__(
            "staleness",
            actuators=[
                BoundedActuator(
                    name="max_staleness", value=float(k_max),
                    min_value=1.0, max_value=float(max(k_max, 1)),
                    apply=apply_k,
                    shrink=lambda v: max(v // 2, 1.0),
                    regrow=lambda v: v + 1.0,
                    integer=True,
                ),
                BoundedActuator(
                    name="buffer_high_watermark", value=float(wm_max),
                    min_value=float(wm_min), max_value=float(wm_max),
                    apply=apply_wm,
                    shrink=lambda v: v // 2,
                    regrow=lambda v: v + max(float(wm_max) / 4.0, 1.0),
                    integer=True,
                ),
            ],
            high=float(lag_target_ms), low=0.5 * float(lag_target_ms),
            cooldown_steps=cooldown_steps, dwell_steps=dwell_steps,
        )

    def read(self, step: int, metrics: Mapping[str, Any]) -> float | None:
        from distrl_llm_tpu.lineage import POLICY_LAG_MS

        v = metrics.get(POLICY_LAG_MS + "_p90")
        return float(v) if v is not None else None


# ---------------------------------------------------- worker-health actor


class WorkerHealthGovernor:
    """Converts a per-worker tok/s regression into proactive quarantine:
    the worker is demoted (``DriverClient.quarantine_worker``) so
    dispatches avoid it, and the PR 5 rejoin loop PING-probes and
    re-admits it (cold) once it answers again — recovery is automatic, the
    actor only decides *when to stop trusting* a live-but-degraded worker
    instead of waiting for a hard failure.

    Signal: per-worker token rates derived from the fleet view's
    cumulative ``gen_tokens`` marks (the FleetAggregator's own math),
    each tracked against its own EMA — the same regression definition the
    sentinel applies to the whole engine, per worker. Bounds: never below
    ``min_healthy`` healthy workers (enforced here AND by the driver), a
    per-worker re-quarantine cooldown, and the runtime's global budget."""

    def __init__(self, driver, fleet_provider: Callable[[], Mapping | None],
                 *, drop_frac: float = 0.5, warmup_obs: int = 3,
                 ema_alpha: float = 0.3, cooldown_steps: int = 8,
                 min_healthy: int = 1):
        if not 0.0 < drop_frac < 1.0:
            raise ValueError(f"drop_frac must be in (0, 1), got {drop_frac}")
        self.name = "worker_health"
        self.driver = driver
        self.fleet_provider = fleet_provider
        self.drop_frac = float(drop_frac)
        self.warmup_obs = int(warmup_obs)
        self.ema_alpha = float(ema_alpha)
        self.cooldown_steps = int(cooldown_steps)
        self.min_healthy = max(int(min_healthy), 1)
        # per-worker (ts, cumulative tokens) marks + rate EMA + obs count
        self._marks: dict[str, tuple[float, float]] = {}
        self._ema: dict[str, float] = {}
        self._obs: dict[str, int] = {}
        self._pids: dict[str, Any] = {}
        self._last_q_step: dict[str, int] = {}
        self.last_rates: dict[str, float] = {}

    def _reset_track(self, addr: str) -> None:
        self._ema.pop(addr, None)
        self._obs.pop(addr, None)
        self._marks.pop(addr, None)

    def _rates(self) -> dict[str, float]:
        fleet = None
        try:
            fleet = self.fleet_provider()
        except Exception:  # noqa: BLE001 — a failed refresh is a skipped obs
            log.warning("fleet refresh failed in worker-health governor",
                        exc_info=True)
        rates: dict[str, float] = {}
        if not fleet:
            return rates
        # only CURRENTLY healthy, warm workers are judged: a dead worker's
        # counter stalls (the fleet cumulative never regresses by design),
        # and judging the stall — or a freshly rejoined worker's
        # recompile window — against the healthy EMA would quarantine the
        # recovery itself. Unknown state (no workers list) judges all.
        workers = fleet.get("workers")
        healthy = (
            {w.get("address") for w in workers
             if w.get("healthy") and not w.get("cold")}
            if workers is not None else None
        )
        for addr, rec in (fleet.get("worker_metrics") or {}).items():
            pid = rec.get("pid")
            last_pid = self._pids.get(addr)
            self._pids[addr] = pid
            if pid is not None and last_pid is not None and pid != last_pid:
                # restart: the EXACT incarnation signal (the cumulative
                # total deliberately never regresses, so a delta check
                # cannot see this) — start the track over
                self._reset_track(addr)
            if healthy is not None and addr not in healthy:
                self._reset_track(addr)
                continue
            tokens = float(rec.get("gen_tokens", 0.0))
            ts = float(rec.get("ts", time.time()))
            mark = self._marks.get(addr)
            self._marks[addr] = (ts, tokens)
            if mark is None or ts <= mark[0]:
                continue
            delta = tokens - mark[1]
            if delta < 0:
                # defensive: a raw regression means our mark predates
                # some reset the pid check missed — start over
                self._reset_track(addr)
                continue
            rates[addr] = delta / (ts - mark[0])
        self.last_rates = rates
        return rates

    def _scan(self, step: int, runtime: ControlRuntime, *,
              trigger: str | None, reason_prefix: str) -> list[ControlAction]:
        applied: list[ControlAction] = []
        for addr, rate in self._rates().items():
            ema = self._ema.get(addr)
            n = self._obs.get(addr, 0) + 1
            self._obs[addr] = n
            if ema is None:
                self._ema[addr] = rate
                continue
            regressed = (
                n > self.warmup_obs and rate < self.drop_frac * ema
            )
            # EMA updates regardless (the sentinel's ordering): a genuine
            # slow fade tracks down with the EMA instead of re-triggering
            self._ema[addr] = (
                self.ema_alpha * rate + (1 - self.ema_alpha) * ema
            )
            if not regressed:
                continue
            last_q = self._last_q_step.get(addr)
            if last_q is not None and step - last_q < self.cooldown_steps:
                runtime.note_cooldown_skip()
                continue
            if runtime.budget_left() <= 0:
                # checked BEFORE touching the driver: a quarantine the
                # budget cannot account for must not happen at all
                telemetry.counter_add(CONTROL_BUDGET_EXHAUSTED)
                break
            if not self.driver.quarantine_worker(
                addr, min_healthy=self.min_healthy
            ):
                continue  # refused (min_healthy / already unhealthy)
            action = ControlAction(
                step=step, controller=self.name, actuator=f"worker:{addr}",
                kind="quarantine", old=round(ema, 1), new=round(rate, 1),
                reason=(
                    f"{reason_prefix}: {rate:.1f} tok/s < "
                    f"{self.drop_frac:.2g} x EMA {ema:.1f}"
                ),
                trigger=trigger,
            )
            if runtime.act(action):
                self._last_q_step[addr] = step
                # quarantine resets the track: the rejoined worker's
                # post-recompile rate must not be judged against its
                # pre-quarantine EMA
                self._ema.pop(addr, None)
                self._obs.pop(addr, None)
                applied.append(action)
        return applied

    def step(self, step: int, metrics: Mapping[str, Any],
             runtime: ControlRuntime) -> list[ControlAction]:
        return self._scan(
            step, runtime, trigger=None, reason_prefix="tok/s regression"
        )

    def on_trigger(self, trigger: str, step: int, runtime: ControlRuntime,
                   extra: Mapping[str, Any]) -> bool:
        """tok_s_regression escalation: an immediate per-worker scan —
        the engine-wide EMA regressed, find the laggard now."""
        return bool(self._scan(
            step, runtime, trigger=trigger,
            reason_prefix=f"sentinel {trigger!r} scan",
        ))


# ---------------------------------------------------- autoscale governor


class AutoscaleGovernor:
    """Elastic pool sizing (ISSUE 20): steers the FleetSupervisor's
    ``scale_to`` actuator over target worker count [fleet_min, fleet_max].

    Signals, each normalized by its threshold so the deadband math is
    unitless (the SloShedGovernor convention):

    * **up-pressure** — the step's worst observed serving queue wait
      (``serving/queue_wait_ms_max`` or the fleet-folded worker max) over
      ``queue_wait_high_ms``, and ``obs/learner_idle_frac`` over
      ``idle_high``. Load ratio > 1.0 scales up one worker (cooldown- and
      budget-guarded): spawn + cold admission through
      ``engine.add_worker`` with a full-tensor weight-bus resync.
    * **down-pressure** — per-worker tok/s (rate EMAs derived from the
      fleet view's cumulative ``gen_tokens`` marks, the WorkerHealth
      math) below ``tok_s_low`` while the up-signal sits under
      ``release_frac`` (hysteresis: the band between ``release_frac`` and
      1.0 holds), sustained for ``dwell_steps`` consecutive observations.
      Scale-down retires the *least-productive* worker (lowest rate EMA)
      through the graceful-drain path. ``tok_s_low=None`` disables
      scale-down entirely — absence of load is never, by itself, a reason
      to shrink (and the armed-but-quiescent run stays byte-identical to
      controllers-off, the PR 14 pin).

    Every step also pumps ``supervisor.poll()`` — death observation and
    bounded respawn ride the control pass, so a preemption during a scale
    event converges back to the target without a separate watchdog."""

    ESCALATE_KIND = "scale_up"

    def __init__(self, supervisor, fleet_provider: Callable[[], Mapping | None] | None,
                 *, min_workers: int, max_workers: int,
                 queue_wait_high_ms: float | None = None,
                 idle_high: float | None = None,
                 tok_s_low: float | None = None,
                 release_frac: float = 0.7, ema_alpha: float = 0.3,
                 cooldown_steps: int = 4, dwell_steps: int = 3):
        if not (1 <= int(min_workers) <= int(max_workers)):
            raise ValueError(
                f"need 1 <= min_workers <= max_workers, got "
                f"[{min_workers}, {max_workers}]"
            )
        if not 0.0 < release_frac <= 1.0:
            raise ValueError(
                f"release_frac must be in (0, 1], got {release_frac}"
            )
        if dwell_steps < 1:
            raise ValueError(f"dwell_steps must be >= 1, got {dwell_steps}")
        self.name = "autoscale"
        self.supervisor = supervisor
        self.fleet_provider = fleet_provider
        self.queue_wait_high_ms = queue_wait_high_ms
        self.idle_high = idle_high
        self.tok_s_low = tok_s_low
        self.release_frac = float(release_frac)
        self.ema_alpha = float(ema_alpha)
        self.cooldown_steps = int(cooldown_steps)
        self.dwell_steps = int(dwell_steps)
        self._last_action_step: int | None = None
        self._ok_run = 0
        self.last_signal: float | None = None
        self._victims: tuple = ()
        # per-worker (ts, cumulative tokens) marks + rate EMAs — the
        # least-productive ranking scale-down retires by
        self._marks: dict[str, tuple[float, float]] = {}
        self._ema: dict[str, float] = {}
        self._pids: dict[str, Any] = {}
        initial = float(
            getattr(supervisor, "target_workers", 0)
            or getattr(supervisor, "pool_size", 0) or min_workers
        )
        self.actuator = BoundedActuator(
            name="target_workers",
            value=min(max(initial, float(min_workers)), float(max_workers)),
            min_value=float(min_workers), max_value=float(max_workers),
            apply=self._apply_target,
            # directionality note: for a pool, the HIGH-signal response is
            # MORE capacity — the custom step() below maps breach→regrow
            # (+1) and sustained-calm→shrink (−1), inverse of the scalar
            # Governor base (which is why this is a custom shape)
            shrink=lambda v: v - 1.0,
            regrow=lambda v: v + 1.0,
            integer=True,
        )

    def _apply_target(self, v: float) -> None:
        victims, self._victims = self._victims, ()
        self.supervisor.scale_to(int(v), victims=victims)

    # ------------------------------------------------------------- signals

    def _load(self, metrics: Mapping[str, Any]) -> float | None:
        """Worst up-pressure ratio across the armed signals, or None when
        no armed signal has an observation this step."""
        from distrl_llm_tpu.serving_obs import (
            FLEET_SERVING_QUEUE_WAIT_MAX_MS,
            SERVING_QUEUE_WAIT_MS,
        )

        ratios: list[float] = []
        if self.queue_wait_high_ms is not None:
            vals = [
                float(metrics[k])
                for k in (SERVING_QUEUE_WAIT_MS + "_max",
                          FLEET_SERVING_QUEUE_WAIT_MAX_MS)
                if metrics.get(k) is not None
            ]
            if vals:
                ratios.append(max(vals) / float(self.queue_wait_high_ms))
        if self.idle_high is not None:
            from distrl_llm_tpu.obs import OBS_LEARNER_IDLE

            idle = metrics.get(OBS_LEARNER_IDLE)
            if idle is not None:
                ratios.append(float(idle) / float(self.idle_high))
        return max(ratios) if ratios else None

    def _rates(self) -> dict[str, float]:
        """Per-worker tok/s this pass, updating the rate EMAs (the
        WorkerHealthGovernor mark math; a pid change resets the track)."""
        fleet = None
        if self.fleet_provider is not None:
            try:
                fleet = self.fleet_provider()
            except Exception:  # noqa: BLE001 — a failed refresh skips the obs
                log.warning("fleet refresh failed in autoscale governor",
                            exc_info=True)
        rates: dict[str, float] = {}
        if not fleet:
            return rates
        workers = fleet.get("workers")

        def norm(a):
            # worker_states addresses are (host, port) tuples; the
            # worker_metrics table keys are "host:port" track labels
            if isinstance(a, (tuple, list)):
                return f"{a[0]}:{a[1]}"
            return str(a)

        live = (
            {norm(w.get("address")) for w in workers
             if w.get("healthy") and not w.get("cold")
             and not w.get("retired")}
            if workers is not None else None
        )
        for addr, rec in (fleet.get("worker_metrics") or {}).items():
            pid = rec.get("pid")
            last_pid = self._pids.get(addr)
            self._pids[addr] = pid
            if pid is not None and last_pid is not None and pid != last_pid:
                self._ema.pop(addr, None)
                self._marks.pop(addr, None)
            if live is not None and addr not in live:
                self._ema.pop(addr, None)
                self._marks.pop(addr, None)
                continue
            tokens = float(rec.get("gen_tokens", 0.0))
            ts = float(rec.get("ts", time.time()))
            mark = self._marks.get(addr)
            self._marks[addr] = (ts, tokens)
            if mark is None or ts <= mark[0] or tokens < mark[1]:
                continue
            rate = (tokens - mark[1]) / (ts - mark[0])
            rates[addr] = rate
            ema = self._ema.get(addr)
            self._ema[addr] = (
                rate if ema is None
                else self.ema_alpha * rate + (1 - self.ema_alpha) * ema
            )
        # forget tracks the fleet view no longer reports (scaled-in)
        known = set((fleet.get("worker_metrics") or {}))
        for addr in list(self._ema):
            if addr not in known:
                self._ema.pop(addr, None)
                self._marks.pop(addr, None)
        return rates

    def _least_productive(self) -> tuple:
        """Victims for a scale-down, least-productive first: the lowest
        rate EMAs among the supervisor's live pool (workers without an
        EMA yet — cold joins — are never first pick)."""
        pool = {
            f"{h}:{p}" for h, p in getattr(
                self.supervisor, "addresses", lambda: []
            )()
        }
        ranked = sorted(
            (a for a in self._ema if a in pool), key=lambda a: self._ema[a]
        )
        return tuple(ranked)

    # ---------------------------------------------------------------- step

    def _resize(self, step: int, runtime: ControlRuntime, kind: str,
                new_value: float, reason: str, victims: tuple = (),
                trigger: str | None = None) -> list[ControlAction]:
        new = self.actuator.clamp(new_value)
        if new == self.actuator.value:
            return []  # at the bound already
        action = ControlAction(
            step=step, controller=self.name, actuator=self.actuator.name,
            kind=kind, old=self.actuator.value, new=new, reason=reason,
            trigger=trigger,
        )

        def push():
            self.actuator.value = new
            self._victims = victims
            self.actuator.apply(new)

        if runtime.act(action, apply=push):
            self._last_action_step = step
            self._ok_run = 0
            return [action]
        return []

    def step(self, step: int, metrics: Mapping[str, Any],
             runtime: ControlRuntime) -> list[ControlAction]:
        # observe the pool first: deaths noticed here respawn within the
        # supervisor's restart budget, so a preemption mid-scale-event
        # converges without a separate watchdog thread
        poll = getattr(self.supervisor, "poll", None)
        if poll is not None:
            poll()
        rates = self._rates()
        load = self._load(metrics)
        self.last_signal = load
        if load is not None and load > 1.0:
            self._ok_run = 0
            if (self.actuator.value < self.actuator.max_value
                    and cooldown_ok(self, step, runtime)):
                return self._resize(
                    step, runtime, "scale_up", self.actuator.value + 1,
                    f"load at {load:.3g}x its scale-up threshold",
                )
            return []
        if load is not None and load >= self.release_frac:
            # inside the deadband: hysteresis hold, no dwell credit
            self._ok_run = 0
            return []
        # below the band (or no up-signal): down-pressure needs an actual
        # low-throughput observation — calm alone never shrinks the pool
        if self.tok_s_low is None:
            self._ok_run = 0
            return []
        per_worker = None
        if rates:
            per_worker = sum(rates.values()) / len(rates)
        if per_worker is None or per_worker >= self.tok_s_low:
            self._ok_run = 0
            return []
        self._ok_run += 1
        if self._ok_run < self.dwell_steps:
            return []
        if (self.actuator.value > self.actuator.min_value
                and cooldown_ok(self, step, runtime)):
            return self._resize(
                step, runtime, "scale_down", self.actuator.value - 1,
                f"per-worker rate {per_worker:.1f} tok/s < "
                f"{self.tok_s_low:.1f} for {self._ok_run} steps "
                f"(dwell {self.dwell_steps})",
                victims=self._least_productive(),
            )
        return []

    def on_trigger(self, trigger: str, step: int, runtime: ControlRuntime,
                   extra: Mapping[str, Any]) -> bool:
        """queue_wait_blowup escalation (registered only when the SLO
        shedder is not armed — a trigger maps to one governor): one
        immediate scale-up, cooldown- and budget-guarded."""
        self._ok_run = 0
        if not cooldown_ok(self, step, runtime):
            return False
        return bool(self._resize(
            step, runtime, "scale_up", self.actuator.value + 1,
            f"sentinel trigger {trigger!r}", trigger=trigger,
        ))


# ---------------------------------------------------- nan-loss rollback


class NanRollbackController:
    """Restores the last-good (adapter, optimizer state, version) snapshot
    when an optimizer step produces a non-finite loss, so the run skips the
    poisoned step instead of training on NaNs from there on.

    The snapshot is the learner-side twin of the weight bus's versioned
    state: it always holds a version every worker has already acked (the
    trainer snapshots after each finite step's push), so a rollback needs
    NO resync — dispatches keep naming a version the workers' AdapterCache
    still holds, which the action record asserts when a bus is present.
    Bounded by ``max_rollbacks`` and the runtime budget; an exhausted
    controller leaves the step untouched (the pre-ISSUE-14 behavior)."""

    def __init__(self, *, max_rollbacks: int = 3):
        if max_rollbacks < 1:
            raise ValueError(
                f"max_rollbacks must be >= 1, got {max_rollbacks}"
            )
        self.name = "nan_rollback"
        self.max_rollbacks = int(max_rollbacks)
        self.rollbacks = 0
        self._snap: tuple[int, Any, Any] | None = None

    @staticmethod
    def _copy_tree(tree):
        import jax
        import jax.numpy as jnp

        return jax.tree_util.tree_map(jnp.copy, tree)

    def note_good(self, version: int, lora, opt_state) -> None:
        """Snapshot the state a finite step produced (device copies — the
        donating train step never aliases them)."""
        self._snap = (
            int(version), self._copy_tree(lora), self._copy_tree(opt_state)
        )

    @property
    def snapshot_version(self) -> int | None:
        return self._snap[0] if self._snap is not None else None

    def rollback(self, step: int, runtime: ControlRuntime,
                 bus=None) -> tuple[Any, Any, int] | None:
        """Restore the last-good snapshot, or None when no snapshot exists
        / the per-run rollback bound is spent / the budget is exhausted.
        Returns fresh copies — a second consecutive rollback must find the
        snapshot intact after the first restore's buffers were donated."""
        if self._snap is None:
            return None
        if self.rollbacks >= self.max_rollbacks:
            log.error(
                "nan_loss at step %d but the rollback bound (%d) is spent "
                "— leaving the step as-is", step, self.max_rollbacks,
            )
            return None
        version, lora, opt_state = self._snap
        extra = ""
        if bus is not None:
            # the restored version's broadcast already completed (or the
            # bus is still resyncing it) — either way no NEW push is
            # needed; record what the workers hold for the incident trail
            extra = f"; bus last_acked=v{bus.last_acked_version}"
        action = ControlAction(
            step=step, controller=self.name, actuator="weight_version",
            kind="rollback", old=None, new=float(version),
            reason=f"non-finite loss; restored v{version}{extra}",
            trigger="nan_loss",
        )
        if not runtime.act(action):
            return None  # budget-suppressed: the caller leaves the step as-is
        self.rollbacks += 1
        telemetry.counter_add(CONTROL_NAN_ROLLBACKS)
        return self._copy_tree(lora), self._copy_tree(opt_state), version


# ------------------------------------------------------------- assembly


def build_runtime(config, *, engine=None, recorder=None,
                  driver=None, fleet_provider=None,
                  fleet_supervisor=None) -> ControlRuntime | None:
    """Assemble the ControlRuntime for a trainer from its TrainConfig
    (None when no controller is armed). The staleness governor attaches
    later — its plant (policy + buffer) only exists once the async loop
    builds them (:func:`attach_staleness`)."""
    armed = set(config.armed_controllers())
    if not armed:
        return None
    limits = None
    if armed & {"hbm", "shed"}:
        limits = ControlLimits()
        if engine is not None and hasattr(engine, "control_limits"):
            engine.control_limits = limits
    runtime = ControlRuntime(
        budget=config.control_budget, recorder=recorder, limits=limits,
    )
    if "hbm" in armed:
        runtime.register(
            HbmGovernor(
                limits,
                cooldown_steps=config.control_cooldown_steps,
                dwell_steps=config.control_dwell_steps,
            ),
            triggers=("hbm_breach",),
        )
    if "shed" in armed:
        runtime.register(
            SloShedGovernor(
                limits,
                slo_ttft_ms=config.slo_ttft_ms,
                slo_queue_wait_ms=config.slo_queue_wait_ms,
                cooldown_steps=config.control_cooldown_steps,
                dwell_steps=config.control_dwell_steps,
            ),
            triggers=("ttft_blowup", "queue_wait_blowup"),
        )
    if "worker_health" in armed and driver is not None:
        if fleet_provider is None:
            # no ObsPlane fleet aggregator: build a private one off the
            # same driver (rates still need workers exporting obs blobs —
            # worker_main --metrics-port / DISTRL_OBS=1; without them the
            # governor sees no per-worker counters and stays inert)
            from distrl_llm_tpu.obs import FleetAggregator

            fleet_provider = FleetAggregator(driver).refresh
        runtime.register(
            WorkerHealthGovernor(
                driver, fleet_provider,
                cooldown_steps=max(4 * config.control_cooldown_steps, 4),
            ),
            triggers=("tok_s_regression",),
        )
    if "autoscale" in armed:
        supervisor = fleet_supervisor or getattr(
            engine, "fleet_supervisor", None
        )
        if supervisor is None:
            # armed without a supervised pool (e.g. workers started by
            # hand): there is no process actuator to resize — stay inert
            # rather than register a governor that can only half-act
            log.warning(
                "autoscale armed but no FleetSupervisor owns the worker "
                "pool — the governor is not registered"
            )
        else:
            runtime.register(
                AutoscaleGovernor(
                    supervisor, fleet_provider,
                    min_workers=config.fleet_min,
                    max_workers=config.fleet_max,
                    queue_wait_high_ms=config.slo_queue_wait_ms,
                    # scale up when the learner is starved for data most
                    # of the step — but only where idle is a *signal*: in
                    # sync mode the learner structurally waits out every
                    # rollout, so a calm run would read as breached and the
                    # armed-but-quiescent byte-identity pin would break.
                    # Scale-down stays opt-in (tok_s_low) — calm alone
                    # must never shrink the pool.
                    idle_high=(
                        0.9 if config.rollout_mode != "sync" else None
                    ),
                    cooldown_steps=max(2 * config.control_cooldown_steps, 2),
                    dwell_steps=config.control_dwell_steps,
                ),
                # a sentinel trigger maps to ONE governor: the shedder
                # owns queue_wait_blowup when armed; otherwise the blowup
                # escalates here as an immediate scale-up
                triggers=(
                    () if "shed" in armed else ("queue_wait_blowup",)
                ),
            )
    if "nan_rollback" in armed:
        runtime.nan = NanRollbackController()
    return runtime


def attach_staleness(runtime: ControlRuntime, config, policy,
                     buffer) -> None:
    """Register the staleness governor once the async loop's policy and
    buffer exist (no-op unless the controller is armed)."""
    if "staleness" not in set(config.armed_controllers()):
        return
    runtime.register(
        StalenessGovernor(
            policy, buffer,
            lag_target_ms=config.control_lag_ms,
            batch_size=config.batch_size,
            cooldown_steps=config.control_cooldown_steps,
            dwell_steps=config.control_dwell_steps,
        ),
        # kl_blowup (ISSUE 16): runaway behavior↔policy KL is the learning
        # symptom of the same disease staleness_blowup is the systems
        # symptom of — both escalate to the governor's bounded one-shot
        # shrink of the effective staleness bound (cooldown/budget-guarded;
        # unarmed unless learn_kl_limit set the trigger)
        triggers=("staleness_blowup", "kl_blowup"),
    )
