"""Driver-side reward shaping: baselines, GRPO advantages, top-k filtering.

Parity with the reference Trainer's inline numpy blocks
(distributed_trainer.py:262–294). Shaping runs on the host between the rollout
round and the learner step; arrays are small (batch·n scalars) so there is
nothing to jit here.

Contract recap (SURVEY §3.6.7): per-candidate rewards arrive as (n, 2) arrays —
column 0 format, column 1 accuracy. Training consumes the row sum; metrics
split the columns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, MutableMapping, Sequence

import numpy as np


@dataclass
class ShapingStats:
    """Per-round metric accumulators matching the reference's lists
    (distributed_trainer.py:256–260)."""

    mean_acc: list[float] = field(default_factory=list)
    max_acc: list[float] = field(default_factory=list)
    min_acc: list[float] = field(default_factory=list)
    mean_format: list[float] = field(default_factory=list)
    mean_token_length: list[float] = field(default_factory=list)


def shape_rewards(
    candidates: Sequence[MutableMapping[str, Any]],
    learner_type: str,
) -> ShapingStats:
    """Shape each task group's (n, 2) rewards in place and collect metrics.

    PG: ``rewards`` ← per-candidate summed reward, plus a ``baselines`` list of
    group means (subtracted later in the learner — distributed_trainer.py:277–279).
    GRPO: ``rewards`` ← (r − mean)/(std + 1e-8) group-normalized advantages
    (:273, :275–276). Metrics mirror :266–272.
    """
    stats = ShapingStats()
    for cand in candidates:
        baselines, summed, advantages = [], [], []
        for group_reward, group_tokens in zip(cand["rewards"], cand["token_lengths"]):
            group_reward = np.asarray(group_reward)
            total = group_reward.sum(axis=1)
            mean = float(np.mean(total))
            baselines.append(mean)
            summed.append(total)
            advantages.append((total - mean) / (np.std(total) + 1e-8))

            stats.mean_acc.append(float(np.mean(group_reward[:, 1])))
            stats.max_acc.append(float(np.max(group_reward[:, 1])))
            stats.min_acc.append(float(np.min(group_reward[:, 1])))
            stats.mean_format.append(float(np.mean(group_reward[:, 0])))
            stats.mean_token_length.append(float(np.mean(group_tokens)))

        if learner_type == "grpo":
            cand["rewards"] = advantages
        else:
            cand["baselines"] = baselines
            cand["rewards"] = summed
    return stats


def topk_filter(candidates: Sequence[MutableMapping[str, Any]], topk: int) -> None:
    """Keep the top-k candidates per task group by shaped reward, in place
    (distributed_trainer.py:281–294). Answers and rewards are selected by
    argsort; problems are truncated, not reordered — safe because every entry
    in a group is the identical prompt (SURVEY §3.6.5)."""
    for cand in candidates:
        kept_answers, kept_rewards, kept_problems = [], [], []
        for j, rewards in enumerate(cand["rewards"]):
            idx = np.argsort(rewards)[-topk:]
            kept_answers.append([cand["answers"][j][i] for i in idx])
            kept_rewards.append(np.asarray(rewards)[idx])
            kept_problems.append(cand["problem"][j][:topk])
        cand["answers"] = kept_answers
        cand["rewards"] = kept_rewards
        cand["problem"] = kept_problems


def flatten_for_update(
    candidates: Sequence[MutableMapping[str, Any]], learner_type: str
) -> tuple[list[str], list[str], np.ndarray]:
    """Flatten shaped candidates into (problems, answers, scalar-coefficient)
    lists for the learner. PG applies reward − baseline here
    (distributed_actor.py:399–406); GRPO passes advantages through (:495–504)."""
    problems: list[str] = []
    answers: list[str] = []
    coeffs: list[float] = []
    for cand in candidates:
        if learner_type == "grpo":
            for a, p, r in zip(cand["answers"], cand["problem"], cand["rewards"]):
                problems.extend(p)
                answers.extend(a)
                coeffs.extend(np.asarray(r).tolist())
        else:
            for a, p, r, b in zip(
                cand["answers"], cand["problem"], cand["rewards"], cand["baselines"]
            ):
                problems.extend(p)
                answers.extend(a)
                coeffs.extend((np.asarray(r) - b).tolist())
    return problems, answers, np.asarray(coeffs, dtype=np.float32)
