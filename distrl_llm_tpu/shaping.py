"""Driver-side reward shaping: baselines, GRPO advantages, top-k filtering.

Parity with the reference Trainer's inline numpy blocks
(distributed_trainer.py:262–294). Shaping runs on the host between the rollout
round and the learner step; arrays are small (batch·n scalars) so there is
nothing to jit here.

Contract recap (SURVEY §3.6.7): per-candidate rewards arrive as (n, 2) arrays —
column 0 format, column 1 accuracy. Training consumes the row sum; metrics
split the columns.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, MutableMapping, Sequence

import numpy as np


@dataclass
class ShapingStats:
    """Per-round metric accumulators matching the reference's lists
    (distributed_trainer.py:256–260)."""

    mean_acc: list[float] = field(default_factory=list)
    max_acc: list[float] = field(default_factory=list)
    min_acc: list[float] = field(default_factory=list)
    mean_format: list[float] = field(default_factory=list)
    mean_token_length: list[float] = field(default_factory=list)


def shape_rewards(
    candidates: Sequence[MutableMapping[str, Any]],
    learner_type: str,
) -> ShapingStats:
    """Shape each task group's (n, 2) rewards in place and collect metrics.

    PG: ``rewards`` ← per-candidate summed reward, plus a ``baselines`` list of
    group means (subtracted later in the learner — distributed_trainer.py:277–279).
    GRPO: ``rewards`` ← (r − mean)/(std + 1e-8) group-normalized advantages
    (:273, :275–276). Metrics mirror :266–272.
    """
    stats = ShapingStats()
    for cand in candidates:
        baselines, summed, advantages = [], [], []
        for group_reward, group_tokens in zip(cand["rewards"], cand["token_lengths"]):
            group_reward = np.asarray(group_reward)
            total = group_reward.sum(axis=1)
            mean = float(np.mean(total))
            baselines.append(mean)
            summed.append(total)
            advantages.append((total - mean) / (np.std(total) + 1e-8))

            stats.mean_acc.append(float(np.mean(group_reward[:, 1])))
            stats.max_acc.append(float(np.max(group_reward[:, 1])))
            stats.min_acc.append(float(np.min(group_reward[:, 1])))
            stats.mean_format.append(float(np.mean(group_reward[:, 0])))
            stats.mean_token_length.append(float(np.mean(group_tokens)))

        if learner_type == "grpo":
            cand["rewards"] = advantages
        else:
            cand["baselines"] = baselines
            cand["rewards"] = summed
    return stats


def topk_filter(candidates: Sequence[MutableMapping[str, Any]], topk: int) -> None:
    """Keep the top-k candidates per task group by shaped reward, in place
    (distributed_trainer.py:281–294). Answers and rewards are selected by
    argsort; problems are truncated, not reordered — safe because every entry
    in a group is the identical prompt (SURVEY §3.6.5)."""
    for cand in candidates:
        kept_answers, kept_rewards, kept_problems = [], [], []
        kept_tokens, kept_logps, kept_lens, kept_tags = [], [], [], []
        kept_masks = []
        has_raw = "answer_tokens" in cand
        has_tags = "version_tags" in cand
        has_mask = "loss_mask" in cand
        for j, rewards in enumerate(cand["rewards"]):
            idx = np.argsort(rewards)[-topk:]
            kept_answers.append([cand["answers"][j][i] for i in idx])
            kept_rewards.append(np.asarray(rewards)[idx])
            kept_problems.append(cand["problem"][j][:topk])
            if has_raw:  # raw engine tokens/logps follow the same selection
                kept_tokens.append(np.asarray(cand["answer_tokens"][j])[idx])
                kept_logps.append(np.asarray(cand["behavior_logps"][j])[idx])
                kept_lens.append(np.asarray(cand["gen_lengths"][j])[idx])
            if has_tags:  # policy-version tags stay row-aligned too
                kept_tags.append(np.asarray(cand["version_tags"][j])[idx])
            if has_mask:  # per-turn loss masks stay row-aligned too
                kept_masks.append(np.asarray(cand["loss_mask"][j])[idx])
        cand["answers"] = kept_answers
        cand["rewards"] = kept_rewards
        cand["problem"] = kept_problems
        if has_raw:
            cand["answer_tokens"] = kept_tokens
            cand["behavior_logps"] = kept_logps
            cand["gen_lengths"] = kept_lens
        if has_tags:
            cand["version_tags"] = kept_tags
        if has_mask:
            cand["loss_mask"] = kept_masks


def flatten_for_update(
    candidates: Sequence[MutableMapping[str, Any]], learner_type: str
) -> tuple[list[str], list[str], np.ndarray, dict | None]:
    """Flatten shaped candidates into (problems, answers, coefficients,
    raw_rollout) lists for the learner. PG applies reward − baseline here
    (distributed_actor.py:399–406); GRPO passes advantages through (:495–504).

    ``raw_rollout`` (None when the engine captured no logprobs) carries the
    engine's own answer token ids and behavior logprobs row-aligned with the
    text lists — the PPO-clip objective trains on these instead of
    retokenized text. When present, per-token policy-version tags
    (rollout/trajectory.py) ride along as ``version_tags``.

    ``group_weights`` on a candidate dict (the async staleness policy's
    down-weights, one per task group) scale that group's flattened
    coefficients — absent (every sync/pipelined round) the math is
    untouched."""
    problems: list[str] = []
    answers: list[str] = []
    coeffs: list[float] = []
    tokens: list[np.ndarray] = []
    logps: list[np.ndarray] = []
    tags: list[np.ndarray] = []
    masks: list[np.ndarray] = []
    lens: list[int] = []
    has_raw = all("answer_tokens" in c for c in candidates) and candidates
    has_tags = has_raw and all("version_tags" in c for c in candidates)
    has_mask = has_raw and all("loss_mask" in c for c in candidates)
    for cand in candidates:
        gw = cand.get("group_weights")
        if learner_type == "grpo":
            for j, (a, p, r) in enumerate(
                zip(cand["answers"], cand["problem"], cand["rewards"])
            ):
                problems.extend(p)
                answers.extend(a)
                w = 1.0 if gw is None else float(gw[j])
                coeffs.extend((np.asarray(r) * w).tolist())
                if has_raw:
                    tokens.extend(np.asarray(cand["answer_tokens"][j]))
                    logps.extend(np.asarray(cand["behavior_logps"][j]))
                    lens.extend(np.asarray(cand["gen_lengths"][j]).tolist())
                if has_tags:
                    tags.extend(np.asarray(cand["version_tags"][j]))
                if has_mask:
                    masks.extend(np.asarray(cand["loss_mask"][j]))
        else:
            for j, (a, p, r, b) in enumerate(
                zip(
                    cand["answers"], cand["problem"], cand["rewards"],
                    cand["baselines"],
                )
            ):
                problems.extend(p)
                answers.extend(a)
                w = 1.0 if gw is None else float(gw[j])
                coeffs.extend(((np.asarray(r) - b) * w).tolist())
                if has_raw:
                    tokens.extend(np.asarray(cand["answer_tokens"][j]))
                    logps.extend(np.asarray(cand["behavior_logps"][j]))
                    lens.extend(np.asarray(cand["gen_lengths"][j]).tolist())
                if has_tags:
                    tags.extend(np.asarray(cand["version_tags"][j]))
                if has_mask:
                    masks.extend(np.asarray(cand["loss_mask"][j]))
    raw = None
    if has_raw and tokens:
        raw = {
            "answer_tokens": np.asarray(tokens),
            "behavior_logps": np.asarray(logps, dtype=np.float32),
            "lengths": np.asarray(lens, dtype=np.int32),
        }
        if has_tags and tags:
            raw["version_tags"] = np.asarray(tags, dtype=np.int32)
        if has_mask and masks:
            # multi-turn env rounds (ISSUE 17): 1 on policy spans, 0 on
            # env-injected observation tokens — the learner multiplies this
            # into its answer mask so injected tokens never train
            raw["loss_mask"] = np.asarray(masks, dtype=np.int32)
    return problems, answers, np.asarray(coeffs, dtype=np.float32), raw
