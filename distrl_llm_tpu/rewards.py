"""Rule-based rewards for R1-style math reasoning completions.

Behavioral parity with the reference's reward_functions.py (BY571/DistRL-LLM):
the public contract is ``reward_function(completions, solutions) ->
np.ndarray[N, 2]`` with column 0 = format reward (soft format + XML tag count)
and column 1 = accuracy (exact answer match) — reward_functions.py:44–49.
Training consumes the row *sum*; logging and eval read the columns separately
(distributed_trainer.py:267–274, :403–405), so the 2-column shape is load-bearing.

Deliberate parity quirks preserved (SURVEY §2a#9):
  * ``soft_format_reward`` uses ``re.match`` with no DOTALL — the pattern is
    anchored at the start of the completion and ``.`` does not cross newlines
    (reward_functions.py:20–24), so multi-line ``<think>`` bodies score 0.
  * ``count_xml`` penalises trailing text after ``</answer>`` at 0.001/char
    (reward_functions.py:26–38).

TPU-host addition: reward computation was the reference's driver-side hot loop
(single-threaded regex over batch·n completions — SURVEY §3.2 hot loop #2).
``RewardComputer`` fans batches out over host processes.
"""

from __future__ import annotations

import multiprocessing
import re
from concurrent.futures import ProcessPoolExecutor
from functools import partial
from typing import Sequence

import numpy as np

_SOFT_FORMAT_RE = re.compile(r"<think>.*?</think>\s*<answer>.*?</answer>")


def extract_xml_answer(text: str) -> str:
    """Text between the last ``<answer>`` and the next ``</answer>``, stripped
    (reward_functions.py:4–7)."""
    tail = text.rsplit("<answer>", 1)[-1]
    return tail.split("</answer>", 1)[0].strip()


def correctness_reward(completions: Sequence[str], solutions: Sequence[str]) -> np.ndarray:
    """1.0 per exact string match of the extracted answer (reward_functions.py:9–11)."""
    return np.asarray(
        [1.0 if extract_xml_answer(c) == s else 0.0 for c, s in zip(completions, solutions)],
        dtype=np.float64,
    )


def soft_format_reward(completions: Sequence[str]) -> np.ndarray:
    """0.1 if the completion starts with single-line think/answer tags
    (reward_functions.py:20–24; anchored match, no DOTALL — parity quirk)."""
    return np.asarray(
        [0.1 if _SOFT_FORMAT_RE.match(c) else 0.0 for c in completions], dtype=np.float64
    )


def strict_format_reward(completions: Sequence[str]) -> np.ndarray:
    """Strict newline-delimited variant (reward_functions.py:14–18). Defined for
    API parity; the reference never wires it into ``reward_function``."""
    pattern = re.compile(r"^<think>\n.*?\n</think>\n<answer>\n.*?\n</answer>\n$")
    return np.asarray(
        [0.1 if pattern.match(c) else 0.0 for c in completions], dtype=np.float64
    )


def _count_xml(text: str) -> float:
    """Per-tag shaping: +0.05 per well-formed tag occurrence, minus a length
    penalty for text trailing the closing answer tag (reward_functions.py:26–38)."""
    score = 0.0
    if text.count("<think>\n") == 1:
        score += 0.05
    if text.count("\n</think>\n") == 1:
        score += 0.05
    if text.count("\n<answer>\n") == 1:
        score += 0.05
        score -= len(text.split("\n</answer>\n")[-1]) * 0.001
    if text.count("\n</answer>") == 1:
        score += 0.05
        score -= (len(text.split("\n</answer>")[-1]) - 1) * 0.001
    return score


def xmlcount_reward(completions: Sequence[str]) -> np.ndarray:
    return np.asarray([_count_xml(c) for c in completions], dtype=np.float64)


def reward_function(completions: Sequence[str], solutions: Sequence[str]) -> np.ndarray:
    """The (N, 2) reward contract: column 0 = format (soft + xmlcount),
    column 1 = accuracy (reward_functions.py:44–49)."""
    accuracy = correctness_reward(completions, solutions)
    fmt = soft_format_reward(completions) + xmlcount_reward(completions)
    return np.column_stack((fmt, accuracy))


def strict_reward_function(
    completions: Sequence[str], solutions: Sequence[str]
) -> np.ndarray:
    """(N, 2) contract with the strict newline-delimited format gate in
    column 0 — makes ``strict_format_reward`` a selectable scorer
    (``format_reward="strict"``) instead of dead parity code. Module-level so
    ``RewardComputer``'s process pool can pickle it."""
    accuracy = correctness_reward(completions, solutions)
    fmt = strict_format_reward(completions) + xmlcount_reward(completions)
    return np.column_stack((fmt, accuracy))


def soft_format_scorer(completions: Sequence[str]) -> np.ndarray:
    """Format column of :func:`reward_function` alone (soft + xmlcount)."""
    return soft_format_reward(completions) + xmlcount_reward(completions)


def strict_format_scorer(completions: Sequence[str]) -> np.ndarray:
    """Format column of :func:`strict_reward_function` alone."""
    return strict_format_reward(completions) + xmlcount_reward(completions)


_FORMAT_SCORERS = {"soft": soft_format_scorer, "strict": strict_format_scorer}
_REWARD_FUNCTIONS = {"soft": reward_function, "strict": strict_reward_function}


def make_format_scorer(name: str = "soft"):
    """Per-completion format scorer used by env-routed scoring (column 0)."""
    try:
        return _FORMAT_SCORERS[name]
    except KeyError:
        raise ValueError(
            f"unknown format scorer {name!r}; available: soft, strict"
        ) from None


def make_reward_function(name: str = "soft"):
    """Select the (N, 2) reward function by format gate. ``"soft"`` returns
    :func:`reward_function` itself — the identical object, so the default
    config keeps byte-identity with pre-env trainers."""
    try:
        return _REWARD_FUNCTIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown format reward {name!r}; available: soft, strict"
        ) from None


def _reward_task(fn, args: tuple[Sequence[str], Sequence[str]]) -> np.ndarray:
    return fn(*args)


class RewardComputer:
    """Host-parallel reward evaluation over many (completions, solutions) groups.

    The reference computes rewards serially on the driver
    (distributed_trainer.py:205–219). On a TPU host with dozens of cores we fan
    groups out across processes; for small workloads the serial path avoids
    pool overhead.

    ``reward_fn`` is the function actually evaluated — the trainer builds the
    computer around its ``reward_function`` argument (the reference's
    ``Trainer(train_ds, test_ds, reward_fn, config)`` contract,
    distributed_trainer.py:14), defaulting to the parity ``reward_function``.
    The parallel path pickles the fn to worker processes, so custom fns must
    be module-level for ``num_workers > 0`` (closures work on the serial path).
    """

    def __init__(self, num_workers: int = 0, parallel_threshold: int = 256,
                 reward_fn=None):
        self.num_workers = num_workers
        self.parallel_threshold = parallel_threshold
        # distinguishes "caller explicitly chose a fn" from the default, so a
        # Trainer can refuse a genuine conflict without mutating a computer
        # that is shared across Trainers
        self.fn_explicit = reward_fn is not None
        self.reward_fn = reward_fn if reward_fn is not None else reward_function
        self._pool: ProcessPoolExecutor | None = None

    def _ensure_pool(self) -> ProcessPoolExecutor:
        if self._pool is None:
            # spawn, not fork: the driver has a live JAX/TPU runtime by the time
            # rewards are computed, and forking after XLA init is unsupported.
            ctx = multiprocessing.get_context("spawn")
            self._pool = ProcessPoolExecutor(max_workers=self.num_workers, mp_context=ctx)
        return self._pool

    def __call__(
        self,
        groups: Sequence[tuple[Sequence[str], Sequence[str]]],
        reward_fn=None,
    ) -> list[np.ndarray]:
        fn = reward_fn if reward_fn is not None else self.reward_fn
        total = sum(len(c) for c, _ in groups)
        if self.num_workers and total >= self.parallel_threshold:
            task = partial(_reward_task, fn)
            return list(self._ensure_pool().map(task, groups))
        return [fn(c, s) for c, s in groups]

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
