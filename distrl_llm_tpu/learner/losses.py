"""Policy-gradient and GRPO losses over recomputed answer logprobs.

Parity with the reference learner math (distributed_actor.py:215–260, :349–395,
:440–493):

* **Fixed-shape logprob recompute** — prompt left-padded to max_prompt_tokens,
  answer right-padded to max_new_tokens, one forward over the concat, shift by
  one, slice the answer region (:217–249). The reference chose fixed shapes to
  bound GPU memory; here they also mean exactly one XLA compilation.
* **PG loss** ``−(((logp·mask).Σ/mask.Σ)·coeff).mean()`` (:375) where coeff is
  reward − baseline (applied upstream, :406).
* **GRPO loss** uses the ratio trick ``exp(logp − stop_grad(logp))`` (≡1 at
  compute time, gradient = ∇logp · adv) with group-normalized advantages
  (:467–470). No KL, no clipping — the reference takes exactly one update per
  rollout batch, so the clipped objective never binds (SURVEY §3.6.2).

Instead of materializing the [B, T, V] log_softmax and gathering row-by-row in
a Python loop (the reference's memory cap, :252–260), per-token logprobs are
``gathered_logit − logsumexp`` — O(B·T) extra memory and XLA fuses the
logsumexp into the projection epilogue.

``logit_chunk`` goes further — the fused-cross-entropy equivalent of
unsloth's Triton CE kernel (SURVEY §2b N3): the lm_head projection +
logsumexp run per time-chunk under ``lax.scan`` with ``jax.checkpoint``, so
the live logits buffer is [B, Tc, V] instead of [B, T, V] in both the
forward AND the backward (the chunk recomputes its logits from the saved
[B, Tc, D] hidden slice). At the reference learner shapes (micro 8 × 1200
answer tokens × 152k vocab, f32) that is 5.8 GB → ~0.6 GB at Tc=128, with
bit-identical per-position math (each position's logsumexp still spans the
full vocab).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.models.transformer import forward
from distrl_llm_tpu.ops.linear import linear


def answer_logprobs(
    params,
    cfg: ModelConfig,
    prompt_ids: jax.Array,  # [B, P] left-padded
    prompt_mask: jax.Array,  # [B, P]
    answer_ids: jax.Array,  # [B, T] right-padded
    answer_mask: jax.Array,  # [B, T]
    *,
    lora=None,
    lora_scale: float = 1.0,
    remat: bool = True,
    attn_impl: str = "reference",
    attn_mesh=None,
    lora_dropout: float = 0.0,
    dropout_rng: jax.Array | None = None,
    logit_chunk: int = 0,  # 0 = dense [B, T, V]; >0 = chunked CE (see module doc)
    return_entropy: bool = False,  # also return per-position vocab entropy
) -> jax.Array:
    """Per-token logprobs of the answer under the current policy, [B, T] f32.

    Equivalent to the reference's compute_current_policy_probs
    (distributed_actor.py:215–260): token t's logprob comes from the logit at
    position P−1+t of the concatenated sequence.

    ``return_entropy=True`` additionally returns the full-vocab policy
    entropy per position, [B, T] f32 — ``H = lse − Σ softmax(logits)·logits``,
    read off the same logits/logsumexp the logprob gather already
    materializes (both the dense and the chunked-CE path), so the
    training-dynamics bundle (ISSUE 16) costs no extra projection and no
    extra host transfer. The flag is static: the default-off program is
    unchanged.
    """
    full_ids = jnp.concatenate([prompt_ids, answer_ids], axis=1)
    full_mask = jnp.concatenate([prompt_mask, answer_mask], axis=1)
    p = prompt_ids.shape[1]
    t = answer_ids.shape[1]
    fwd_kwargs = dict(
        attention_mask=full_mask, lora=lora, lora_scale=lora_scale,
        remat=remat, attn_impl=attn_impl, attn_mesh=attn_mesh,
        # project only positions P-1 .. P-1+T-1 (the logits predicting answer
        # tokens) — prompt logits would be discarded, so don't compute them
        logits_slice=(p - 1, t),
        lora_dropout=lora_dropout, dropout_rng=dropout_rng,
    )
    if logit_chunk <= 0 or logit_chunk >= t:
        pred, _ = forward(params, cfg, full_ids, **fwd_kwargs)  # [B, T, V]
        gathered = jnp.take_along_axis(pred, answer_ids[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(pred, axis=-1)
        if not return_entropy:
            return gathered - lse
        entropy = lse - (jax.nn.softmax(pred, axis=-1) * pred).sum(-1)
        return gathered - lse, entropy

    x, _ = forward(params, cfg, full_ids, skip_lm_head=True, **fwd_kwargs)
    b, _, d = x.shape
    chunk = logit_chunk
    # pad T up to a chunk multiple (padded positions are sliced off below) —
    # falling back to a DIVISOR of T would silently collapse to tiny chunks
    # for awkward lengths (prime T → chunk 1 → T sequential [B,1,V] matmuls)
    n_chunks = -(-t // chunk)
    pad = n_chunks * chunk - t
    padded_ids = answer_ids
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
        padded_ids = jnp.pad(answer_ids, ((0, 0), (0, pad)))
    lm_head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
    xs = x.reshape(b, n_chunks, chunk, d).swapaxes(0, 1)  # [n, B, C, D]
    ids = padded_ids.reshape(b, n_chunks, chunk).swapaxes(0, 1)

    def chunk_logprobs(x_c, ids_c):
        logits = linear(x_c, lm_head).astype(jnp.float32)  # [B, C, V]
        g = jnp.take_along_axis(logits, ids_c[..., None], axis=-1)[..., 0]
        lse = jax.nn.logsumexp(logits, axis=-1)
        if not return_entropy:
            return g - lse
        ent = lse - (jax.nn.softmax(logits, axis=-1) * logits).sum(-1)
        return g - lse, ent

    def body(carry, xc_ic):
        # checkpoint: the backward recomputes this chunk's logits from its
        # [B, C, D] hidden slice instead of keeping [B, C, V] alive per chunk
        return carry, jax.checkpoint(chunk_logprobs)(*xc_ic)

    _, out = jax.lax.scan(body, None, (xs, ids))  # [n, B, C]

    def unchunk(o):
        return o.swapaxes(0, 1).reshape(b, n_chunks * chunk)[:, :t]

    if not return_entropy:
        return unchunk(out)
    return unchunk(out[0]), unchunk(out[1])


def _masked_mean_seq(logp_like: jax.Array, mask: jax.Array) -> jax.Array:
    """(x·mask).Σ/mask.Σ per row, guarding empty answers (all-pad rows would be
    0/0 = NaN in the reference)."""
    denom = jnp.maximum(mask.sum(-1), 1.0)
    return (logp_like * mask).sum(-1) / denom


def pg_loss(
    logprobs: jax.Array,  # [B, T]
    answer_mask: jax.Array,  # [B, T]
    coeffs: jax.Array,  # [B] reward − baseline
    sample_mask: jax.Array | None = None,  # [B] 1 = real row (padding rows 0)
) -> jax.Array:
    """Vanilla PG: mean over rows of −(mean answer logprob)·coeff
    (distributed_actor.py:375)."""
    per_row = _masked_mean_seq(logprobs, answer_mask) * coeffs
    if sample_mask is None:
        return -per_row.mean()
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    return -(per_row * sample_mask).sum() / denom


def grpo_loss(
    logprobs: jax.Array,
    answer_mask: jax.Array,
    advantages: jax.Array,
    sample_mask: jax.Array | None = None,
) -> jax.Array:
    """Single-update GRPO: ratio ≡ 1 at compute time, gradient flows through
    exp(logp − stop_grad(logp)) (distributed_actor.py:467–470)."""
    ratio = jnp.exp(logprobs - jax.lax.stop_gradient(logprobs))
    per_row = _masked_mean_seq(ratio, answer_mask) * advantages
    if sample_mask is None:
        return -per_row.mean()
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    return -(per_row * sample_mask).sum() / denom


def grpo_clip_loss(
    logprobs: jax.Array,  # [B, T] current-policy logprobs
    behavior_logps: jax.Array,  # [B, T] rollout-time logprobs (engine-captured)
    answer_mask: jax.Array,  # [B, T]
    advantages: jax.Array,  # [B]
    sample_mask: jax.Array | None = None,
    clip_ratio: float = 0.2,
) -> jax.Array:
    """PPO-clip surrogate over raw-basis importance ratios — the stability
    mechanism the reference lacks (its GRPO has "no KL, no clipping",
    distributed_actor.py:467–470, and its README admits "training becomes
    unstable with longer training", README.md:91). The behavior logprobs
    come from the engine at sample time (GenerationResult.logprobs, the
    vLLM-logprobs equivalent), so the ratio is exact even when the update
    is off-policy (async_rollout's one-step staleness, or multiple
    optimizer steps per rollout batch). Both logprob sides are RAW
    log_softmax (see ops/sampling.token_logprob for the convention and its
    approximation at temperature != 1):

        ratio_t = exp(logp_current − logp_behavior)
        loss = −mean_rows( mean_t min(ratio·A, clip(ratio, 1±ε)·A) )
    """
    ratio = jnp.exp(logprobs - behavior_logps)
    clipped = jnp.clip(ratio, 1.0 - clip_ratio, 1.0 + clip_ratio)
    adv = advantages[:, None]
    surrogate = jnp.minimum(ratio * adv, clipped * adv)
    per_row = _masked_mean_seq(surrogate, answer_mask)
    if sample_mask is None:
        return -per_row.mean()
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    return -(per_row * sample_mask).sum() / denom


def grpo_aipo_loss(
    logprobs: jax.Array,  # [B, T] current-policy logprobs
    behavior_logps: jax.Array,  # [B, T] rollout-time logprobs (engine-captured)
    answer_mask: jax.Array,  # [B, T]
    advantages: jax.Array,  # [B]
    sample_mask: jax.Array | None = None,
    is_cap: float = 2.0,
    version_lag: jax.Array | None = None,  # [B, T] optimizer-step lag per token
    max_staleness: int = 0,
) -> jax.Array:
    """Truncated-importance-sampling policy gradient — the asynchronous-RL
    objective (AIPO, LlamaRL arxiv 2505.24034 §4.2; PipelineRL trains the
    same shape). Where ``grpo_clip_loss`` clips the surrogate around 1±ε
    (right for near-on-policy data, one step stale at most), the async
    regime trains on trajectories up to ``max_staleness`` optimizer steps
    old, where ratios legitimately drift far from 1 — clipping both sides
    there zeroes the gradient of exactly the samples that need correcting.
    Truncated IS instead keeps the estimator unbiased-below-the-cap and
    bounds its variance above it:

        ratio_t = min(exp(logp_current − logp_behavior), C)
        loss = −mean_rows( mean_t ratio_t · A )

    ``version_lag`` keys the correction on the per-token policy-version tags
    (rollout/trajectory.py): a trajectory that spans K in-flight weight
    swaps carries per-token lags, and tokens whose OWN lag exceeds
    ``max_staleness`` are masked out of the objective — the admission
    policy's drop, enforced token-wise for mixed-version trajectories whose
    head is fresh but whose tail predates the bound (or vice versa).
    """
    ratio = jnp.minimum(jnp.exp(logprobs - behavior_logps), is_cap)
    mask = answer_mask
    if version_lag is not None and max_staleness > 0:
        mask = mask * (version_lag <= max_staleness).astype(mask.dtype)
    per_row = _masked_mean_seq(ratio * advantages[:, None], mask)
    if sample_mask is None:
        return -per_row.mean()
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    return -(per_row * sample_mask).sum() / denom


def kl_to_ref(
    logprobs: jax.Array,  # [B, T] current-policy logprobs of sampled tokens
    ref_logps: jax.Array,  # [B, T] reference-policy logprobs (stop-gradient)
    answer_mask: jax.Array,  # [B, T]
    sample_mask: jax.Array | None = None,
) -> jax.Array:
    """Per-token KL(π‖π_ref) via the k3 estimator the GRPO paper uses
    (unbiased, always ≥ 0): exp(ref − cur) − (ref − cur) − 1, masked-meaned
    per row then averaged over real rows. The reference repo never loads a
    reference model (SURVEY §3.6.2); with LoRA the frozen base IS π_ref, so
    the penalty costs one extra no-adapter forward and no extra memory."""
    # zero the exponent at masked pads BEFORE exp: pad positions hold
    # garbage logprobs of the zero-filled token id, and exp(diff) overflows
    # to inf past ~88 nats — inf·0 mask would then poison the mean with NaN
    diff = (ref_logps - logprobs) * answer_mask
    k3 = jnp.exp(diff) - diff - 1.0
    per_row = _masked_mean_seq(k3, answer_mask)
    if sample_mask is None:
        return per_row.mean()
    denom = jnp.maximum(sample_mask.sum(), 1.0)
    return (per_row * sample_mask).sum() / denom


def entropy_bonus(logprobs_full: jax.Array, alpha: float) -> jax.Array:
    """Entropy regularizer over the vocab distribution — defined for API parity
    with the reference's compute_entropy_bonus (distributed_actor.py:266–281),
    which is never enabled there either (call sites commented out)."""
    probs = jnp.exp(logprobs_full)
    entropy = -(probs * logprobs_full).sum(-1)
    return alpha * entropy.mean()
