"""Adam with blockwise 8-bit quantized moment state, as an Optax transform.

TPU-native equivalent of the reference's ``bnb.optim.Adam8bit``
(distributed_actor.py:209–211, :432–434 — SURVEY §2b N4): both Adam moments are
stored int8 with per-block absmax scales (block = 256 elements, matching
bitsandbytes' blockwise dynamic quantization granularity), dequantized for the
update and requantized after. For LoRA-sized states the memory win is modest,
but the transform works for full-rank fine-tuning too.

The quantize/dequantize round-trip runs inside the jitted update — XLA fuses it
with the Adam arithmetic, so there is no extra HBM traffic beyond reading int8
instead of f32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

BLOCK = 256


@dataclass
class _Quantized:
    """int8 payload + per-block absmax scale; flat layout with tail padding.
    ``size``/``shape`` are static pytree aux data, not traced leaves."""

    q: jax.Array  # int8 [nblocks * BLOCK]
    scale: jax.Array  # f32 [nblocks]
    size: int  # original element count (static)
    shape: tuple  # original shape (static)


jax.tree_util.register_pytree_node(
    _Quantized,
    lambda z: ((z.q, z.scale), (z.size, z.shape)),
    lambda aux, children: _Quantized(children[0], children[1], aux[0], aux[1]),
)


def _quantize(x: jax.Array) -> _Quantized:
    flat = x.reshape(-1)
    size = flat.shape[0]
    pad = (-size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(blocks / safe[:, None] * 127.0), -127, 127).astype(jnp.int8)
    return _Quantized(q.reshape(-1), scale, size, tuple(x.shape))


def _dequantize(z: _Quantized, dtype=jnp.float32) -> jax.Array:
    blocks = z.q.reshape(-1, BLOCK).astype(dtype)
    x = blocks * (z.scale[:, None] / 127.0).astype(dtype)
    return x.reshape(-1)[: z.size].reshape(z.shape)


class Adam8bitState(NamedTuple):
    count: jax.Array
    mu: dict
    nu: dict


def adam8bit(
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> optax.GradientTransformation:
    """Adam(lr) with int8 blockwise moment state. Defaults match
    bnb.optim.Adam8bit's (the reference passes only lr)."""

    def init_fn(params):
        zeros = jax.tree_util.tree_map(lambda p: _quantize(jnp.zeros_like(p, jnp.float32)), params)
        nu = jax.tree_util.tree_map(lambda p: _quantize(jnp.zeros_like(p, jnp.float32)), params)
        return Adam8bitState(count=jnp.zeros([], jnp.int32), mu=zeros, nu=nu)

    def update_fn(updates, state, params=None):
        count = state.count + 1
        def upd(g, mu_q, nu_q):
            g = g.astype(jnp.float32)
            mu = b1 * _dequantize(mu_q) + (1 - b1) * g
            nu = b2 * _dequantize(nu_q) + (1 - b2) * g * g
            mu_hat = mu / (1 - b1 ** count.astype(jnp.float32))
            nu_hat = nu / (1 - b2 ** count.astype(jnp.float32))
            step = -learning_rate * mu_hat / (jnp.sqrt(nu_hat) + eps)
            return step, _quantize(mu), _quantize(nu)

        flat_u, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = [upd(g, m, n) for g, m, n in zip(flat_u, flat_mu, flat_nu)]
        steps = treedef.unflatten([o[0] for o in out])
        new_mu = treedef.unflatten([o[1] for o in out])
        new_nu = treedef.unflatten([o[2] for o in out])
        steps = jax.tree_util.tree_map(
            lambda s, g: s.astype(g.dtype), steps, updates
        )
        return steps, Adam8bitState(count=count, mu=new_mu, nu=new_nu)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(lr: float, use_8bit: bool = True) -> optax.GradientTransformation:
    """The learner optimizer: Adam(lr), 8-bit state by default (reference:
    Adam8bit with no weight decay — distributed_actor.py:209–211)."""
    return adam8bit(lr) if use_8bit else optax.adam(lr)
