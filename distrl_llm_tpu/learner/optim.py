"""Adam with blockwise 8-bit quantized moment state, as an Optax transform.

TPU-native equivalent of the reference's ``bnb.optim.Adam8bit``
(distributed_actor.py:209–211, :432–434 — SURVEY §2b N4): both Adam moments are
stored int8 with per-block absmax scales (block = 256 elements, matching
bitsandbytes' blockwise quantization granularity), dequantized for the update
and requantized after. For LoRA-sized states the memory win is modest, but the
transform works for full-rank fine-tuning too.

Moment codes are DYNAMIC (exponent + linear fraction), not linear. Linear
absmax codes round any element below 1/254 of its block's max to ZERO — for
the second moment that turns ``1/(sqrt(nu)+eps)`` into ``1/eps`` and the Adam
step explodes by ~1e8·lr (observed as adapter weights at 1e6 in an RL
training run; this is why bitsandbytes uses its "dynamic" quantization map
for optimizer state). The dynamic code splits the 127 magnitude levels across
7 decades with 2^(6−d) linear fractions in decade d: ~0.7% relative error
near the block max (where most moment mass sits), coarser but NEVER ZERO down
to 1e-7·blockmax — so a denominator can be off by a bounded factor but can
never collapse to eps.

The quantize/dequantize round-trip runs inside the jitted update — XLA fuses it
with the Adam arithmetic, so there is no extra HBM traffic beyond reading int8
instead of f32.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

BLOCK = 256


def _dynamic_table() -> np.ndarray:
    """127 ascending magnitudes in (0, 1]: decade d (values f·10^−d,
    f ∈ [0.1, 1)) gets 2^(6−d) linear fraction levels — 64 in the top decade
    down to a single level at 1e-7. The max (1.0) is exactly representable so
    each block's absmax round-trips bit-exact."""
    mags: list[float] = []
    for d in range(7):
        n = 2 ** (6 - d)
        if d == 0:
            fr = np.linspace(0.1, 1.0, n)  # include 1.0
        else:
            fr = np.linspace(0.1, 1.0, n, endpoint=False)
        mags.extend((fr * 10.0**-d).tolist())
    table = np.sort(np.asarray(mags, np.float64))
    assert table.shape == (127,) and table[-1] == 1.0
    return table


_TABLE = _dynamic_table()
# decision boundaries: below mid(0) → code 0 (zero); else nearest table entry
_MIDS = np.concatenate(([_TABLE[0] / 2.0], (_TABLE[:-1] + _TABLE[1:]) / 2.0))
_LUT = np.concatenate(([0.0], _TABLE)).astype(np.float32)  # code → magnitude


@dataclass
class _Quantized:
    """int8 payload + per-block absmax scale; flat layout with tail padding.
    ``size``/``shape`` are static pytree aux data, not traced leaves."""

    q: jax.Array  # int8 [nblocks * BLOCK]
    scale: jax.Array  # f32 [nblocks]
    size: int  # original element count (static)
    shape: tuple  # original shape (static)


jax.tree_util.register_pytree_node(
    _Quantized,
    lambda z: ((z.q, z.scale), (z.size, z.shape)),
    lambda aux, children: _Quantized(children[0], children[1], aux[0], aux[1]),
)


def _quantize(x: jax.Array) -> _Quantized:
    """Signed dynamic code: q = sign·m, m ∈ {0..127} indexing ``_LUT``."""
    flat = x.reshape(-1)
    size = flat.shape[0]
    pad = (-size) % BLOCK
    flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(-1, BLOCK)
    scale = jnp.max(jnp.abs(blocks), axis=1)
    safe = jnp.where(scale > 0, scale, 1.0)[:, None]
    r = jnp.abs(blocks) / safe
    m = jnp.searchsorted(jnp.asarray(_MIDS, jnp.float32), r, side="right")
    q = (jnp.sign(blocks) * m.astype(jnp.float32)).astype(jnp.int8)
    return _Quantized(q.reshape(-1), scale, size, tuple(x.shape))


def _dequantize(z: _Quantized, dtype=jnp.float32) -> jax.Array:
    q = z.q.reshape(-1, BLOCK).astype(jnp.int32)
    mag = jnp.asarray(_LUT)[jnp.abs(q)]
    val = jnp.sign(q.astype(jnp.float32)) * mag * z.scale[:, None]
    return val.astype(dtype).reshape(-1)[: z.size].reshape(z.shape)


# bump when the int8 code semantics change (v2 = dynamic LUT + sqrt-nu
# storage; v1 was linear absmax over raw nu). The version leaf makes a resume
# from an incompatible checkpoint fail LOUDLY at restore (tree-structure /
# value mismatch) instead of silently mis-decoding the moment payloads.
STATE_FORMAT = 2


class Adam8bitState(NamedTuple):
    count: jax.Array
    mu: dict
    nu: dict  # stores sqrt(nu) — see adam8bit docstring
    code_version: jax.Array  # == STATE_FORMAT


def adam8bit(
    learning_rate: float,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    clip_normalized: float = 5.0,
) -> optax.GradientTransformation:
    """Adam(lr) with int8 blockwise moment state. Defaults match
    bnb.optim.Adam8bit's (the reference passes only lr).

    Two hardening choices beyond bnb, both motivated by an observed RL
    blowup (see module docstring):

    * the second moment is stored as ``sqrt(nu)`` — squaring on dequant
      doubles the code's dynamic range in nu-space (grad ratios down to
      1e-7 of the block max stay representable, vs 3e-4 if nu were stored
      directly);
    * the normalized update ``mu_hat/(sqrt(nu_hat)+eps)`` is clipped to
      ``±clip_normalized`` (exact Adam keeps it near ±1, so 5.0 never binds
      on healthy steps) — the backstop for elements whose second moment
      still quantizes to zero, where the step would otherwise be
      ``mu_hat/eps ~ 1e8``.
    """

    def init_fn(params):
        zeros = jax.tree_util.tree_map(lambda p: _quantize(jnp.zeros_like(p, jnp.float32)), params)
        nu = jax.tree_util.tree_map(lambda p: _quantize(jnp.zeros_like(p, jnp.float32)), params)
        return Adam8bitState(
            count=jnp.zeros([], jnp.int32), mu=zeros, nu=nu,
            code_version=jnp.asarray(STATE_FORMAT, jnp.int32),
        )

    def update_fn(updates, state, params=None):
        count = state.count + 1
        def upd(g, mu_q, nu_q):
            g = g.astype(jnp.float32)
            mu = b1 * _dequantize(mu_q) + (1 - b1) * g
            nu = b2 * jnp.square(_dequantize(nu_q)) + (1 - b2) * g * g
            mu_hat = mu / (1 - b1 ** count.astype(jnp.float32))
            nu_hat = nu / (1 - b2 ** count.astype(jnp.float32))
            normalized = jnp.clip(
                mu_hat / (jnp.sqrt(nu_hat) + eps),
                -clip_normalized, clip_normalized,
            )
            step = -learning_rate * normalized
            return step, _quantize(mu), _quantize(jnp.sqrt(nu))

        flat_u, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = [upd(g, m, n) for g, m, n in zip(flat_u, flat_mu, flat_nu)]
        steps = treedef.unflatten([o[0] for o in out])
        new_mu = treedef.unflatten([o[1] for o in out])
        new_nu = treedef.unflatten([o[2] for o in out])
        steps = jax.tree_util.tree_map(
            lambda s, g: s.astype(g.dtype), steps, updates
        )
        return steps, Adam8bitState(
            count=count, mu=new_mu, nu=new_nu,
            code_version=state.code_version,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def check_state_format(opt_state) -> None:
    """Raise if a (restored) 8-bit Adam state's code version differs from
    this build's ``STATE_FORMAT`` — same-structure format changes would
    otherwise restore cleanly and silently mis-decode the moment payloads
    (different-structure changes already fail at Orbax restore)."""
    if isinstance(opt_state, Adam8bitState):
        got = int(opt_state.code_version)
        if got != STATE_FORMAT:
            raise ValueError(
                f"checkpointed 8-bit Adam state is format v{got}; this build "
                f"reads v{STATE_FORMAT} — restart without resume (the moment "
                "payloads are not decodable across formats)"
            )


def make_optimizer(lr: float, use_8bit: bool = True) -> optax.GradientTransformation:
    """The learner optimizer: Adam(lr), 8-bit state by default (reference:
    Adam8bit with no weight decay — distributed_actor.py:209–211)."""
    return adam8bit(lr) if use_8bit else optax.adam(lr)
