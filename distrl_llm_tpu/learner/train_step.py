"""The pjit'd learner update: grad-accumulated PG/GRPO step over the learner mesh.

Replaces the reference's entire update machinery — the per-learner microbatch
loop with loss/num_batches scaling (distributed_actor.py:352–389), the
CPU-pickled gradient dicts, the driver-side mean, and the one-learner optimizer
step (:283–333, distributed_trainer.py:308–342) — with ONE jitted function:

* microbatches run as a ``lax.scan`` over fixed-shape slices, accumulating
  gradients on device;
* data parallelism is the mesh's ``dp`` axis — the batch is sharded over it and
  GSPMD inserts the gradient ``psum`` (ICI), which also fixes the reference's
  stale-learner bug by construction (SURVEY §3.4): every learner shard applies
  the same merged update in the same step;
* the zero-reward microbatch skip implements the reference's *intent* (skip
  only when every reward in the microbatch is zero — the reference's
  ``batch_rewards.all() == 0`` actually skips when ANY reward is zero,
  SURVEY §3.6.3; set ``skip_semantics="any_zero"`` for bug-parity).

Batch layout (host-prepared by ``prepare_update_batch``): all arrays lead with
N = num_micro · micro_size; rows beyond the real sample count are padding with
``sample_mask`` 0.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.learner.losses import (
    answer_logprobs, grpo_aipo_loss, grpo_clip_loss, grpo_loss, kl_to_ref,
    pg_loss,
)
from distrl_llm_tpu.models.configs import ModelConfig

# the device-side IS-ratio histogram (ISSUE 16) pre-bins over the SAME
# bucket ladder the host registry uses, so LearnLedger can replay the
# counts through hist_observe(count=) and the registry's own bisect
# reproduces them exactly; one extra overflow slot past the last bound
_RATIO_BOUNDS: tuple[float, ...] = telemetry.HIST_BUCKET_BOUNDS
_GRAD_DEPTH_BUCKETS = 4  # LoRA grad-norm depth groups (a0..a3 / b0..b3)


class UpdateBatch(NamedTuple):
    """Fixed-shape flattened candidates for one policy update."""

    prompt_ids: jax.Array  # [N, P] int32, left-padded
    prompt_mask: jax.Array  # [N, P]
    answer_ids: jax.Array  # [N, T] int32, right-padded
    answer_mask: jax.Array  # [N, T]
    coeffs: jax.Array  # [N] f32 — reward−baseline (PG) or advantage (GRPO)
    sample_mask: jax.Array  # [N] f32 — 0 for padding rows
    # rollout-time logprobs of answer tokens [N, T] (engine-captured) — the
    # PPO-clip objective's behavior policy; None for the no-clip losses
    behavior_logps: jax.Array | None = None
    # per-token policy-version lag [N, T] (learner version − sampling
    # version, from the rollout trajectory tags) — the AIPO objective masks
    # tokens beyond max_staleness; None outside the async regime
    version_lag: jax.Array | None = None
    # multi-turn env rounds (ISSUE 17): [N, T] — answer_mask restricted to
    # POLICY-generated spans. Environment-injected observation tokens stay
    # in answer_mask (they are attention context for later turns — the
    # behavior policy conditioned on them) but are excluded here, so every
    # loss/metric term trains only policy spans; None = single-turn rounds,
    # loss masks on answer_mask as always
    loss_mask: jax.Array | None = None


def _microbatch_dynamics(
    logps, entropy, mb: UpdateBatch, *,
    clip_ratio: float, off_policy: str, is_cap: float,
) -> dict:
    """Per-microbatch training-dynamics SUMS (ISSUE 16), computed under
    ``stop_gradient`` from intermediates the loss already materialized —
    the ``lax.scan`` accumulates elementwise and ``_derive_dynamics``
    normalizes after, so the whole bundle rides the step's existing single
    host fetch. Keys are static per step build (the behavior-logprob
    entries exist only when the batch carries them)."""
    logps = jax.lax.stop_gradient(logps)
    # dynamics over TRAINABLE tokens: multi-turn rounds exclude env-injected
    # spans (their behavior logprobs are zeroed placeholders — counting them
    # would poison the KL/ratio stats with fake ratios)
    train_mask = mb.answer_mask if mb.loss_mask is None else mb.loss_mask
    mask = train_mask.astype(jnp.float32) * mb.sample_mask[:, None]
    real = mb.sample_mask
    dyn = {
        "tok_count": mask.sum(),
        "entropy_sum": (jax.lax.stop_gradient(entropy) * mask).sum(),
        # advantage moments over real rows (coeffs are the baseline-
        # subtracted rewards / group-normalized advantages)
        "adv_count": real.sum(),
        "adv_sum": (mb.coeffs * real).sum(),
        "adv_sq_sum": (jnp.square(mb.coeffs) * real).sum(),
        "adv_pos": ((mb.coeffs > 0.0).astype(jnp.float32) * real).sum(),
    }
    if mb.behavior_logps is not None:
        # behavior↔policy KL via the k3 estimator (kl_to_ref's idiom:
        # zero the exponent at pads BEFORE exp — garbage pad logprobs
        # would overflow exp and poison the sum through inf·0)
        diff = (mb.behavior_logps - logps) * mask
        dyn["kl_sum"] = ((jnp.exp(diff) - diff - 1.0) * mask).sum()
        # device-binned IS-ratio histogram: bisect_left over the shared
        # bucket ladder (searchsorted side="left" = the registry's
        # inclusive-le semantics), masked tokens weighted out
        log_ratio = (logps - mb.behavior_logps) * mask
        ratio = jnp.exp(log_ratio)
        bounds = jnp.asarray(_RATIO_BOUNDS, jnp.float32)
        idx = jnp.searchsorted(bounds, ratio, side="left")
        dyn["ratio_counts"] = (
            jax.nn.one_hot(idx, len(_RATIO_BOUNDS) + 1, dtype=jnp.float32)
            * mask[..., None]
        ).sum((0, 1))
        if clip_ratio > 0.0 and off_policy == "aipo":
            # AIPO cap saturation: tokens whose raw ratio the truncation
            # flattened — the silently-saturating regime the bundle exists
            # to surface (answer-mask scope; the version-lag mask is an
            # admission decision, not a saturation signal)
            dyn["cap_count"] = (
                (ratio >= is_cap).astype(jnp.float32) * mask
            ).sum()
        elif clip_ratio > 0.0:
            dyn["clip_count"] = (
                (jnp.abs(ratio - 1.0) > clip_ratio).astype(jnp.float32)
                * mask
            ).sum()
    return dyn


def _grad_norm_groups(grads, train_mode: str,
                      n_buckets: int = _GRAD_DEPTH_BUCKETS) -> dict:
    """Whole-tree grad norm, plus — for the LoRA pytree ``{"layers":
    {target: {"a": [L, …], "b": [L, …]}}}`` — per-group norms split A vs B
    and bucketed over the leading layer axis into ``n_buckets`` depth
    groups (summed across targets). Full-finetune trees get the total
    only."""
    leaves = jax.tree_util.tree_leaves(grads)
    total_sq = sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32))) for leaf in leaves
    )
    out = {"grad_norm_total": jnp.sqrt(total_sq)}
    layers = (
        grads.get("layers")
        if train_mode == "lora" and isinstance(grads, dict) else None
    )
    if not layers:
        return out
    for ab in ("a", "b"):
        per_layer = None  # [L] sum of squares across targets
        for target in layers.values():
            if ab not in target:
                continue
            g = target[ab].astype(jnp.float32)
            sq = jnp.sum(jnp.square(g), axis=tuple(range(1, g.ndim)))
            per_layer = sq if per_layer is None else per_layer + sq
        if per_layer is None:
            continue
        n = min(n_buckets, per_layer.shape[0])
        for i, seg in enumerate(jnp.array_split(per_layer, n)):
            out[f"grad_norm_{ab}{i}"] = jnp.sqrt(seg.sum())
    return out


def _derive_dynamics(sums, grads, *, train_mode: str) -> dict:
    """Normalize the scan-accumulated sums into the published bundle."""
    tok = jnp.maximum(sums["tok_count"], 1.0)
    nadv = jnp.maximum(sums["adv_count"], 1.0)
    adv_mean = sums["adv_sum"] / nadv
    adv_var = jnp.maximum(
        sums["adv_sq_sum"] / nadv - jnp.square(adv_mean), 0.0
    )
    dyn = {
        "entropy": sums["entropy_sum"] / tok,
        "tokens": sums["tok_count"],
        "adv_mean": adv_mean,
        "adv_std": jnp.sqrt(adv_var),
        "adv_pos_frac": sums["adv_pos"] / nadv,
    }
    if "kl_sum" in sums:
        dyn["kl"] = sums["kl_sum"] / tok
        dyn["ratio_counts"] = sums["ratio_counts"]
    if "cap_count" in sums:
        dyn["cap_frac"] = sums["cap_count"] / tok
    if "clip_count" in sums:
        dyn["clip_frac"] = sums["clip_count"] / tok
    dyn.update(_grad_norm_groups(grads, train_mode))
    return dyn


def _microbatch_loss(
    lora, base_params, cfg: ModelConfig, mb: UpdateBatch, *,
    learner_type: str, lora_scale: float, skip_semantics: str, remat: bool,
    attn_impl: str, attn_mesh=None, lora_dropout: float = 0.0,
    dropout_rng=None, logit_chunk: int = 0, train_mode: str = "lora",
    clip_ratio: float = 0.0, kl_coeff: float = 0.0,
    off_policy: str = "clip", is_cap: float = 2.0, max_staleness: int = 0,
    emit_dynamics: bool = False,
):
    """Loss for one microbatch with the zero-reward skip folded in as a weight.

    ``train_mode="lora"``: ``lora`` is the trainable adapter over the frozen
    ``base_params``. ``train_mode="full"``: ``lora`` IS the full trainable
    param tree (bf16 full-rank — BASELINE config 3's no-LoRA mode) and
    ``base_params`` is ignored.

    ``emit_dynamics`` (static) appends the per-microbatch dynamics sums to
    the aux pytree; off leaves the program and the aux shape exactly as
    before."""
    entropy = None
    if train_mode == "full":
        out = answer_logprobs(
            lora, cfg, mb.prompt_ids, mb.prompt_mask, mb.answer_ids,
            mb.answer_mask, lora=None, remat=remat,
            attn_impl=attn_impl, attn_mesh=attn_mesh,
            logit_chunk=logit_chunk, return_entropy=emit_dynamics,
        )
    else:
        out = answer_logprobs(
            base_params, cfg, mb.prompt_ids, mb.prompt_mask, mb.answer_ids,
            mb.answer_mask, lora=lora, lora_scale=lora_scale, remat=remat,
            attn_impl=attn_impl, attn_mesh=attn_mesh,
            lora_dropout=lora_dropout, dropout_rng=dropout_rng,
            logit_chunk=logit_chunk, return_entropy=emit_dynamics,
        )
    logps, entropy = out if emit_dynamics else (out, None)
    # loss terms mask on POLICY spans only (multi-turn env rounds);
    # answer_mask above stays the attention mask — env-injected tokens are
    # context the behavior policy conditioned on, they just don't train
    loss_m = mb.answer_mask if mb.loss_mask is None else mb.loss_mask
    if clip_ratio > 0.0 and off_policy == "aipo":
        # async regime: truncated-IS correction keyed on per-token version
        # lag (rollout/staleness.py) instead of the 1±ε clip — staleness up
        # to K steps makes ratios drift past the clip band, where the
        # clipped surrogate's gradient vanishes exactly on the samples that
        # need correcting
        loss = grpo_aipo_loss(
            logps, mb.behavior_logps, loss_m.astype(jnp.float32),
            mb.coeffs, mb.sample_mask, is_cap=is_cap,
            version_lag=mb.version_lag, max_staleness=max_staleness,
        )
    elif clip_ratio > 0.0:
        loss = grpo_clip_loss(
            logps, mb.behavior_logps, loss_m.astype(jnp.float32),
            mb.coeffs, mb.sample_mask, clip_ratio=clip_ratio,
        )
    else:
        loss_fn = grpo_loss if learner_type == "grpo" else pg_loss
        loss = loss_fn(
            logps, loss_m.astype(jnp.float32), mb.coeffs, mb.sample_mask
        )
    if kl_coeff > 0.0:
        # π_ref = the frozen base (no adapter): one extra stop-gradient
        # forward; the GRPO paper's KL term the reference never wires up
        ref_logps = jax.lax.stop_gradient(answer_logprobs(
            base_params, cfg, mb.prompt_ids, mb.prompt_mask, mb.answer_ids,
            mb.answer_mask, lora=None, remat=remat,
            attn_impl=attn_impl, attn_mesh=attn_mesh, logit_chunk=logit_chunk,
        ))
        loss = loss + kl_coeff * kl_to_ref(
            logps, ref_logps, loss_m.astype(jnp.float32),
            mb.sample_mask,
        )

    # The skip operates on COEFFS (baseline-subtracted rewards / advantages),
    # exactly like the reference: Learner.train flattens `r - b` and GRPO
    # flattens advantages BEFORE compute_loss tests `batch_rewards.all() == 0`
    # (distributed_actor.py:406, :495–504, :367). A GRPO group with identical
    # rewards therefore zeroes out and is skipped in both frameworks.
    real = mb.sample_mask > 0
    if skip_semantics == "any_zero":  # reference bug-parity (.all()==0)
        skip = jnp.any(real & (mb.coeffs == 0.0))
    else:  # "all_zero" — the documented intent
        skip = ~jnp.any(real & (mb.coeffs != 0.0))
    has_real = jnp.any(real)
    weight = jnp.where(skip | ~has_real, 0.0, 1.0)
    if emit_dynamics:
        dyn = _microbatch_dynamics(
            logps, entropy, mb,
            clip_ratio=clip_ratio, off_policy=off_policy, is_cap=is_cap,
        )
        return loss * weight, (weight, has_real.astype(jnp.float32), dyn)
    return loss * weight, (weight, has_real.astype(jnp.float32))


def make_train_step(
    cfg: ModelConfig,
    *,
    learner_type: str = "pg",
    optimizer: optax.GradientTransformation,
    lora_scale: float,
    micro_size: int,
    skip_semantics: str = "all_zero",
    remat: bool = True,
    attn_impl: str = "reference",
    attn_mesh=None,
    donate: bool = True,
    lora_dropout: float = 0.0,
    logit_chunk: int = 0,  # chunked fused-CE logprobs (losses.answer_logprobs)
    train_mode: str = "lora",  # "lora" | "full" (arg0 is the whole param tree)
    clip_ratio: float = 0.0,  # >0: PPO-clip surrogate over engine logprobs
    kl_coeff: float = 0.0,  # >0: + coeff·KL(π‖frozen base); LoRA mode only
    off_policy: str = "clip",  # "clip" (1±ε) | "aipo" (truncated IS, async)
    is_cap: float = 2.0,  # AIPO ratio truncation C
    max_staleness: int = 0,  # AIPO: mask tokens with version lag beyond this
    emit_dynamics: bool = False,  # ISSUE 16: fuse the dynamics bundle in
) -> Callable:
    """Build the jitted train step.

    Returns ``step(lora, opt_state, base_params, batch) -> (lora, opt_state,
    loss_sum)`` where ``loss_sum`` matches the reference's returned metric: the
    sum of unscaled microbatch losses (its ``total_loss`` accumulation at
    distributed_actor.py:387–389 cancels the /num_batches scaling).

    ``emit_dynamics=True`` (static) returns ``(lora, opt_state, loss_sum,
    dynamics)`` instead, where ``dynamics`` is the device-computed
    training-dynamics bundle (ISSUE 16): masked answer-token entropy,
    behavior↔policy KL + the pre-binned IS-ratio histogram + clip/cap
    saturation (only when the batch carries behavior logprobs), advantage
    moments, and per-layer-group grad norms — all derived under
    ``stop_gradient`` from intermediates the loss already materializes, so
    the loss/update subgraph is unchanged and the bundle rides the caller's
    existing single host fetch. Off compiles to the exact pre-ISSUE-16
    program.
    """

    if train_mode == "full" and kl_coeff > 0.0:
        # the config layer also rejects this; guard the mechanism too — in
        # full mode there is no frozen base to serve as the reference policy
        raise ValueError("kl_coeff requires train_mode='lora' (frozen base = ref)")
    if off_policy not in ("clip", "aipo"):
        raise ValueError(
            f"off_policy must be 'clip' or 'aipo', got {off_policy!r}"
        )
    loss_fn = partial(
        _microbatch_loss,
        cfg=cfg,
        learner_type=learner_type,
        lora_scale=lora_scale,
        skip_semantics=skip_semantics,
        remat=remat,
        attn_impl=attn_impl,
        attn_mesh=attn_mesh,
        lora_dropout=lora_dropout,
        logit_chunk=logit_chunk,
        train_mode=train_mode,
        clip_ratio=clip_ratio,
        kl_coeff=kl_coeff,
        off_policy=off_policy,
        is_cap=is_cap,
        max_staleness=max_staleness,
        emit_dynamics=emit_dynamics,
    )

    def step(lora, opt_state, base_params, batch: UpdateBatch,
             dropout_rng=None):
        n = batch.prompt_ids.shape[0]
        assert n % micro_size == 0, f"batch {n} not divisible by micro {micro_size}"
        num_micro = n // micro_size
        micro = jax.tree_util.tree_map(
            lambda x: x.reshape((num_micro, micro_size) + x.shape[1:]), batch
        )

        grad_fn = jax.value_and_grad(
            lambda lo, mb, key: loss_fn(lo, base_params, mb=mb, dropout_rng=key),
            has_aux=True,
        )
        # independent dropout masks per microbatch (None → dropout disabled)
        micro_keys = (
            jax.random.split(dropout_rng, num_micro)
            if dropout_rng is not None else None
        )

        def accumulate(carry, xs):
            mb, key = xs
            grads_acc, loss_acc, nb_acc = carry
            (loss, aux), grads = grad_fn(lora, mb, key)
            weight, has_real = aux[0], aux[1]
            grads_acc = jax.tree_util.tree_map(jnp.add, grads_acc, grads)
            # dynamics sums ride the scan's ys output (stacked then summed
            # below) so the carry shape is untouched; None when off — the
            # exact pre-ISSUE-16 scan
            ys = aux[2] if emit_dynamics else None
            return (grads_acc, loss_acc + loss, nb_acc + has_real), ys

        zero_grads = jax.tree_util.tree_map(jnp.zeros_like, lora)
        (grads, loss_sum, num_real_micro), dyn_stacked = jax.lax.scan(
            accumulate, (zero_grads, jnp.zeros([]), jnp.zeros([])),
            (micro, micro_keys),
        )
        # reference scaling: each microbatch contributes grad/num_batches
        # (distributed_actor.py:382); num_batches counts microbatches with real
        # rows, skipped-or-not — padding-only microbatches are excluded.
        denom = jnp.maximum(num_real_micro, 1.0)
        grads = jax.tree_util.tree_map(lambda g: g / denom, grads)

        dynamics = None
        if emit_dynamics:
            sums = jax.tree_util.tree_map(
                lambda x: x.sum(axis=0), dyn_stacked
            )
            # grad norms read the averaged grads the optimizer consumes —
            # the same tree, pure reads, no effect on the update
            dynamics = _derive_dynamics(sums, grads, train_mode=train_mode)
        updates, opt_state = optimizer.update(grads, opt_state, lora)
        lora = optax.apply_updates(lora, updates)
        if emit_dynamics:
            return lora, opt_state, loss_sum, dynamics
        return lora, opt_state, loss_sum

    return jax.jit(step, donate_argnums=(0, 1) if donate else ())


def _bucket_width(mask, buckets, cap: int) -> int:
    """Smallest bucket holding the longest real row of ``mask`` (row length
    = mask sum), capped at ``cap``; ``cap`` when no bucket is large enough.
    The single owner of the learner-side bucket-selection rule (the engine's
    ``bucket_for`` is the same rule over its own bucket list)."""
    lens = np.asarray(mask).sum(axis=1)
    need = max(1, int(lens.max()) if lens.size else 1)
    return min(next((b for b in sorted(buckets) if b >= need), cap), cap)


def prepare_update_batch(
    tokenizer,
    problems: list[str],
    answers: list[str],
    coeffs: np.ndarray,
    *,
    max_prompt_tokens: int,
    max_new_tokens: int,
    micro_size: int,
    mesh=None,
    raw_rollout: dict | None = None,
    answer_buckets: "Sequence[int] | None" = None,
    prompt_buckets: "Sequence[int] | None" = None,
    current_version: int | None = None,
) -> UpdateBatch:
    """Host-side tokenize+pad to the fixed learner shapes.

    Mirrors the reference's encode calls (distributed_actor.py:217–229):
    prompts left-padded/truncated to max_prompt_tokens, answers right-padded/
    truncated to max_new_tokens. N is padded up to a multiple of micro_size
    with sample_mask-0 rows so the scan shape is static.

    ``answer_buckets``: learner-side length bucketing (the engine's
    prompt-bucket idea applied to the update step). The answer width is cut
    to the smallest bucket holding the batch's LONGEST real answer instead
    of always padding to max_new_tokens — the reference pads every row to
    the full window (distributed_actor.py:224–229), which at its own ~470
    mean generation length wastes ~60% of learner FLOPs on masked padding.
    Dropping trailing all-masked columns is exact (masked positions
    contribute zero loss and are causally invisible to real positions —
    pinned by TestAnswerBuckets parity). One compiled step per bucket
    width; buckets cap the recompile count.

    ``prompt_buckets``: the same cut on the LEFT-padded prompt side
    (leading all-masked columns dropped to the smallest bucket holding the
    longest real prompt) — reuses the engine's prompt-bucket config, since
    learner prompts are the same strings the engine saw. Equality here is
    up to RoPE float round-off rather than bit-exact: dropping k leading
    columns shifts every position in a row by the same constant, and RoPE
    attention depends only on relative distance (the same invariance the
    left-padded golden test pins), but the absolute angles differ.

    When ``mesh`` is given, every array is placed on it with the row dim over
    "dp" — the learner-mesh equivalent of the reference dispatching chunks to
    learner processes (distributed_trainer.py:312–327).
    """
    from distrl_llm_tpu.tokenizer import encode_fixed

    n_real = len(problems)
    prompt_ids, prompt_mask = encode_fixed(
        tokenizer, problems, max_prompt_tokens, side="left"
    )
    if prompt_buckets:
        # prompts are LEFT-padded: keep the trailing `width` columns
        # (leading all-masked columns are pure padding — exactly the
        # engine's bucket slice, engine.py::_generate_wave)
        p_width = _bucket_width(prompt_mask, prompt_buckets, max_prompt_tokens)
        if p_width < max_prompt_tokens:
            prompt_ids = np.asarray(prompt_ids)[:, -p_width:]
            prompt_mask = np.asarray(prompt_mask)[:, -p_width:]
    behavior_logps = None
    version_lag = None
    loss_mask = None
    if raw_rollout is not None:
        # PPO-clip path: train on the ENGINE'S token ids (retokenizing the
        # decoded text can shift token boundaries and desync the per-token
        # behavior logprobs — flatten_for_update docstring)
        eng_tokens = np.asarray(raw_rollout["answer_tokens"], np.int32)
        eng_logps = np.asarray(raw_rollout["behavior_logps"], np.float32)
        t_eng = eng_tokens.shape[1]
        width = min(t_eng, max_new_tokens)
        answer_ids = np.zeros((n_real, max_new_tokens), np.int32)
        behavior = np.zeros((n_real, max_new_tokens), np.float32)
        answer_ids[:, :width] = eng_tokens[:, :width]
        behavior[:, :width] = eng_logps[:, :width]
        # mask from real generated lengths: engine pads after EOS with a pad
        # token whose id may be a REAL vocab id, so the text-derived mask
        # cannot be reused
        # defensive clamp: engine lengths are bounded by the engine's token
        # buffer (t_eng), but if that invariant ever broke, an unclamped
        # length would unmask positions holding zero-filled ids / logprobs
        lengths = np.minimum(np.asarray(raw_rollout["lengths"], np.int32), width)
        answer_mask = (
            np.arange(max_new_tokens)[None, :] < lengths[:, None]
        ).astype(np.int32)
        if "loss_mask" in raw_rollout:
            # multi-turn env rounds (ISSUE 17): environment-injected
            # observation tokens stay in answer_mask (attention context —
            # the behavior policy conditioned on them) but are excluded
            # from the separate loss mask so they never train
            lm = np.zeros((n_real, max_new_tokens), np.int32)
            lm_src = np.asarray(raw_rollout["loss_mask"], np.int32)
            lm[:, :width] = lm_src[:, :width]
            loss_mask = answer_mask * lm
        behavior_logps = behavior
        if current_version is not None and "version_tags" in raw_rollout:
            # per-token optimizer-step lag from the rollout version tags
            # (rollout/trajectory.py); padded columns get lag 0 — they are
            # masked anyway, and a large filler value would trip the AIPO
            # staleness mask's comparison on garbage positions
            tags = np.asarray(raw_rollout["version_tags"], np.int32)
            version_lag = np.zeros((n_real, max_new_tokens), np.float32)
            version_lag[:, :width] = np.maximum(
                current_version - tags[:, :width], 0
            )
            version_lag *= loss_mask if loss_mask is not None else answer_mask
    else:
        answer_ids, answer_mask = encode_fixed(
            tokenizer, answers, max_new_tokens, side="right"
        )
    if answer_buckets:
        # smallest bucket holding the longest real answer (answers are
        # right-padded, so trailing columns past it are all-masked and
        # dropping them is exact); no bucket large enough → full width
        width = _bucket_width(answer_mask, answer_buckets, max_new_tokens)
        if width < max_new_tokens:
            answer_ids = np.asarray(answer_ids)[:, :width]
            answer_mask = np.asarray(answer_mask)[:, :width]
            if behavior_logps is not None:
                behavior_logps = behavior_logps[:, :width]
            if version_lag is not None:
                version_lag = version_lag[:, :width]
            if loss_mask is not None:
                loss_mask = loss_mask[:, :width]
    n = -(-max(n_real, 1) // micro_size) * micro_size
    pad = n - n_real

    def pad_rows(x):
        return np.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))

    sample_mask = np.zeros(n, np.float32)
    sample_mask[:n_real] = 1.0
    batch = UpdateBatch(
        prompt_ids=jnp.asarray(pad_rows(prompt_ids)),
        prompt_mask=jnp.asarray(pad_rows(prompt_mask)),
        answer_ids=jnp.asarray(pad_rows(np.asarray(answer_ids))),
        answer_mask=jnp.asarray(pad_rows(np.asarray(answer_mask))),
        coeffs=jnp.asarray(pad_rows(np.asarray(coeffs, np.float32))),
        sample_mask=jnp.asarray(sample_mask),
        behavior_logps=(
            jnp.asarray(pad_rows(behavior_logps))
            if behavior_logps is not None else None
        ),
        version_lag=(
            jnp.asarray(pad_rows(version_lag))
            if version_lag is not None else None
        ),
        loss_mask=(
            jnp.asarray(pad_rows(np.asarray(loss_mask)))
            if loss_mask is not None else None
        ),
    )
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        # rows shard over dp only if the count divides evenly; otherwise the
        # batch stays replicated (tiny smoke runs) rather than failing
        def place(x):
            dp = mesh.shape["dp"]
            spec = P("dp", *([None] * (x.ndim - 1))) if x.shape[0] % dp == 0 else P()
            return jax.device_put(x, NamedSharding(mesh, spec))

        batch = jax.tree_util.tree_map(place, batch)
    return batch
