from distrl_llm_tpu.learner.losses import (  # noqa: F401
    answer_logprobs,
    entropy_bonus,
    grpo_loss,
    pg_loss,
)
from distrl_llm_tpu.learner.optim import adam8bit, make_optimizer  # noqa: F401
from distrl_llm_tpu.learner.train_step import (  # noqa: F401
    UpdateBatch,
    make_train_step,
    prepare_update_batch,
)
