"""Typed configuration for the TPU-native distributed RL framework.

Replaces the reference's flat argparse→dict config (train_distributed.py:10–35,
:54–81 in BY571/DistRL-LLM). Every reference flag name and default is preserved —
the CLI contract is part of parity — plus TPU-specific knobs (mesh shape, chip
roles, dtype/quantization policy) the reference expressed as GPU-process counts.

One deliberate default divergence: ``model`` defaults to the plain
"Qwen/Qwen2.5-7B-Instruct" checkpoint rather than the reference's GPU-only
"unsloth/Qwen2.5-7B-Instruct-bnb-4bit"; NF4-style base quantization is the
orthogonal ``base_quant`` knob here.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any


@dataclass
class SamplingConfig:
    """Sampling parameters for a generation round.

    Mirrors the reference's vllm.SamplingParams usage: train-time params built
    from the GenerationConfig (distributed_actor.py:43–48), eval-time params
    hardcoded (distributed_trainer.py:53–58).
    """

    max_tokens: int = 1200
    temperature: float = 1.2
    top_p: float = 0.95
    n: int = 16  # candidates per prompt
    # top-p filter implementation: False = sort-free bisection (fast path;
    # kept set is a superset of the exact nucleus by at most the boundary
    # tie mass), True = exact rank-based sort filter matching the reference's
    # vLLM semantics — for eval/reproducibility runs (ADVICE r1).
    top_p_exact: bool = False
    # explicit impl override (a key of ops.sampling.TOP_P_IMPLS, e.g.
    # "bisect_mw"); None derives from top_p_exact. Engines resolve via
    # resolved_top_p_impl().
    top_p_impl: str | None = None

    def resolved_top_p_impl(self, plan_default: str | None = None) -> str:
        """Effective top-p implementation. Priority: an explicit
        ``top_p_impl`` pin, then ``top_p_exact`` (reference semantics were
        asked for by name), then the engine's autotuned plan default
        (``plan_default`` — ExecutionPlan.top_p_impl), then "bisect"."""
        if self.top_p_impl:  # "" and None both mean "derive"
            from distrl_llm_tpu.ops.sampling import TOP_P_IMPLS

            if self.top_p_impl not in TOP_P_IMPLS:
                raise ValueError(
                    f"top_p_impl must be one of {sorted(TOP_P_IMPLS)}, "
                    f"got {self.top_p_impl!r}"
                )
            return self.top_p_impl
        if self.top_p_exact:
            return "exact"
        if plan_default:
            # already validated: plan_default only ever carries
            # ExecutionPlan.top_p_impl, checked against TOP_P_IMPLS at plan
            # construction (autotune/plan.py)
            return plan_default
        return "bisect"

    def replace(self, **kw) -> "SamplingConfig":
        return dataclasses.replace(self, **kw)


def parse_buckets(
    spec: str | None, field: str = "prompt_buckets"
) -> tuple[int, ...]:
    """Parse a comma-separated bucket list ("128,256") into a tuple; shared
    by the CLI and bench so the format cannot drift. ``field`` names the
    flag in the error message."""
    if not spec:
        return ()
    try:
        return tuple(int(x) for x in str(spec).split(",") if x.strip())
    except ValueError as e:
        raise ValueError(
            f"{field} must be comma-separated integers, got {spec!r}"
        ) from e


@dataclass
class MeshConfig:
    """How chips are carved into roles and parallelism axes.

    The reference maps roles to whole GPUs via Ray placement groups
    (distributed_actor.py:517–585). Here roles are partitions of one global
    ``jax.sharding.Mesh``: the first ``number_of_actors`` data-parallel groups
    are rollout chips, the next ``number_of_learners`` groups are learner chips.
    Within a group, ``tp`` shards attention heads / MLP and ``sp`` shards
    sequence for ring attention.
    """

    number_of_actors: int = 2
    number_of_learners: int = 1
    tp: int = 1  # tensor-parallel size within each role group
    sp: int = 1  # sequence-parallel (ring attention) size
    fsdp: int = 1  # parameter sharding of the learner state
    # When there are fewer physical devices than roles (e.g. 1 chip), roles
    # time-share the whole mesh instead of partitioning it; this matches the
    # reference's hybrid learner-generation behavior in spirit.
    allow_timeshare: bool = True

    @property
    def num_roles(self) -> int:
        return self.number_of_actors + self.number_of_learners


@dataclass
class TrainConfig:
    """Full training configuration. Field names follow the reference CLI
    (train_distributed.py:10–35); TPU-specific fields are grouped at the end."""

    # --- reference CLI contract -------------------------------------------
    model: str = "Qwen/Qwen2.5-7B-Instruct"
    dataset: str = "HuggingFaceH4/MATH-500"
    run_name: str | None = None
    project_name: str = "math-reasoning"
    lora_save_path: str = "lora_request_math"
    lr: float = 2e-5
    max_new_tokens: int = 1200
    max_prompt_tokens: int = 350
    temperature: float = 1.2
    episodes: int = 15
    num_candidates: int = 16
    batch_size: int = 30
    learner_chunk_size: int = 8
    train_batch_size: int = 8
    save_every: int = 100
    eval_every: int = 10
    number_of_actors: int = 2
    number_of_learners: int = 1
    learner: str = "pg"  # {"pg", "grpo"}
    max_lora_rank: int = 32
    # float (16 == 16.0 keeps reference-dict parity): lora_scale is
    # alpha/rank float math and worker_main --lora-alpha is float — an
    # int-only driver could not express an alpha the workers accept
    lora_alpha: float = 16.0
    lora_dropout: float = 0.0
    topk: int = 16
    # HBM fraction for weights+KV (vLLM gpu_memory_utilization contract,
    # ref train_distributed.py:34-35): sizes the paged engine's KV page
    # pool (engine/budget.py). actor_gpu_usage applies on disjoint rollout
    # meshes (the reference's actor GPUs); learner_gpu_usage applies when
    # roles timeshare one mesh (the reference's learner GPU, where training
    # state shares the chip with the engine).
    actor_gpu_usage: float = 0.91
    learner_gpu_usage: float = 0.35

    # --- TPU-native additions ---------------------------------------------
    mesh: MeshConfig = field(default_factory=MeshConfig)
    seed: int = 3407  # reference fixes random_state=3407 (helper.py:43)
    dtype: str = "bfloat16"
    # weight-only quantization of the frozen base: {"none","int8","int4"}
    # (reference uses NF4 via bitsandbytes — LOAD_IN_4BIT, distributed_actor.py:17)
    base_quant: str = "none"
    # quantization group size along the input dim for base_quant (ISSUE 15):
    # None = per-format default (int8: per-column scales; int4: 64-wide
    # blocks, bnb's blockwise NF4 knob). Must divide the model's projection
    # input dims; requires base_quant != "none" (dead-flag policy).
    quant_group_size: int | None = None
    # 8-bit blockwise optimizer state (reference: bnb.optim.Adam8bit, :209)
    optimizer_8bit: bool = True
    # Skip semantics for all-zero-reward microbatches. The reference intends
    # "skip if all rewards are zero" but `.all() == 0` skips when ANY reward is
    # zero (distributed_actor.py:367 — SURVEY §3.6.3). We implement the intent.
    skip_all_zero_reward_batches: bool = True
    eval_temperature: float = 0.6
    eval_top_p: float = 0.95
    eval_n: int = 8
    # use the exact sort-based nucleus filter (reference vLLM semantics)
    # instead of the fast bisection filter, for reproducibility runs
    top_p_exact: bool = False
    # chunked fused-cross-entropy logprobs in the learner (unsloth CE-kernel
    # equivalent, SURVEY §2b N3): lm_head + logsumexp run per time-chunk of
    # this many answer positions under scan+checkpoint, shrinking the live
    # logits buffer from [B, T, V] to [B, chunk, V] with bit-identical math.
    # 0 = dense. At the default learner shapes (8×1200×152k vocab, f32)
    # chunk=128 is ~5.8 GB → ~0.6 GB of logits memory.
    logprob_chunk: int = 128
    # bf16 full-rank fine-tuning (BASELINE config 3: "bf16 full-rank, no
    # 4-bit"): the WHOLE param tree trains instead of a LoRA adapter; weight
    # sync pushes the full tree to the rollout mesh each step. Requires an
    # unquantized base; LoRA rank/alpha/dropout and the adapter-file writer
    # do not apply.
    full_finetune: bool = False
    # prompt length buckets for the rollout engine (SURVEY §2b N1): each
    # round compiles/runs at the smallest bucket holding its longest real
    # prompt. Empty = single bucket at max_prompt_tokens.
    prompt_buckets: tuple[int, ...] = ()
    # answer length buckets for the LEARNER update step: each update runs at
    # the smallest bucket holding the batch's longest real answer instead of
    # always padding to max_new_tokens (the reference pads every row to the
    # full window, distributed_actor.py:224–229 — ~60% wasted learner FLOPs
    # at its own ~470-token mean). Exact semantics (trailing all-masked
    # columns contribute nothing); one compiled step per bucket. Empty =
    # single width at max_new_tokens.
    learner_len_buckets: tuple[int, ...] = ()
    # the same cut on the learner's LEFT-padded prompt side (leading
    # all-masked columns dropped). Deliberately a SEPARATE flag from the
    # engine's prompt_buckets: the learner slice shifts absolute RoPE
    # positions (exact only up to float round-off — relative distances are
    # unchanged) and multiplies compiled step widths, so it must be an
    # explicit opt-in rather than riding an engine knob.
    learner_prompt_buckets: tuple[int, ...] = ()
    # rollout engine implementation: "dense" (fixed-shape cache), "paged"
    # (packed ragged KV pages + Pallas paged-attention decode — the full N1),
    # or "paged_sharded" (ONE paged engine whose page pool is partitioned
    # over the rollout mesh's dp axis via shard_map — engine/sharded_paged.py;
    # wave scheduler, dp-only meshes)
    engine_impl: str = "dense"
    # KV cache quantization: "none" or "int8" (per-token absmax; the
    # compact-scales Pallas launches keep per-element traffic at
    # ~1 byte — ops/paged_int8.py — so int8 KV is a bandwidth AND capacity
    # knob). None (default) = let the autotune plan DB decide per
    # (device, model, geometry) via ExecutionPlan.kv_format — the int8
    # serving default is MEASURED in, not hard-coded; with an empty DB the
    # engines fall back to "none", byte-identical to the historical
    # default. An EXPLICIT value — including "none" — always wins over any
    # stored plan (the decode_scan_chunk convention: default ≠ pin).
    kv_cache_quant: str | None = None
    # K decode steps per dispatch in the dense engine (lax.scan inside one
    # jitted program). Over a network-tunneled PJRT client each dispatch can
    # cost a round trip that bounds decode throughput regardless of chip
    # speed (tools/dispatch_probe.py measures it); chunking divides that
    # overhead by K. The engine compile-checks the chunked program's
    # memory_analysis and falls back to one dispatch per step if the TPU
    # compiler double-buffered the KV cache in the scan carry.
    # None (default) = let the autotune plan DB decide (static default: off);
    # an EXPLICIT value — including 0 — always wins over any stored plan.
    decode_scan_chunk: int | None = None
    # execution-plan autotuner (distrl_llm_tpu/autotune): engines resolve
    # their dispatch choices (scan chunk, cache-read formulation, top-p
    # impl, prompt buckets) from a persistent DB of on-device measurements
    # instead of hard-coded guesses. Explicitly-set flags always win; with
    # no DB entry behavior is byte-identical to the static defaults.
    # autotune=False pins the static defaults without consulting any DB.
    autotune: bool = True
    # plan-DB path (tools/autotune.py writes it). None = $DISTRL_PLAN_DB or
    # ~/.cache/distrl_llm_tpu/plan_db.json
    plan_db: str | None = None
    # control-plane rollout workers ("host:port", ...): when set, generation
    # dispatches to these worker processes (distributed/worker_main.py) over
    # the C++ control plane instead of running on local chips — the
    # multi-host actor fan-out (SURVEY §2b N5). The adapter ships with every
    # round; the local mesh serves the learner only.
    rollout_workers: tuple[str, ...] = ()
    # driver-side declaration that every rollout worker was started with
    # worker_main --capture-logprobs (its engine records behavior logprobs
    # per token). Required for clip_ratio > 0 / rollout_mode="async" over
    # workers — the driver cannot introspect worker engine flags, and a
    # worker round returning no logprobs fails the first training batch.
    workers_capture_logprobs: bool = False
    # learner→worker weight transport for rollout_workers (ISSUE 9):
    # "broadcast" (default) ships each optimizer step's adapter ONCE per
    # version over an out-of-band MSG_WEIGHTS push — delta-encoded against
    # the worker's last acked version, full-tensor on first contact or
    # checksum mismatch — and MSG_DISPATCH payloads carry only a
    # {weight_version} reference resolved from the worker's 2-slot adapter
    # cache. "dispatch" is the legacy fallback: the full LoRA pytree rides
    # in every dispatch payload (N workers × every round). Broadcast is
    # what makes inflight_weight_updates possible over remote workers.
    weight_bus: str = "broadcast"
    # --- control-plane resilience (distributed/resilience.py) -------------
    # background reconnect loop: unhealthy rollout workers are re-dialed
    # with seeded exponential backoff and re-admitted after a PING, so
    # capacity recovers instead of shrinking monotonically to "no healthy
    # workers remain". The first round after a rejoin re-warms (the fresh
    # worker process recompiles, so the cold deadline applies again).
    worker_rejoin: bool = True
    # transient worker-side errors (MSG_ERROR classified by exception type:
    # OSError / Connection* / Timeout flavors) retry on the same worker
    # this many times with seeded exponential backoff (base rpc_backoff_s)
    # before the shard is requeued to a different worker
    rpc_retries: int = 2
    rpc_backoff_s: float = 0.25
    # poison-shard quarantine: a shard that fails on this many DISTINCT
    # workers raises ShardFailedError naming the shard instead of grinding
    # every worker to unhealthy
    poison_shard_k: int = 3
    # degrade instead of raise on a quarantined shard: the round returns
    # the surviving groups (lost prompts are dropped by the trainer with
    # exact conservation accounting, counted in cp/degraded_groups) rather
    # than failing the run
    degrade_on_poison: bool = False
    # supervised restart budget for the async RolloutService producer: a
    # failed produce round retries in place (seeded backoff) this many
    # times across the run before the failure closes the buffer and
    # surfaces (rollout/producer_restarts counts the retries)
    producer_restarts: int = 2
    # cap on concurrent candidate rows in the rollout engine (vLLM
    # max_num_seqs; the reference tunes the same capacity knob — 256
    # concurrent sequences, train_distributed.py:34). 0 = unlimited; rounds
    # beyond the cap run as sequential waves of whole prompt groups.
    max_concurrent_sequences: int = 0
    # continuous batching for the paged engine: keep exactly
    # max_concurrent_sequences candidate rows decoding and admit a pending
    # candidate into every slot whose occupant hit EOS (vLLM's scheduler),
    # instead of draining whole waves. Requires engine_impl="paged" and a
    # max_concurrent_sequences cap.
    continuous_batching: bool = False
    # copy-on-write prompt-prefix sharing (ISSUE 12): a group's N rollouts
    # alias ONE refcounted prompt page chain (vLLM prefix caching) instead
    # of each holding a private copy — the partial tail page splits
    # copy-on-write at first decode write, prompt KV is resident ~once per
    # group, and finished groups' prompt pages recycle into decode
    # capacity. Greedy outputs are bit-identical to the unshared engine
    # (pinned in tests/test_prefix_sharing.py). Requires
    # continuous_batching (the refill scheduler's slot machinery).
    prefix_sharing: bool = False
    # serving-grade continuous admission (ISSUE 12): replace the
    # fixed-episode-batch prefill with a group request queue — each
    # prompt prefills lazily into pool-allocated chain pages as freed
    # slots and page budget allow, so short completions backfill
    # immediately instead of idling until the batch drains. Implies
    # prefix_sharing (chains are pool-allocated); requires
    # continuous_batching. Leaving BOTH flags unset keeps the engine
    # plan-DB-resolvable (a stored cb_mode="continuous" entry may enable
    # it; empty DB = historical fixed batches, byte-identical).
    continuous_admission: bool = False
    # tiered KV cache, tier 1 (ISSUE 18): cross-request radix prefix index
    # over the continuous-admission pool — any prompt sharing a cached
    # prefix (multi-turn history, shared task preambles) aliases those
    # pages and prefills ONLY its un-cached suffix, with unpinned cache
    # nodes LRU-evicted under page pressure. Greedy outputs stay
    # bit-identical to the cache-off engine (the warm suffix prefill runs
    # the same packed attention numerics over the cached pages —
    # tests/test_prefix_sharing.py pins it). None = plan-DB-resolvable
    # (stored prefix_cache="on" enables; empty DB = off, byte-identical);
    # an explicit bool — including False — pins past any stored plan.
    # Requires continuous_admission and an unquantized KV pool.
    prefix_cache: bool | None = None
    # tiered KV cache, tier 2 (ISSUE 18): preempted chains spill their
    # written KV pages to a host-RAM page store on a background thread and
    # restore bit-exactly on resume (no recompute); idle cache nodes spill
    # on eviction and page back in on the next radix hit. Explicit-only
    # (never plan-resolved); requires prefix_cache; incompatible with
    # spec_draft (speculative chains resume by recompute).
    kv_spill: bool = False
    # host page-store byte cap in MiB for kv_spill (0 = unbounded); the
    # store LRU-drops whole payloads past the cap, and a dropped preempt
    # payload falls back to the recompute resume path
    kv_spill_host_mb: int = 0
    # speculative decoding for the paged refill engine: draft spec_draft
    # tokens per step and verify them in one forward (the verify attention
    # runs as ONE fused blocked kernel sweep — spec_verify); rejection
    # sampling keeps the output distribution identical to plain decoding
    # (exact under greedy). Requires continuous_batching. None = engine
    # default (off unless a tuned plan-DB entry for this geometry says
    # otherwise); an EXPLICIT value — INCLUDING 0 — pins the choice past
    # any stored plan (the decode_scan_chunk convention: default ≠ pin),
    # so --spec_draft 0 is always a real A/B control.
    spec_draft: int | None = None
    # lookup n-gram size for the ngram drafter. None = engine default (2)
    # unless a tuned plan-DB entry says otherwise; an explicit value pins
    # past any stored plan (the decode_scan_chunk convention).
    spec_ngram: int | None = None
    # draft source: "ngram" (prompt lookup over the row's own history) or
    # "self" — the policy's own PREVIOUS LoRA version, sourced from the
    # in-flight weight-update swap log (PipelineRL: recent-checkpoint
    # weights stay near-on-policy, so the previous version is a
    # high-acceptance draft model for free). "self" needs a LoRA run (the
    # drafter rides the adapter mailbox; full_finetune has no adapter
    # stream to draft from). None = engine default ("ngram") unless a
    # tuned plan-DB entry says otherwise; an EXPLICIT value — including
    # "ngram" itself — pins the choice past any stored plan (the
    # decode_scan_chunk convention: default ≠ pin).
    spec_drafter: str | None = None
    # verify-attention kernel: "fused" (one blocked Pallas sweep for the
    # whole draft block; probe-gated with an exact unrolled fallback) or
    # "unrolled" (d+1 per-position dispatches — the A/B control). None =
    # engine default ("fused") / plan-DB; explicit value pins.
    spec_verify: str | None = None
    # acceptance-rate-driven draft-length adaptation: shrink the effective
    # draft length (halving, floor 1) when the accept-rate EMA says drafts
    # are being wasted, grow it back when acceptance recovers
    spec_adapt: bool = False
    # Rollout/learner coupling regime (distrl_llm_tpu/rollout):
    #   "sync"      — the reference's strictly synchronous loop: generation
    #                 and learning serialize; byte-identical to the pre-async
    #                 trainer (pinned by tests/test_rollout_modes.py).
    #   "pipelined" — one-step overlap (LlamaRL/PipelineRL-style): batch t+1
    #                 generates WHILE the learner updates on batch t;
    #                 rollouts sample exactly one optimizer step stale.
    #   "async"     — fully decoupled: a RolloutService generates
    #                 continuously into a bounded trajectory buffer and the
    #                 learner pulls batches on its own cadence; staleness is
    #                 bounded by max_staleness and corrected by the
    #                 AIPO/truncated-IS objective over per-token version
    #                 tags (requires clip_ratio > 0 for the engine-captured
    #                 behavior logprobs the correction ratios against).
    rollout_mode: str = "sync"
    # staleness bound for rollout_mode="async": trajectories whose stalest
    # token lags the learner by more than this many optimizer steps are
    # dropped or down-weighted (staleness_policy) and the version-lag mask
    # inside the AIPO objective enforces the same bound token-wise.
    # sync/pipelined derive their allowed lag (0 / 1) from the mode.
    max_staleness: int = 2
    # trajectory-buffer capacity in task GROUPS for rollout_mode="async";
    # 0 = auto (4 × batch_size, floor 2 × batch_size — the learner pulls
    # batch_size groups per update, so the floor keeps a get from
    # deadlocking against producer backpressure)
    rollout_buffer_groups: int = 0
    # what happens to a pulled group beyond max_staleness: "drop" (discard,
    # counted in rollout/dropped_stale) or "downweight" (train with its
    # update coefficients scaled by staleness_downweight^(lag − K))
    staleness_policy: str = "drop"
    staleness_downweight: float = 0.5
    # AIPO truncation cap C for the async objective's per-token importance
    # ratio min(exp(logp_cur − logp_behavior), C)
    rollout_is_cap: float = 2.0
    # DEPRECATED alias for --rollout_mode pipelined (the pre-rollout-service
    # spelling): async_rollout=True with the default rollout_mode selects
    # "pipelined"; after __post_init__ this field always reads as
    # (rollout_mode != "sync") so existing call sites keep working.
    async_rollout: bool = False
    # in-flight weight updates (PipelineRL-style): push each optimizer
    # step's adapter into the generation round still in flight instead of
    # waiting for it to drain — the engines swap at the next decode
    # dispatch, and the PPO-clip objective ratios every token against the
    # captured behavior logprob of the policy that actually sampled it.
    # Requires async_rollout (there must BE an in-flight round), clip_ratio
    # > 0 (the off-policy correction), local LoRA rollout.
    inflight_weight_updates: bool = False
    # PPO-clip surrogate epsilon (0 = reference parity: the no-KL/no-clip
    # single-update objective). With clip_ratio > 0 the learner ratios the
    # current policy against ENGINE-CAPTURED behavior logprobs
    # (GenerationResult.logprobs — the vLLM-logprobs equivalent) and trains
    # on the engine's raw token ids, making updates stable off-policy
    # (async_rollout staleness; the reference's documented long-training
    # instability, README.md:91).
    clip_ratio: float = 0.0
    # KL(π‖π_ref) penalty coefficient (the GRPO paper's regularizer; the
    # reference never loads a reference model — SURVEY §3.6.2). π_ref is the
    # FROZEN BASE, so this is LoRA-mode only (full_finetune would need a
    # second resident tree) and costs one extra no-adapter forward.
    kl_coeff: float = 0.0
    # per-update sample dump (the reference prints a problem/completion/
    # reward sample every update, distributed_trainer.py:297–299)
    print_samples: bool = True
    # write HF-format merged-model snapshots to run_dir/model_{step} at every
    # save_every step and episode end (the reference's save_pretrained
    # artifacts, distributed_trainer.py:372–380). Heavy (full model write);
    # requires run_name and an unquantized base.
    export_hf_snapshots: bool = False
    checkpoint_dir: str | None = None
    resume: bool = False
    metrics_backend: str = "auto"  # {"auto","wandb","jsonl","null"}
    # attention implementation for learner/prefill forwards:
    # "reference" (XLA softmax), "flash" (Pallas blockwise kernel, TPU only,
    # GQA via repeat — ops/flash_attention.py), "splash" (Pallas multi-query
    # kernel, native GQA with no KV repeat — ops/splash.py), "ring"
    # (sequence-parallel by KV rotation — ops/ring_attention.py), or
    # "ulysses" (sequence-parallel by all-to-all head scatter — ops/ulysses.py;
    # needs heads divisible by sp); non-TPU backends fall back to the
    # reference path with a warning
    attn_impl: str = "reference"
    write_adapter_file: bool = False  # artifact-parity adapter writer
    # jax.profiler trace capture (SURVEY §5 tracing): traces the step window
    # [profile_start_step, profile_start_step + profile_num_steps) into
    # profile_dir (TensorBoard format). Step 1 is skipped by default — it is
    # dominated by compilation.
    profile_dir: str | None = None
    profile_start_step: int = 2
    profile_num_steps: int = 3
    # Span-trace capture (telemetry.py): when set, the trainer records
    # driver (generation/reward/update/eval), engine (prefill/decode), and
    # worker spans — workers ship theirs back over the control plane — and
    # writes one Chrome-trace/Perfetto JSON to trace_dir/trace.json.
    # trace_steps > 0 limits recording to the first N train steps (the file
    # is written when the window closes); 0 traces the whole run and writes
    # at shutdown. Orthogonal to profile_dir (device-level XLA traces).
    trace_dir: str | None = None
    trace_steps: int = 0
    # --- continuous observability (distrl_llm_tpu/obs.py, ISSUE 8) --------
    # Live metrics endpoint: serve the cumulative telemetry registry over
    # HTTP (Prometheus text at /metrics, JSON at /metrics.json) from the
    # driver process. With remote rollout workers the endpoint additionally
    # publishes fleet/* series aggregated from the per-worker snapshots
    # piggybacked on control-plane results. None = off; 0 = auto-assign a
    # port (read it from the startup log).
    metrics_port: int | None = None
    # Anomaly sentinel: deterministic triggers per train step (NaN/Inf
    # loss, reward collapse, staleness blowup, tok/s regression vs a
    # running EMA, HBM watermark breach); each fires at most once and dumps
    # the flight-recorder ring into an incident directory. Requires
    # flight_recorder_dir (the evidence has to land somewhere).
    sentinel: bool = False
    # Incident bundle output directory: arming it keeps a bounded
    # in-memory ring of recent step records (obs_ring_size) that sentinel
    # triggers dump as incident_step<N>_<trigger>/ with the metric ring,
    # telemetry span tail, and config/plan snapshot.
    flight_recorder_dir: str | None = None
    obs_ring_size: int = 256
    # --- trajectory lineage ledger (distrl_llm_tpu/lineage.py, ISSUE 10) --
    # Follow every sampled group from prompt through the buffer into the
    # optimizer step that consumed it and out as a broadcast weight version:
    # per-group LineageRecords (sampling worker + causal dispatch_id, weight
    # versions, buffer passage, staleness verdict, consuming step) plus the
    # derived lag histograms (lineage/sample_to_learn_ms,
    # lineage/learn_to_act_ms, lineage/policy_lag_ms) on the registry /
    # metrics endpoint. Async-mode only (the sync loop has no buffer or
    # staleness machinery to trace). One attribute check per hook site when
    # off. lineage_dir set alone implies lineage=True.
    lineage: bool = False
    # per-run JSONL output (lineage_dir/lineage.jsonl, streamed as records
    # close; tools/lineage_report.py reads it). None = ring only.
    lineage_dir: str | None = None
    # bounded ring of OPEN records; overflow is counted
    # (lineage/ring_evictions), never silent
    lineage_ring: int = 1024
    # --- serving observability (distrl_llm_tpu/serving_obs.py, ISSUE 13) --
    # Request-level serving ledger over the continuous-batching engine:
    # per-group lifecycle events (enqueue → admit → prefill done → first
    # token → finish) recorded at the refill loop's host chunk boundaries,
    # yielding serving/ttft_ms, serving/tpot_ms, serving/queue_wait_ms,
    # serving/e2e_ms histograms plus the admission audit
    # (serving/admission_stalls/<reason>). Requires engine_impl='paged' +
    # continuous_batching (the instrumented loops); over rollout_workers
    # the ledger is armed worker-side (worker_main --serving-obs) and the
    # driver folds the fleet view. One attribute check per hook when off.
    serving_obs: bool = False
    # per-run JSONL (serving_dir/serving.jsonl, streamed as records close;
    # tools/serving_report.py reads it). Implies serving_obs.
    serving_dir: str | None = None
    # bounded ring of OPEN serving records; overflow counted
    # (serving/ring_evictions), never silent
    serving_ring: int = 1024
    # SLO gates (ISSUE 13): arm the sentinel's ttft_blowup /
    # queue_wait_blowup triggers — the step's worst observed latency above
    # the limit dumps a flight-recorder bundle. Require --sentinel.
    slo_ttft_ms: float | None = None
    slo_queue_wait_ms: float | None = None
    # --- multi-tenant serving gateway (distrl_llm_tpu/gateway/, ISSUE 19) -
    # Streaming HTTP front-end + priority-class scheduling over the
    # continuous-admission engine: POST /v1/generate streams tokens as the
    # refill loop emits them, requests carry tenant + priority class
    # (interactive > batch > scavenger) from headers, and the gateway's
    # round former drains its open queue class-then-FIFO-with-aging.
    # gateway_port None = gateway off (the default; off is byte-identical
    # to a build without the subsystem). 0 = auto-assign (the bound port
    # is printed as "GATEWAY <n>").
    gateway_port: int | None = None
    # comma-separated subset of priority classes this gateway serves
    # (empty = all three). Requests naming an unserved class are rejected
    # with HTTP 400, never silently reclassified.
    gateway_classes: str | None = None
    # per-tenant reserved-token quotas, "tenant=tokens,..."; the pseudo-
    # tenant "default" caps tenants not named. Admission declines on quota
    # are the ``quota`` stall reason in the serving ledger's conservation
    # sum. Requires the gateway (dead otherwise).
    tenant_quota: str | None = None
    # --- training-dynamics observability (learn_obs.py, ISSUE 16) ---------
    # Device-computed training-dynamics bundle fused into the jitted train
    # step (learner/train_step.py emit_dynamics): masked policy entropy,
    # behavior↔policy KL, pre-binned IS-ratio histogram, clip/cap-saturation
    # fractions, advantage moments, per-layer-group LoRA grad norms — all
    # riding the ONE host transfer the loss already pays. The armed run is
    # byte-identical to off in losses and adapter (pinned,
    # tools/learn_smoke.py). Publishes learn/* registry series + a per-step
    # JSONL (learn_dir/learn.jsonl; tools/learn_report.py reads it).
    # learn_dir set alone implies learn_obs=True.
    learn_obs: bool = False
    learn_dir: str | None = None
    # reward-distribution drift reference window (steps); drift is the
    # z-score of the step's reward mean against the trailing window of
    # older means
    learn_drift_window: int = 32
    # Training-dynamics sentinel triggers (ISSUE 16): each arms one
    # deterministic trigger on the learn/* view; all require --sentinel
    # (the evidence lands in the flight recorder) and auto-arm learn_obs
    # (the signal's producer). Default None = off.
    # entropy_collapse: masked answer-token entropy below this floor
    learn_entropy_floor: float | None = None
    # kl_blowup: behavior↔policy KL above this limit; also an escalation
    # input to the staleness governor when control_staleness is armed
    learn_kl_limit: float | None = None
    # ratio_saturation: AIPO cap-saturation (or PPO clip) fraction above
    # this threshold — fraction of answer tokens whose IS ratio the
    # correction truncated
    learn_ratio_sat_frac: float | None = None
    # grad_spike: whole-adapter grad norm above this multiple of its
    # running EMA (must be > 1)
    learn_grad_spike: float | None = None
    # --- self-healing runtime (distrl_llm_tpu/control/, ISSUE 14) ---------
    # Closed-loop governors that ACT on the observability plane: bounded,
    # hysteretic, cooldown-guarded actuations with a global per-run budget.
    # --control arms every controller the run's shape supports (silently
    # skipping inapplicable ones); the per-controller flags arm exactly one
    # and LOUDLY reject a run shape that cannot host it (dead-flag policy).
    # All default OFF; a run with controllers off is byte-identical to one
    # without the subsystem (pinned).
    control: bool = False
    # HBM governor: shrinks the continuous-admission chain cap under
    # watermark pressure / hbm_breach, regrows after a sustained-headroom
    # dwell. Requires a LOCAL paged engine with continuous_admission
    # (fleet runs arm it worker-side: worker_main --control-hbm).
    control_hbm: bool = False
    # SLO load-shedder: throttles admit_groups (decline reason "shed")
    # while serving TTFT/queue-wait breach the PR 13 SLOs. Requires
    # continuous_admission + at least one slo_* limit; worker-side over
    # rollout_workers (worker_main --control-shed).
    control_shed: bool = False
    # staleness governor: adapts the EFFECTIVE max_staleness and buffer
    # high watermark from the live lineage/policy_lag_ms distribution
    # (async mode only; drop/downweight semantics preserved — only the
    # bound moves, never past the configured max_staleness). Requires
    # lineage (the signal's producer).
    control_staleness: bool = False
    # worker-health actor: converts a per-worker tok/s regression into
    # proactive quarantine + rejoin-probe (the PR 5 machinery). Requires
    # rollout_workers + worker_rejoin.
    control_worker_health: bool = False
    # nan-loss rollback: restore the last-good (adapter, opt state,
    # version) snapshot and skip the poisoned step instead of training on
    # NaNs from there on. Applicable to every run shape.
    control_nan_rollback: bool = False
    # --- elastic fleet (distrl_llm_tpu/distributed/fleet.py, ISSUE 20) ----
    # autoscaling governor: steers a FleetSupervisor-owned worker pool's
    # target size over [fleet_min, fleet_max] — scale-up admits a cold
    # worker through add_worker (full weight-bus resync), scale-down
    # retires the least-productive worker through the graceful-drain path.
    # Requires rollout_workers + worker_rejoin + fleet bounds. NOT armed by
    # the --control master (resizing the pool is a capacity decision, not
    # a self-healing default) — always explicit.
    control_autoscale: bool = False
    # target-pool bounds for the autoscaler / FleetSupervisor; 0 = unset
    # (the fleet stays static at the connect-time worker set)
    fleet_min: int = 0
    fleet_max: int = 0
    # global actuation budget per run: once spent, every knob freezes at
    # its current (clamped) value — a runaway controller is bounded by
    # construction
    control_budget: int = 64
    # minimum steps between two actions of one governor
    control_cooldown_steps: int = 2
    # consecutive healthy observations required before a governor regrows
    # a previously shrunk knob (the sustained-headroom dwell)
    control_dwell_steps: int = 3
    # staleness governor setpoint: policy-lag p90 above this shrinks the
    # effective staleness bound / buffer watermark; sustained p90 under
    # half of it regrows them
    control_lag_ms: float = 5000.0
    # Hang detector on generation rounds — parity with the reference's
    # ray.get(timeout=240) (distributed_trainer.py:200). 0 disables (the
    # default: a first rollout legitimately spends minutes in XLA compilation;
    # production configs should set it once compile times are known). On
    # timeout the trainer checkpoints and raises EngineHangError — restart
    # with resume=True to continue from the last completed step.
    generation_timeout_s: float = 0.0

    # --- Pluggable environments (ISSUE 17) ---------------------------------
    # rollout environment: "math" (the legacy single-turn scorer — the exact
    # pre-env generation/reward path, byte-identical), "code" (multi-turn
    # sandboxed <tool> execution with outputs fed back), or "verifier"
    # (multi-turn verifier-feedback, per-turn improvement reward). Multi-turn
    # envs interleave engine generation with env.step on the local paged
    # refill engine: continuing conversations are re-admitted onto their
    # resident KV chains (no re-prefill) and env-injected observation tokens
    # are loss-masked in the learner.
    env: str = "math"
    # max conversation turns per episode for multi-turn envs. env="math" is
    # single-turn by construction, so >1 there is a dead flag (rejected).
    max_turns: int = 1
    # format-reward gate: "soft" (the reference's anchored single-line
    # pattern — the parity default) or "strict" (the newline-delimited
    # variant, previously dead parity code)
    format_reward: str = "soft"

    def __post_init__(self):
        if self.learner not in ("pg", "grpo"):
            raise ValueError(f"learner must be 'pg' or 'grpo', got {self.learner!r}")
        if self.rollout_mode not in ("sync", "pipelined", "async"):
            raise ValueError(
                f"rollout_mode must be sync/pipelined/async, got "
                f"{self.rollout_mode!r}"
            )
        # --async_rollout is the deprecated spelling of --rollout_mode
        # pipelined; after normalization async_rollout reads as "any
        # overlapped mode" (the trainer's pushed-copy/no-hybrid paths apply
        # to pipelined AND async alike)
        if self.async_rollout and self.rollout_mode == "sync":
            self.rollout_mode = "pipelined"
        self.async_rollout = self.rollout_mode != "sync"
        if self.max_staleness < 0:
            raise ValueError(
                f"max_staleness must be >= 0, got {self.max_staleness}"
            )
        if self.staleness_policy not in ("drop", "downweight"):
            raise ValueError(
                f"staleness_policy must be drop/downweight, got "
                f"{self.staleness_policy!r}"
            )
        if self.rollout_buffer_groups < 0:
            raise ValueError(
                f"rollout_buffer_groups must be >= 0, got "
                f"{self.rollout_buffer_groups}"
            )
        if self.rollout_mode == "async":
            if self.clip_ratio <= 0:
                raise ValueError(
                    "rollout_mode='async' requires clip_ratio > 0: the "
                    "bounded-staleness regime trains on trajectories up to "
                    "max_staleness optimizer steps old, and the truncated-IS "
                    "correction consumes the engine-captured behavior "
                    "logprobs that clip_ratio enables"
                )
            if self.max_staleness < 1:
                raise ValueError(
                    "rollout_mode='async' requires max_staleness >= 1 (0 "
                    "would drop every trajectory the moment the learner "
                    "steps; use rollout_mode='sync' for strict on-policy)"
                )
        if self.base_quant not in ("none", "int8", "int4"):
            raise ValueError(f"base_quant must be none/int8/int4, got {self.base_quant!r}")
        if self.engine_impl not in ("dense", "paged", "paged_sharded"):
            raise ValueError(
                f"engine_impl must be dense/paged/paged_sharded, got "
                f"{self.engine_impl!r}"
            )
        if self.kv_cache_quant not in (None, "none", "int8"):
            raise ValueError(
                f"kv_cache_quant must be none/int8 (or unset = plan-DB-"
                f"resolved), got {self.kv_cache_quant!r}"
            )
        if self.quant_group_size is not None and self.quant_group_size < 1:
            raise ValueError(
                f"quant_group_size must be >= 1, got {self.quant_group_size}"
            )
        if self.quant_group_size is not None and self.base_quant == "none":
            # dead-flag policy: the group size shapes the base containers,
            # which only exist under base_quant
            raise ValueError(
                "quant_group_size configures base_quant's groupwise scales "
                "— set base_quant int8/int4 (it would be silently ignored)"
            )
        if self.engine_impl == "paged_sharded" and (
            self.continuous_batching or self.spec_draft
        ):
            raise ValueError(
                "paged_sharded runs the wave scheduler only; continuous "
                "batching / speculative decoding are per-replica engine "
                "features (engine/sharded_paged.py)"
            )
        if self.full_finetune and self.base_quant != "none":
            raise ValueError(
                "full_finetune trains the base weights — they cannot be "
                "quantized (base_quant must be 'none')"
            )
        if self.full_finetune and self.write_adapter_file:
            raise ValueError(
                "full_finetune has no LoRA adapter to export; use "
                "export_hf_snapshots for full-model artifacts"
            )
        if self.full_finetune and self.lora_dropout:
            raise ValueError(
                "full_finetune has no adapter for lora_dropout to act on — "
                "set lora_dropout=0"
            )
        if self.full_finetune and self.kl_coeff:
            raise ValueError(
                "kl_coeff uses the frozen base as the reference policy — "
                "full_finetune has no frozen base (keep a LoRA run, or 0)"
            )
        if self.full_finetune and self.rollout_workers:
            # remote workers hold their own frozen base and receive only the
            # adapter; with no adapter the trained weights would never reach
            # them — silently severely-off-policy RL
            raise ValueError(
                "full_finetune cannot ship full weights to rollout_workers "
                "(workers receive adapters only); run local rollout"
            )
        if self.decode_scan_chunk is not None and self.decode_scan_chunk < 0:
            raise ValueError(
                f"decode_scan_chunk must be >= 0, got {self.decode_scan_chunk}"
            )
        if self.trace_steps < 0:
            raise ValueError(
                f"trace_steps must be >= 0, got {self.trace_steps}"
            )
        if self.trace_steps and not self.trace_dir:
            raise ValueError("trace_steps requires trace_dir")
        if self.metrics_port is not None and not (
            0 <= self.metrics_port <= 65535
        ):
            raise ValueError(
                f"metrics_port must be in [0, 65535] (0 = auto-assign), "
                f"got {self.metrics_port}"
            )
        if self.sentinel and not self.flight_recorder_dir:
            raise ValueError(
                "sentinel requires flight_recorder_dir — a trigger's whole "
                "point is the incident bundle it dumps there"
            )
        if self.obs_ring_size < 1:
            raise ValueError(
                f"obs_ring_size must be >= 1, got {self.obs_ring_size}"
            )
        if self.lineage_dir and not self.lineage:
            # an output directory is an unambiguous ask — arm the ledger
            self.lineage = True
        if self.lineage_ring < 1:
            raise ValueError(
                f"lineage_ring must be >= 1, got {self.lineage_ring}"
            )
        if self.lineage and self.rollout_mode != "async":
            raise ValueError(
                "lineage requires rollout_mode='async' — the ledger traces "
                "the buffer passage, staleness verdict, and decoupled "
                "consumption that only exist in the async regime (sync/"
                "pipelined rounds are consumed by construction)"
            )
        if self.serving_dir and not self.serving_obs:
            # an output directory is an unambiguous ask — arm the ledger
            self.serving_obs = True
        if self.serving_ring < 1:
            raise ValueError(
                f"serving_ring must be >= 1, got {self.serving_ring}"
            )
        for slo_name in ("slo_ttft_ms", "slo_queue_wait_ms"):
            slo = getattr(self, slo_name)
            if slo is not None and slo <= 0:
                raise ValueError(f"{slo_name} must be > 0, got {slo}")
        if (
            (self.slo_ttft_ms is not None
             or self.slo_queue_wait_ms is not None)
            and not self.sentinel
        ):
            raise ValueError(
                "slo_ttft_ms/slo_queue_wait_ms arm sentinel triggers "
                "(ttft_blowup / queue_wait_blowup) — set --sentinel (and "
                "--flight_recorder_dir) or drop the SLO flags"
            )
        if (
            (self.slo_ttft_ms is not None
             or self.slo_queue_wait_ms is not None)
            and not self.rollout_workers and not self.serving_obs
        ):
            # a local-engine SLO gate without the ledger could never fire
            # (nothing produces serving/*_max) — an SLO is an unambiguous
            # ask, arm the measurement; fleet runs instead read the
            # worker-fed fleet/serving_* gauges
            self.serving_obs = True
        if self.learn_dir and not self.learn_obs:
            # an output directory is an unambiguous ask — arm the ledger
            self.learn_obs = True
        if self.learn_drift_window < 2:
            raise ValueError(
                f"learn_drift_window must be >= 2 (a one-sample reference "
                f"window has no variance), got {self.learn_drift_window}"
            )
        for learn_name in ("learn_entropy_floor", "learn_kl_limit",
                           "learn_ratio_sat_frac", "learn_grad_spike"):
            limit = getattr(self, learn_name)
            if limit is not None and limit <= 0:
                raise ValueError(f"{learn_name} must be > 0, got {limit}")
        if (
            self.learn_ratio_sat_frac is not None
            and self.learn_ratio_sat_frac > 1.0
        ):
            raise ValueError(
                f"learn_ratio_sat_frac is a token fraction in (0, 1], got "
                f"{self.learn_ratio_sat_frac}"
            )
        if self.learn_grad_spike is not None and self.learn_grad_spike <= 1.0:
            raise ValueError(
                f"learn_grad_spike is a multiple of the grad-norm EMA and "
                f"must be > 1, got {self.learn_grad_spike}"
            )
        _learn_triggers = (
            self.learn_entropy_floor is not None
            or self.learn_kl_limit is not None
            or self.learn_ratio_sat_frac is not None
            or self.learn_grad_spike is not None
        )
        if _learn_triggers and not self.sentinel:
            raise ValueError(
                "learn_entropy_floor/learn_kl_limit/learn_ratio_sat_frac/"
                "learn_grad_spike arm sentinel triggers (entropy_collapse / "
                "kl_blowup / ratio_saturation / grad_spike) — set "
                "--sentinel (and --flight_recorder_dir) or drop them"
            )
        if _learn_triggers and not self.learn_obs:
            # a trigger without the producer could never fire — a threshold
            # is an unambiguous ask, arm the measurement (the SLO precedent)
            self.learn_obs = True
        if self.serving_obs:
            # dead-flag policy (the prefix_sharing precedent): the ledger
            # instruments the refill/continuous loops only
            if self.rollout_workers:
                raise ValueError(
                    "serving_obs over rollout_workers is armed WORKER-side "
                    "(worker_main --serving-obs; the driver folds the "
                    "fleet serving view from the obs blobs) — the driver "
                    "has no local refill engine to instrument"
                )
            if self.engine_impl != "paged" or not self.continuous_batching:
                raise ValueError(
                    "serving_obs instruments the paged engine's refill/"
                    "continuous loops — requires engine_impl='paged' and "
                    "continuous_batching"
                )
        # --- serving gateway validation (ISSUE 19) ------------------------
        if self.gateway_port is not None:
            if not (0 <= self.gateway_port <= 65535):
                raise ValueError(
                    f"gateway_port must be in [0, 65535] (0 = auto-assign), "
                    f"got {self.gateway_port}"
                )
            if (
                self.engine_impl != "paged"
                or not self.continuous_batching
                or not self.continuous_admission
            ):
                raise ValueError(
                    "the serving gateway schedules the continuous-admission "
                    "refill engine — requires engine_impl='paged', "
                    "continuous_batching, and continuous_admission"
                )
            if self.rollout_workers:
                raise ValueError(
                    "the serving gateway fronts a LOCAL engine; over "
                    "rollout_workers arm it worker-side "
                    "(worker_main --gateway-port)"
                )
            # validate eagerly so a bad spec fails at config time, not when
            # the first request arrives
            from distrl_llm_tpu.gateway.scheduler import (
                parse_gateway_classes,
                parse_tenant_quota,
            )
            parse_gateway_classes(self.gateway_classes)
            parse_tenant_quota(self.tenant_quota)
        elif self.gateway_classes or self.tenant_quota:
            # dead-flag policy: class/quota knobs shape the gateway's
            # admission plane only
            raise ValueError(
                "gateway_classes/tenant_quota configure the serving "
                "gateway — set gateway_port (they would be silently "
                "ignored otherwise)"
            )
        # decode_scan_chunk covers every engine_impl and scheduler (dense,
        # paged wave + refill + speculative, paged_sharded)
        if self.continuous_batching and (
            self.engine_impl != "paged" or not self.max_concurrent_sequences
        ):
            raise ValueError(
                "continuous_batching requires engine_impl='paged' and a "
                "max_concurrent_sequences cap (the decode slot count)"
            )
        if self.spec_draft and not self.continuous_batching:
            raise ValueError(
                "spec_draft (speculative decoding) requires "
                "continuous_batching (the refill scheduler hosts it)"
            )
        # dead-flag policy (mirrors the spec satellite knobs): prefix
        # sharing and continuous admission live on the refill scheduler —
        # without continuous_batching they would silently never engage
        if (self.prefix_sharing or self.continuous_admission) and (
            not self.continuous_batching
        ):
            raise ValueError(
                "prefix_sharing/continuous_admission run on the refill "
                "scheduler — set continuous_batching (and a "
                "max_concurrent_sequences cap); they would be silently "
                "ignored otherwise"
            )
        # dead-flag policy for the tiered KV cache (ISSUE 18): tier 1
        # aliases cached chains out of the continuous-admission pool, tier 2
        # spills through tier 1's host store — surface dead wiring here
        # rather than letting the engine raise mid-run
        if self.prefix_cache and not self.continuous_admission:
            raise ValueError(
                "prefix_cache (the radix KV cache) aliases cached prompt "
                "chains out of the continuous-admission pool — set "
                "continuous_admission (it would be a dead flag otherwise)"
            )
        if self.prefix_cache and self.kv_cache_quant == "int8":
            raise ValueError(
                "prefix_cache requires a lossless KV pool: int8 pages "
                "cannot reproduce the cold prefill's attention inputs "
                "bit-exactly — drop kv_cache_quant or prefix_cache"
            )
        if self.kv_spill and not self.prefix_cache:
            raise ValueError(
                "kv_spill parks KV pages through the tiered cache's host "
                "store — it requires prefix_cache"
            )
        if self.kv_spill and self.spec_draft:
            raise ValueError(
                "kv_spill restores raw decode cursors the speculative "
                "scheduler does not expose — preempted speculative chains "
                "already resume by recompute; drop kv_spill or spec_draft"
            )
        if self.kv_spill_host_mb and not self.kv_spill:
            raise ValueError(
                "kv_spill_host_mb caps the kv_spill host store — set "
                "kv_spill (it would be a dead knob otherwise)"
            )
        # Pluggable environments (ISSUE 17). Import here, not at module
        # top: config must stay importable without pulling the env package
        # (worker processes construct configs before JAX spins up).
        from distrl_llm_tpu.env import env_names
        if self.env not in env_names():
            raise ValueError(
                f"env must be one of {', '.join(env_names())}, got "
                f"{self.env!r}"
            )
        if self.max_turns < 1:
            raise ValueError(f"max_turns must be >= 1, got {self.max_turns}")
        if self.format_reward not in ("soft", "strict"):
            raise ValueError(
                f"format_reward must be 'soft' or 'strict', got "
                f"{self.format_reward!r}"
            )
        if self.env == "math" and self.max_turns > 1:
            # dead-flag policy: the math env is single-turn by construction
            raise ValueError(
                "max_turns > 1 is a dead flag with env='math' (single-turn "
                "by construction) — pick env='code' or env='verifier'"
            )
        if self.env != "math":
            # multi-turn envs need the refill scheduler's slot machinery:
            # the engine turn hook re-admits continuing conversations onto
            # their resident KV chains between turns
            if not (self.continuous_batching and self.continuous_admission):
                raise ValueError(
                    f"env={self.env!r} (multi-turn) requires "
                    "continuous_batching + continuous_admission: turn "
                    "continuations re-enter through the refill scheduler's "
                    "admission queue onto resident KV chains"
                )
            if self.engine_impl != "paged":
                raise ValueError(
                    f"env={self.env!r} requires engine_impl='paged' (the "
                    "turn hook lives on the local paged refill engine)"
                )
            if self.spec_draft:
                raise ValueError(
                    f"env={self.env!r} is incompatible with spec_draft: "
                    "the turn hook and the speculative resume path contend "
                    "for the same slot state"
                )
            if self.rollout_workers:
                raise ValueError(
                    f"env={self.env!r} runs driver-local only this "
                    "iteration — rollout_workers have no turn hook"
                )
        if self.spec_draft is not None and not 0 <= self.spec_draft <= 16:
            raise ValueError(
                f"spec_draft must be in [0, 16] (longer draft blocks waste "
                f"verify width faster than they amortize weight reads), got "
                f"{self.spec_draft}"
            )
        if self.spec_ngram is not None and self.spec_ngram < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {self.spec_ngram}")
        if self.spec_drafter not in (None, "ngram", "self"):
            raise ValueError(
                f"spec_drafter must be 'ngram' or 'self', got "
                f"{self.spec_drafter!r}"
            )
        if self.spec_verify not in (None, "fused", "unrolled"):
            raise ValueError(
                f"spec_verify must be 'fused' or 'unrolled', got "
                f"{self.spec_verify!r}"
            )
        # the satellite knobs are dead flags unless speculation can engage:
        # loud errors here keep this entry point consistent with
        # worker_main's parser (which rejects the same combinations)
        # instead of silently running plain decode
        if not self.continuous_batching and (
            self.spec_ngram is not None or self.spec_drafter is not None
            or self.spec_verify is not None or self.spec_adapt
        ):
            raise ValueError(
                "spec_ngram/spec_drafter/spec_verify/spec_adapt configure "
                "speculative decoding, which requires continuous_batching "
                "(the refill scheduler hosts it) — they would be silently "
                "ignored"
            )
        if self.spec_draft == 0 and (
            self.spec_ngram is not None or self.spec_drafter is not None
            or self.spec_verify is not None
        ):
            raise ValueError(
                "spec_ngram/spec_drafter/spec_verify with spec_draft=0: an "
                "explicit 0 pins speculation off, so they would be "
                "silently ignored (leave spec_draft unset to let the plan "
                "DB decide)"
            )
        # spec_draft None counts: a plan-DB entry may enable speculation at
        # engine construction, and full_finetune never grows an adapter
        # stream, so the combination is invalid whenever speculation COULD
        # engage (only an explicit 0 pins it off)
        if self.spec_drafter == "self" and self.spec_draft != 0:
            if self.full_finetune:
                raise ValueError(
                    "spec_drafter='self' drafts with the policy's previous "
                    "LoRA adapter (the weight-update mailbox stream) — "
                    "full_finetune has no adapter stream; use "
                    "spec_drafter='ngram'"
                )
        if self.spec_adapt and self.spec_draft == 0:
            # spec_draft=None stays legal here: a tuned plan-DB entry may
            # enable speculation, and the engine re-validates post-resolution
            raise ValueError(
                "spec_adapt adapts the speculative draft length — set "
                "spec_draft > 0"
            )
        if self.weight_bus not in ("broadcast", "dispatch"):
            raise ValueError(
                f"weight_bus must be 'broadcast' or 'dispatch', got "
                f"{self.weight_bus!r}"
            )
        if self.inflight_weight_updates:
            if not self.async_rollout:
                raise ValueError(
                    "inflight_weight_updates requires async_rollout (there "
                    "must be an in-flight generation round to update)"
                )
            if self.clip_ratio <= 0:
                raise ValueError(
                    "inflight_weight_updates requires clip_ratio > 0: tokens "
                    "sampled pre-swap are off-policy for the update, and the "
                    "clip objective is the correction that consumes their "
                    "captured behavior logprobs"
                )
            if self.full_finetune:
                raise ValueError(
                    "inflight_weight_updates requires a LoRA run "
                    "(full_finetune swaps the whole param tree, not an "
                    "adapter)"
                )
            if self.rollout_workers and self.weight_bus != "broadcast":
                # the silent-no-op fix (ISSUE 9): this combination used to
                # pretend to work while never updating worker weights
                # mid-round — the engine lacked a real push_lora. The
                # broadcast bus provides one; anything else is an error,
                # never a silent regression (the trainer additionally
                # rejects any engine without push_lora at construction).
                raise ValueError(
                    "inflight_weight_updates over rollout_workers requires "
                    "weight_bus='broadcast' (the versioned weight bus is "
                    "what delivers mid-round adapters to workers; "
                    "'dispatch' ships weights only at round entry and "
                    "would silently never swap)"
                )
        if (
            self.clip_ratio > 0 and self.rollout_workers
            and not self.workers_capture_logprobs
        ):
            # clip needs per-token behavior logprobs captured at generation
            # time; by default worker engines are built without
            # capture_logprobs, so a remote-rollout clip run would only fail
            # at the first training batch — reject it up front unless the
            # caller declares the workers were started with
            # --capture-logprobs (worker_main)
            raise ValueError(
                "clip_ratio > 0 with rollout_workers requires workers "
                "started with --capture-logprobs AND "
                "--workers_capture_logprobs on the driver (declares the "
                "worker engines record behavior logprobs)"
            )
        if self.rpc_retries < 0:
            raise ValueError(f"rpc_retries must be >= 0, got {self.rpc_retries}")
        if self.rpc_backoff_s < 0:
            raise ValueError(
                f"rpc_backoff_s must be >= 0, got {self.rpc_backoff_s}"
            )
        if self.poison_shard_k < 1:
            raise ValueError(
                f"poison_shard_k must be >= 1, got {self.poison_shard_k}"
            )
        if self.producer_restarts < 0:
            raise ValueError(
                f"producer_restarts must be >= 0, got {self.producer_restarts}"
            )
        if self.rollout_workers and (
            self.kv_cache_quant not in (None, "none")
            or self.engine_impl != "dense"
        ):
            # remote workers build their own engines (worker_main flags);
            # silently ignoring these knobs would misreport memory behavior
            raise ValueError(
                "engine_impl/kv_cache_quant are local-engine knobs; with "
                "rollout_workers, configure the workers via worker_main flags"
            )
        if self.batch_size <= 0:
            raise ValueError("batch_size must be positive")
        if any(
            b <= 0 or b > self.max_new_tokens for b in self.learner_len_buckets
        ):
            # same contract as the engine's prompt buckets (engine.py raises
            # for out-of-range buckets): a bucket past max_new_tokens would
            # silently clamp into a no-op while logging answer_width as if
            # bucketing were active
            raise ValueError(
                f"learner_len_buckets must be in (0, max_new_tokens="
                f"{self.max_new_tokens}], got {self.learner_len_buckets}"
            )
        if any(
            b <= 0 or b > self.max_prompt_tokens
            for b in self.learner_prompt_buckets
        ):
            raise ValueError(
                f"learner_prompt_buckets must be in (0, max_prompt_tokens="
                f"{self.max_prompt_tokens}], got {self.learner_prompt_buckets}"
            )
        if self.number_of_learners <= 0:
            raise ValueError("need at least one learner")
        if self.number_of_actors < 0:
            raise ValueError("number_of_actors must be >= 0")
        # The flat flags are authoritative for role counts (the reference CLI
        # contract); a custom MeshConfig may only restate them, never override.
        default_mesh = MeshConfig()
        mesh_roles = (self.mesh.number_of_actors, self.mesh.number_of_learners)
        flat_roles = (self.number_of_actors, self.number_of_learners)
        default_roles = (default_mesh.number_of_actors, default_mesh.number_of_learners)
        if mesh_roles != default_roles and mesh_roles != flat_roles:
            raise ValueError(
                f"mesh role counts {mesh_roles} conflict with number_of_actors/"
                f"number_of_learners {flat_roles}; set the flat flags instead"
            )
        self.mesh = dataclasses.replace(
            self.mesh,
            number_of_actors=self.number_of_actors,
            number_of_learners=self.number_of_learners,
        )
        # --- self-healing runtime (ISSUE 14): per-controller dead-flag
        # policy — an EXPLICIT per-controller flag on a run shape that
        # cannot host the controller is a loud error; the --control master
        # arms only the applicable subset (armed_controllers()).
        if self.control_budget < 1:
            raise ValueError(
                f"control_budget must be >= 1, got {self.control_budget}"
            )
        if self.control_cooldown_steps < 0:
            raise ValueError(
                f"control_cooldown_steps must be >= 0, got "
                f"{self.control_cooldown_steps}"
            )
        if self.control_dwell_steps < 1:
            raise ValueError(
                f"control_dwell_steps must be >= 1, got "
                f"{self.control_dwell_steps}"
            )
        if self.control_lag_ms <= 0:
            raise ValueError(
                f"control_lag_ms must be > 0, got {self.control_lag_ms}"
            )
        if self.control_hbm and not self._hbm_controller_applicable():
            raise ValueError(
                "control_hbm shrinks the continuous-admission chain cap — "
                "requires a LOCAL engine_impl='paged' with "
                "continuous_admission (fleet runs arm it worker-side: "
                "worker_main --control-hbm)"
            )
        if self.control_shed and not self._shed_controller_applicable():
            raise ValueError(
                "control_shed throttles continuous admission against an "
                "SLO — requires continuous_admission plus slo_ttft_ms or "
                "slo_queue_wait_ms, on a local engine (fleet runs arm it "
                "worker-side: worker_main --control-shed)"
            )
        if self.control_staleness and not self.lineage:
            raise ValueError(
                "control_staleness steers on the lineage/policy_lag_ms "
                "distribution — requires --lineage (async mode), which "
                "produces that signal"
            )
        if self.control_worker_health and not (
            self.rollout_workers and self.worker_rejoin
        ):
            raise ValueError(
                "control_worker_health quarantines regressing workers and "
                "relies on the rejoin loop to re-admit them — requires "
                "rollout_workers with worker_rejoin"
            )
        # --- elastic fleet (ISSUE 20) ---------------------------------
        if (self.fleet_min or self.fleet_max) and not (
            1 <= self.fleet_min <= self.fleet_max
        ):
            raise ValueError(
                f"fleet bounds need 1 <= fleet_min <= fleet_max, got "
                f"[{self.fleet_min}, {self.fleet_max}]"
            )
        if self.control_autoscale and not self._autoscale_applicable():
            raise ValueError(
                "control_autoscale resizes a dynamic rollout pool — "
                "requires rollout_workers with worker_rejoin (cold joins "
                "ride the rejoin/resync path) and fleet_min/fleet_max "
                "bounds for the target-size actuator"
            )

    def _hbm_controller_applicable(self) -> bool:
        return bool(
            self.engine_impl == "paged"
            and self.continuous_admission
            and not self.rollout_workers
        )

    def _shed_controller_applicable(self) -> bool:
        return bool(
            self._hbm_controller_applicable()
            and (self.slo_ttft_ms is not None
                 or self.slo_queue_wait_ms is not None)
        )

    def _autoscale_applicable(self) -> bool:
        return bool(
            self.rollout_workers and self.worker_rejoin
            and self.fleet_max > 0
        )

    def armed_controllers(self) -> tuple[str, ...]:
        """Which ISSUE 14 controllers this run arms: the explicit
        per-controller flags, plus — under the --control master — every
        controller the run's shape supports. Explicit flags on unsupported
        shapes already raised in __post_init__."""
        armed: list[str] = []
        if self.control_hbm or (
            self.control and self._hbm_controller_applicable()
        ):
            armed.append("hbm")
        if self.control_shed or (
            self.control and self._shed_controller_applicable()
        ):
            armed.append("shed")
        if self.control_staleness or (self.control and self.lineage):
            armed.append("staleness")
        if self.control_worker_health or (
            self.control and self.rollout_workers and self.worker_rejoin
        ):
            armed.append("worker_health")
        if self.control_nan_rollback or self.control:
            armed.append("nan_rollback")
        # explicit-only (never under the --control master): resizing the
        # pool is a capacity decision — __post_init__ already rejected the
        # flag on shapes that cannot host it
        if self.control_autoscale:
            armed.append("autoscale")
        return tuple(armed)

    @property
    def max_seq_length(self) -> int:
        # reference: max_seq_length = prompt + new tokens (distributed_actor.py:25)
        return self.max_prompt_tokens + self.max_new_tokens

    @property
    def allowed_weight_lag(self) -> int:
        """How many optimizer steps the rollout-resident adapter may lag the
        learner before StaleWeightsError fires — derived from the rollout
        regime instead of the old hard-coded ``1 if async_rollout else 0``:
        sync serializes (0), pipelined overlaps exactly one step (1), async
        is bounded by the staleness policy (max_staleness)."""
        if self.rollout_mode == "sync":
            return 0
        if self.rollout_mode == "pipelined":
            return 1
        return max(self.max_staleness, 1)

    @property
    def run_directory(self) -> str:
        return f"run_{self.run_name}"

    def train_sampling(self) -> SamplingConfig:
        return SamplingConfig(
            max_tokens=self.max_new_tokens,
            temperature=self.temperature,
            top_p=0.95,  # reference hardcodes top_p=0.95 (distributed_actor.py:47)
            n=self.num_candidates,
            top_p_exact=self.top_p_exact,
        )

    def eval_sampling(self) -> SamplingConfig:
        # reference eval params at distributed_trainer.py:53–58
        return SamplingConfig(
            max_tokens=self.max_new_tokens,
            temperature=self.eval_temperature,
            top_p=self.eval_top_p,
            n=self.eval_n,
            top_p_exact=self.top_p_exact,
        )

    def to_flat_dict(self) -> dict[str, Any]:
        """The reference-shaped flat config dict (train_distributed.py:54–81),
        used for wandb config logging parity."""
        return {
            "run_name": self.run_name,
            "project_name": self.project_name,
            "lora_save_path": self.lora_save_path,
            "lr": self.lr,
            "max_prompt_tokens": self.max_prompt_tokens,
            "max_new_tokens": self.max_new_tokens,
            "episodes": self.episodes,
            "num_candidates": self.num_candidates,
            "batch_size": self.batch_size,
            "train_batch_size": self.train_batch_size,
            "temperature": self.temperature,
            "save_every": self.save_every,
            "eval_every": self.eval_every,
            "model": self.model,
            "dataset": self.dataset,
            "number_of_actors": self.number_of_actors,
            "number_of_learners": self.number_of_learners,
            "learner": self.learner,
            "use_vllm": False,  # TPU build: jit generation engine, not vLLM
            "max_lora_rank": self.max_lora_rank,
            "topk": self.topk,
            "learner_chunk_size": self.learner_chunk_size,
            "actor_gpu_usage": self.actor_gpu_usage,
            "learner_gpu_usage": self.learner_gpu_usage,
            "lora_alpha": self.lora_alpha,
            "lora_dropout": self.lora_dropout,
        }
