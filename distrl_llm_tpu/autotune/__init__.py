"""Execution-plan autotuner: measured, cached dispatch policies replace
hard-coded perf defaults.

Public surface:

* :mod:`plan` — the plan space (:class:`ExecutionPlan`), plan keys
  (device kind × model hash × shape bucket), candidate enumeration;
* :mod:`store` — the persistent versioned JSON :class:`PlanStore`
  (env/CLI override, corrupt-file-safe);
* :func:`resolve_plan` — the per-engine lookup (explicit kwargs > stored
  plan > static defaults, telemetered);
* :mod:`microbench` — the in-process measurement harness ``tools/autotune.py``
  drives (imported explicitly, not re-exported: it imports the engines,
  which themselves import :func:`resolve_plan`).
"""

from distrl_llm_tpu.autotune.plan import (
    DEFAULT_PLAN,
    IMPL_TO_PAGED_KERNEL,
    PAGED_KERNEL_TO_IMPL,
    ExecutionPlan,
    TUNABLE_FIELDS,
    candidate_plans,
    canonical_device_kind,
    current_device_kind,
    model_config_hash,
    plan_key,
    rows_bucket,
    shape_bucket,
)
from distrl_llm_tpu.autotune.resolve import ResolvedPlan, resolve_plan
from distrl_llm_tpu.autotune.store import (
    DB_ENV,
    ENABLE_ENV,
    SCHEMA_VERSION,
    PlanStore,
    autotune_enabled,
    default_db_path,
)

__all__ = [
    "DEFAULT_PLAN",
    "DB_ENV",
    "ENABLE_ENV",
    "IMPL_TO_PAGED_KERNEL",
    "PAGED_KERNEL_TO_IMPL",
    "ExecutionPlan",
    "PlanStore",
    "ResolvedPlan",
    "SCHEMA_VERSION",
    "TUNABLE_FIELDS",
    "autotune_enabled",
    "candidate_plans",
    "canonical_device_kind",
    "current_device_kind",
    "default_db_path",
    "model_config_hash",
    "plan_key",
    "resolve_plan",
    "rows_bucket",
    "shape_bucket",
]
