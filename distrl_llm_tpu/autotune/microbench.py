"""In-process micro-bench harness: score each candidate plan on THIS device.

Three rules, all learned from round 5's contaminated rows (VERDICT.md):

* **Warmup/steady-state separation.** The first generate() pays XLA
  compilation (round 3 burned 246 s of a 9-minute tunnel window on it); a
  candidate's score is the mean of the post-warmup repeats only, and both
  times are reported so a pathological compile also shows up.
* **Infeasible, not fatal.** Every candidate runs under the existing
  memory-envelope math (engine/budget.py — the ``--actor_gpu_usage``
  contract's single owner) BEFORE an engine is built, and the build+run is
  wrapped: a candidate that would OOM, trip the compiler, or hit a Mosaic
  lowering surprise is scored ``feasible=False`` with the reason, and the
  sweep continues. The engines' own compile-time guards
  (``compile_chunk_guarded``) stay active underneath, so a chunk candidate
  whose program double-buffers is measured as what it actually ran
  (host-dispatched fallback) and flagged via ``scan_chunk_active``.
* **Deterministic volume.** EOS is unreachable (the pinned-fallback trick
  bench.py uses), so every candidate decodes exactly the same token count
  and tok/s is comparable across candidates.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, NamedTuple, Sequence

import numpy as np

from distrl_llm_tpu.autotune.plan import ExecutionPlan

log = logging.getLogger(__name__)


class CandidateResult(NamedTuple):
    plan: ExecutionPlan
    feasible: bool
    tok_s: float  # steady-state tokens/sec (0.0 when infeasible)
    warmup_s: float  # compile + first run
    steady_s: float  # mean timed-run seconds
    tokens: int  # tokens generated per timed run
    note: str  # infeasibility reason / honesty flags ("chunk fell back")


def plan_memory_guard(
    model_cfg,
    plan: ExecutionPlan,
    *,
    rows: int,
    max_prompt_tokens: int,
    max_new_tokens: int,
    param_bytes: int,
    kv_quant: str = "none",
    hbm_bytes: int | None = None,
) -> str | None:
    """None when the candidate's resident footprint fits the device, else
    the reason string. Reuses the budget module's page math (the single
    owner of KV bytes) and its activation reserve — the same envelope the
    refill pool is sized with, so "infeasible" here means "the engine's own
    budget would have clamped or OOMed"."""
    from distrl_llm_tpu.engine.budget import (
        ACTIVATION_RESERVE, device_hbm_bytes, page_bytes,
    )

    hbm = hbm_bytes if hbm_bytes is not None else device_hbm_bytes()
    total = max_prompt_tokens + max_new_tokens
    kv = rows * total * page_bytes(model_cfg, 1, kv_quant)
    budget = int(hbm * (1.0 - ACTIVATION_RESERVE))
    need = param_bytes + kv
    if need > budget:
        return (
            f"resident footprint {need / 2**30:.2f} GiB (weights "
            f"{param_bytes / 2**30:.2f} + KV {kv / 2**30:.2f}) exceeds "
            f"{budget / 2**30:.2f} GiB usable HBM"
        )
    return None


def time_candidate(
    run: Callable[[int], int],
    *,
    warmup: int = 1,
    repeats: int = 2,
) -> tuple[float, float, int]:
    """(warmup_s, steady_s_mean, tokens_per_run) for ``run(seed) -> tokens``.
    Warmup runs are timed but excluded from the score."""
    t0 = time.perf_counter()
    tokens = 0
    for i in range(max(warmup, 1)):
        tokens = run(i)
    warmup_s = time.perf_counter() - t0
    times = []
    for i in range(max(repeats, 1)):
        t1 = time.perf_counter()
        tokens = run(100 + i)
        times.append(time.perf_counter() - t1)
    return warmup_s, float(np.mean(times)), tokens


def build_engine_for_plan(
    model_cfg,
    plan: ExecutionPlan,
    *,
    max_prompt_tokens: int,
    max_new_tokens: int,
    rows: int,
    pad_id: int = 0,
    eos_ids: Sequence[int] = (-1,),
    cache_dtype=None,
    kv_quant: str = "none",
    spec_draft: int = 4,
):
    """The engine a candidate plan describes, built with ``autotune=False``
    so the measurement exercises the CANDIDATE, not a previously stored
    plan."""
    import jax.numpy as jnp

    from distrl_llm_tpu.engine.engine import GenerationEngine
    from distrl_llm_tpu.engine.paged_engine import PagedGenerationEngine

    if cache_dtype is None:
        import jax

        cache_dtype = (
            jnp.bfloat16 if jax.devices()[0].platform == "tpu" else jnp.float32
        )
    common = dict(
        max_prompt_tokens=max_prompt_tokens,
        max_new_tokens=max_new_tokens,
        eos_token_ids=list(eos_ids),
        pad_token_id=pad_id,
        cache_dtype=cache_dtype,
        # a kv_format candidate (ISSUE 15) IS the engine's KV format; a
        # None-field candidate falls back to the sweep-level kv_quant
        kv_quant=(
            plan.kv_format if plan.kv_format is not None else kv_quant
        ),
        scan_chunk=plan.scan_chunk,
        autotune=False,
    )
    if plan.decode_path == "dense":
        return GenerationEngine(
            model_cfg,
            cache_read_formulation=plan.cache_read_formulation,
            prompt_buckets=plan.prompt_buckets or None,
            **common,
        )
    from distrl_llm_tpu.autotune.plan import PAGED_KERNEL_TO_IMPL

    paged_kw = dict(
        # candidate paged-kernel variant rides as the engine kwargs the
        # plan fields map to ("auto" when the candidate leaves it derived)
        paged_impl=PAGED_KERNEL_TO_IMPL.get(plan.paged_kernel, "auto"),
        pages_per_block=plan.pages_per_block,
    )
    if plan.cb_mode is not None:
        # the admission-regime candidate pins continuous admission on or
        # off ("batch" measures the fixed-batch control); it needs the
        # refill scheduler — the slot machinery that hosts both prefix
        # sharing and the lazy group-admission queue
        paged_kw["continuous_admission"] = plan.cb_mode == "continuous"
    if plan.decode_path == "paged":
        if plan.cb_mode is not None:
            paged_kw.update(
                scheduler="refill",
                max_concurrent_rows=max(min(rows, 64), 1),
            )
        return PagedGenerationEngine(model_cfg, **paged_kw, **common)
    # speculative: refill scheduler hosts it; slots capped at the row
    # count. The plan's spec fields ARE the candidate (draft length,
    # drafter, verify kernel); ``spec_draft`` only backstops pre-spec-field
    # plans (spec_draft_len 0), and 0-valued satellites fall back to the
    # engine defaults via None
    return PagedGenerationEngine(
        model_cfg,
        scheduler="refill",
        max_concurrent_rows=max(min(rows, 64), 1),
        spec_draft=plan.spec_draft_len or spec_draft,
        spec_ngram=plan.spec_ngram_k or None,
        spec_drafter=plan.spec_drafter,
        spec_verify=plan.spec_verify,
        **paged_kw,
        **common,
    )


def _perturbed_drafter(lora, *, rel: float = 0.05, seed: int = 0):
    """A deterministically noise-perturbed copy of ``lora`` to stand in as
    the self-drafter's 'previous version' during a microbench.

    With nothing pushed through the mailbox the self-drafter would fall
    back to the TARGET adapter itself — q == p, acceptance ≡ 1.0, and every
    'self' candidate would be scored at the best case it can ever achieve
    (systematically optimistic vs the production regime, where the drafter
    is a genuinely superseded version). A small relative perturbation
    (``rel`` × per-leaf RMS, seeded) keeps the drafter NEAR-on-policy — the
    regime PipelineRL argues production actually sits in — while pushing
    the measured acceptance off the trivial upper bound. The measurement is
    still a proxy (the real update delta is unknowable offline); bench A/B
    on the live run remains the ground truth for drafter choice."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(lora)
    key = jax.random.PRNGKey(seed)
    out = []
    for i, leaf in enumerate(leaves):
        leaf = jnp.asarray(leaf)
        rms = float(
            jnp.sqrt(jnp.mean(jnp.square(leaf.astype(jnp.float32))))
        )
        if rms == 0.0:
            # zero-init leaves (LoRA B matrices) are exactly the ones whose
            # production updates make the drafter differ — perturb them at
            # the init-scheme's own fan scale instead of not at all
            rms = leaf.shape[-1] ** -0.5
        noise = jax.random.normal(
            jax.random.fold_in(key, i), leaf.shape, jnp.float32
        )
        out.append(
            (leaf.astype(jnp.float32) + rel * rms * noise).astype(leaf.dtype)
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def tune_geometry(
    model_cfg,
    params,
    lora,
    candidates: Sequence[ExecutionPlan],
    *,
    n_prompts: int,
    n_candidates: int,
    max_prompt_tokens: int,
    max_new_tokens: int,
    kv_quant: str = "none",
    warmup: int = 1,
    repeats: int = 2,
    hbm_bytes: int | None = None,
    pad_id: int = 0,
) -> list[CandidateResult]:
    """Measure every candidate at one geometry; returns results in input
    order (``best_result`` picks the winner)."""
    import jax

    from distrl_llm_tpu.config import SamplingConfig
    from distrl_llm_tpu.engine.budget import tree_bytes

    rows = n_prompts * n_candidates
    # per-base-format param trees (ISSUE 15), quantized once per format the
    # candidate space names: a base_quant candidate is measured over the
    # int8/int4 containers it describes (the fused dequant-matmul kernel
    # where enabled), and its memory guard sees the SHRUNK resident bytes
    # — the capacity win is part of what makes a quantized plan feasible
    _params_by_quant: dict[str, object] = {"none": params}

    def _params_for(plan: ExecutionPlan):
        bq = plan.base_quant or "none"
        if bq not in _params_by_quant:
            from distrl_llm_tpu.ops.quant import (
                default_group_size, quant_bits_for, quantize_params,
            )

            bits = quant_bits_for(bq)
            _params_by_quant[bq] = quantize_params(
                params, bits=bits, group_size=default_group_size(bits)
            )
        return _params_by_quant[bq]

    rng = np.random.default_rng(0)
    prompts = rng.integers(
        1, min(model_cfg.vocab_size, 50_000),
        size=(n_prompts, max_prompt_tokens),
    ).astype(np.int32)
    pmask = np.ones_like(prompts)

    results: list[CandidateResult] = []
    for plan in candidates:
        cand_params = _params_for(plan)
        cand_kv = plan.kv_format if plan.kv_format is not None else kv_quant
        reason = plan_memory_guard(
            model_cfg, plan, rows=rows, max_prompt_tokens=max_prompt_tokens,
            max_new_tokens=max_new_tokens,
            param_bytes=tree_bytes(cand_params),
            kv_quant=cand_kv, hbm_bytes=hbm_bytes,
        )
        if reason is not None:
            log.warning("autotune: %s infeasible: %s", plan.to_dict(), reason)
            results.append(CandidateResult(plan, False, 0.0, 0.0, 0.0, 0, reason))
            continue
        if plan.spec_drafter == "self" and lora is None:
            # the self-drafter IS the previous adapter version — with no
            # adapter there is nothing to draft from (mirrors the config
            # validation: spec_drafter='self' requires a LoRA run)
            results.append(CandidateResult(
                plan, False, 0.0, 0.0, 0.0, 0,
                "spec_drafter='self' requires a LoRA adapter to measure",
            ))
            continue
        try:
            engine = build_engine_for_plan(
                model_cfg, plan,
                max_prompt_tokens=max_prompt_tokens,
                max_new_tokens=max_new_tokens, rows=rows,
                pad_id=pad_id, kv_quant=kv_quant,
            )
            if plan.spec_drafter == "self":
                # seed the mailbox's superseded-adapter slot: without this
                # the drafter falls back to the target adapter (q == p,
                # acceptance ≡ 1.0) and 'self' scores its own unreachable
                # best case — see _perturbed_drafter
                engine._prev_lora = _perturbed_drafter(lora)
                engine._prev_lora_version = -1
            sampling = SamplingConfig(
                max_tokens=max_new_tokens, temperature=1.2, top_p=0.95,
                n=n_candidates, top_p_impl=plan.top_p_impl,
            )

            def run(seed: int) -> int:
                res = engine.generate(
                    cand_params, lora, prompts, pmask, sampling,
                    jax.random.PRNGKey(seed),
                )
                return int(res.lengths.sum())

            warmup_s, steady_s, tokens = time_candidate(
                run, warmup=warmup, repeats=repeats,
            )
            note = ""
            if plan.scan_chunk > 1 and engine.scan_chunk_active is False:
                # honesty flag: the measurement is real but it timed the
                # host-dispatched fallback, not the chunked program
                note = "scan_chunk fell back to host dispatch"
            results.append(CandidateResult(
                plan, True, tokens / steady_s if steady_s > 0 else 0.0,
                warmup_s, steady_s, tokens, note,
            ))
        except Exception as e:  # noqa: BLE001 — infeasible, not fatal
            log.warning(
                "autotune: %s failed (%s: %s) — scored infeasible",
                plan.to_dict(), type(e).__name__, e,
            )
            results.append(CandidateResult(
                plan, False, 0.0, 0.0, 0.0, 0, f"{type(e).__name__}: {e}",
            ))
    return results


def best_result(results: Sequence[CandidateResult]) -> CandidateResult | None:
    feasible = [r for r in results if r.feasible and r.tok_s > 0]
    if not feasible:
        return None
    return max(feasible, key=lambda r: r.tok_s)
