"""PlanStore: the persistent, versioned on-disk plan DB.

One JSON file maps ``plan_key`` → {plan, measurements, note}. Design rules:

* **Never crash a run.** A missing, corrupt, truncated, or
  schema-incompatible file loads as EMPTY (with a warning and an
  ``autotune/db_reset`` counter) — the caller falls back to the static
  defaults exactly as if nothing had ever been tuned, and the next
  ``tools/autotune.py`` run rewrites the file. Pinned by
  tests/test_autotune.py.
* **Atomic writes.** ``save()`` writes a sibling temp file and
  ``os.replace``s it, so a killed tuner can only ever leave the OLD db or
  the NEW db, never a half-written one (which rule 1 would shrug off
  anyway).
* **Override chain.** ``DISTRL_PLAN_DB`` (env) beats the default
  ``~/.cache/distrl_llm_tpu/plan_db.json``; the ``--plan-db`` CLI flag /
  engine ``plan_db=`` kwarg beats both. ``DISTRL_AUTOTUNE=0`` disables
  consultation entirely (resolution returns the static defaults).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.autotune.plan import ExecutionPlan

log = logging.getLogger(__name__)

SCHEMA_VERSION = 1

# corrupt/missing-DB fallback counter (one owner; pinned by the autotune
# smoke as the "never crash a run" evidence)
AUTOTUNE_DB_RESET = "autotune/db_reset"

DB_ENV = "DISTRL_PLAN_DB"
ENABLE_ENV = "DISTRL_AUTOTUNE"


def default_db_path() -> str:
    env = os.environ.get(DB_ENV)
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "distrl_llm_tpu", "plan_db.json"
    )


def autotune_enabled() -> bool:
    return os.environ.get(ENABLE_ENV, "1") != "0"


class PlanStore:
    """In-memory view of one plan-DB file; ``load()`` runs at construction.

    ``entries`` maps key → {"plan": dict, "measurements": list, "note": str}.
    """

    def __init__(self, path: str | None = None):
        self.path = path or default_db_path()
        self.entries: dict[str, dict] = {}
        self.load()

    def load(self) -> "PlanStore":
        self.entries = {}
        if not os.path.exists(self.path):
            return self
        try:
            with open(self.path) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError) as e:
            log.warning(
                "plan DB %s is unreadable (%s: %s) — starting empty; "
                "re-run tools/autotune.py to repopulate",
                self.path, type(e).__name__, e,
            )
            telemetry.counter_add(AUTOTUNE_DB_RESET)
            return self
        if not isinstance(doc, dict) or doc.get("schema_version") != SCHEMA_VERSION:
            log.warning(
                "plan DB %s has schema_version %r (this build reads %d) — "
                "starting empty; re-run tools/autotune.py to repopulate",
                self.path,
                doc.get("schema_version") if isinstance(doc, dict) else None,
                SCHEMA_VERSION,
            )
            telemetry.counter_add(AUTOTUNE_DB_RESET)
            return self
        entries = doc.get("entries")
        if isinstance(entries, dict):
            self.entries = {
                k: v for k, v in entries.items() if isinstance(v, dict)
            }
        return self

    def get(self, key: str) -> ExecutionPlan | None:
        """The stored plan for ``key``, or None. An entry whose plan fails
        validation (hand-edited file, older buggy writer) counts as absent —
        resolution falls back to defaults rather than crashing, the same
        re-tune semantics as a corrupt file."""
        entry = self.entries.get(key)
        if not entry:
            return None
        try:
            return ExecutionPlan.from_dict(entry.get("plan", {}))
        except (ValueError, TypeError) as e:
            log.warning(
                "plan DB entry %s is invalid (%s) — ignoring it; re-run "
                "tools/autotune.py to repopulate", key, e,
            )
            telemetry.counter_add(AUTOTUNE_DB_RESET)
            return None

    def put(self, key: str, plan: ExecutionPlan,
            measurements: list[dict] | None = None,
            note: str | None = None) -> None:
        entry: dict = {"plan": plan.to_dict()}
        if measurements:
            entry["measurements"] = list(measurements)
        if note:
            entry["note"] = note
        self.entries[key] = entry

    def save(self) -> str:
        doc = {"schema_version": SCHEMA_VERSION, "entries": self.entries}
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".plan_db_", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return self.path

    def report(self) -> str:
        """Human-readable table of every stored plan (the CLI's plan
        report)."""
        if not self.entries:
            return f"plan DB {self.path}: empty"
        lines = [f"plan DB {self.path}: {len(self.entries)} entr"
                 f"{'y' if len(self.entries) == 1 else 'ies'}"]
        for key in sorted(self.entries):
            plan = self.get(key)
            if plan is None:
                lines.append(f"  {key}: <invalid entry>")
                continue
            best = None
            for m in self.entries[key].get("measurements", []):
                if isinstance(m, dict) and isinstance(m.get("tok_s"), (int, float)):
                    best = max(best or 0.0, float(m["tok_s"]))
            perf = f"  ({best:.0f} tok/s measured)" if best else ""
            lines.append(
                f"  {key}: path={plan.decode_path} scan_chunk={plan.scan_chunk}"
                f" formulation={plan.cache_read_formulation or 'auto'}"
                f" top_p={plan.top_p_impl or 'auto'}"
                + (f" buckets={list(plan.prompt_buckets)}"
                   if plan.prompt_buckets else "")
                + perf
            )
        return "\n".join(lines)
