"""Execution-plan space: the discrete dispatch choices the engines used to
hard-code, as one typed record.

Round 5's headline regression (VERDICT.md) was a PLAN bug, not a kernel bug:
the bench's "production default" engaged the scan-chunk lever silicon had
measured 2.5× slower, and the paged path ran 5–6× behind dense at the benched
geometry. Every knob in :class:`ExecutionPlan` is one of those choices — the
things a measurement on the device, not a guess in the source, should pick
(the system-level tuning discipline LlamaRL/RLAX apply to keep RL pipelines
at hardware speed across geometries; PAPERS.md).

Plans are keyed by ``(device kind, model-config hash, shape bucket)`` —
``plan_key`` — because every one of these choices is hardware- and
geometry-dependent: chunked dispatch wins over a 40 ms/step network tunnel
and loses 2.5× on a local chip; the paged path wins when capacity binds and
loses when the grid-step floor does.

``DEFAULT_PLAN`` is deliberately identical to the engines' historical
hard-coded defaults, so resolution against an empty DB is a byte-identical
no-op (the acceptance contract pinned by tests/test_autotune.py).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from dataclasses import dataclass

DECODE_PATHS = ("dense", "paged", "speculative")
FORMULATIONS = (None, "dot", "mulred")
PAGED_KERNELS = (None, "one_page", "folded", "blocked")
SPEC_DRAFTERS = (None, "ngram", "self")
SPEC_VERIFIES = (None, "fused", "unrolled")
#: continuous-batching admission regimes for the paged refill scheduler
#: (ISSUE 12): "continuous" = prefix-shared prompt chains + lazy per-group
#: prefill feeding freed slots; "batch" = the fixed-episode-batch pin;
#: None = the engine default (fixed batches)
CB_MODES = (None, "batch", "continuous")
#: KV-cache storage formats (ISSUE 15): "int8" = per-token absmax int8 KV
#: (compact-scales Pallas variants on the paged/blocked/verify kernels —
#: ops/paged_int8.py); "none" = bf16/f32; None = the engine default
#: ("none"), i.e. an empty DB keeps today's behavior byte-identically.
#: Engines take ``kv_quant=None`` → consult this field; an explicit
#: "none"/"int8" kwarg pins past any stored plan (the decode_scan_chunk
#: convention: default ≠ pin).
KV_FORMATS = (None, "none", "int8")
#: frozen-base weight formats (ISSUE 15): int8/int4 weight-only containers
#: (ops/quant.py) consumed by the fused dequant-matmul kernel
#: (ops/quant_matmul.py). The ENGINE never loads weights, so this field is
#: consumed by the callers that build the base tree (bench production
#: defaults, tools/autotune.py measure, microbench) — stored so a tuned
#: "int4 base + int8 KV" serving stack is one DB entry, not a flag recipe.
BASE_QUANTS = (None, "none", "int8", "int4")
#: tiered KV prefix cache (ISSUE 18): "on" = cross-request radix prefix
#: index + host-RAM spill store on the refill pool (paged_engine's
#: prefix_cache kwarg); "off" pins it off; None = the engine default
#: (off), so an empty DB keeps today's behavior byte-identically. Engines
#: take ``prefix_cache=None`` → consult this field; an explicit True/False
#: kwarg pins past any stored plan (the decode_scan_chunk convention).
PREFIX_CACHES = (None, "off", "on")
#: draft lengths beyond this waste verify width faster than they amortize
#: weight reads (and the engine rejects them) — plan validation mirrors it
MAX_SPEC_DRAFT_LEN = 16

#: plan-field ↔ engine ``paged_impl`` spellings of the native paged-kernel
#: variants (the engine kwarg predates the plan field; "auto"/"kernel"/
#: "reference" have no plan spelling — they stay engine-kwarg-only)
PAGED_KERNEL_TO_IMPL = {
    "one_page": "native",
    "folded": "native_folded",
    "blocked": "native_blocked",
}
IMPL_TO_PAGED_KERNEL = {v: k for k, v in PAGED_KERNEL_TO_IMPL.items()}


@dataclass(frozen=True)
class ExecutionPlan:
    """One resolved set of dispatch choices for an engine + geometry.

    Field defaults ARE the engines' pre-autotuner hard-coded defaults;
    ``None``/empty means "derive exactly as the engine always has" (e.g.
    ``cache_read_formulation=None`` → mulred iff scan_chunk, the invariant
    engine.py documents).
    """

    # which engine class/scheduler serves decode. Engines can't change their
    # own class, so this field is consulted by the CALLERS that pick one
    # (bench.py; tools/autotune.py reports it) and pinned to the actual
    # class by the engine's own resolution (honest bench records).
    decode_path: str = "dense"
    # K decode steps fused per dispatch via lax.scan; 0 = host loop
    scan_chunk: int = 0
    # decode cache-read formulation (dense engine); None derives from
    # scan_chunk (ops/attention.py::attention_cached has the layout story)
    cache_read_formulation: str | None = None
    # top-p filter implementation (a key of ops.sampling.TOP_P_IMPLS); None
    # derives from SamplingConfig.top_p_exact as always. An explicit
    # SamplingConfig pin (top_p_impl / top_p_exact) still wins at generate().
    top_p_impl: str | None = None
    # prompt length buckets for the dense engine; () = the single
    # max_prompt_tokens bucket (engine-compiled per bucket used)
    prompt_buckets: tuple[int, ...] = ()
    # paged-attention kernel variant (paged/speculative paths); None derives
    # exactly as the engine always has (paged_impl="auto": the probe-gated
    # chain). "one_page"/"folded"/"blocked" pin the native kernel variants —
    # the grid-step ladder of the r5 overhead analysis (ops/paged_native.py)
    paged_kernel: str | None = None
    # blocked-kernel page collapse (pages folded per grid step); 0 = the
    # kernel default (ops.paged.DEFAULT_PAGES_PER_BLOCK). Only consumed by
    # paged_kernel="blocked"
    pages_per_block: int = 0
    # ---- speculative decoding (decode_path="speculative"; engines only
    # adopt these from the DB when they run the refill scheduler — the
    # slot machinery that hosts speculation). 0/None = the engines'
    # historical defaults (off / k=2 / "ngram" / "fused").
    # draft tokens proposed per verify step
    spec_draft_len: int = 0
    # n-gram lookup size for the "ngram" drafter; 0 = engine default (2)
    spec_ngram_k: int = 0
    # draft source: "ngram" (prompt lookup) | "self" (the policy's own
    # previous LoRA version, off the LoraMailbox swap log)
    spec_drafter: str | None = None
    # verify attention: "fused" (one blocked sweep for the whole draft
    # block — ops/paged_native.py) | "unrolled" (d+1 per-position calls)
    spec_verify: str | None = None
    # continuous-batching admission (refill scheduler only): "continuous"
    # turns on prefix-shared prompt chains + the lazy per-group admission
    # queue (paged_engine's continuous_admission kwarg); "batch" pins the
    # fixed-episode-batch regime; None = engine default (fixed). Engines
    # that can't host it (wave scheduler, no row cap) drop a stored
    # "continuous" entry with a warning, same policy as the spec fields.
    cb_mode: str | None = None
    # KV-cache storage format (ISSUE 15): "int8" per-token-absmax KV /
    # "none" bf16-f32; None = engine default ("none"). Engines built with
    # kv_quant=None adopt this; an explicit engine kwarg pins past it.
    kv_format: str | None = None
    # frozen-base weight format (ISSUE 15): "int8"/"int4" weight-only
    # containers / "none" full-width; None = caller default. Consumed by
    # the weight-loading callers (bench/autotune), not the engines.
    base_quant: str | None = None
    # tiered KV prefix cache (ISSUE 18): "on" arms the cross-request radix
    # prefix index + host spill store on the refill pool (requires
    # continuous admission — engines that can't host it drop a stored "on"
    # with a warning); "off" pins it off; None = engine default (off).
    prefix_cache: str | None = None

    def __post_init__(self):
        if self.decode_path not in DECODE_PATHS:
            raise ValueError(
                f"decode_path must be one of {DECODE_PATHS}, got "
                f"{self.decode_path!r}"
            )
        if not isinstance(self.scan_chunk, int) or self.scan_chunk < 0:
            raise ValueError(
                f"scan_chunk must be an int >= 0, got {self.scan_chunk!r}"
            )
        if self.cache_read_formulation not in FORMULATIONS:
            raise ValueError(
                f"cache_read_formulation must be one of {FORMULATIONS}, got "
                f"{self.cache_read_formulation!r}"
            )
        if self.top_p_impl is not None:
            from distrl_llm_tpu.ops.sampling import TOP_P_IMPLS

            if self.top_p_impl not in TOP_P_IMPLS:
                raise ValueError(
                    f"top_p_impl must be one of {sorted(TOP_P_IMPLS)}, got "
                    f"{self.top_p_impl!r}"
                )
        # normalize list → tuple (JSON round-trips through lists)
        object.__setattr__(
            self, "prompt_buckets", tuple(int(b) for b in self.prompt_buckets)
        )
        if any(b <= 0 for b in self.prompt_buckets):
            raise ValueError(
                f"prompt_buckets must be positive, got {self.prompt_buckets}"
            )
        if self.paged_kernel not in PAGED_KERNELS:
            raise ValueError(
                f"paged_kernel must be one of {PAGED_KERNELS}, got "
                f"{self.paged_kernel!r}"
            )
        if not isinstance(self.pages_per_block, int) or self.pages_per_block < 0:
            raise ValueError(
                f"pages_per_block must be an int >= 0, got "
                f"{self.pages_per_block!r}"
            )
        if (
            not isinstance(self.spec_draft_len, int)
            or not 0 <= self.spec_draft_len <= MAX_SPEC_DRAFT_LEN
        ):
            raise ValueError(
                f"spec_draft_len must be an int in [0, {MAX_SPEC_DRAFT_LEN}],"
                f" got {self.spec_draft_len!r}"
            )
        if not isinstance(self.spec_ngram_k, int) or self.spec_ngram_k < 0:
            raise ValueError(
                f"spec_ngram_k must be an int >= 0, got {self.spec_ngram_k!r}"
            )
        if self.spec_drafter not in SPEC_DRAFTERS:
            raise ValueError(
                f"spec_drafter must be one of {SPEC_DRAFTERS}, got "
                f"{self.spec_drafter!r}"
            )
        if self.spec_verify not in SPEC_VERIFIES:
            raise ValueError(
                f"spec_verify must be one of {SPEC_VERIFIES}, got "
                f"{self.spec_verify!r}"
            )
        if self.cb_mode not in CB_MODES:
            raise ValueError(
                f"cb_mode must be one of {CB_MODES}, got {self.cb_mode!r}"
            )
        if self.kv_format not in KV_FORMATS:
            raise ValueError(
                f"kv_format must be one of {KV_FORMATS}, got "
                f"{self.kv_format!r}"
            )
        if self.base_quant not in BASE_QUANTS:
            raise ValueError(
                f"base_quant must be one of {BASE_QUANTS}, got "
                f"{self.base_quant!r}"
            )
        if self.prefix_cache not in PREFIX_CACHES:
            raise ValueError(
                f"prefix_cache must be one of {PREFIX_CACHES}, got "
                f"{self.prefix_cache!r}"
            )

    def replace(self, **kw) -> "ExecutionPlan":
        return dataclasses.replace(self, **kw)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["prompt_buckets"] = list(self.prompt_buckets)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ExecutionPlan":
        """Tolerant of unknown keys (a newer writer within the same schema
        version may add fields); missing keys take the defaults."""
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in dict(d).items() if k in fields})


DEFAULT_PLAN = ExecutionPlan()

#: the ExecutionPlan fields a caller may pin explicitly (resolution order:
#: explicit user kwarg > stored plan > DEFAULT_PLAN, per field)
TUNABLE_FIELDS = tuple(f.name for f in dataclasses.fields(ExecutionPlan))


# ------------------------------------------------------------------ plan keys


def model_config_hash(model_cfg) -> str:
    """Stable short hash of a ModelConfig: same architecture → same plans,
    regardless of which named constant or checkpoint produced it."""
    blob = json.dumps(
        dataclasses.asdict(model_cfg), sort_keys=True, default=str
    )
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


# one canonical name per accelerator family: jax reports the same silicon as
# "TPU v5e" / "TPU v5 lite" / "tpu v5 litepod" depending on runtime version,
# and plans measured under one alias must resolve under the others
_KIND_ALIASES = (
    ("v6", "tpu_v6"),
    ("v5p", "tpu_v5p"),
    ("v5e", "tpu_v5e"),
    ("v5 lite", "tpu_v5e"),
    ("v5litepod", "tpu_v5e"),
    ("v4", "tpu_v4"),
    ("v3", "tpu_v3"),
    ("v2", "tpu_v2"),
)


def canonical_device_kind(raw: str) -> str:
    low = raw.lower()
    for sub, canon in _KIND_ALIASES:
        if sub in low:
            return canon
    return re.sub(r"[^a-z0-9]+", "_", low).strip("_") or "unknown"


def current_device_kind() -> str:
    """Canonical kind of this host's first accelerator ("cpu" on CPU hosts,
    "unknown" when no backend initializes)."""
    try:
        import jax

        dev = jax.devices()[0]
        if dev.platform != "tpu":
            return dev.platform  # "cpu" / "gpu"
        return canonical_device_kind(dev.device_kind)
    except Exception:  # noqa: BLE001 — no backend at all
        return "unknown"


def rows_bucket(rows: int) -> int:
    """Concurrent-row count bucketed to the next power of two (480 → 512):
    plans generalize across nearby batch sizes, not across orders of
    magnitude."""
    if rows <= 0:
        return 0
    b = 1
    while b < rows:
        b *= 2
    return b


def shape_bucket(max_prompt_tokens: int, max_new_tokens: int,
                 rows: int = 0) -> str:
    """Geometry key component. ``rows=0`` is the any-row-count bucket —
    engines resolve with it (batch size arrives at generate(), after the
    plan is already baked into compiled programs); tuners that know the row
    count write both the exact and the any-rows entry."""
    base = f"p{max_prompt_tokens}_n{max_new_tokens}"
    rb = rows_bucket(rows)
    return f"{base}_r{rb}" if rb else base


def plan_key(device_kind: str, model_hash: str, bucket: str) -> str:
    return f"{device_kind}/{model_hash}/{bucket}"


# ------------------------------------------------------------ candidate space


def candidate_plans(
    *,
    decode_paths=("dense",),
    scan_chunks=(0, 16),
    formulations=(None,),
    top_p_impls=(None,),
    paged_kernels=(None,),
    pages_per_blocks=(0,),
    spec_draft_lens=(0,),
    spec_drafters=(None,),
    spec_verifies=(None,),
    cb_modes=(None,),
    kv_formats=(None,),
    base_quants=(None,),
    prefix_caches=(None,),
) -> list[ExecutionPlan]:
    """Enumerate a candidate space for the tuner (cartesian product, with
    the always-meaningless combos dropped: a formulation override without a
    dense path, a scan_chunk of 1 — scan-of-one has no fusion benefit and
    the engines refuse to report it as chunked, a paged-kernel pin on the
    dense path, a pages_per_block without the blocked kernel, spec knobs
    anywhere but the speculative path, a cb_mode on the dense path — the
    admission scheduler is paged-refill machinery — and a speculative path
    with no draft length, which is just the paged path wearing a costume).
    ``kv_formats``/``base_quants`` (ISSUE 15) apply on every path: the
    dense engine hosts the int8 scale-carrying cache and the paged/
    speculative kernels their compact-scales variants, and the quantized
    base rides any decode path."""
    out = []
    for path in decode_paths:
        for chunk in scan_chunks:
            if chunk == 1:
                continue
            for form in formulations:
                if form is not None and path != "dense":
                    continue
                for pk in paged_kernels:
                    if pk is not None and path == "dense":
                        continue
                    for ppb in pages_per_blocks:
                        if ppb and pk != "blocked":
                            continue
                        for sd in spec_draft_lens:
                            if (sd > 0) != (path == "speculative"):
                                continue
                            for drafter in spec_drafters:
                                if drafter is not None and not sd:
                                    continue
                                for sv in spec_verifies:
                                    if sv is not None and not sd:
                                        continue
                                    for cb in cb_modes:
                                        if cb is not None and path == "dense":
                                            continue
                                        for pc in prefix_caches:
                                            # the radix cache rides the
                                            # continuous-admission chain
                                            # machinery (ISSUE 18)
                                            if pc == "on" and cb != "continuous":
                                                continue
                                            if pc is not None and path == "dense":
                                                continue
                                            for kvf in kv_formats:
                                                for bq in base_quants:
                                                    for tp in top_p_impls:
                                                        out.append(ExecutionPlan(
                                                            decode_path=path,
                                                            scan_chunk=chunk,
                                                            cache_read_formulation=form,
                                                            top_p_impl=tp,
                                                            paged_kernel=pk,
                                                            pages_per_block=ppb,
                                                            spec_draft_len=sd,
                                                            spec_drafter=drafter,
                                                            spec_verify=sv,
                                                            cb_mode=cb,
                                                            kv_format=kvf,
                                                            base_quant=bq,
                                                            prefix_cache=pc,
                                                        ))
    return out
