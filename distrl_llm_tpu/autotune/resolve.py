"""resolve_plan(): the one lookup every engine and bench makes at build time.

Resolution order, PER FIELD: explicit user kwarg > stored plan (exact
rows-bucket key, then the any-rows key) > static default. An empty DB is a
byte-identical no-op — the engines behave exactly as their pre-autotuner
hard-coded defaults did — and pinned flags keep winning over any DB entry,
so an A/B run can never be silently retuned out from under its config.

Every resolution is recorded through the PR-1 telemetry layer: an
``autotune/plan_resolved`` counter plus ``autotune/plan_db_hit`` /
``autotune/plan_default``, and (when tracing is on) an ``autotune/resolve``
span carrying the key, source, and resolved choices — so a trace shows
which plan a round ran under without cross-reading bench JSONs after the
fact (the round-5 failure mode).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Mapping, NamedTuple

from distrl_llm_tpu import telemetry
from distrl_llm_tpu.autotune.plan import (
    DEFAULT_PLAN,
    ExecutionPlan,
    TUNABLE_FIELDS,
    current_device_kind,
    model_config_hash,
    plan_key,
    shape_bucket,
)
from distrl_llm_tpu.autotune.store import PlanStore, autotune_enabled, default_db_path

log = logging.getLogger(__name__)

# resolution-outcome counters (one owner each; three distinct outcomes so
# an operator can tell a DB miss from autotune being disabled)
AUTOTUNE_PLAN_RESOLVED = "autotune/plan_resolved"
AUTOTUNE_PLAN_DB_HIT = "autotune/plan_db_hit"
AUTOTUNE_PLAN_DEFAULT = "autotune/plan_default"
AUTOTUNE_PLAN_DISABLED = "autotune/plan_disabled"


class ResolvedPlan(NamedTuple):
    plan: ExecutionPlan
    # where the plan substantively came from: "db" (a stored entry was
    # found), "default" (no entry — static defaults), or "disabled"
    # (autotune off: kwarg, or DISTRL_AUTOTUNE=0)
    source: str
    # the DB key consulted (the any-rows form when rows was 0)
    key: str
    # per-field provenance: field name -> "user" | "db" | "default"
    sources: dict[str, str]


# stores are cached per path and reloaded when the file changes — engine
# construction happens in loops (tests, per-bucket builds) and must not
# re-parse an unchanged file every time
_STORES: dict[str, tuple[tuple, PlanStore]] = {}
_STORES_MU = threading.Lock()


def _store_for(path: str) -> PlanStore:
    try:
        st = os.stat(path)
        stamp = (st.st_mtime_ns, st.st_size)
    except OSError:
        stamp = (0, -1)  # missing file: one shared empty-store stamp
    with _STORES_MU:
        cached = _STORES.get(path)
        if cached and cached[0] == stamp:
            return cached[1]
        store = PlanStore(path)
        _STORES[path] = (stamp, store)
        return store


def resolve_plan(
    *,
    model_cfg,
    max_prompt_tokens: int,
    max_new_tokens: int,
    rows: int = 0,
    requested: Mapping[str, object] | None = None,
    db_path: str | None = None,
    device_kind: str | None = None,
    enabled: bool = True,
) -> ResolvedPlan:
    """Resolve the execution plan for one (device, model, geometry).

    ``requested`` holds ONLY the fields the caller pinned explicitly (an
    engine kwarg the user actually passed, a BENCH_* env var that was set);
    those always win. Invalid requested values raise — a typo'd explicit
    kwarg must fail loudly, while an invalid STORED plan only logs and falls
    back (PlanStore.get)."""
    requested = dict(requested or {})
    unknown = set(requested) - set(TUNABLE_FIELDS)
    if unknown:
        raise ValueError(f"unknown plan fields requested: {sorted(unknown)}")

    kind = device_kind or current_device_kind()
    mhash = model_config_hash(model_cfg)
    key = plan_key(kind, mhash, shape_bucket(max_prompt_tokens, max_new_tokens, rows))
    consult = enabled and autotune_enabled()

    with telemetry.span("autotune/resolve", key=key) as sp:
        stored = None
        if consult:
            store = _store_for(db_path or default_db_path())
            stored = store.get(key)
            if stored is None and rows:
                # fall back to the any-rows entry for this geometry
                any_key = plan_key(
                    kind, mhash, shape_bucket(max_prompt_tokens, max_new_tokens, 0)
                )
                stored = store.get(any_key)
                if stored is not None:
                    key = any_key
            if stored is not None and "decode_path" in requested:
                # ``decode_path`` may be a single pin or a tuple of paths
                # the caller can actually host (a refill engine with spec
                # unpinned hosts "paged" OR "speculative" — which one is
                # exactly what the DB decides)
                req_path = requested["decode_path"]
                allowed = (
                    (req_path,) if isinstance(req_path, str) else tuple(req_path)
                )
                if stored.decode_path not in allowed:
                    # the stored plan was measured on a decode path the
                    # caller cannot host (e.g. the tuner's winner was
                    # paged, this is a dense engine): its scan_chunk/top_p
                    # were never measured here, and adopting them would be
                    # exactly the unmeasured-lever regression this
                    # subsystem exists to prevent — treat the entry as a
                    # miss
                    log.debug(
                        "autotune: %s stored plan is for decode_path=%s but "
                        "the caller pinned %s — ignoring the entry",
                        key, stored.decode_path, req_path,
                    )
                    stored = None

        fields: dict = {}
        sources: dict[str, str] = {}
        for name in TUNABLE_FIELDS:
            if name == "decode_path" and not isinstance(
                requested.get(name, ""), str
            ):
                # tuple form: a CONSTRAINT, not a pin — the surviving
                # stored entry names the path that actually runs; with no
                # entry the first element is the caller's default path
                if stored is not None:
                    fields[name] = stored.decode_path
                    sources[name] = "db"
                else:
                    fields[name] = tuple(requested[name])[0]
                    sources[name] = "default"
            elif name in requested:
                fields[name] = requested[name]
                sources[name] = "user"
            elif stored is not None:
                fields[name] = getattr(stored, name)
                sources[name] = "db"
            else:
                fields[name] = getattr(DEFAULT_PLAN, name)
                sources[name] = "default"
        plan = ExecutionPlan(**fields)  # validates; user typos raise here

        source = (
            "db" if stored is not None
            else ("default" if consult else "disabled")
        )
        telemetry.counter_add(AUTOTUNE_PLAN_RESOLVED)
        # three distinct outcomes, three counters: an operator triaging
        # "why didn't my tuned plan apply" must be able to tell a DB miss
        # (re-tune) from autotune being disabled (flip the switch)
        telemetry.counter_add(
            AUTOTUNE_PLAN_DB_HIT if stored is not None
            else (AUTOTUNE_PLAN_DEFAULT if consult
                  else AUTOTUNE_PLAN_DISABLED)
        )
        sp.set(source=source, decode_path=plan.decode_path,
               scan_chunk=plan.scan_chunk,
               formulation=plan.cache_read_formulation,
               top_p_impl=plan.top_p_impl)
    if stored is not None:
        log.debug("autotune: %s resolved from DB: %s", key, plan.to_dict())
    return ResolvedPlan(plan=plan, source=source, key=key, sources=sources)
