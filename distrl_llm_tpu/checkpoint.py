"""Checkpoint / resume: Orbax state snapshots + the adapter artifact.

The reference is save-only (SURVEY §5 "checkpoint"): a LoRA adapter file every
step (save_lora, distributed_actor.py:84–86 — doubling as the weight-sync bus)
and HF save_pretrained snapshots every ``save_every`` steps (:263–264). There
is no load path and optimizer state is never saved. The TPU build fixes that:

* :class:`CheckpointManager` — Orbax snapshots of {lora params, optimizer
  state, step, episode, rng} with true resume and retention;
* :func:`save_adapter_file` — an optional peft-style adapter artifact
  (safetensors) for compatibility with the reference's per-step adapter file.
  Weight *sync* does NOT go through this file — learner→rollout weights move
  as device arrays (trainer.py) — it is an export artifact only.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any

import jax
import numpy as np

Params = dict[str, Any]


# ------------------------------------------------- rollout-buffer sidecar

# The async rollout regime's in-flight state (queued Trajectory groups +
# the producer's episode/batch cursor) is numpy/str payloads, not a jax
# pytree, so it rides NEXT TO the Orbax snapshot as a pickle sidecar keyed
# by the same step: a resumed run reloads the unconsumed buffer and restarts
# the producer at its cursor instead of losing or re-generating data.

def rollout_state_path(directory: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(directory), f"rollout_state_{step}.pkl"
    )


def save_rollout_state(directory: str, step: int, state: dict,
                       keep: int = 3) -> str:
    """Atomically write the rollout sidecar for ``step``; prunes sidecars
    beyond the newest ``keep`` (mirrors the Orbax retention so orphaned
    pickles don't accumulate)."""
    path = rollout_state_path(directory, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f)
    os.replace(tmp, path)
    stale = sorted(
        (
            p for p in os.listdir(os.path.dirname(path))
            if p.startswith("rollout_state_") and p.endswith(".pkl")
        ),
        key=lambda p: int(p[len("rollout_state_"):-len(".pkl")]),
    )[:-keep]
    for p in stale:
        try:
            os.remove(os.path.join(os.path.dirname(path), p))
        except OSError:  # a concurrent save already pruned it
            pass
    return path


def load_rollout_state(directory: str, step: int) -> dict | None:
    """Read the sidecar for ``step``; None when absent or unreadable (a
    missing/corrupt sidecar degrades to a fresh buffer — never blocks the
    Orbax resume itself)."""
    path = rollout_state_path(directory, step)
    if not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except Exception:  # noqa: BLE001 — corrupt sidecar: warn-and-fresh
        import logging

        logging.getLogger(__name__).warning(
            "rollout sidecar %s unreadable; resuming with an empty buffer",
            path,
        )
        return None


class CheckpointManager:
    """Orbax-backed save/restore of the full learner state.

    State tree: ``{"lora": ..., "opt_state": ..., "step": ..., "episode": ...,
    "rng": ...}``. Restore requires a template with matching structure (build
    it from a fresh init) — shapes/dtypes are validated by Orbax.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        import orbax.checkpoint as ocp

        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: dict) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))
        self._mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, template: dict, step: int | None = None) -> dict | None:
        """Restore into ``template``'s structure; None if no checkpoint."""
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        abstract = jax.tree_util.tree_map(
            self._ocp.utils.to_shape_dtype_struct, template
        )
        return self._mgr.restore(step, args=self._ocp.args.StandardRestore(abstract))

    def close(self) -> None:
        self._mgr.close()


# HF/peft adapter tensor-name mapping for the export artifact. Our stacked
# [L, in, out] LoRA layout unstacks to per-layer peft names so the artifact is
# loadable by peft-compatible tooling (the reference's adapter artifact is a
# peft save_lora output, distributed_actor.py:84–86).
_PEFT_NAMES = {
    "wq": "self_attn.q_proj",
    "wk": "self_attn.k_proj",
    "wv": "self_attn.v_proj",
    "wo": "self_attn.o_proj",
    "w_gate": "mlp.gate_proj",
    "w_up": "mlp.up_proj",
    "w_down": "mlp.down_proj",
}


def save_adapter_file(
    lora: Params, path: str, *, rank: int, alpha: float, model_name: str = ""
) -> None:
    """Write a peft-style adapter directory: adapter_model.safetensors +
    adapter_config.json. LoRA pytree layout: ``lora[key]["a"]`` [L, in, r],
    ``lora[key]["b"]`` [L, r, out] (models/lora.py).

    ATOMIC like ``save_rollout_state``: everything is written into a
    sibling tmp dir first — the adapter doubles as the rollout weight bus
    in reference-parity setups, and a preemption mid-write must never
    leave a truncated safetensors (or a tensors/config mismatch) there
    for an engine to load. Two publication paths keep the PAIR
    consistent: the steady state (per-step saves, unchanged config)
    replaces only the tensors file — a single atomic rename; a changed
    config (new rank/alpha/targets) swaps the WHOLE directory, so a
    reader can never pair new tensors with a stale config."""
    import shutil
    import tempfile

    from safetensors.numpy import save_file

    path = os.path.abspath(path)
    os.makedirs(path, exist_ok=True)
    tensors: dict[str, np.ndarray] = {}
    for key, mats in lora.get("layers", lora).items():
        peft = _PEFT_NAMES.get(key, key)
        a, b = np.asarray(mats["a"]), np.asarray(mats["b"])
        for layer in range(a.shape[0]):
            base = f"base_model.model.model.layers.{layer}.{peft}"
            # peft stores lora_A [r, in] and lora_B [out, r]
            tensors[f"{base}.lora_A.weight"] = np.ascontiguousarray(a[layer].T)
            tensors[f"{base}.lora_B.weight"] = np.ascontiguousarray(b[layer].T)
    config = {
        "peft_type": "LORA",
        "r": rank,
        "lora_alpha": alpha,
        "base_model_name_or_path": model_name,
        "target_modules": sorted(
            {v.rsplit(".", 1)[-1] for v in _PEFT_NAMES.values()}
        ),
    }
    config_text = json.dumps(config, indent=2)
    cfg_path = os.path.join(path, "adapter_config.json")
    try:
        with open(cfg_path) as f:
            config_unchanged = f.read() == config_text
    except OSError:
        config_unchanged = False
    # same-parent tmp dir so every rename is a same-filesystem atomic op
    tmp = tempfile.mkdtemp(
        prefix=os.path.basename(path) + ".tmp",
        dir=os.path.dirname(path) or ".",
    )
    try:
        save_file(tensors, os.path.join(tmp, "adapter_model.safetensors"))
        if config_unchanged:
            # steady state: ONE rename publishes the new tensors against
            # the identical existing config — the pair stays consistent
            # through any preemption point
            os.replace(
                os.path.join(tmp, "adapter_model.safetensors"),
                os.path.join(path, "adapter_model.safetensors"),
            )
        else:
            with open(os.path.join(tmp, "adapter_config.json"), "w") as f:
                f.write(config_text)
            if not os.listdir(path):
                # first save: rename over the empty target dir (POSIX
                # allows renaming onto an empty directory)
                os.replace(tmp, path)
                tmp = None
            else:
                # config changed over a populated dir: swap directories so
                # no reader can observe new tensors + old config. The only
                # exposure is a sub-syscall ENOENT window between the two
                # renames — strictly narrower than the old cross-file
                # mismatch window.
                old = path + f".old{os.getpid()}"
                os.rename(path, old)
                os.rename(tmp, path)
                tmp = old
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)


def load_adapter_file(path: str, template: Params) -> Params:
    """Read an adapter directory back into our stacked layout (shape/dtype from
    ``template``) — the round-trip half the reference never had."""
    from safetensors.numpy import load_file

    tensors = load_file(os.path.join(path, "adapter_model.safetensors"))
    nested = "layers" in template and isinstance(template.get("layers"), dict)
    layer_template = template["layers"] if nested else template
    out: Params = {}
    for key, mats in layer_template.items():
        peft = _PEFT_NAMES.get(key, key)
        a_t, b_t = np.asarray(mats["a"]), np.asarray(mats["b"])
        a = np.stack(
            [
                tensors[f"base_model.model.model.layers.{l}.{peft}.lora_A.weight"].T
                for l in range(a_t.shape[0])
            ]
        ).astype(a_t.dtype)
        b = np.stack(
            [
                tensors[f"base_model.model.model.layers.{l}.{peft}.lora_B.weight"].T
                for l in range(b_t.shape[0])
            ]
        ).astype(b_t.dtype)
        out[key] = {"a": a, "b": b}
    return {"layers": out} if nested else out
