from distrl_llm_tpu.models.configs import (  # noqa: F401
    LLAMA3_8B,
    PRESETS,
    QWEN2_0_5B,
    QWEN2_7B,
    QWEN2_72B,
    TINY,
    ModelConfig,
    preset_for_model_name,
)
from distrl_llm_tpu.models.lora import (  # noqa: F401
    DEFAULT_TARGETS,
    init_lora_params,
    lora_scale,
    merge_lora,
)
from distrl_llm_tpu.models.transformer import (  # noqa: F401
    forward,
    init_kv_cache,
    init_params,
)
