"""Pure-JAX GQA decoder (Qwen2 / Llama-3 family) over param pytrees.

TPU-first design choices, deliberately unlike the reference's torch modules:

* **Stacked layers + lax.scan** — per-layer params are stacked on a leading
  [L, ...] axis and the decoder scans one compiled layer body over them. XLA
  compiles the layer once instead of L times, and the same scan carries the KV
  cache through prefill/decode.
* **Functional everywhere** — params are nested dicts; the forward is a pure
  function of (params, lora, inputs, cache), so jit/pjit/grad/remat compose
  trivially and weight sync is array movement, not module surgery.
* **Fixed shapes** — callers pad to static prompt/answer lengths (the
  reference already does this on the learner side: distributed_actor.py:217–229),
  so every distinct shape compiles exactly once.

LoRA (q/k/v/o/gate/up/down targets — helper.py:29–37) is a separate pytree of
stacked (A, B) factors applied additively inside the layer body; the base tree
is frozen and may hold quantized weight containers (ops/quant.py).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from distrl_llm_tpu.models.configs import ModelConfig
from distrl_llm_tpu.ops.attention import attention, attention_cached, causal_padding_mask
from distrl_llm_tpu.ops.linear import linear, lora_delta

Params = dict[str, Any]


def rms_norm(x: jax.Array, weight: jax.Array, eps: float,
             offset: bool = False) -> jax.Array:
    orig_dtype = x.dtype
    x = x.astype(jnp.float32)
    x = x * jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    w = weight.astype(jnp.float32)
    if offset:  # Gemma stores the norm weight as a delta around 1
        w = w + 1.0
    return (x * w).astype(orig_dtype)


def rope_cos_sin(positions: jax.Array, head_dim: int, theta: float):
    """positions [B, S] → (cos, sin) each [B, S, head_dim/2], f32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Rotate [B, S, H, D] by per-position angles (HF rotate-half convention)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    cos = cos[:, :, None, :].astype(x.dtype)
    sin = sin[:, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)


# stable per-target stream ids so each projection's dropout mask differs
_TARGET_STREAM = {
    "wq": 0, "wk": 1, "wv": 2, "wo": 3, "w_gate": 4, "w_up": 5, "w_down": 6,
}


def _proj(h, p, lora, key, bias_key, lora_scale,
          lora_dropout: float = 0.0, dropout_rng=None):
    """One projection with optional bias and optional LoRA delta.

    A quantized base weight with an active adapter (and no LoRA dropout —
    dropout perturbs the adapter INPUT, which the epilogue can't express)
    dispatches to the fused Pallas dequant-matmul with the LoRA delta
    applied in the kernel epilogue (ops/quant_matmul.py): one program, one
    output-tile round-trip, weight streamed at int width. Same math order
    as the split path — (dot + bias) + delta — so greedy decode is
    bit-identical whichever path ran."""
    has_lora = lora is not None and key in lora
    w = p[key]
    if (
        has_lora and isinstance(w, dict) and w["q"].ndim == 3
        and (lora_dropout <= 0.0 or dropout_rng is None)
    ):
        from distrl_llm_tpu.ops.quant_matmul import (
            dispatch_choices, quant_matmul, quant_matmul_dispatch,
        )

        a, b = lora[key]["a"], lora[key]["b"]
        bits = 4 if w["q"].dtype == jnp.int4 else 8
        use, interp = quant_matmul_dispatch(
            w["q"].shape, bits, a.shape[-1], h.shape[-1], h.dtype
        )
        dispatch_choices[
            (bits, h.shape[-1], w["q"].shape[-1], a.shape[-1])
        ] = "kernel" if use else "xla"
        if use:
            return quant_matmul(
                h, w, p.get(bias_key), a, b, lora_scale, interpret=interp
            )
    y = linear(h, p[key], p.get(bias_key))
    if has_lora:
        rng = (
            jax.random.fold_in(dropout_rng, _TARGET_STREAM[key])
            if dropout_rng is not None else None
        )
        y = y + lora_delta(
            h, lora[key]["a"], lora[key]["b"], lora_scale,
            dropout_rate=lora_dropout, dropout_rng=rng,
        )
    return y


def _layer(
    x: jax.Array,  # [B, S, D]
    p: Params,  # one layer's params (leading L axis already sliced off)
    lora: Params | None,
    cache_k: jax.Array | None,  # [B, K, hd, Smax] — S minormost (attention_cached)
    cache_v: jax.Array | None,
    *,
    cache_k_scale: jax.Array | None = None,  # f32 [B, K, 1, Smax] — int8 KV
    cache_v_scale: jax.Array | None = None,
    cfg: ModelConfig,
    cos: jax.Array,
    sin: jax.Array,
    mask: jax.Array | None,
    cache_offset: jax.Array | int,
    lora_scale: float,
    attn_impl: str,
    attn_mesh=None,
    key_valid: jax.Array | None = None,  # [B, S] for the ring path
    paged_lengths: jax.Array | None = None,  # [B] — paged-cache mode
    page_indices: jax.Array | None = None,  # [B, pps]
    page_size: int = 0,
    paged_impl: str = "auto",
    pages_per_block: int = 0,  # blocked-kernel page collapse (0 = kernel default)
    paged_verify: bool = False,  # S>1 per-row draft-block decode (spec decode)
    paged_verify_impl: str = "fused",  # "fused" | "unrolled" verify sweep
    paged_chunked: bool = False,  # S>1 continuation (chunked) prefill
    paged_prefix: bool = False,  # S>1 warm (radix-hit) suffix prefill
    lora_dropout: float = 0.0,
    dropout_rng: jax.Array | None = None,  # per-layer key (training only)
    cache_read_formulation: str = "dot",  # "mulred" inside scan-chunk bodies
):
    b, s, _ = x.shape
    proj = partial(_proj, lora_dropout=lora_dropout, dropout_rng=dropout_rng)
    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps, offset=cfg.rmsnorm_offset)
    q = proj(h, p, lora, "wq", "bq", lora_scale).reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = proj(h, p, lora, "wk", "bk", lora_scale).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = proj(h, p, lora, "wv", "bv", lora_scale).reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)

    if cache_k is not None and page_indices is not None:
        # paged cache (ops/paged.py — the N1 ragged decode path): cache_k/v
        # are page arrays [K, total_pages, ps, hd]; sequences are PACKED, so
        # attention reads each row's true [0, length) prefix only.
        from distrl_llm_tpu.ops.paged import (
            paged_attention_op, write_prompt_to_pages, write_token_to_pages,
            write_tokens_to_pages,
        )

        if s == 1:
            cache_k = write_token_to_pages(
                cache_k, k[:, 0], paged_lengths, page_indices, page_size)
            cache_v = write_token_to_pages(
                cache_v, v[:, 0], paged_lengths, page_indices, page_size)
            att = paged_attention_op(
                q[:, 0], cache_k, cache_v, paged_lengths + 1, page_indices,
                impl=paged_impl, pages_per_block=pages_per_block,
            )[:, None]
        elif paged_chunked:
            # continuation (chunked) prefill: S tokens extend each row's
            # sequence at its own per-row offset (recompute after preemption —
            # vLLM's chunked prefill). KV is written to pages first (padding
            # positions dropped via ``valid``), then attention runs over the
            # row's dense-gathered context with exact per-position causality.
            from distrl_llm_tpu.ops.paged import (
                chunked_context_attention, gather_pages_dense,
            )

            q_valid = key_valid[:, :s] if key_valid is not None else (
                jnp.ones((b, s), jnp.int32)
            )
            cache_k = write_tokens_to_pages(
                cache_k, k, paged_lengths, page_indices, page_size,
                valid=q_valid > 0)
            cache_v = write_tokens_to_pages(
                cache_v, v, paged_lengths, page_indices, page_size,
                valid=q_valid > 0)
            att = chunked_context_attention(
                q, gather_pages_dense(cache_k, page_indices),
                gather_pages_dense(cache_v, page_indices),
                paged_lengths, q_valid,
            )
        elif paged_prefix:
            # warm (radix-hit) suffix prefill: the row's first
            # ``paged_lengths`` positions are already resident in cached
            # pages; only the suffix re-forwards. Bit-identity with the
            # packed cold prefill demands the SAME attention numerics
            # (``attention_reference`` rounds probs to the value dtype
            # before the PV product; ``chunked_context_attention`` keeps
            # them f32 to match the decode op), so this branch writes the
            # suffix KV to pages and then attends over the row's
            # dense-gathered packed window in COMPUTE dtype through the
            # same ``attention`` front door the cold path uses. Contract:
            # ``page_indices`` carries ONE trailing scratch column (the
            # engine's warm-admission row extension) — the gather drops it
            # so the key window width equals the cold packed width.
            from distrl_llm_tpu.ops.paged import gather_pages_dense

            q_valid = key_valid[:, :s] if key_valid is not None else (
                jnp.ones((b, s), jnp.int32)
            )
            cache_k = write_tokens_to_pages(
                cache_k, k, paged_lengths, page_indices, page_size,
                valid=q_valid > 0)
            cache_v = write_tokens_to_pages(
                cache_v, v, paged_lengths, page_indices, page_size,
                valid=q_valid > 0)
            ctx_k = gather_pages_dense(
                cache_k, page_indices[:, :-1], dtype=q.dtype)
            ctx_v = gather_pages_dense(
                cache_v, page_indices[:, :-1], dtype=q.dtype)
            # query i sits at global position lengths+i; causality over the
            # packed window reproduces the cold mask rows for real lanes
            # (padding lanes attend garbage, but their outputs land on the
            # scratch page and the logits gather never reads them)
            jpos = jnp.arange(ctx_k.shape[1])[None, None, None, :]
            qpos = (paged_lengths[:, None]
                    + jnp.arange(s, dtype=jnp.int32)[None, :])[:, None, :, None]
            att = attention(q, ctx_k, ctx_v, jpos <= qpos, impl=attn_impl)
        elif paged_verify:
            # speculative-decode verify: S draft tokens extend each row's
            # sequence at its own per-row offset. QKV/MLP batch over the
            # whole block (the weight-bandwidth amortization speculative
            # decoding buys); attention goes through paged_verify_op —
            # draft position i attends over the prefix plus draft tokens
            # ≤ i (lengths + i + 1, exact causality), as ONE fused blocked
            # sweep when the hardware can (ops/paged_native.py
            # paged_attention_native_verify) or unrolled per position
            # (paged_verify_impl="unrolled" / non-TPU backends)
            from distrl_llm_tpu.ops.paged import paged_verify_op

            cache_k = write_tokens_to_pages(
                cache_k, k, paged_lengths, page_indices, page_size)
            cache_v = write_tokens_to_pages(
                cache_v, v, paged_lengths, page_indices, page_size)
            att = paged_verify_op(
                q, cache_k, cache_v, paged_lengths, page_indices,
                impl=paged_impl, pages_per_block=pages_per_block,
                verify_impl=paged_verify_impl,
            )
        else:
            # packed prefill: write the prompt pages, attend over the input
            cache_k = write_prompt_to_pages(cache_k, k, page_indices, page_size)
            cache_v = write_prompt_to_pages(cache_v, v, page_indices, page_size)
            att = attention(q, k, v, mask, impl=attn_impl, key_valid=key_valid)
    elif cache_k is not None:
        quant = cache_k_scale is not None
        if quant:
            # int8 KV cache: quantize the new positions per (B, K, position)
            # over head_dim and write values + scales; attention reads the
            # cache at 1 byte/element with dequant folded into the einsums
            from distrl_llm_tpu.ops.attention import quantize_kv_position

            k_t, ks = quantize_kv_position(k.transpose(0, 2, 3, 1))
            v_t, vs = quantize_kv_position(v.transpose(0, 2, 3, 1))
            cache_k_scale = jax.lax.dynamic_update_slice(
                cache_k_scale, ks, (0, 0, 0, cache_offset))
            cache_v_scale = jax.lax.dynamic_update_slice(
                cache_v_scale, vs, (0, 0, 0, cache_offset))
        else:
            k_t = k.astype(cache_k.dtype).transpose(0, 2, 3, 1)  # [B, K, hd, S]
            v_t = v.astype(cache_v.dtype).transpose(0, 2, 3, 1)
        cache_k = jax.lax.dynamic_update_slice(cache_k, k_t, (0, 0, 0, cache_offset))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v_t, (0, 0, 0, cache_offset))
        if attn_impl == "flash" and isinstance(cache_offset, int) and cache_offset == 0 and s > 1:
            # prefill: the cache holds nothing beyond the prompt being
            # written, so attention is plain self-attention over the input —
            # run the flash kernel on the fresh k/v and only WRITE the cache
            att = attention(
                q, k, v, mask[..., :s], impl="flash",
                key_valid=key_valid[:, :s] if key_valid is not None else None,
            )
        elif quant:
            from distrl_llm_tpu.ops.attention import attention_cached_quant

            att = attention_cached_quant(
                q, cache_k, cache_k_scale, cache_v, cache_v_scale, mask,
                formulation=cache_read_formulation,
            )
        else:
            att = attention_cached(
                q, cache_k.astype(q.dtype), cache_v.astype(q.dtype), mask,
                formulation=cache_read_formulation,
            )
    elif attn_impl == "ring" and attn_mesh is not None:
        # sequence-parallel training path: causal+padding semantics come from
        # global positions inside the ring, not from the materialized mask
        from distrl_llm_tpu.ops.ring_attention import ring_attention

        att = ring_attention(q, k, v, key_valid, mesh=attn_mesh)
    elif attn_impl == "ulysses" and attn_mesh is not None:
        # sequence parallelism by head scatter (two all-to-alls per layer);
        # needs H and K divisible by sp — ring covers the rest
        from distrl_llm_tpu.ops.ulysses import ulysses_attention

        att = ulysses_attention(q, k, v, key_valid, mesh=attn_mesh)
    else:
        att = attention(q, k, v, mask, impl=attn_impl, key_valid=key_valid)
    att = att.reshape(b, s, cfg.q_dim)
    x = x + proj(att, p, lora, "wo", "bo", lora_scale)

    h = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps, offset=cfg.rmsnorm_offset)
    act = (
        jax.nn.silu if cfg.hidden_act == "silu"
        else partial(jax.nn.gelu, approximate=True)  # Gemma gelu_pytorch_tanh
    )
    gate = act(proj(h, p, lora, "w_gate", "b_gate", lora_scale))
    up = proj(h, p, lora, "w_up", "b_up", lora_scale)
    x = x + proj(gate * up, p, lora, "w_down", "b_down", lora_scale)
    return x, cache_k, cache_v, cache_k_scale, cache_v_scale


def forward(
    params: Params,
    cfg: ModelConfig,
    input_ids: jax.Array,  # [B, S]
    *,
    attention_mask: jax.Array | None = None,  # [B, Sk]; 1 = attendable key
    positions: jax.Array | None = None,  # [B, S] absolute positions
    lora: Params | None = None,
    lora_scale: float = 1.0,
    kv_cache: Params | None = None,  # {"k","v": L-tuples of [B, K, hd, Smax]}
    cache_offset: jax.Array | int = 0,
    remat: bool = False,
    attn_impl: str = "reference",
    attn_mesh=None,  # jax Mesh with an "sp" axis; required for attn_impl="ring"
    logits_slice: tuple[int, int] | None = None,  # (start, length) along seq
    logits_positions: jax.Array | None = None,  # [B] per-row position gather
    page_size: int = 0,  # static; paged-cache mode (ops/paged.py)
    paged_impl: str = "auto",
    pages_per_block: int = 0,  # blocked-kernel page collapse (0 = kernel default)
    paged_verify: bool = False,  # speculative-decode draft-block verify
    paged_verify_impl: str = "fused",  # verify sweep: "fused" | "unrolled"
    paged_chunked: bool = False,  # continuation (chunked) prefill over pages
    paged_prefix: bool = False,  # warm (radix-hit) suffix prefill over pages
    lora_dropout: float = 0.0,  # peft-style adapter-input dropout (training)
    dropout_rng: jax.Array | None = None,
    skip_lm_head: bool = False,  # return final-norm hidden states, not logits
    cache_read_formulation: str = "dot",  # see ops.attention.attention_cached
) -> tuple[jax.Array, Params | None]:
    """Decoder forward. Returns (logits f32 [B, S, V], updated kv_cache).

    Without a cache this is the training/prefill path (causal over the input);
    with a dense cache (per-layer tuples from init_kv_cache — NOT a stacked
    array; the cached path also always uses attention_cached, ignoring
    ``attn_impl``), queries attend to all cache keys marked valid by
    ``attention_mask`` (length Smax) and new K/V are written at
    ``cache_offset``. Contract: ``cache_offset + S <= Smax`` — the engine sizes
    caches as prompt+max_tokens so this holds by construction; writes past
    capacity would be silently clamped by dynamic_update_slice.

    A PAGED cache (``init_paged_kv_cache`` plus traced "lengths" [B] and
    "page_indices" [B, pps] entries in the dict, with the static
    ``page_size``/``paged_impl`` kwargs) switches to the ragged N1 path:
    sequences are packed, prefill self-attends over the input while writing
    prompt pages, and decode runs paged attention over each row's true
    [0, length+1) prefix.
    """
    b, s = input_ids.shape
    paged = kv_cache is not None and "page_indices" in kv_cache
    if kv_cache is not None and not paged and isinstance(cache_offset, int):
        smax = kv_cache["k"][0].shape[-1]
        if cache_offset + s > smax:
            raise ValueError(
                f"KV cache overflow: offset {cache_offset} + seq {s} > capacity {smax}"
            )
    if positions is None:
        positions = cache_offset + jnp.arange(s, dtype=jnp.int32)[None, :]
        positions = jnp.broadcast_to(positions, (b, s))
    cos, sin = rope_cos_sin(positions, cfg.head_dim, cfg.rope_theta)

    x = jnp.take(params["embed"], input_ids, axis=0)
    if cfg.scale_embeddings:  # Gemma: hidden states enter at sqrt(D) scale
        x = x * jnp.asarray(cfg.hidden_size**0.5, x.dtype)

    # paged caches attend raggedly by per-row length (decode) or over the
    # packed input only (prefill) — the dense key window is the input itself
    sk = kv_cache["k"][0].shape[-1] if (kv_cache is not None and not paged) else s
    cfg.check_within_window(sk)
    if attention_mask is None:
        attention_mask = jnp.ones((b, sk), dtype=jnp.int32)
    # ring and (uncached) flash consume the [B, S] validity vector directly —
    # building the [B, 1, S, S] mask for them would cost O(S²) memory on
    # exactly the long-context paths those kernels exist to avoid (it is also
    # DCE'd under jit, but eager/non-jit callers would pay it)
    needs_dense_mask = (
        (kv_cache is not None and not paged)
        or (paged and s > 1 and not paged_chunked and not paged_prefix
            and attn_impl not in ("ring", "ulysses", "flash", "splash"))
        or (kv_cache is None and attn_impl not in ("ring", "ulysses", "flash", "splash"))
    )
    mask = (
        causal_padding_mask(
            attention_mask, q_len=s, q_offset=0 if paged else cache_offset
        )
        if needs_dense_mask else None
    )

    layer_fn = partial(
        _layer,
        cfg=cfg,
        cos=cos,
        sin=sin,
        mask=mask,
        cache_offset=cache_offset,
        lora_scale=lora_scale,
        attn_impl=attn_impl,
        attn_mesh=attn_mesh,
        key_valid=attention_mask,
        paged_lengths=kv_cache.get("lengths") if paged else None,
        page_indices=kv_cache.get("page_indices") if paged else None,
        page_size=page_size,
        paged_impl=paged_impl,
        pages_per_block=pages_per_block,
        paged_verify=paged_verify,
        paged_verify_impl=paged_verify_impl,
        paged_chunked=paged_chunked,
        paged_prefix=paged_prefix,
        lora_dropout=lora_dropout if dropout_rng is not None else 0.0,
        cache_read_formulation=cache_read_formulation,
    )

    layer_keys = (
        jax.random.split(dropout_rng, cfg.num_layers)
        if (dropout_rng is not None and lora_dropout > 0.0) else None
    )
    xs = (
        params["layers"],
        lora["layers"] if lora is not None else None,
        layer_keys,
    )

    if kv_cache is None:
        def scan_body(x, xs):
            p, lora_p, key = xs
            y = layer_fn(x, p, lora_p, None, None, dropout_rng=key)[0]
            return y, None

        if remat:
            scan_body = jax.checkpoint(
                scan_body, policy=jax.checkpoint_policies.nothing_saveable
            )
        x, _ = jax.lax.scan(scan_body, x, xs)
        new_k = new_v = None
    else:
        # UNROLLED layer loop over PER-LAYER cache buffers. Carrying a stacked
        # [L, ...] cache through a lax.scan (as slice/update on the scan carry)
        # defeats XLA's in-place buffer aliasing: the while-loop ping-pongs the
        # whole cache, costing a full cache-sized HBM temp (~9 GB at the
        # reference rollout volume, measured via compile memory_analysis).
        # Separate per-layer carry leaves alias to zero temp bytes. Weight
        # slices params["layers"][w][i] are static and fuse into their matmuls.
        kv_quant = "k_scale" in kv_cache  # int8 dense cache carries scales
        new_k, new_v, new_ks, new_vs = [], [], [], []
        for i in range(cfg.num_layers):
            p_i = jax.tree_util.tree_map(lambda w: w[i], params["layers"])
            lora_i = (
                jax.tree_util.tree_map(lambda w: w[i], lora["layers"])
                if lora is not None else None
            )
            key_i = layer_keys[i] if layer_keys is not None else None
            x, ck, cv, cks, cvs = layer_fn(
                x, p_i, lora_i, kv_cache["k"][i], kv_cache["v"][i],
                cache_k_scale=kv_cache["k_scale"][i] if kv_quant else None,
                cache_v_scale=kv_cache["v_scale"][i] if kv_quant else None,
                dropout_rng=key_i,
            )
            new_k.append(ck)
            new_v.append(cv)
            new_ks.append(cks)
            new_vs.append(cvs)
        new_k, new_v = tuple(new_k), tuple(new_v)
        new_scales = (
            {"k_scale": tuple(new_ks), "v_scale": tuple(new_vs)}
            if kv_quant else {}
        )

    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps,
                 offset=cfg.rmsnorm_offset)
    if logits_slice is not None:
        # project only the needed positions — the learner's logprob recompute
        # discards all prompt logits, so slicing the hidden states first skips
        # ~P/(P+T) of the lm_head FLOPs and the [B, P, V] buffer
        x = jax.lax.dynamic_slice_in_dim(x, logits_slice[0], logits_slice[1], axis=1)
    elif logits_positions is not None:
        # per-row gather (packed prompts end at different columns): [B, 1, D]
        idx = jnp.broadcast_to(
            logits_positions[:, None, None].astype(jnp.int32),
            (x.shape[0], 1, x.shape[-1]),
        )
        x = jnp.take_along_axis(x, idx, axis=1)
    if skip_lm_head:
        # caller projects to the vocab itself (e.g. the learner's CHUNKED
        # logprob path, which never wants the whole [B, S, V] buffer live)
        logits = x
    else:
        lm_head = params["embed"].T if cfg.tie_word_embeddings else params["lm_head"]
        logits = linear(x, lm_head).astype(jnp.float32)

    if kv_cache is None:
        new_cache = None
    else:
        new_cache = {**kv_cache, "k": new_k, "v": new_v, **new_scales}
    return logits, new_cache


def init_params(
    rng: jax.Array, cfg: ModelConfig, dtype=jnp.float32
) -> Params:
    """Random init with HF-comparable scales (normal 0.02 for projections)."""
    keys = iter(jax.random.split(rng, 16))
    init = lambda k, shape: (0.02 * jax.random.normal(k, shape)).astype(dtype)
    L, D, F = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
    layers: Params = {
        "attn_norm": jnp.ones((L, D), dtype),
        "mlp_norm": jnp.ones((L, D), dtype),
        "wq": init(next(keys), (L, D, cfg.q_dim)),
        "wk": init(next(keys), (L, D, cfg.kv_dim)),
        "wv": init(next(keys), (L, D, cfg.kv_dim)),
        "wo": init(next(keys), (L, cfg.q_dim, D)),
        "w_gate": init(next(keys), (L, D, F)),
        "w_up": init(next(keys), (L, D, F)),
        "w_down": init(next(keys), (L, F, D)),
    }
    if cfg.attention_bias:
        layers["bq"] = jnp.zeros((L, cfg.q_dim), dtype)
        layers["bk"] = jnp.zeros((L, cfg.kv_dim), dtype)
        layers["bv"] = jnp.zeros((L, cfg.kv_dim), dtype)
    params: Params = {
        "embed": init(next(keys), (cfg.vocab_size, D)),
        "final_norm": jnp.ones((D,), dtype),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = init(next(keys), (D, cfg.vocab_size))
    return params


def init_kv_cache(
    cfg: ModelConfig, batch: int, max_seq: int, dtype=jnp.bfloat16
) -> Params:
    """Per-layer tuples of [B, K, hd, Smax], S minormost.

    Two deliberate choices, both required for the decode loop to update the
    cache in place (zero HBM temps, verified with compile memory_analysis):
    separate per-layer buffers (a stacked [L, ...] array carried through a
    scan gets ping-pong-buffered by XLA), and S as the minormost dim (the
    layout XLA assigns the loop carry; any other logical order inserts
    cache-sized layout-conversion copies)."""
    shape = (batch, cfg.num_kv_heads, cfg.head_dim, max_seq)
    return {
        "k": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
        "v": tuple(jnp.zeros(shape, dtype) for _ in range(cfg.num_layers)),
    }


def init_kv_cache_int8(cfg: ModelConfig, batch: int, max_seq: int) -> Params:
    """int8 dense decode cache with per-(B, K, position) f32 scales —
    1 + 4/head_dim bytes per element vs bf16's 2. Same per-layer-tuple /
    S-minormost layout rules as ``init_kv_cache``; the "k_scale"/"v_scale"
    keys switch the dense-cache forward onto the fused-dequant attention
    path (ops/attention.py::attention_cached_quant)."""
    shape = (batch, cfg.num_kv_heads, cfg.head_dim, max_seq)
    sshape = (batch, cfg.num_kv_heads, 1, max_seq)
    return {
        "k": tuple(jnp.zeros(shape, jnp.int8) for _ in range(cfg.num_layers)),
        "v": tuple(jnp.zeros(shape, jnp.int8) for _ in range(cfg.num_layers)),
        "k_scale": tuple(jnp.zeros(sshape, jnp.float32) for _ in range(cfg.num_layers)),
        "v_scale": tuple(jnp.zeros(sshape, jnp.float32) for _ in range(cfg.num_layers)),
    }
