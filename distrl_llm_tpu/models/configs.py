"""Model architecture configs for the dense decoder family the reference
trains (Qwen2.5 / Llama-3 — reference model flags at train_distributed.py:11
and BASELINE.json configs).

One ``ModelConfig`` covers the whole family: GQA attention with optional QKV
bias (Qwen2 yes, Llama no), SwiGLU MLP, RMSNorm, RoPE, optional tied
embeddings.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    attention_bias: bool = False  # Qwen2: bias on q/k/v only
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 32768

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @staticmethod
    def from_hf_config(hf) -> "ModelConfig":
        """Build from a transformers PretrainedConfig (Qwen2Config/LlamaConfig)."""
        get = lambda k, d=None: getattr(hf, k, d)
        num_heads = hf.num_attention_heads
        return ModelConfig(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_layers=hf.num_hidden_layers,
            num_heads=num_heads,
            num_kv_heads=get("num_key_value_heads", num_heads),
            head_dim=get("head_dim", None) or hf.hidden_size // num_heads,
            rope_theta=get("rope_theta", 10000.0),
            rms_norm_eps=get("rms_norm_eps", 1e-6),
            attention_bias=hf.model_type == "qwen2" or bool(get("attention_bias", False)),
            tie_word_embeddings=bool(get("tie_word_embeddings", False)),
            max_position_embeddings=get("max_position_embeddings", 32768),
        )


# Tiny config for unit/golden tests — shapes chosen to exercise GQA (heads !=
# kv_heads) while staying sub-millisecond on CPU.
TINY = ModelConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    rope_theta=10000.0,
    attention_bias=True,
    tie_word_embeddings=False,
)

QWEN2_0_5B = ModelConfig(
    vocab_size=151936, hidden_size=896, intermediate_size=4864, num_layers=24,
    num_heads=14, num_kv_heads=2, head_dim=64, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=True,
)

QWEN2_7B = ModelConfig(
    vocab_size=152064, hidden_size=3584, intermediate_size=18944, num_layers=28,
    num_heads=28, num_kv_heads=4, head_dim=128, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=False,
)

QWEN2_72B = ModelConfig(
    vocab_size=152064, hidden_size=8192, intermediate_size=29568, num_layers=80,
    num_heads=64, num_kv_heads=8, head_dim=128, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=False,
)

LLAMA3_8B = ModelConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, head_dim=128, rope_theta=500000.0,
    rms_norm_eps=1e-5, attention_bias=False, tie_word_embeddings=False,
)

PRESETS: dict[str, ModelConfig] = {
    "tiny": TINY,
    "qwen2.5-0.5b": QWEN2_0_5B,
    "qwen2.5-7b": QWEN2_7B,
    "qwen2.5-72b": QWEN2_72B,
    "llama-3-8b": LLAMA3_8B,
}


def preset_for_model_name(name: str) -> ModelConfig | None:
    """Map an HF-style model id (e.g. 'Qwen/Qwen2.5-7B-Instruct') to a preset."""
    low = name.lower()
    if low == "tiny":  # exact only — "tiny" substrings occur in real model ids
        return TINY
    for key, cfg in PRESETS.items():
        if key != "tiny" and key in low.replace("_", "-"):
            return cfg
    if "0.5b" in low and "qwen" in low:
        return QWEN2_0_5B
    if "7b" in low and "qwen" in low:
        return QWEN2_7B
    if "72b" in low and "qwen" in low:
        return QWEN2_72B
    if "8b" in low and "llama" in low:
        return LLAMA3_8B
    return None
