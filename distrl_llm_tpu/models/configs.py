"""Model architecture configs for the dense decoder families the reference
trains through unsloth (train_distributed.py:11 — any FastLanguageModel
checkpoint; BASELINE.json configs name Qwen2.5 and Llama-3).

One ``ModelConfig`` covers the supported families — Qwen2.5, Llama-3,
Mistral, Gemma — via the knobs where they actually differ: GQA attention
with optional QKV bias (Qwen2 yes), gated MLP with SiLU or tanh-GELU
(Gemma), RMSNorm with optional +1 weight offset (Gemma), optional
sqrt(hidden) embedding scaling (Gemma), optional tied embeddings, and a
recorded sliding window (Mistral v0.1 — full attention is exact for
sequences within the window; the engines enforce that).
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class ModelConfig:
    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6
    attention_bias: bool = False  # Qwen2: bias on q/k/v only
    tie_word_embeddings: bool = False
    max_position_embeddings: int = 32768
    hidden_act: str = "silu"  # "silu" | "gelu_tanh" (Gemma)
    rmsnorm_offset: bool = False  # Gemma: norm scales by (1 + weight)
    scale_embeddings: bool = False  # Gemma: embeddings × sqrt(hidden_size)
    # Mistral v0.1 sliding-window size; recorded so the forward/engines can
    # REFUSE sequences longer than the window (full attention ≡ SWA within
    # it) rather than silently change the model's semantics
    sliding_window: int | None = None

    def __post_init__(self):
        if self.hidden_act not in ("silu", "gelu_tanh"):
            raise ValueError(
                f"hidden_act must be silu/gelu_tanh, got {self.hidden_act!r}"
            )

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    def check_within_window(self, key_span: int) -> None:
        """Raise if attending over ``key_span`` keys would exceed the
        checkpoint's sliding window — full attention ≡ SWA only within it;
        running past it would silently change the model (Mistral v0.1).
        Single owner of the check for the forward and both engines."""
        if self.sliding_window is not None and key_span > self.sliding_window:
            raise ValueError(
                f"key window {key_span} exceeds the checkpoint's "
                f"sliding_window {self.sliding_window}; sliding-window "
                "attention is not implemented — keep prompt+generation "
                "within the window"
            )

    @property
    def matmul_param_count(self) -> int:
        """Parameters participating in matmuls (projections + MLP + lm_head;
        biases/norms excluded as FLOP-negligible, embedding lookups are not
        matmuls). The 2·N term of every FLOPs-per-token estimate — the
        single owner for bench.py and the telemetry MFU series."""
        per_layer = (
            self.hidden_size * self.q_dim          # q proj
            + 2 * self.hidden_size * self.kv_dim   # k, v proj
            + self.q_dim * self.hidden_size        # o proj
            + 3 * self.hidden_size * self.intermediate_size  # gate, up, down
        )
        return self.num_layers * per_layer + self.hidden_size * self.vocab_size

    def decode_flops_per_token(self, mean_kv_len: float = 0.0) -> float:
        """Model FLOPs per decoded token: 2·(matmul params) for the dense
        path plus the attention score/value dot-products (2 FLOPs × q_dim
        keys-side + values-side) at the mean resident KV length."""
        attn = 4.0 * self.num_layers * self.q_dim * mean_kv_len
        return 2.0 * self.matmul_param_count + attn

    def train_flops_per_token(self, seq_len: int) -> float:
        """Model FLOPs per trained token: 3× the forward's cost (fwd + ~2×
        for backward through frozen base + LoRA), causal attention at mean
        key length ``seq_len / 2``."""
        return 3.0 * self.decode_flops_per_token(seq_len / 2.0)

    @property
    def model_type(self) -> str:
        """The HF model_type this config round-trips through
        ``from_hf_config`` as (used by HF-format snapshot export)."""
        if self.rmsnorm_offset:
            return "gemma"
        if self.sliding_window is not None:
            return "mistral"
        return "qwen2" if self.attention_bias else "llama"

    @staticmethod
    def from_hf_config(hf) -> "ModelConfig":
        """Build from a transformers PretrainedConfig (Qwen2/Llama/Mistral/
        Gemma Config)."""
        get = lambda k, d=None: getattr(hf, k, d)
        num_heads = hf.num_attention_heads
        mt = str(get("model_type", ""))
        if mt.startswith("gemma") and mt != "gemma":
            # Gemma-2/3 add pre/post-FFN norms, logit softcapping, and
            # alternating SWA — loading them as Gemma-1 would silently
            # produce wrong logits (the state-dict mapper ignores keys it
            # doesn't know)
            raise ValueError(
                f"model_type {mt!r} is not supported (Gemma-1 only); "
                "its extra norms/softcapping would be silently dropped"
            )
        gemma = mt == "gemma"
        act = str(get("hidden_activation", None) or get("hidden_act", "silu"))
        # Qwen2 configs carry sliding_window but gate it off by default
        window = get("sliding_window") if get("use_sliding_window", True) else None
        return ModelConfig(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_layers=hf.num_hidden_layers,
            num_heads=num_heads,
            num_kv_heads=get("num_key_value_heads", num_heads),
            head_dim=get("head_dim", None) or hf.hidden_size // num_heads,
            rope_theta=get("rope_theta", 10000.0),
            rms_norm_eps=get("rms_norm_eps", 1e-6),
            attention_bias=hf.model_type == "qwen2" or bool(get("attention_bias", False)),
            tie_word_embeddings=bool(get("tie_word_embeddings", False)),
            max_position_embeddings=get("max_position_embeddings", 32768),
            hidden_act="gelu_tanh" if "gelu" in act else "silu",
            rmsnorm_offset=gemma,
            scale_embeddings=gemma,
            sliding_window=int(window) if window else None,
        )


# Tiny config for unit/golden tests — shapes chosen to exercise GQA (heads !=
# kv_heads) while staying sub-millisecond on CPU.
TINY = ModelConfig(
    vocab_size=256,
    hidden_size=64,
    intermediate_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=16,
    rope_theta=10000.0,
    attention_bias=True,
    tie_word_embeddings=False,
)

QWEN2_0_5B = ModelConfig(
    vocab_size=151936, hidden_size=896, intermediate_size=4864, num_layers=24,
    num_heads=14, num_kv_heads=2, head_dim=64, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=True,
)

QWEN2_7B = ModelConfig(
    vocab_size=152064, hidden_size=3584, intermediate_size=18944, num_layers=28,
    num_heads=28, num_kv_heads=4, head_dim=128, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=False,
)

QWEN2_72B = ModelConfig(
    vocab_size=152064, hidden_size=8192, intermediate_size=29568, num_layers=80,
    num_heads=64, num_kv_heads=8, head_dim=128, rope_theta=1000000.0,
    attention_bias=True, tie_word_embeddings=False,
)

LLAMA3_8B = ModelConfig(
    vocab_size=128256, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, head_dim=128, rope_theta=500000.0,
    rms_norm_eps=1e-5, attention_bias=False, tie_word_embeddings=False,
)

MISTRAL_7B = ModelConfig(  # v0.1: 4k sliding window (v0.2+ configs drop it)
    vocab_size=32000, hidden_size=4096, intermediate_size=14336, num_layers=32,
    num_heads=32, num_kv_heads=8, head_dim=128, rope_theta=10000.0,
    rms_norm_eps=1e-5, attention_bias=False, tie_word_embeddings=False,
    sliding_window=4096,
)

GEMMA_2B = ModelConfig(  # MQA (1 kv head), GeGLU, +1 norm offset, tied
    vocab_size=256000, hidden_size=2048, intermediate_size=16384, num_layers=18,
    num_heads=8, num_kv_heads=1, head_dim=256, rope_theta=10000.0,
    rms_norm_eps=1e-6, attention_bias=False, tie_word_embeddings=True,
    hidden_act="gelu_tanh", rmsnorm_offset=True, scale_embeddings=True,
    max_position_embeddings=8192,
)

GEMMA_7B = ModelConfig(
    vocab_size=256000, hidden_size=3072, intermediate_size=24576, num_layers=28,
    num_heads=16, num_kv_heads=16, head_dim=256, rope_theta=10000.0,
    rms_norm_eps=1e-6, attention_bias=False, tie_word_embeddings=True,
    hidden_act="gelu_tanh", rmsnorm_offset=True, scale_embeddings=True,
    max_position_embeddings=8192,
)

PRESETS: dict[str, ModelConfig] = {
    "tiny": TINY,
    "qwen2.5-0.5b": QWEN2_0_5B,
    "qwen2.5-7b": QWEN2_7B,
    "qwen2.5-72b": QWEN2_72B,
    "llama-3-8b": LLAMA3_8B,
    "mistral-7b": MISTRAL_7B,
    "gemma-2b": GEMMA_2B,
    "gemma-7b": GEMMA_7B,
}


def preset_for_model_name(name: str) -> ModelConfig | None:
    """Map an HF-style model id (e.g. 'Qwen/Qwen2.5-7B-Instruct') to a preset."""
    low = name.lower()
    if low == "tiny":  # exact only — "tiny" substrings occur in real model ids
        return TINY
    if "r1-distill" in low:
        # BASELINE config 4's model family: tensor dims match the Qwen2/Llama
        # presets but NOT the RoPE config (R1-Distill-Qwen-7B derives from
        # Qwen2.5-MATH-7B: rope_theta 1e4 vs the preset's 1e6, 131k context).
        # A preset would silently rotate positions at the wrong frequencies —
        # force config.json-driven loading instead.
        return None
    for key, cfg in PRESETS.items():
        # tiny: exact-match only; mistral-7b: guarded below (the v0.1 preset
        # must not claim v0.2/v0.3 checkpoints, which drop the window)
        if key in ("tiny", "mistral-7b"):
            continue
        if key in low.replace("_", "-"):
            return cfg
    if "0.5b" in low and "qwen" in low:
        return QWEN2_0_5B
    if "7b" in low and "qwen" in low:
        return QWEN2_7B
    if "72b" in low and "qwen" in low:
        return QWEN2_72B
    if "8b" in low and "llama" in low:
        return LLAMA3_8B
    if (
        "mistral-7b" in low.replace("_", "-")
        and "mixtral" not in low
        and not any(v in low for v in ("v0.2", "v0.3"))
        # v0.2/v0.3 drop the sliding window (and v0.3 grows the vocab);
        # the v0.1 preset would wrongly cap their sequence length — let
        # those fall through to config.json-driven loading
    ):
        return MISTRAL_7B
    if "gemma-2b" in low.replace("_", "-"):
        return GEMMA_2B
    if "gemma-7b" in low.replace("_", "-"):
        return GEMMA_7B
    return None
