"""HF checkpoint → param-pytree loading.

Maps transformers-style state dicts (Qwen2/Llama safetensors) onto the stacked
[L, ...] layout of models/transformer.py. Replaces the reference's
FastLanguageModel.from_pretrained load path (distributed_actor.py:58–66) —
here loading is a host-side numpy pass followed by an optional device_put with
sharding, so multi-host loads stream straight to their shards.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Mapping

import numpy as np

from distrl_llm_tpu.models.configs import ModelConfig

Params = dict[str, Any]

# our layer key → (HF projection name, transpose?)  — HF Linear stores [out, in]
_HF_LAYER_MAP = {
    "wq": "self_attn.q_proj.weight",
    "wk": "self_attn.k_proj.weight",
    "wv": "self_attn.v_proj.weight",
    "wo": "self_attn.o_proj.weight",
    "w_gate": "mlp.gate_proj.weight",
    "w_up": "mlp.up_proj.weight",
    "w_down": "mlp.down_proj.weight",
    "bq": "self_attn.q_proj.bias",
    "bk": "self_attn.k_proj.bias",
    "bv": "self_attn.v_proj.bias",
    "attn_norm": "input_layernorm.weight",
    "mlp_norm": "post_attention_layernorm.weight",
}


def _get(sd: Mapping[str, np.ndarray], name: str) -> np.ndarray:
    if name in sd:
        return np.asarray(sd[name])
    # some exports drop the "model." prefix
    alt = name.removeprefix("model.")
    if alt in sd:
        return np.asarray(sd[alt])
    raise KeyError(name)


def params_from_state_dict(
    sd: Mapping[str, np.ndarray], cfg: ModelConfig, dtype=np.float32
) -> Params:
    """Numpy state dict (HF names) → our stacked param pytree."""

    def stack(key: str, hf_name: str) -> np.ndarray:
        per_layer = [
            _get(sd, f"model.layers.{i}.{hf_name}") for i in range(cfg.num_layers)
        ]
        out = np.stack(per_layer).astype(dtype)
        if key.startswith("w"):  # weights: HF [out, in] → ours [in, out]
            out = out.transpose(0, 2, 1)
        return out

    layers = {
        key: stack(key, hf_name)
        for key, hf_name in _HF_LAYER_MAP.items()
        if cfg.attention_bias or not key.startswith("b")
    }
    params: Params = {
        "embed": _get(sd, "model.embed_tokens.weight").astype(dtype),
        "final_norm": _get(sd, "model.norm.weight").astype(dtype),
        "layers": layers,
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _get(sd, "lm_head.weight").astype(dtype).T
    return params


def load_safetensors_dir(path: str) -> dict[str, np.ndarray]:
    """All tensors from a checkpoint directory's .safetensors shards, on host.
    Honors the index file when present."""
    from safetensors.numpy import load_file

    index_path = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            shards = sorted(set(json.load(f)["weight_map"].values()))
    else:
        shards = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    sd: dict[str, np.ndarray] = {}
    for shard in shards:
        sd.update(load_file(os.path.join(path, shard)))
    return sd


def state_dict_from_params(params: Params, cfg: ModelConfig) -> dict[str, np.ndarray]:
    """Our stacked param pytree → HF-named numpy state dict (the exact
    inverse of ``params_from_state_dict``)."""
    sd: dict[str, np.ndarray] = {}
    layers = params["layers"]
    for key, hf_name in _HF_LAYER_MAP.items():
        if key not in layers:
            continue
        stacked = np.asarray(layers[key])
        if key.startswith("w"):  # ours [L, in, out] → HF [out, in]
            stacked = stacked.transpose(0, 2, 1)
        for i in range(cfg.num_layers):
            sd[f"model.layers.{i}.{hf_name}"] = np.ascontiguousarray(stacked[i])
    sd["model.embed_tokens.weight"] = np.asarray(params["embed"])
    sd["model.norm.weight"] = np.asarray(params["final_norm"])
    if not cfg.tie_word_embeddings:
        sd["lm_head.weight"] = np.ascontiguousarray(np.asarray(params["lm_head"]).T)
    return sd


def save_hf_checkpoint(
    params: Params,
    cfg: ModelConfig,
    path: str,
    *,
    lora: Params | None = None,
    lora_alpha: float = 16.0,
    model_type: str | None = None,  # default: derived from cfg.model_type
) -> None:
    """Write an HF-format checkpoint directory (model.safetensors +
    config.json), optionally with the LoRA adapter MERGED into the base —
    the reference's per-``save_every`` ``save_pretrained`` snapshot
    (distributed_actor.py:263–264 ← distributed_trainer.py:372–380), loadable
    back through ``load_pretrained`` or transformers."""
    from safetensors.numpy import save_file

    from distrl_llm_tpu.models.lora import merge_lora

    if lora is not None:
        params = merge_lora(params, lora, lora_alpha)
    os.makedirs(path, exist_ok=True)
    sd = state_dict_from_params(params, cfg)
    save_file(sd, os.path.join(path, "model.safetensors"))
    torch_dtype = str(sd["model.embed_tokens.weight"].dtype)
    model_type = model_type or cfg.model_type
    arch = {
        "qwen2": "Qwen2ForCausalLM",
        "llama": "LlamaForCausalLM",
        "mistral": "MistralForCausalLM",
        "gemma": "GemmaForCausalLM",
    }.get(model_type, "LlamaForCausalLM")
    hf_cfg = {
        "model_type": model_type,
        "architectures": [arch],
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "tie_word_embeddings": cfg.tie_word_embeddings,
        "max_position_embeddings": cfg.max_position_embeddings,
        "torch_dtype": torch_dtype,
    }
    if cfg.hidden_act == "gelu_tanh":
        hf_cfg["hidden_act"] = "gelu_pytorch_tanh"
    if cfg.sliding_window is not None:
        hf_cfg["sliding_window"] = cfg.sliding_window
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)


def load_pretrained(
    path: str,
    cfg: ModelConfig | None = None,
    dtype=np.float32,
    shard_fn: Callable[[Params], Params] | None = None,
) -> tuple[Params, ModelConfig]:
    """Load an HF-format local checkpoint directory. ``shard_fn`` (e.g. a
    device_put with NamedSharding) is applied to the host tree, letting each
    process materialize only its shards."""
    if cfg is None:
        with open(os.path.join(path, "config.json")) as f:
            hf_cfg = json.load(f)

        class _NS:
            def __init__(self, d):
                self.__dict__.update(d)

        cfg = ModelConfig.from_hf_config(_NS(hf_cfg))
    sd = load_safetensors_dir(path)
    params = params_from_state_dict(sd, cfg, dtype=dtype)
    if shard_fn is not None:
        params = shard_fn(params)
    return params, cfg
