"""LoRA adapter pytrees over the frozen base decoder.

Equivalent of the reference's unsloth PEFT wrap (helper.py:25–46): rank-r
adapters on q/k/v/o/gate/up/down projections, alpha scaling (rsLoRA off),
zero-init B so step 0 is the base model. Unlike the reference, the adapter is
a plain pytree — weight sync to rollout workers is `jax.device_put` of these
arrays, not a filesystem round-trip (SURVEY §2b N2).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from distrl_llm_tpu.models.configs import ModelConfig

Params = dict[str, Any]

# layer-param key → (in_dim_attr, out_dim_attr) resolved against ModelConfig
_TARGET_DIMS = {
    "wq": ("hidden_size", "q_dim"),
    "wk": ("hidden_size", "kv_dim"),
    "wv": ("hidden_size", "kv_dim"),
    "wo": ("q_dim", "hidden_size"),
    "w_gate": ("hidden_size", "intermediate_size"),
    "w_up": ("hidden_size", "intermediate_size"),
    "w_down": ("intermediate_size", "hidden_size"),
}

# reference target_modules (helper.py:29–37) in our key naming
DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def lora_scale(rank: int, alpha: float) -> float:
    return alpha / rank


def init_lora_params(
    rng: jax.Array,
    cfg: ModelConfig,
    rank: int,
    targets: Sequence[str] = DEFAULT_TARGETS,
    dtype=jnp.float32,
) -> Params:
    """A ~ N(0, 1/r) (std r^-1/2), B = 0 — output delta starts at 0 and the
    initial A@B gradient scale is rank-independent."""
    layers: Params = {}
    keys = jax.random.split(rng, len(targets))
    for key, target in zip(keys, targets):
        d_in = getattr(cfg, _TARGET_DIMS[target][0])
        d_out = getattr(cfg, _TARGET_DIMS[target][1])
        a = jax.random.normal(key, (cfg.num_layers, d_in, rank)) * (rank**-0.5)
        layers[target] = {
            "a": a.astype(dtype),
            "b": jnp.zeros((cfg.num_layers, rank, d_out), dtype),
        }
    return {"layers": layers}


def merge_lora(base: Params, lora: Params, alpha: float) -> Params:
    """Fold adapters into a copy of the base weights (W + A@B·alpha/r) — used
    for checkpoint export, mirroring the reference's save_pretrained artifact
    (distributed_actor.py:263–264). Rank is derived from the adapter shapes so
    the scale can't silently mismatch."""
    rank = next(iter(lora["layers"].values()))["a"].shape[-1]
    scale = lora_scale(rank, alpha)
    merged_layers = dict(base["layers"])
    for target, ab in lora["layers"].items():
        w = base["layers"][target]
        from distrl_llm_tpu.ops.quant import is_quantized

        if is_quantized(w):
            raise NotImplementedError("cannot merge LoRA into quantized base weights")
        delta = jnp.einsum("lir,lro->lio", ab["a"].astype(w.dtype), ab["b"].astype(w.dtype))
        merged_layers[target] = w + delta * scale
    out = dict(base)
    out["layers"] = merged_layers
    return out


def lora_param_count(lora: Params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora))
