"""One owner of the JAX_PLATFORMS workaround for this environment.

The sitecustomize-registered axon TPU plugin IGNORES the ``JAX_PLATFORMS``
env var, so a process that wants the CPU backend (tests, smokes, host-only
prep) hangs in tunnel-down TPU client init unless it pins the platform via
``jax.config`` BEFORE the first backend touch. Every entry point that
honors the env var should call :func:`honor_jax_platforms` right after
``import jax`` instead of carrying its own copy of the check (eight
near-identical variants had accumulated by round 5).
"""

from __future__ import annotations

import os


def honor_jax_platforms(default: str | None = None) -> str | None:
    """Pin ``jax_platforms`` to the env-requested value (or ``default``).

    Returns the platform string that was pinned, or None when neither the
    env var nor ``default`` asks for one (leaving backend autodetection —
    i.e. the TPU plugin — in charge). Must run before jax touches a
    backend; safe to call multiple times with the same value.
    """
    import jax

    requested = os.environ.get("JAX_PLATFORMS", "").strip() or default
    if requested:
        jax.config.update("jax_platforms", requested)
    return requested or None
